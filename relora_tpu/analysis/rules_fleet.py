"""RTL7xx — fleet-plane consistency: the string-keyed contracts.

The observability/fleet tier is stitched together by names: a serving
replica registers ``ttft_seconds`` under the ``relora_serve`` namespace, the
collector derives ``relora_serve_ttft_seconds_p95`` from scraped bucket
deltas, the autoscaler and ``tools/fleet_report.py`` consume that exact
string, and ``tools/bench_gate.py`` regresses on the derived report.  None
of that is type-checked — a typo on either side silently yields "no data"
instead of an error.  These rules recover the contract statically by
building the produced-name and consumed-name universes over the whole
project (:class:`~relora_tpu.analysis.core.ProjectIndex`, including the
read-only ``tools/``/``tests/``/``bench.py`` context files) and diffing
them.

Produced series = metric registrations (``inc``/``set_gauge``/``observe``/
``materialize_histogram`` literals crossed with every known registry
namespace), direct ``add_sample``/``add_samples`` literals, and the
collector's own derivations (literal and f-string subscript stores in
``parse_prometheus``-consuming modules; a leading f-string constant becomes
a prefix wildcard, a trailing one a derivation suffix like ``_per_s`` whose
base must itself be produced).

- RTL701: consumed series name (``*_SERIES`` constant, ``*_COLUMNS`` table
  row, ``latest``/``window_values``/``samples`` literal, ``series=`` kwarg)
  with no producer.
- RTL702: consumed event kind (``*_KINDS`` constant, ``events(kinds=...)``
  literal) that nothing emits; supervisor-routed kinds are matched through
  the ``supervisor_`` prefixing rule.
- RTL703: counter consumed by a collector delta-derivation that is not
  materialized at zero anywhere (``inc(name, ..., 0)`` / ``by=0``) — the
  derived series silently never exists until the first organic hit.
- RTL704: fault-site name (``faults.configure`` literal or a
  ``RELORA_TPU_FAULTS`` env string) with no check site in
  ``relora_tpu`` (``should``/``maybe_fail``/``crash_point``/``perturb``).
- RTL705: event kind emitted by the fleet plane (``add_event`` /
  ``record_supervisor_event``) that no timeline/report/alert surface
  consumes — dead telemetry, warn-level.

Deliberately out of scope: a never-consumed *series* warn (the collector's
generic ``*_per_s`` derivation consumes every counter, so the vice-versa
check for series is all noise), and ``bench_gate`` JSON fields (it reads
derived BENCH reports, whose series provenance is checked at the
collector/report layer above).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from relora_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectIndex,
    catalog,
    dotted_name,
    get_kwarg,
    project_checker,
)

catalog(
    RTL701="consumed fleet series has no producer (typo'd or dropped registration)",
    RTL702="consumed event kind is never emitted anywhere",
    RTL703="delta-derived counter is not materialized at zero",
    RTL704="fault site is configured but has no check site in utils/faults",
    RTL705="event kind is emitted but no report/alert surface consumes it",
)

METRIC_REG_METHODS = {
    "inc": "counter",
    "set_gauge": "gauge",
    "observe": "hist",
    "materialize_histogram": "hist",
}
FAULT_CHECK_METHODS = frozenset(
    {"should", "maybe_fail", "crash_point", "perturb", "active", "tick"}
)
EVENT_EMITTERS_STRICT = frozenset({"add_event", "record_supervisor_event"})
EVENT_EMITTERS_LOOSE = EVENT_EMITTERS_STRICT | frozenset({"_event", "_emit", "deploy_emit"})

_FAULT_SPEC_RE = re.compile(r"^[a-z_][a-z0-9_]*:[a-z0-9_.]+=")

Anchor = Tuple[str, FileContext, ast.AST]  # (name, owning file, anchor node)


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _str_elts(node: Optional[ast.AST]) -> List[str]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    s = _const_str(node)
    return [s] if s is not None else []


def _fstring_parts(node: ast.AST) -> Tuple[str, str, bool]:
    """(leading constant, trailing constant, has dynamic part) of a JoinedStr."""
    if not isinstance(node, ast.JoinedStr) or not node.values:
        return "", "", False
    lead = _const_str(node.values[0]) or ""
    tail = _const_str(node.values[-1]) or ""
    dynamic = any(isinstance(v, ast.FormattedValue) for v in node.values)
    return lead, tail, dynamic


class _Facts:
    def __init__(self) -> None:
        # producers
        self.namespaces: Set[str] = set()
        self.metric_bases: Set[str] = set()
        self.metric_fstring_prefixes: Set[str] = set()
        self.zero_counters: Set[str] = set()
        self.series_exact: Set[str] = set()  # add_sample/add_samples/derived
        self.series_prefixes: Set[str] = set()  # f"healthz_{k}" stores
        self.series_suffixes: Set[str] = set()  # f"{name}_per_s" derivations
        self.events_produced: Set[str] = set()  # loose emitter set
        self.events_strict: List[Anchor] = []  # fleet-plane emissions
        self.fault_sites_known: Set[str] = set()
        # consumers
        self.series_consumed: List[Anchor] = []
        self.events_consumed: List[Anchor] = []
        self.event_prefixes_consumed: Set[str] = set()
        self.counters_consumed: List[Anchor] = []
        self.fault_sites_consumed: List[Anchor] = []


class _FileScan(ast.NodeVisitor):
    def __init__(self, ctx: FileContext, facts: _Facts) -> None:
        self.ctx = ctx
        self.facts = facts
        rel = ctx.relpath
        self.in_pkg = rel.startswith("relora_tpu/")
        #: the production universe: series/event producer AND consumer
        #: surfaces are the package plus tools/bench — test fixtures neither
        #: satisfy a production consumer nor get their ad-hoc stores checked
        self.consumer = self.in_pkg or rel.startswith("tools/") or rel == "bench.py"
        self.producer = self.consumer
        self.pp_module = "parse_prometheus" in ctx.text
        self.faults_env = "RELORA_TPU_FAULTS" in ctx.text

    # -- assignments: constants, tables, derivation stores -------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and self.consumer:
                if tgt.id.endswith("_SERIES"):
                    s = _const_str(node.value)
                    if s:
                        self.facts.series_consumed.append((s, self.ctx, node))
                elif tgt.id.endswith("_COLUMNS"):
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        for row in node.value.elts:
                            if isinstance(row, (ast.Tuple, ast.List)) and len(row.elts) >= 2:
                                s = _const_str(row.elts[1])
                                if s:
                                    self.facts.series_consumed.append((s, self.ctx, row))
                elif tgt.id.endswith("_KINDS"):
                    for s in _str_elts(node.value):
                        self.facts.events_consumed.append((s, self.ctx, node))
            if isinstance(tgt, ast.Subscript) and self.pp_module and self.producer:
                key = tgt.slice
                s = _const_str(key)
                if s:
                    self.facts.series_exact.add(s)
                else:
                    lead, tail, dynamic = _fstring_parts(key)
                    if dynamic and lead:
                        self.facts.series_prefixes.add(lead)
                    elif dynamic and tail:
                        self.facts.series_suffixes.add(tail)
        self.generic_visit(node)

    # -- defaults: MetricsRegistry namespaces --------------------------------

    def _visit_func(self, node) -> None:
        if node.name == "__init__" and self.producer:
            args = node.args
            defaults = args.defaults
            names = [a.arg for a in args.args]
            for name, default in zip(names[len(names) - len(defaults):], defaults):
                if name == "namespace":
                    s = _const_str(default)
                    if s:
                        self.facts.namespaces.add(s)
        self.generic_visit(node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- calls: registrations, stores, consumers, faults ---------------------

    def visit_Call(self, node: ast.Call) -> None:
        basename = ""
        if isinstance(node.func, ast.Attribute):
            basename = node.func.attr
        elif isinstance(node.func, ast.Name):
            basename = node.func.id

        if self.producer:
            ns = _const_str(get_kwarg(node, "namespace"))
            if ns:
                self.facts.namespaces.add(ns)

            if basename in METRIC_REG_METHODS:
                name = _const_str(node.args[0]) if node.args else None
                if name:
                    self.facts.metric_bases.add(name)
                    if basename == "inc" and self._inc_is_zero(node):
                        self.facts.zero_counters.add(name)
                elif node.args:
                    lead, _tail, dynamic = _fstring_parts(node.args[0])
                    if dynamic and lead:
                        self.facts.metric_fstring_prefixes.add(lead)

            if basename == "add_sample" and len(node.args) >= 2:
                s = _const_str(node.args[1])
                if s:
                    self.facts.series_exact.add(s)
            elif basename == "add_samples" and len(node.args) >= 2:
                if isinstance(node.args[1], ast.Dict):
                    for k in node.args[1].keys:
                        s = _const_str(k)
                        if s:
                            self.facts.series_exact.add(s)

            if basename in EVENT_EMITTERS_LOOSE and node.args:
                s = _const_str(node.args[0])
                if s:
                    self.facts.events_produced.add(s)
                    if basename in EVENT_EMITTERS_STRICT and self.in_pkg:
                        self.facts.events_strict.append((s, self.ctx, node))

        if self.consumer:
            if basename in ("latest", "window_values", "samples") and len(node.args) >= 2:
                s = _const_str(node.args[1])
                if s:
                    self.facts.series_consumed.append((s, self.ctx, node))
            series_kw = get_kwarg(node, "series")
            s = _const_str(series_kw)
            if s:
                self.facts.series_consumed.append((s, self.ctx, series_kw))
            if basename == "events":
                kinds = get_kwarg(node, "kinds")
                if kinds is None and node.args:
                    kinds = node.args[0]
                for s in _str_elts(kinds):
                    self.facts.events_consumed.append((s, self.ctx, node))
            if basename == "startswith" and isinstance(node.func, ast.Attribute):
                recv_has_event = any(
                    isinstance(n, ast.Constant) and n.value == "_event"
                    for n in ast.walk(node.func.value)
                )
                if recv_has_event and node.args:
                    for s in _str_elts(node.args[0]):
                        self.facts.event_prefixes_consumed.add(s)

        if self.pp_module and self.producer and basename == "endswith" and node.args:
            for s in _str_elts(node.args[0]):
                if s.endswith("_total") and s != "_total":
                    self.facts.counters_consumed.append((s, self.ctx, node))

        if self.in_pkg and basename in FAULT_CHECK_METHODS and node.args:
            s = _const_str(node.args[0])
            if s:
                self.facts.fault_sites_known.add(s)
        if (
            basename == "get"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "_FAULTS"
            and node.args
        ):
            s = _const_str(node.args[0])
            if s:
                self.facts.fault_sites_known.add(s)
        if basename == "configure" and node.args:
            dotted = dotted_name(node.func)
            if dotted == "configure" or "faults" in dotted:
                s = _const_str(node.args[0])
                if s:
                    self.facts.fault_sites_consumed.append((s, self.ctx, node))

        self.generic_visit(node)

    @staticmethod
    def _inc_is_zero(node: ast.Call) -> bool:
        by = get_kwarg(node, "by")
        if isinstance(by, ast.Constant) and by.value == 0:
            return True
        if len(node.args) >= 2:
            last = node.args[-1]
            if isinstance(last, ast.Constant) and last.value == 0:
                return True
        return False

    # -- `"X_total." in name` membership tests (RTL703 consumers) ------------

    def visit_Compare(self, node: ast.Compare) -> None:
        if self.pp_module and self.producer and any(
            isinstance(op, ast.In) for op in node.ops
        ):
            s = _const_str(node.left)
            if s and s.endswith("_total.") and s != "_total.":
                self.facts.counters_consumed.append((s[:-1], self.ctx, node))
        self.generic_visit(node)

    # -- RELORA_TPU_FAULTS env strings (RTL704 consumers) --------------------

    def visit_Constant(self, node: ast.Constant) -> None:
        if (
            self.faults_env
            and isinstance(node.value, str)
            and _FAULT_SPEC_RE.match(node.value)
        ):
            for part in node.value.split(";"):
                site = part.split(":", 1)[0].strip()
                if site:
                    self.facts.fault_sites_consumed.append((site, self.ctx, node))

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if self.faults_env:
            lead = _const_str(node.values[0]) if node.values else None
            if lead and _FAULT_SPEC_RE.match(lead):
                site = lead.split(":", 1)[0]
                self.facts.fault_sites_consumed.append((site, self.ctx, node))
        self.generic_visit(node)


def collect_facts(index: ProjectIndex) -> _Facts:
    facts = _Facts()
    for relpath in sorted(index.contexts):
        ctx = index.contexts[relpath]
        _FileScan(ctx, facts).visit(ctx.tree)
    return facts


def _series_produced(facts: _Facts, name: str, _depth: int = 0) -> bool:
    if name in facts.series_exact:
        return True
    namespaced = {
        f"{ns}_{base}" for ns in facts.namespaces for base in facts.metric_bases
    }
    if name in namespaced:
        return True
    prefixes = set(facts.series_prefixes)
    prefixes.update(
        f"{ns}_{p}" for ns in facts.namespaces for p in facts.metric_fstring_prefixes
    )
    prefixes.update(facts.metric_fstring_prefixes)
    if any(name.startswith(p) for p in prefixes):
        return True
    if _depth == 0:
        for suf in facts.series_suffixes:
            if name.endswith(suf) and len(name) > len(suf):
                if _series_produced(facts, name[: -len(suf)], _depth=1):
                    return True
    return False


def _event_produced(facts: _Facts, kind: str) -> bool:
    if kind in facts.events_produced:
        return True
    # the collector's supervisor routing prefixes non-deploy/autoscale kinds
    if kind.startswith("supervisor_") and kind[len("supervisor_"):] in facts.events_produced:
        return True
    return False


def _event_consumed(facts: _Facts, kind: str) -> bool:
    consumed = {k for k, _ctx, _n in facts.events_consumed}
    for k in (kind, f"supervisor_{kind}"):
        if k in consumed:
            return True
        if any(k.startswith(p) for p in facts.event_prefixes_consumed):
            return True
    return False


def fleet_findings(index: ProjectIndex) -> List[Finding]:
    """The full RTL7xx pass over an index; exposed for fixture tests."""
    facts = collect_facts(index)
    findings: List[Finding] = []

    for name, ctx, node in facts.series_consumed:
        if not _series_produced(facts, name):
            findings.append(
                ctx.finding(
                    node,
                    "RTL701",
                    f"series '{name}' is consumed here but no registration, "
                    "gauge, sample store, or collector derivation produces "
                    "it — typo or dropped producer",
                )
            )

    for kind, ctx, node in facts.events_consumed:
        if not _event_produced(facts, kind):
            findings.append(
                ctx.finding(
                    node,
                    "RTL702",
                    f"event kind '{kind}' is consumed here but nothing emits "
                    "it (add_event/record_supervisor_event)",
                )
            )

    for name, ctx, node in facts.counters_consumed:
        if name not in facts.zero_counters:
            findings.append(
                ctx.finding(
                    node,
                    "RTL703",
                    f"counter '{name}' feeds a delta derivation but is never "
                    "materialized at zero (inc(..., 0) / by=0) — the derived "
                    "series does not exist until the first organic hit",
                )
            )

    for site, ctx, node in facts.fault_sites_consumed:
        if site not in facts.fault_sites_known:
            findings.append(
                ctx.finding(
                    node,
                    "RTL704",
                    f"fault site '{site}' is configured but has no "
                    "should/maybe_fail/crash_point/perturb check site in "
                    "relora_tpu — the injection silently never fires",
                )
            )

    seen_warn: Set[str] = set()
    for kind, ctx, node in facts.events_strict:
        if kind in seen_warn:
            continue
        if not _event_consumed(facts, kind):
            seen_warn.add(kind)
            findings.append(
                ctx.finding(
                    node,
                    "RTL705",
                    f"event kind '{kind}' is emitted but no timeline/report/"
                    "alert surface consumes it — dead telemetry (wire it into "
                    "a _KINDS table or drop the emission)",
                )
            )
    return findings


@project_checker
def check_fleet_consistency(index: ProjectIndex) -> List[Finding]:
    return fleet_findings(index)
