"""Llama model tests: shapes, init-equivalence, scan/unroll parity, merge
losslessness at the model level, and a differential test against HF torch.

These systematize the reference's notebook oracles (SURVEY.md §4):
notebook 12 (wrapped == base at init) and notebook 11 (local model == HF).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec, lora_param_mask, merge_and_reinit, split_param_counts
from relora_tpu.models.llama import LlamaForCausalLM
from relora_tpu.models.params_util import stack_layers, unstack_layers
from relora_tpu.train.losses import causal_lm_loss

TINY = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)


def init_model(lora=None, scan_layers=True, dtype=jnp.float32, **kw):
    from relora_tpu.models.params_util import init_params

    model = LlamaForCausalLM(TINY, lora=lora, dtype=dtype, scan_layers=scan_layers, **kw)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = init_params(model, jax.random.PRNGKey(0), ids)
    return model, params


def test_forward_shape_and_dtype():
    model, params = init_model()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32
    loss, n = causal_lm_loss(logits, ids)
    assert loss.shape == () and float(n) == 2 * 15
    assert 4.0 < float(loss) < 8.0  # ~ln(256) at random init


def test_lora_init_equals_base_model():
    """The reference's init-equivalence invariant (relora.py:120-124):
    B=0 ⇒ the LoRA model's forward equals the base model's, given the same
    base weights."""
    spec = LoraSpec(r=8, alpha=32, dropout=0.0)
    base_model, base_params = init_model(lora=None)
    lora_model, lora_params = init_model(lora=spec)

    # graft the base weights into the LoRA tree (keep fresh lora_a/lora_b)
    from relora_tpu.models.hf_compat import graft_base_weights

    grafted = graft_base_weights(lora_params, base_params)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 256)
    out_base = base_model.apply({"params": base_params}, ids)
    out_lora = lora_model.apply({"params": grafted}, ids)
    np.testing.assert_allclose(np.asarray(out_base), np.asarray(out_lora), atol=1e-5)


def test_graft_skips_source_lora_and_errors_on_mismatch():
    """Warm-starting from a previous LoRA run must keep fresh lora init
    (source lora_a/lora_b ignored); a structure mismatch must raise a
    descriptive error, not a bare KeyError (ADVICE r1)."""
    import pytest

    from relora_tpu.models.hf_compat import graft_base_weights

    spec = LoraSpec(r=8, alpha=32, dropout=0.0)
    lora_model, lora_params = init_model(lora=spec)
    # source: another LoRA checkpoint with different (nonzero) lora leaves
    _, source = init_model(lora=spec)

    def poison_lora(tree):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = poison_lora(v)
            elif k in ("lora_a", "lora_b"):
                out[k] = jnp.ones_like(v) * 7.0
            else:
                out[k] = v
        return out

    grafted = graft_base_weights(lora_params, poison_lora(source))

    def collect(tree, key, acc):
        for k, v in tree.items():
            if isinstance(v, dict):
                collect(v, key, acc)
            elif k == key:
                acc.append(v)
        return acc

    # lora_b stayed at fresh init (zeros), not the source's 7s
    for leaf in collect(grafted, "lora_b", []):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)

    # structure mismatch raises a descriptive error
    with pytest.raises(KeyError, match="graft_base_weights"):
        graft_base_weights(lora_params, {"not_a_real_module": {"kernel": jnp.zeros((2, 2))}})


def test_lora_leaves_exist_only_in_target_modules():
    spec = LoraSpec(r=8, alpha=32)
    _, params = init_model(lora=spec)
    mask = lora_param_mask(params)
    leaves = jax.tree_util.tree_flatten_with_path(mask)[0]
    lora_paths = ["/".join(str(getattr(k, "key", k)) for k in p) for p, v in leaves if v]
    assert all(("self_attn" in p or "mlp" in p) for p in lora_paths)
    assert not any("lm_head" in p or "embed" in p for p in lora_paths)
    # q,k,v,o + gate,up,down = 7 modules × 2 leaves, stacked over layers
    assert len(lora_paths) == 14
    counts = split_param_counts(params)
    assert counts["lora_params"] == 2 * (4 * (64 * 8 + 8 * 64) + 2 * (64 * 8 + 8 * 160) + (160 * 8 + 8 * 64))


def test_scan_and_unrolled_agree():
    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    scan_model, scan_params = init_model(lora=spec, scan_layers=True)
    unrolled_model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32, scan_layers=False)
    unrolled_params = unstack_layers(scan_params)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 256)
    out_scan = scan_model.apply({"params": scan_params}, ids)
    out_unrolled = unrolled_model.apply({"params": unrolled_params}, ids)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_unrolled), atol=1e-5)
    # round trip layout conversion
    restacked = stack_layers(unrolled_params, TINY.num_hidden_layers)
    chex_equal = jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: bool(jnp.array_equal(a, b)), scan_params, restacked)
    )
    assert chex_equal


def test_model_level_merge_is_lossless():
    """Merge-and-reinit must not change the function the model computes
    (oracle (b) from SURVEY.md §4)."""
    spec = LoraSpec(r=8, alpha=32, dropout=0.0)
    model, params = init_model(lora=spec)
    # give lora_b nonzero values so the merge actually moves weight
    params = jax.tree_util.tree_map_with_path(
        lambda p, x: jax.random.normal(jax.random.PRNGKey(5), x.shape) * 0.02
        if "lora_b" in str(p[-1])
        else x,
        params,
    )
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 256)
    out_before = model.apply({"params": params}, ids)
    merged = merge_and_reinit(params, jax.random.PRNGKey(6), spec)
    out_after = model.apply({"params": merged}, ids)
    np.testing.assert_allclose(np.asarray(out_before), np.asarray(out_after), atol=2e-4)


def test_remat_matches_no_remat():
    model, params = init_model(remat=False)
    remat_model = LlamaForCausalLM(TINY, dtype=jnp.float32, scan_layers=True, remat=True)
    ids = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, 256)

    def loss(m, p):
        return causal_lm_loss(m.apply({"params": p}, ids), ids)[0]

    l1, g1 = jax.value_and_grad(lambda p: loss(model, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(remat_model, p))(params)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_attention_impls_agree():
    from relora_tpu.ops.attention import dot_product_attention

    k = jax.random.PRNGKey(0)
    q, kk, v = (jax.random.normal(jax.random.fold_in(k, i), (2, 32, 4, 16)) for i in range(3))
    out_xla = dot_product_attention(q, kk, v, causal=True, impl="xla")
    out_naive = dot_product_attention(q, kk, v, causal=True, impl="naive")
    np.testing.assert_allclose(np.asarray(out_xla), np.asarray(out_naive), atol=2e-5)


def test_grouped_equal_heads_call_matches_expansion():
    """The pallas GQA path's per-group-slice dispatch (no K/V expansion)
    must equal attention over explicitly expanded K/V."""
    from relora_tpu.ops.attention import (
        _expand_grouped_kv,
        _grouped_equal_heads_call,
        dot_product_attention,
    )

    key = jax.random.PRNGKey(1)
    q = jax.random.normal(jax.random.fold_in(key, 0), (2, 16, 8, 8))
    kk = jax.random.normal(jax.random.fold_in(key, 1), (2, 16, 2, 8))
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 16, 2, 8))

    def eq(qq, k_, v_):
        return dot_product_attention(qq, k_, v_, causal=True, impl="naive")

    got = _grouped_equal_heads_call(q, kk, v, eq)
    ke, ve = _expand_grouped_kv(q, kk, v)
    want = dot_product_attention(q, ke, ve, causal=True, impl="naive")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_auto_dispatch_respects_backend():
    """auto resolves through the roofline dispatcher: on the CPU test
    backend the flash arm is struck (fused_available=False), so auto must
    match a non-pallas arm bit-for-bit — dispatch never changes numerics."""
    from relora_tpu.ops import attention as A
    from relora_tpu.ops.attention_dispatch import choose_training_arm

    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (1, 256, 2, 8))
    arm = choose_training_arm(1, 256, 2, 2, 8, act_bytes=4, fused_available=False)
    assert arm in ("xla", "naive")
    out_auto = A.dot_product_attention(q, q, q, causal=True, impl="auto")
    out_arm = A.dot_product_attention(q, q, q, causal=True, impl=arm)
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_arm), atol=0)


@pytest.mark.slow
def test_against_hf_torch_llama():
    """Differential oracle: our forward vs transformers' torch Llama with
    identical weights (systematizes notebook 11)."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFLlama

    from relora_tpu.models.hf_compat import hf_to_params

    hf_cfg = HFConfig(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.hidden_size,
        intermediate_size=TINY.intermediate_size,
        num_hidden_layers=TINY.num_hidden_layers,
        num_attention_heads=TINY.num_attention_heads,
        num_key_value_heads=TINY.num_attention_heads,
        max_position_embeddings=TINY.max_sequence_length,
        rms_norm_eps=TINY.rms_norm_eps,
        rope_theta=TINY.rotary_emb_base,
        attention_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = HFLlama(hf_cfg).eval()
    params = hf_to_params(hf_model.state_dict(), TINY, scan_layers=True)

    ids_np = np.random.RandomState(0).randint(0, TINY.vocab_size, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids_np)).logits.numpy()

    model = LlamaForCausalLM(TINY, dtype=jnp.float32, scan_layers=True)
    ours = model.apply({"params": jax.tree_util.tree_map(jnp.asarray, params)}, jnp.asarray(ids_np))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=2e-4, rtol=2e-3)


def test_rope_scaling_variants():
    """linear / dynamic-NTK rope scaling (parity: modeling_pythia.py:333-375)."""
    from relora_tpu.models.llama import rotary_tables

    pos = jnp.arange(16)[None, :]
    base_cos, _ = rotary_tables(pos, 16)
    lin_cos, _ = rotary_tables(pos, 16, scaling_type="linear", scaling_factor=2.0)
    # linear scaling at factor 2 equals halved positions
    half_cos, _ = rotary_tables(pos / 2, 16)
    np.testing.assert_allclose(np.asarray(lin_cos), np.asarray(half_cos), atol=1e-6)
    # dynamic NTK only kicks in beyond the trained max
    dyn_short, _ = rotary_tables(pos, 16, scaling_type="dynamic", scaling_factor=2.0,
                                 max_position=32, current_length=16)
    np.testing.assert_allclose(np.asarray(dyn_short), np.asarray(base_cos), atol=1e-6)
    dyn_long, _ = rotary_tables(pos, 16, scaling_type="dynamic", scaling_factor=2.0,
                                max_position=8, current_length=16)
    assert not np.allclose(np.asarray(dyn_long), np.asarray(base_cos))
    with pytest.raises(ValueError, match="scaling type"):
        rotary_tables(pos, 16, scaling_type="ntk")
    # models accept the config fields
    cfg = ModelConfig(**{**TINY.to_dict(), "rope_scaling_type": "linear",
                         "rope_scaling_factor": 2.0})
    model = LlamaForCausalLM(cfg, dtype=jnp.float32)
    ids = jnp.zeros((1, 8), jnp.int32)
    from relora_tpu.models.params_util import init_params
    params = init_params(model, jax.random.PRNGKey(0), ids)
    assert model.apply({"params": params}, ids).shape == (1, 8, cfg.vocab_size)


def test_lora_only_mode():
    """Pure-LoRA layers: no kernel leaf, forward is the LoRA branch alone,
    merge skips them (parity: relora.py:209-211, 271-273)."""
    from relora_tpu.core.relora import trainable_param_mask

    spec = LoraSpec(r=4, alpha=32, dropout=0.0, lora_only=True)
    model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 256)
    from relora_tpu.models.params_util import init_params
    params = init_params(model, jax.random.PRNGKey(1), ids)
    q = params["layers"]["self_attn"]["q_proj"]
    assert "kernel" not in q and "lora_a" in q
    # everything that exists is trainable
    mask = trainable_param_mask(params)
    assert all(jax.tree_util.tree_leaves(mask))
    out = model.apply({"params": params}, ids)
    assert out.shape == (2, 16, 256)
    # merge leaves lora_only modules untouched
    merged = merge_and_reinit(params, jax.random.PRNGKey(2), spec)
    np.testing.assert_array_equal(
        np.asarray(merged["layers"]["self_attn"]["q_proj"]["lora_a"]),
        np.asarray(q["lora_a"]),
    )


def test_bf16_logits_option():
    """bf16 logits: same predictions, loss within bf16 tolerance of f32."""
    model_f32, params = init_model()
    model_bf16 = LlamaForCausalLM(TINY, dtype=jnp.float32, logits_dtype=jnp.bfloat16)
    ids = jax.random.randint(jax.random.PRNGKey(9), (2, 16), 0, 256)
    lf = model_f32.apply({"params": params}, ids)
    lb = model_bf16.apply({"params": params}, ids)
    assert lb.dtype == jnp.bfloat16
    loss_f = float(causal_lm_loss(lf, ids)[0])
    loss_b = float(causal_lm_loss(lb, ids)[0])
    assert loss_b == pytest.approx(loss_f, rel=2e-2)
    np.testing.assert_array_equal(
        np.argmax(np.asarray(lf), -1), np.argmax(np.asarray(lb.astype(jnp.float32)), -1)
    )


def test_causal_lm_loss_explicit_labels_matches_shift():
    """labels= path (zigzag layout) equals the shifted path on identity
    permutation, and respects -100 ignore."""
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (2, 8, 16))
    ids = jax.random.randint(jax.random.fold_in(rng, 1), (2, 8), 0, 16)
    loss_shift, n_shift = causal_lm_loss(logits, ids)
    labels = jnp.concatenate([ids[:, 1:], jnp.full((2, 1), -100, ids.dtype)], axis=1)
    loss_lab, n_lab = causal_lm_loss(logits, ids, labels=labels)
    # shifted path scores logits[:, :-1] vs ids[:, 1:]; labels path scores
    # logits[:, i] vs labels[:, i] — same pairs, same mean
    assert float(n_shift) == float(n_lab) == 2 * 7
    assert float(loss_shift) == pytest.approx(float(loss_lab), rel=1e-6)
    # all-ignored rows contribute nothing
    loss_none, n_none = causal_lm_loss(logits, ids, labels=jnp.full((2, 8), -100))
    assert float(n_none) == 1.0 and float(loss_none) == 0.0


def test_attention_dispatch_errors():
    from relora_tpu.ops.attention import dot_product_attention
    from relora_tpu.parallel.mesh import set_current_mesh

    q = jnp.zeros((1, 8, 2, 4))
    with pytest.raises(ValueError, match="Unknown attention impl"):
        dot_product_attention(q, q, q, impl="flashy")
    set_current_mesh(None)
    with pytest.raises(RuntimeError, match="needs a mesh"):
        dot_product_attention(q, q, q, impl="ring")
    with pytest.raises(RuntimeError, match="needs a mesh"):
        dot_product_attention(q, q, q, impl="ulysses")


def test_chunked_softmax_ce_matches_dense():
    """Streamed vocab-chunk CE equals dense log_softmax CE in value and
    gradients (incl. a non-dividing vocab and ignored targets)."""
    from relora_tpu.train.losses import chunked_softmax_ce

    rng = jax.random.PRNGKey(0)
    B, S, E, V = 2, 6, 16, 50  # V=50 with chunk 16 -> padded final chunk
    hidden = jax.random.normal(rng, (B, S, E))
    kernel = jax.random.normal(jax.random.fold_in(rng, 1), (E, V)) * 0.3
    targets = jax.random.randint(jax.random.fold_in(rng, 2), (B, S), 0, V)
    targets = targets.at[0, 0].set(-100)

    def dense(h, k):
        logits = (h @ k).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(lp, jnp.maximum(targets, 0)[..., None], axis=-1)[..., 0]
        valid = (targets >= 0).astype(jnp.float32)
        return -(ll * valid).sum() / valid.sum()

    def chunked(h, k):
        return chunked_softmax_ce(h, k, targets, chunk_size=16)[0]

    ld = float(dense(hidden, kernel))
    lc, n = chunked_softmax_ce(hidden, kernel, targets, chunk_size=16)
    assert float(n) == B * S - 1
    assert float(lc) == pytest.approx(ld, rel=1e-5)

    gd = jax.grad(dense, argnums=(0, 1))(hidden, kernel)
    gc = jax.grad(chunked, argnums=(0, 1))(hidden, kernel)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_gqa_against_hf_torch():
    """GQA (num_key_value_heads < heads) matches HF torch Llama."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFLlama

    from relora_tpu.models.hf_compat import hf_to_params

    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_sequence_length=64,
    )
    hf_cfg = HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=cfg.rms_norm_eps,
        rope_theta=cfg.rotary_emb_base, attention_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = HFLlama(hf_cfg).eval()
    params = hf_to_params(hf_model.state_dict(), cfg, scan_layers=True)
    ids_np = np.random.RandomState(0).randint(0, 256, (2, 24))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids_np)).logits.numpy()
    model = LlamaForCausalLM(cfg, dtype=jnp.float32)
    ours = model.apply({"params": jax.tree_util.tree_map(jnp.asarray, params)}, jnp.asarray(ids_np))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=2e-4, rtol=2e-3)
