"""TP × FSDP composition over the 8 virtual CPU devices (conftest).

The tentpole invariants of the parallelism layer, held on CPU where they are
cheap to check bit-for-bit:

- a tp=2 × fsdp=4 train step — tensor collectives INSIDE the layer, fsdp
  param gathers AROUND it — produces the same losses as the identical
  program on one device, through a merge-and-reinit and beyond (dispatch
  and sharding change the compute graph, never the result);
- merge-and-reinit keeps the merged tree on its training shardings (the
  trainer pins ``out_shardings`` for exactly this — a replicated comeback
  after every cycle would OOM at real dims);
- the paged serving engine with the pool sharded over kv-heads
  (``kv_shards > 1``, page budget scaled per shard) stays token-identical
  to the meshless paged engine and to the contiguous scheduler.

The compile-heavy tests are marked ``slow`` (tier-1 runs cold-compiled under
a wall-clock budget); the smoke-test ``parallel`` stage runs all of them via
``-m parallel``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.optim import build_optimizer, init_opt_state_sharded
from relora_tpu.core.partition import partition
from relora_tpu.core.relora import LoraSpec, merge_and_reinit, trainable_param_mask
from relora_tpu.core.schedules import make_schedule
from relora_tpu.models.llama import LlamaForCausalLM
from relora_tpu.models.params_util import init_params, logical_partition_specs
from relora_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    make_mesh,
    param_shardings,
    set_current_mesh,
    shard_params,
)
from relora_tpu.train.state import TrainState
from relora_tpu.train.step import make_train_step

pytestmark = pytest.mark.parallel

# kv_heads=2 splits exactly over tensor=2: the ("kv", tensor) logical rule
# and the serving pool's kv-head sharding both activate
CFG = ModelConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_sequence_length=64,
)
GA, MICRO, SEQ = 2, 4, 16


def _batches(n_steps):
    rs = np.random.RandomState(0)
    return [
        jnp.asarray(rs.randint(0, CFG.vocab_size, (GA, MICRO, SEQ)), jnp.int32)
        for _ in range(n_steps)
    ]


_SINGLE = {}


def _single_device_reference():
    """The meshless oracle trace, shared across parity tests (it is identical
    for every composed mesh under test, and compiling it twice is the single
    most expensive redundancy in this file)."""
    if "ref" not in _SINGLE:
        _SINGLE["ref"] = _run_training(
            jax.devices()[:1], MeshSpec(data=1, fsdp=1, tensor=1, sequence=1)
        )
    return _SINGLE["ref"]


def _run_training(devices, mesh_spec, n_steps=2):
    """Train ``n_steps``, merge-and-reinit, train one more step; return the
    loss trace plus whether any merged leaf stayed non-replicated."""
    mesh = make_mesh(mesh_spec, devices=devices)
    set_current_mesh(mesh)
    try:
        spec = LoraSpec(r=8, alpha=32, dropout=0.0)
        model = LlamaForCausalLM(CFG, lora=spec, dtype=jnp.float32, scan_layers=True)
        sample = jnp.zeros((2, 8), jnp.int32)
        params = init_params(model, jax.random.PRNGKey(0), sample)
        mask = trainable_param_mask(params)
        schedule = make_schedule(
            "cosine_restarts",
            lr=1e-3,
            num_training_steps=100,
            warmup_steps=10,
            cycle_length=50,
            restart_warmup_steps=5,
        )
        tx = build_optimizer(schedule=schedule)
        shardings = param_shardings(mesh, logical_partition_specs(model, sample))
        params = shard_params(params, shardings)
        with mesh:
            opt_state = init_opt_state_sharded(tx, partition(params, mask)[0], mesh)
        state = TrainState.create(params, opt_state)
        step = jax.jit(make_train_step(model, tx, mask, schedule=schedule), donate_argnums=0)

        losses = []
        for batch in _batches(n_steps):
            placed = jax.device_put(batch, batch_sharding(mesh))
            state, metrics = step(state, placed, jax.random.PRNGKey(100))
            losses.append(float(metrics["loss"]))

        # merge-and-reinit pinned to the training shardings, as the trainer
        # does (out_shardings in Trainer._merge_fn)
        merged = jax.jit(
            lambda p, k: merge_and_reinit(p, k, spec), out_shardings=shardings
        )(state.params, jax.random.PRNGKey(3))
        any_sharded = any(
            not leaf.sharding.is_fully_replicated for leaf in jax.tree.leaves(merged)
        )
        state = state.replace(params=merged)
        placed = jax.device_put(_batches(n_steps + 1)[-1], batch_sharding(mesh))
        state, metrics = step(state, placed, jax.random.PRNGKey(101))
        losses.append(float(metrics["loss"]))
        return {"losses": losses, "any_sharded": any_sharded}
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_tp_fsdp_train_step_matches_single_device():
    """The acceptance oracle for the composed mesh: tp=2 × fsdp=4 loss trace
    (including the post-merge step) matches the single-device run to f32
    collective-reduction tolerance, and the merged tree is still sharded.

    The train-compile-heavy tests in this file are ``slow`` — the suite
    compiles everything cold (persistent cache off, see conftest) and tier-1
    runs under a hard wall-clock budget; the smoke stage runs the whole file
    via ``-m parallel``, which selects slow tests too."""
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provide 8 virtual CPU devices"
    composed = _run_training(
        devices[:8], MeshSpec(data=1, fsdp=4, tensor=2, sequence=1)
    )
    single = _single_device_reference()
    np.testing.assert_allclose(
        composed["losses"], single["losses"], rtol=5e-4, atol=1e-5
    )
    assert composed["any_sharded"], (
        "merge-and-reinit returned a fully replicated tree on the tp x fsdp "
        "mesh — out_shardings must pin the merged params to their training "
        "shardings"
    )
    # the losses actually moved (the trace is not a frozen constant)
    assert composed["losses"][0] != composed["losses"][-1]


@pytest.mark.slow
def test_data_x_tensor_mesh_also_matches():
    """Same oracle with the batch axes split as data=2 × fsdp=2 and tensor=2:
    grad all-reduce, fsdp gathers, and tensor collectives all live in one
    step."""
    devices = jax.devices()
    composed = _run_training(
        devices[:8], MeshSpec(data=2, fsdp=2, tensor=2, sequence=1)
    )
    single = _single_device_reference()
    np.testing.assert_allclose(
        composed["losses"], single["losses"], rtol=5e-4, atol=1e-5
    )


# ---------------------------------------------------------------------------
# serving: page pool sharded over kv-heads
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_paged_engine_pool_sharded_over_kv_heads():
    """A tensor=2 mesh shards the page pool's kv_heads axis and doubles the
    page budget (num_pages is per-chip); the sharded engine must stay
    token-identical to the meshless paged engine for the same requests."""
    from relora_tpu.serve.engine import InferenceEngine, build_decode_model
    from relora_tpu.serve.scheduler import PagedContinuousBatchingScheduler, Request

    devices = jax.devices()
    mesh = make_mesh(MeshSpec(data=1, fsdp=1, tensor=2, sequence=1), devices[:2])

    model = build_decode_model(CFG, cache_size=32)
    base = type(model)(CFG, lora=None, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    kwargs = dict(cache_size=32, page_size=8, num_pages=13, chunk_size=8)
    plain = InferenceEngine(CFG, params, **kwargs)
    sharded = InferenceEngine(CFG, params, mesh=mesh, **kwargs)

    assert sharded.kv_shards == 2
    assert sharded.num_pages == 2 * plain.num_pages  # per-chip budget scaled
    pool = sharded.init_pool()
    assert any(
        not leaf.sharding.is_fully_replicated for leaf in jax.tree.leaves(pool)
    ), "page pool came back replicated despite the tensor axis"

    reqs = lambda: [
        Request(uid=1, prompt=list(range(1, 14)), max_new_tokens=5),
        Request(uid=2, prompt=[7, 8, 9], max_new_tokens=5),
    ]
    want = PagedContinuousBatchingScheduler(plain, max_batch=2).run(reqs())
    got = PagedContinuousBatchingScheduler(sharded, max_batch=2).run(reqs())
    assert {u: c.tokens for u, c in got.items()} == {
        u: c.tokens for u, c in want.items()
    }


def test_pool_sharding_skipped_when_kv_heads_indivisible():
    """kv_heads=2 does not divide tensor=4: the engine must fall back to a
    replicated pool (kv_shards=1) rather than produce an invalid sharding."""
    from relora_tpu.serve.engine import InferenceEngine, build_decode_model

    devices = jax.devices()
    mesh = make_mesh(MeshSpec(data=1, fsdp=1, tensor=4, sequence=1), devices[:4])
    base = type(build_decode_model(CFG, cache_size=32))(
        CFG, lora=None, dtype=jnp.float32, scan_layers=True
    )
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    eng = InferenceEngine(
        CFG, params, mesh=mesh, cache_size=32, page_size=8, num_pages=13
    )
    assert eng.kv_shards == 1
    assert eng.num_pages == 13
