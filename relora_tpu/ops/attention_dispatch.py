"""Shape-aware dispatch for cached/paged attention.

The serving stack has three ways to attend a query against the KV cache,
and the right one depends on ``(B, S, S_kv, heads, page_size)`` the same
way the LoRA composite depends on (M, K, N, r) — *Run LoRA Run* roofline
territory, in the :mod:`relora_tpu.ops.lora_dispatch` mold:

- **naive** — :func:`relora_tpu.ops.attention.paged_cached_attention` /
  ``cached_attention``: gather (paged) then masked einsum softmax einsum.
  Always available, any S, the differential oracle.  Pays HBM for the
  gathered cache copy *and* the ``(B, heads, S, S_kv)`` score matrix.
- **flash** — the Pallas flash kernel via ``dot_product_attention``:
  O(seq) memory for the pure causal self-attention case (prefill from
  scratch, S == S_kv, 128-aligned).  Not applicable to cache-visibility
  masking, so it never serves the paged pool — it is modeled here so one
  cost table ranks every attention arm the repo has.
- **paged_decode** — :func:`relora_tpu.ops.attention.paged_decode_attention`:
  small-S decode straight out of the page pool through the block table —
  S == 1 plain decode or the speculative-decoding ``(B, K+1)`` verify
  window (``PAGED_DECODE_MAX_S`` bounds it; long chunked-prefill shapes
  stay naive) — one launch, no gathered copy, no score matrix, optional
  in-VMEM int8 dequant.  TPU-only for auto (the interpreter is a
  correctness tool).

:func:`choose_arm` ranks arms with the same ``t(arm) = max(bytes/BW,
flops/peak) + launches·t_launch`` roofline over static python ints
(``lru_cache``-d — no tracing, no retraces).  :func:`paged_attention` is
the execution entry used by the model cache-write path; forcing ``arm=``
bypasses the cost model (how CPU tests pin each arm).

:func:`choose_training_arm` is the *training/prefill* half of the same
table: pure causal self-attention (S == S_kv) as the model forward runs it
under autodiff, where the cost of an arm is forward **plus backward** —
the backward pays ~2× the forward matmul FLOPs, re-materializes whatever
the remat policy dropped, and (for the score-materializing arms) moves the
``S × S`` matrix through HBM several more times.  ``dot_product_attention``'s
``impl="auto"`` resolves through it, which is what retired the
``RELORA_TPU_PALLAS_MIN_SEQ`` sequence-length threshold.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from relora_tpu.ops.attention import (
    flash_block_size,
    packed_paged_attention,
    paged_cached_attention,
    paged_decode_attention,
)

# Shared roofline constants (see lora_dispatch for provenance: only ratios
# matter for ranking, so v5e numbers rank correctly on CPU too).
from relora_tpu.ops.lora_dispatch import (
    HBM_BW_BYTES,
    LAUNCH_OVERHEAD_S,
    PEAK_FLOPS,
)

__all__ = [
    "ARMS",
    "TRAIN_ARMS",
    "estimate_arm_times",
    "estimate_training_arm_times",
    "choose_arm",
    "choose_training_arm",
    "paged_attention",
    "packed_attention",
]

ARMS: Tuple[str, ...] = ("naive", "flash", "paged_decode", "packed")

#: largest query length the fused paged kernel serves: covers plain decode
#: (S=1) and every speculative verify window (K+1 for K <= 15) while the
#: per-row VMEM state (N*S rows of online-softmax scratch) stays small;
#: chunked prefill at the default chunk_size=64 keeps the naive arm
PAGED_DECODE_MAX_S = 16

#: arms a training forward can execute (attention.dot_product_attention
#: impls; "flash" maps to impl="pallas" there)
TRAIN_ARMS: Tuple[str, ...] = ("naive", "xla", "flash")

_F32 = 4  # score/softmax math is f32 in every arm


@functools.lru_cache(maxsize=4096)
def estimate_arm_times(
    B: int,
    S: int,
    S_kv: int,
    heads: int,
    kv_heads: int,
    head_dim: int,
    page_size: int,
    kv_bytes: int = 2,
    act_bytes: int = 4,
) -> Dict[str, float]:
    """Modeled seconds per arm for one attention of the given shape.

    ``kv_bytes`` is the *stored* cache width (2 for bf16 pools, 1 for int8
    codes), ``act_bytes`` the activation width of q/out.  The model is
    deliberately coarse — decode attention is bandwidth-bound, so what
    matters is how many times each arm moves the ``S_kv`` cache tokens and
    the ``S × S_kv`` score matrix through HBM:

    - naive: pool read + gathered-copy write + gathered-copy read (3× the
      cache bytes; the paged gather materializes), scores written and
      re-read twice (logits→softmax→probs) at f32, ~6 dispatched ops.
    - flash: q/k/v/out each moved once, no score matrix, one launch.
    - paged_decode: pool + scales moved once, q/out once, no gathered copy,
      no score matrix, one launch.
    """

    def roofline(nbytes: float, flops: float, launches: int) -> float:
        return max(nbytes / HBM_BW_BYTES, flops / PEAK_FLOPS) + launches * LAUNCH_OVERHEAD_S

    qo_bytes = 2.0 * B * S * heads * head_dim * act_bytes  # q read + out write
    cache_bytes = 2.0 * B * S_kv * kv_heads * head_dim * kv_bytes  # K and V
    scale_bytes = 2.0 * B * (S_kv / max(page_size, 1)) * kv_heads * _F32
    score_bytes = float(B) * heads * S * S_kv * _F32
    flops = 4.0 * B * S * S_kv * heads * head_dim  # QK^T + PV

    gathered_f32 = 2.0 * B * S_kv * kv_heads * head_dim * _F32
    dequant_extra = gathered_f32 if kv_bytes == 1 else 0.0
    naive = roofline(
        qo_bytes
        + cache_bytes  # pool read (gather source)
        + 2.0 * gathered_f32  # gathered copy written then re-read (f32 math)
        + dequant_extra  # int8: separate dequant pass writes f32 copy again
        + 4.0 * score_bytes,  # logits w+r, probs w+r
        flops,
        6,
    )

    flash = roofline(qo_bytes + cache_bytes, flops, 1)

    paged_decode = roofline(qo_bytes + cache_bytes + scale_bytes, flops, 1)

    # packed mixed-batch: per-token page streaming — identical HBM traffic
    # shape to paged_decode at (B=T packed tokens, S=1), one launch for the
    # whole mixed batch instead of one per entry kind (the win the dispatch
    # count in serve metrics measures, not this table)
    packed = roofline(qo_bytes + cache_bytes + scale_bytes, flops, 1)

    return {
        "naive": naive,
        "flash": flash,
        "paged_decode": paged_decode,
        "packed": packed,
    }


@functools.lru_cache(maxsize=4096)
def choose_arm(
    B: int,
    S: int,
    S_kv: int,
    heads: int,
    kv_heads: int,
    head_dim: int,
    page_size: int,
    kv_bytes: int = 2,
    fused_available: bool = True,
    allow: Tuple[str, ...] = ARMS,
) -> str:
    """Pick the cheapest *applicable* arm under the roofline model.

    Applicability is structural, not modeled: ``paged_decode`` serves
    small-S queries only (``S <= PAGED_DECODE_MAX_S`` — single-token decode
    and the speculative verify window; its per-row VMEM softmax state
    scales with heads×S); ``flash`` only for pure causal self-attention
    with 128-aligned lengths (S == S_kv, tileable) — the cache-visibility
    mask of chunked prefill is not expressible in it.
    ``fused_available=False`` (non-TPU backend, or caller opt-out) strikes
    both Pallas arms; ``allow`` restricts the candidate set (tests pin
    arms with it).  Pure python over static ints — trace-safe.
    """
    times = estimate_arm_times(
        B, S, S_kv, heads, kv_heads, head_dim, page_size, kv_bytes
    )
    candidates = [arm for arm in allow if arm in ARMS]
    if S > PAGED_DECODE_MAX_S or not fused_available:
        candidates = [a for a in candidates if a != "paged_decode"]
    # the packed arm reads per-token row/position maps: callers rank it with
    # (B = packed tokens, S = 1); any other query shape cannot address it
    if S != 1 or not fused_available:
        candidates = [a for a in candidates if a != "packed"]
    if S != S_kv or flash_block_size(S, S_kv) is None or not fused_available:
        candidates = [a for a in candidates if a != "flash"]
    if not candidates:
        return "naive"
    return min(candidates, key=lambda arm: times[arm])


@functools.lru_cache(maxsize=4096)
def estimate_training_arm_times(
    B: int,
    S: int,
    heads: int,
    kv_heads: int,
    head_dim: int,
    act_bytes: int = 2,
    with_backward: bool = True,
) -> Dict[str, float]:
    """Modeled seconds per arm for one *training* causal self-attention
    (S == S_kv), forward + backward.

    The decode table (:func:`estimate_arm_times`) ranks bandwidth-bound
    single-token shapes; training shapes are compute-heavy and pay the
    backward too, which shifts the balance:

    - matmul FLOPs: 4·B·S²·h·d forward; the backward's dq/dk/dv matmuls
      are ~2× that, and under the remat policies we train with (``dots`` /
      ``dots_narrow`` recompute batched dots) the probs are recomputed once
      more — modeled as a 3.5× forward multiplier for every arm.  The flash
      kernel's grid skips fully-masked causal blocks, so its effective
      FLOPs are ~half the dense count; XLA/naive compute the full square.
    - HBM: every arm moves q/k/v/out once forward and ~2× more backward
      (reads + grads).  The score-materializing arms additionally stream
      the ``B·h·S²`` matrix — twice forward (probs write + PV read) and
      ~twice backward for ``xla`` at activation width, double that and at
      f32 for ``naive`` (logits→softmax→probs each written and re-read).
      ``flash`` keeps scores in VMEM, forward and backward.
    - launches: naive is ~6 fused ops forward + ~8 backward; the XLA fused
      path ~2 + 4; flash is 1 forward + 2 backward kernels (dq, dkv).
    """

    def roofline(nbytes: float, flops: float, launches: int) -> float:
        return max(nbytes / HBM_BW_BYTES, flops / PEAK_FLOPS) + launches * LAUNCH_OVERHEAD_S

    bwd_flops_mult = 3.5 if with_backward else 1.0
    bwd_io_mult = 3.0 if with_backward else 1.0

    io_bytes = (
        2.0 * B * S * heads * head_dim * act_bytes  # q + out
        + 2.0 * B * S * kv_heads * head_dim * act_bytes  # k + v
    )
    score_bytes = float(B) * heads * S * S  # × itemsize below
    flops_full = 4.0 * B * S * S * heads * head_dim
    flops_causal = flops_full / 2.0

    naive = roofline(
        bwd_io_mult * io_bytes * 2  # f32 math: inputs upcast
        + (8.0 if with_backward else 4.0) * score_bytes * _F32,
        bwd_flops_mult * flops_full,
        14 if with_backward else 6,
    )
    xla = roofline(
        bwd_io_mult * io_bytes + (4.0 if with_backward else 2.0) * score_bytes * act_bytes,
        bwd_flops_mult * flops_full,
        6 if with_backward else 2,
    )
    flash = roofline(
        bwd_io_mult * io_bytes,
        bwd_flops_mult * flops_causal,
        3 if with_backward else 1,
    )
    return {"naive": naive, "xla": xla, "flash": flash}


@functools.lru_cache(maxsize=4096)
def choose_training_arm(
    B: int,
    S: int,
    heads: int,
    kv_heads: int,
    head_dim: int,
    act_bytes: int = 2,
    with_backward: bool = True,
    fused_available: bool = True,
    allow: Tuple[str, ...] = TRAIN_ARMS,
) -> str:
    """Cheapest applicable arm for a training/prefill causal self-attention.

    Applicability mirrors :func:`choose_arm`: ``flash`` needs the Pallas
    kernel (TPU, 128-aligned tileable S — :func:`flash_block_size`);
    ``fused_available=False`` strikes it.  ``xla`` and ``naive`` always
    apply.  Pure python over static trace-time ints, so the per-shape
    choice is free and can never retrace.
    """
    times = estimate_training_arm_times(
        B, S, heads, kv_heads, head_dim, act_bytes, with_backward
    )
    candidates = [arm for arm in allow if arm in TRAIN_ARMS]
    if not fused_available or flash_block_size(S, S) is None:
        candidates = [a for a in candidates if a != "flash"]
    if not candidates:
        return "xla"
    return min(candidates, key=lambda arm: times[arm])


def paged_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    arm: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Attend ``q`` against the page pool via the chosen arm.

    The execution entry point used by the model cache-write path
    (models/llama.attend_with_paged_cache).  ``arm="auto"`` consults
    :func:`choose_arm` with the static trace-time shapes; long chunked
    prefill resolves to the naive arm, while single-token decode and the
    small-S speculative verify window (T <= PAGED_DECODE_MAX_S) take the
    fused kernel on TPU.  Explicit ``arm=`` bypasses the model; the flash
    arm is not servable from a pool and is rejected here.
    """
    if arm not in ("auto", "naive", "paged_decode"):
        raise ValueError(
            f"unknown/unservable arm {arm!r}; expected auto|naive|paged_decode"
        )
    B, T, N, H = q.shape
    _, page_size, n_kv, _ = pool_k.shape
    S_kv = block_tables.shape[1] * page_size
    if arm == "auto":
        fused_ok = jax.default_backend() == "tpu"
        arm = choose_arm(
            B, T, S_kv, N, n_kv, H, page_size,
            jnp.dtype(pool_k.dtype).itemsize,
            fused_available=fused_ok, allow=("naive", "paged_decode"),
        )
    if arm == "paged_decode":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return paged_decode_attention(
            q, pool_k, pool_v, block_tables, positions,
            k_scale=k_scale, v_scale=v_scale, scale=scale, interpret=interpret,
        )
    return paged_cached_attention(
        q, pool_k, pool_v, block_tables, positions,
        k_scale=k_scale, v_scale=v_scale, scale=scale,
    )


def packed_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_tables: jax.Array,
    row_map: jax.Array,
    positions: jax.Array,
    *,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    arm: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Attend a token-major packed mixed batch against the page pool.

    ``q`` is ``(1, T, N, H)`` — T packed tokens from a mix of decode rows,
    speculative verify windows, and prefill chunks — with ``row_map`` ``(T,)``
    selecting each token's row of ``block_tables`` ``(R, W)`` and
    ``positions`` ``(T,)`` its absolute position.  On TPU the fused
    :func:`relora_tpu.ops.attention.packed_paged_attention` kernel serves it
    in one launch; elsewhere (or with ``arm="naive"``) each token attends
    through its own gathered table as a batch row of
    :func:`relora_tpu.ops.attention.paged_cached_attention` — same masked
    einsum math as the sequential decode path, which is what the
    packed-vs-sequential token-parity tests lean on.
    """
    if arm not in ("auto", "naive", "packed"):
        raise ValueError(f"unknown/unservable arm {arm!r}; expected auto|naive|packed")
    B, T, N, H = q.shape
    if B != 1:
        raise ValueError(f"packed attention is token-major: expected B=1, got {B}")
    _, page_size, n_kv, _ = pool_k.shape
    S_kv = block_tables.shape[1] * page_size
    rm = row_map.reshape(T)
    pos = positions.reshape(T)
    if arm == "auto":
        fused_ok = jax.default_backend() == "tpu"
        arm = choose_arm(
            T, 1, S_kv, N, n_kv, H, page_size,
            jnp.dtype(pool_k.dtype).itemsize,
            fused_available=fused_ok, allow=("naive", "packed"),
        )
    if arm == "packed":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return packed_paged_attention(
            q, pool_k, pool_v, block_tables, rm, pos,
            k_scale=k_scale, v_scale=v_scale, scale=scale, interpret=interpret,
        )
    # naive: tokens become batch rows, each with its own table — (T, 1, N, H)
    # queries against (T, W) per-token tables, then back to token-major
    token_tables = jnp.take(block_tables, rm.astype(jnp.int32), axis=0)
    out = paged_cached_attention(
        q.reshape(T, 1, N, H), pool_k, pool_v, token_tables, pos.reshape(T, 1),
        k_scale=k_scale, v_scale=v_scale, scale=scale,
    )
    return out.reshape(1, T, N, H)
