"""Optimizer construction and ReLoRA optimizer-state resets.

Design: the train step partitions params into trainable / frozen subtrees
(relora_tpu.core.relora.trainable_param_mask) and the optimizer only ever
sees the trainable subtree.  That gives the reference's ZeRO-1 HBM win
"for free" and more: frozen base kernels carry **no** Adam state at all
(the reference still allocated state for them unless lora_only —
torchrun_main.py:658-677), and under a mesh the remaining state is sharded
like the params it mirrors.

The reset (`reset_optimizer_state`) reimplements
training_utils.optimizer_reset (:267-364) as a pure function over the optax
state pytree, with the reference's three mutually exclusive modes:

- ``zero``  — reset_optimizer_on_relora.  The reference implements this as
  99.9% *random* pruning purely to dodge a torch ZeroRedundancyOptimizer
  state_dict KeyError (training_utils.py:291-295, comment :307-346).  That
  bug class doesn't exist here, so we implement the intended semantics:
  exact zeroing.
- ``random`` — keep each entry with prob (1 - ratio) (training_utils.py:150-157).
- ``magnitude`` — zero entries with |x| <= quantile(|x|, ratio), quantile in
  f32 per tensor (training_utils.py:160-170).

Only LoRA-factor leaves are pruned (parity: reset_params=lora_params,
torchrun_main.py:905-912); embeddings/norms/lm_head keep their moments.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from relora_tpu.core.relora import is_lora_path
from relora_tpu.core.schedules import Schedule

PyTree = Any


def build_optimizer(
    *,
    schedule: Schedule,
    beta1: float = 0.9,
    beta2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW over the trainable subtree (parity: torchrun_main.py:658-667).

    Weight decay applies to every trainable param, like torch AdamW with a
    single param group.  Gradient clipping is done in the train step (over
    trainable grads, before the NaN gate) to mirror
    clip_grad_norm_(trainable_params) at torchrun_main.py:805-808.
    """
    return optax.chain(
        optax.scale_by_adam(b1=beta1, b2=beta2, eps=eps),
        optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
        optax.scale_by_learning_rate(schedule),  # negates: updates = -lr * step
    )


def init_opt_state_sharded(
    tx: optax.GradientTransformation,
    trainable: PyTree,
    mesh: jax.sharding.Mesh,
    shardings: Optional[PyTree] = None,
) -> PyTree:
    """``tx.init`` with the Adam moments pinned to the trainables' shardings.

    A bare ``jax.jit(tx.init)`` leaves output shardings to XLA, which
    materializes every moment replicated until the first train step
    re-shards them — a transient up-to-mesh-size× HBM spike (observed 4× on
    adam moments in tools/dryrun_at_shape.py at 7B fsdp=8,tensor=4) that
    OOMs exactly the pod-scale configs the sharding exists to fit.  Each
    param-shaped state leaf inherits the matching param's sharding; scalar
    counters (adam count, schedule count) are replicated.

    ``shardings`` (a NamedSharding tree matching ``trainable``) is the
    placement plan for leaves not already on ``mesh``: warm starts graft
    uncommitted default-device leaves into an otherwise mesh-sharded tree,
    and those must land on their planned shardings rather than force the
    whole init through XLA-chosen (replicated) placement.  Without a plan,
    a tree with any off-mesh leaf falls back to plain ``tx.init`` and the
    caller's placement normalization.
    """
    mesh_devices = mesh.devices.tolist()

    def on_mesh(p) -> bool:
        s = getattr(p, "sharding", None)
        return isinstance(s, jax.sharding.NamedSharding) and s.mesh.devices.tolist() == mesh_devices

    leaves = jax.tree_util.tree_leaves(trainable)
    if not leaves or (shardings is None and not all(on_mesh(p) for p in leaves)):
        return jax.jit(tx.init)(trainable)

    if shardings is not None:
        trainable = jax.tree_util.tree_map(
            lambda p, s: p if on_mesh(p) else jax.device_put(p, s),
            trainable,
            shardings,
        )

    replicated = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    out_shardings = optax.tree_map_params(
        tx,
        lambda _, s: s,
        jax.eval_shape(tx.init, trainable),
        jax.tree_util.tree_map(lambda p: p.sharding, trainable),
        transform_non_params=lambda _: replicated,
    )
    return jax.jit(tx.init, out_shardings=out_shardings)(trainable)


def lora_label_tree(params: PyTree) -> PyTree:
    """'lora' / 'other' labels over a (trainable) param tree."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: "lora" if is_lora_path(p) else "other", params
    )


# ---------------------------------------------------------------------------
# Reset / pruning
# ---------------------------------------------------------------------------


def _prune_random(key: jax.Array, t: jax.Array, ratio: float) -> jax.Array:
    keep = jax.random.uniform(key, t.shape) > ratio
    return t * keep.astype(t.dtype)


def _prune_magnitude(t: jax.Array, ratio: float) -> jax.Array:
    mag = jnp.abs(t).astype(jnp.float32)
    threshold = jnp.quantile(mag.reshape(-1), ratio)
    return t * (mag > threshold).astype(t.dtype)


def reset_optimizer_state(
    opt_state: PyTree,
    *,
    mode: str,
    ratio: float,
    rng: Optional[jax.Array] = None,
    lora_mask: Optional[PyTree] = None,
) -> PyTree:
    """Prune/zero Adam first+second moments of LoRA leaves, in a pure update.

    ``opt_state`` is any optax state pytree; every ``ScaleByAdamState`` found
    inside has its ``mu``/``nu`` leaves pruned where ``lora_mask`` is True
    (``None`` masks by the ``lora_`` path-name convention).  The Adam step
    count is left untouched, matching the reference (it never resets
    optimizer.state[p]["step"]).

    Jit this with ``donate_argnums=0``; the pytree structure is preserved.
    """
    if mode not in ("zero", "random", "magnitude"):
        raise ValueError(f"Unknown optimizer reset mode {mode!r}")
    if mode == "random" and rng is None:
        raise ValueError("random pruning needs an rng key")

    def prune_moment_tree(tree: PyTree, salt: int) -> PyTree:
        def per_leaf(path, leaf):
            if lora_mask is not None:
                select = _mask_lookup(lora_mask, path)
            else:
                select = is_lora_path(path)
            if not select or not hasattr(leaf, "dtype"):
                return leaf
            if mode == "zero":
                return jnp.zeros_like(leaf)
            if mode == "random":
                leaf_key = jax.random.fold_in(
                    jax.random.fold_in(rng, salt), _path_hash(path)
                )
                return _prune_random(leaf_key, leaf, ratio)
            return _prune_magnitude(leaf, ratio)

        return jax.tree_util.tree_map_with_path(per_leaf, tree)

    def walk(state):
        if isinstance(state, optax.ScaleByAdamState):
            return state._replace(
                mu=prune_moment_tree(state.mu, 0),
                nu=prune_moment_tree(state.nu, 1),
            )
        if isinstance(state, tuple):
            if hasattr(state, "_fields"):
                # Recurse into wrapper states (MultiSteps, multi_transform,
                # inject_hyperparams, ...) so nested Adam states are found.
                return type(state)(*(walk(s) for s in state))
            return tuple(walk(s) for s in state)
        if isinstance(state, dict):
            return {k: walk(v) for k, v in state.items()}
        return state

    return walk(opt_state)


def _path_hash(path: Tuple) -> int:
    """Deterministic across processes and runs (str hash is salted per
    process, which would desync pruning masks across hosts)."""
    import zlib

    return zlib.crc32("/".join(str(p) for p in path).encode())


def _mask_lookup(mask: PyTree, path: Tuple) -> bool:
    node = mask
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if isinstance(node, dict) and key in node:
            node = node[key]
        else:
            return False
    return bool(node)


def set_schedule_count(opt_state: PyTree, count: int) -> PyTree:
    """Set the step counter of every ScaleByScheduleState (used when resuming
    without restoring optimizer state, so the LR schedule continues from the
    checkpoint's position — parity: the scheduler replay at
    torchrun_main.py:693-699)."""
    import jax.numpy as jnp

    def walk(state):
        if isinstance(state, optax.ScaleByScheduleState):
            return state._replace(count=jnp.asarray(count, jnp.int32))
        if isinstance(state, tuple):
            if hasattr(state, "_fields"):
                return type(state)(*(walk(s) for s in state))
            return tuple(walk(s) for s in state)
        return state

    return walk(opt_state)


def _zeroed_fraction_impl(opt_state: PyTree) -> jax.Array:
    zeros = jnp.asarray(0.0)
    total = jnp.asarray(0.0)

    def walk(state):
        nonlocal zeros, total
        if isinstance(state, optax.ScaleByAdamState):
            for tree in (state.mu, state.nu):
                for leaf in jax.tree_util.tree_leaves(tree):
                    zeros = zeros + jnp.sum(leaf == 0).astype(jnp.float32)
                    total = total + leaf.size
        elif isinstance(state, tuple):  # incl. wrapper NamedTuple states
            for s in state:
                walk(s)
        elif isinstance(state, dict):
            for s in state.values():
                walk(s)

    walk(opt_state)
    return zeros / (1e-7 + total)


_zeroed_fraction_jit = jax.jit(_zeroed_fraction_impl)


def zeroed_fraction(opt_state: PyTree) -> jax.Array:
    """Fraction of zeros across all Adam moments (parity logging:
    training_utils.py:363-364).

    Jitted into ONE program on purpose: eagerly summing each moment leaf of
    a multi-process-sharded opt_state dispatches dozens of tiny collective
    programs, and interleaving those with the train step's collectives
    deadlocked a real 2-process fsdp run (each process wedged in a
    different program at the first merge+reset boundary).  A single
    compiled reduction is one collective both processes dispatch at the
    same point in the step sequence.
    """
    return _zeroed_fraction_jit(opt_state)


def global_norm(tree: PyTree) -> jax.Array:
    """L2 norm across a grad pytree (f32 accumulation)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def clip_by_global_norm(tree: PyTree, max_norm: float) -> Tuple[PyTree, jax.Array]:
    """Clip grads to max_norm, returning (clipped, pre-clip norm).

    Parity: torch.nn.utils.clip_grad_norm_(trainable_params, clip_grad_norm)
    at torchrun_main.py:805-808.
    """
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda l: (l.astype(jnp.float32) * scale).astype(l.dtype), tree), norm
