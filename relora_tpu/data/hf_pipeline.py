"""HF datasets pipeline: tokenize-and-chunk plus resumable batch iterators.

Capability parity with peft_pretraining/dataloader.py:

- ``tokenize_and_chunk``       — tokenize + append EOS, concatenate and cut
  into fixed ``seq_length`` blocks, drop the remainder, drop attention masks
  (:57-124).  Used offline by pretokenize.py and validated at train time via
  the args.json provenance file.
- ``TokenBatchIterator``       — batches a pretokenized dataset into
  ``(grad_accum, microbatch, seq)`` device-ready numpy arrays with
  deterministic skip for resume (SkipDataLoader semantics, :128-170) and
  per-host sharding (each JAX process reads only its slice — replacing
  datasets.distributed.split_dataset_by_node, torchrun_main.py:722-723).
- ``StreamingTokenIterator``   — on-the-fly tokenize+pack for iterable/raw
  datasets (PreprocessedIterableDataset semantics, :13-54).
"""

from __future__ import annotations

import itertools
from typing import Iterator, Optional

import numpy as np

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def tokenize_and_chunk(
    dataset,
    tokenizer,
    text_field: str = "text",
    sequence_length: int = 512,
    num_proc: int = 8,
):
    """Pretokenize a text dataset into fixed-length input_ids blocks.

    Every document gets an EOS appended, documents are concatenated, the
    stream is cut into ``sequence_length`` chunks and the tail remainder is
    dropped (parity: dataloader.py:57-124 — including the "extra [EOS]"
    between documents behavior).
    """
    eos = tokenizer.eos_token_id
    if eos is None:
        raise ValueError("tokenizer must define an EOS token")

    def tokenize(examples):
        out = tokenizer(examples[text_field], add_special_tokens=False)
        return {"input_ids": [ids + [eos] for ids in out["input_ids"]]}

    tokenized = dataset.map(
        tokenize,
        batched=True,
        num_proc=num_proc,
        remove_columns=list(dataset.column_names),
        desc="tokenizing",
    )

    def group(examples):
        concat = list(itertools.chain.from_iterable(examples["input_ids"]))
        total = (len(concat) // sequence_length) * sequence_length
        return {
            "input_ids": [
                concat[i : i + sequence_length] for i in range(0, total, sequence_length)
            ]
        }

    return tokenized.map(
        group, batched=True, num_proc=num_proc, desc="chunking"
    )


class TokenBatchIterator:
    """Device-ready batches from a pretokenized dataset.

    Yields int32 arrays of shape ``(grad_accum, microbatch, seq)`` (train) or
    ``(microbatch, seq)`` (eval, grad_accum=None).  ``skip_updates`` fast-
    forwards whole update steps for resume — index arithmetic, not data reads
    (cheaper than the reference's batch-consuming SkipDataLoader,
    dataloader.py:150-170).  ``process_index/process_count`` shard batches
    across hosts contiguously at the batch level, mirroring
    DistributedBatchSampler rank slicing (megatron_dataset/samplers.py:159-165).
    """

    def __init__(
        self,
        dataset,
        *,
        microbatch: int,
        grad_accum: Optional[int] = None,
        skip_updates: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        drop_last: bool = True,
    ):
        self.dataset = dataset
        self.microbatch = microbatch
        self.grad_accum = grad_accum
        self.process_index = process_index
        self.process_count = process_count
        seqs_per_update = microbatch * (grad_accum or 1) * process_count
        self._seqs_per_update = seqs_per_update
        self._start = skip_updates * seqs_per_update
        n = len(dataset)
        self._n_updates_total = n // seqs_per_update if drop_last else -(-n // seqs_per_update)

    def __len__(self) -> int:
        return max(0, self._n_updates_total - self._start // self._seqs_per_update)

    def __iter__(self) -> Iterator[np.ndarray]:
        mb, ga, pc, pi = (
            self.microbatch,
            self.grad_accum,
            self.process_count,
            self.process_index,
        )
        per_host = mb * (ga or 1)
        for start in range(self._start, self._n_updates_total * self._seqs_per_update, self._seqs_per_update):
            # contiguous per-host slice within the global update batch
            lo = start + pi * per_host
            rows = self.dataset[lo : lo + per_host]["input_ids"]
            arr = np.asarray(rows, dtype=np.int32)
            if ga is None:
                yield arr
            else:
                yield arr.reshape(ga, mb, -1)


class StreamingTokenIterator:
    """On-the-fly tokenize + pack for raw/iterable text datasets
    (parity: PreprocessedIterableDataset, dataloader.py:13-54).

    Documents are tokenized with EOS appended and packed into a rolling token
    buffer; full ``(grad_accum, microbatch, seq)`` batches are emitted as the
    buffer fills.  Worker sharding is by document index (islice semantics).
    """

    def __init__(
        self,
        dataset,
        tokenizer,
        *,
        text_field: str = "text",
        sequence_length: int,
        microbatch: int,
        grad_accum: int = 1,
        process_index: int = 0,
        process_count: int = 1,
    ):
        self.dataset = dataset
        self.tokenizer = tokenizer
        self.text_field = text_field
        self.sequence_length = sequence_length
        self.microbatch = microbatch
        self.grad_accum = grad_accum
        self.process_index = process_index
        self.process_count = process_count

    def __iter__(self) -> Iterator[np.ndarray]:
        eos = self.tokenizer.eos_token_id
        need = self.sequence_length * self.microbatch * self.grad_accum
        buffer: list[int] = []
        docs = itertools.islice(
            iter(self.dataset), self.process_index, None, self.process_count
        )
        for doc in docs:
            ids = self.tokenizer(doc[self.text_field], add_special_tokens=False)["input_ids"]
            buffer.extend(ids)
            buffer.append(eos)
            while len(buffer) >= need:
                chunk = np.asarray(buffer[:need], dtype=np.int32)
                buffer = buffer[need:]
                yield chunk.reshape(self.grad_accum, self.microbatch, self.sequence_length)
