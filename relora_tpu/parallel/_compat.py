"""jax version compatibility for the parallelism layer.

The shard_map API moved twice across the jax versions this repo runs on:
``jax.experimental.shard_map.shard_map`` (<= 0.4.x) became ``jax.shard_map``
(>= 0.6), and the replication-check kwarg was renamed ``check_rep`` ->
``check_vma``.  Call sites use the modern spelling; this wrapper translates
for older installs.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore[no-redef]

_PARAMS = inspect.signature(_shard_map).parameters


def shard_map(f, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis, inside a shard_map body.

    ``jax.lax.axis_size`` (>= 0.6) vs ``jax.core.axis_frame`` (0.4.x, where
    it returns the size directly).  Both are trace-time Python ints, usable
    for loop bounds and ppermute permutations.
    """
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax import core

    return core.axis_frame(axis_name)
