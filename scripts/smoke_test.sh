#!/usr/bin/env bash
# Smoke-test battery (systematizes the reference's README.dev.md command
# list): tiny configs covering the common training regimes, runnable on CPU
# in a few minutes.  Exercises the real CLIs end-to-end.
#
#   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
#       bash scripts/smoke_test.sh [workdir]
set -euo pipefail
cd "$(dirname "$0")/.."
WORK="${1:-$(mktemp -d /tmp/relora_smoke.XXXX)}"
echo "workdir: $WORK"

echo "=== 0. static analysis (relora-lint) ==="
# cheapest gate first: stdlib-only AST lint, fails on new RTL findings
bash scripts/lint.sh

echo "=== 0b. fused LoRA kernel parity (interpret mode) ==="
# the fused pallas composite vs the unfused reference, forward and grads,
# on the CPU interpreter — catches kernel regressions before any training
python - <<'EOF'
import jax, jax.numpy as jnp
from relora_tpu.ops.lora_dispatch import lora_matmul
from relora_tpu.ops.quant import quantize_int8

k = jax.random.PRNGKey(0)
M, K, N, r = 32, 256, 128, 8
x = jax.random.normal(jax.random.fold_in(k, 1), (M, K), jnp.float32)
w = jax.random.normal(jax.random.fold_in(k, 2), (K, N), jnp.float32)
a = jax.random.normal(jax.random.fold_in(k, 3), (K, r), jnp.float32) * 0.1
b = jax.random.normal(jax.random.fold_in(k, 4), (r, N), jnp.float32) * 0.1
ref = lambda x, a, b: x @ w + (x @ a) @ b * 0.25
for base, tag in ((w, "dense"), (quantize_int8(w), "int8")):
    wd = base if tag == "dense" else base[0].astype(jnp.float32) * base[1]
    refd = lambda x, a, b, wd=wd: x @ wd + (x @ a) @ b * 0.25
    y = lora_matmul(x, base, a, b, 0.25, arm="fused")
    assert float(jnp.abs(y - refd(x, a, b)).max()) < 1e-4, f"{tag} fwd parity"
    gf = jax.grad(lambda *o: jnp.sum(jnp.sin(lora_matmul(*o[:1], base, *o[1:], 0.25, arm="fused"))), argnums=(0, 1, 2))(x, a, b)
    gr = jax.grad(lambda *o: jnp.sum(jnp.sin(refd(*o))), argnums=(0, 1, 2))(x, a, b)
    for f_, r_ in zip(gf, gr):
        assert float(jnp.abs(f_ - r_).max()) < 1e-4, f"{tag} grad parity"
    print(f"fused kernel parity OK ({tag} base)")
EOF

python - "$WORK" <<'EOF'
import sys, numpy as np
from relora_tpu.data.memmap import MemmapTokenWriter, best_dtype
rs = np.random.RandomState(0)
with MemmapTokenWriter(f"{sys.argv[1]}/corpus", dtype=best_dtype(128)) as w:
    for _ in range(3000):
        start = rs.randint(128); n = rs.randint(10, 80)
        w.add_document([(start + j) % 128 for j in range(n)])
print("corpus written")
EOF

cat > "$WORK/mega.yaml" <<EOF
data_path: $WORK/corpus
split: "8,1,1"
seq_length: 32
seed: 0
data_impl: mmap
EOF

common=(--megatron_dataset_config "$WORK/mega.yaml" --model_config llama_9m
        --batch_size 4 --total_batch_size 8 --max_length 32 --dp_size 2
        --warmup_steps 2 --eval_every 1000 --seed 0)

echo "=== 1. full-rank ==="
python main.py "${common[@]}" --lr 3e-3 --scheduler cosine --cycle_length 8 \
    --num_training_steps 8 --save_every 8 --save_dir "$WORK/full"

echo "=== 2. ReLoRA from warm start ==="
python main.py "${common[@]}" --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --warmed_up_model "$WORK/full/model_8" \
    --num_training_steps 32 --save_every 8 --save_dir "$WORK/relora"

echo "=== 3. ReLoRA + magnitude pruning + int8 base ==="
python main.py "${common[@]}" --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --reset_optimizer_on_relora false --optimizer_magnitude_pruning 0.8 \
    --quantize int8 --warmed_up_model "$WORK/full/model_8" \
    --num_training_steps 24 --save_every 100 --save_dir "$WORK/relora_q"

echo "=== 3b. ReLoRA + nf4 double-quant base ==="
python main.py "${common[@]}" --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --quantize nf4 --use_double_quant true --warmed_up_model "$WORK/full/model_8" \
    --num_training_steps 24 --save_every 100 --save_dir "$WORK/relora_nf4"

echo "=== 4. autoresume continues run 2 ==="
python main.py "${common[@]}" --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --num_training_steps 40 --save_every 8 --save_dir "$WORK/relora" \
    --autoresume true

echo "=== 5. pythia + ReLoRA under fsdp (reference README.dev.md:4-34 regime) ==="
python main.py --megatron_dataset_config "$WORK/mega.yaml" --model_config pythia_14m \
    --batch_size 1 --total_batch_size 8 --max_length 32 --fsdp_size 2 \
    --warmup_steps 2 --eval_every 1000 --seed 0 \
    --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --num_training_steps 16 --save_every 100 --save_dir "$WORK/pythia_relora"

echo "=== 6. fp32 full-rank (reference README.dev.md:65-77 regime) ==="
python main.py "${common[@]}" --lr 3e-3 --scheduler cosine --cycle_length 8 \
    --dtype float32 --num_training_steps 8 --save_every 100 \
    --save_dir "$WORK/full_fp32"

echo "=== 6b. tp x fsdp composition parity (8 virtual devices, pytest -m parallel) ==="
# the tentpole oracle: a tp=2 x fsdp=4 train step (and merge-and-reinit)
# must match the single-device loss trace, and the kv-head-sharded page
# pool must stay token-identical to the meshless paged engine
python -m pytest tests/test_parallel_composition.py -q -m parallel -p no:cacheprovider

echo "=== 7. analysis tools ==="
python tools/analyze_rank.py --before "$WORK/relora/model_16" --after "$WORK/relora/model_40" | head -4
python tools/inspect_optimizer.py "$WORK/relora/model_40" | head -3

echo "=== 8. generate from the ReLoRA checkpoint (serve path) ==="
# one-shot greedy over token-id prompts: loads model_40, merges the LoRA
# factors, and decodes with the KV-cache engine
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --prompt "1 2 3 4" --prompt "5 6 7" --max-new-tokens 8 --cache-size 64 \
    --eos-id -1
# request-loop mode through the continuous-batching scheduler
printf '1 2 3\n4 5 6 7\n8 9\n' > "$WORK/serve_requests.txt"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --input-file "$WORK/serve_requests.txt" --max-new-tokens 6 --cache-size 64 \
    --max-batch 2 --eos-id -1 --run-dir "$WORK/serve_run"
grep -q serve_request "$WORK/serve_run/metrics.jsonl"

echo "=== 9. HTTP serving front-end (boot, healthz, stream, SIGTERM drain) ==="
rm -f "$WORK/serve_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/serve_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 6 --eos-id -1 &
SERVER_PID=$!
for _ in $(seq 300); do [ -s "$WORK/serve_port" ] && break; sleep 0.2; done
[ -s "$WORK/serve_port" ] || { echo "server never wrote its port"; kill "$SERVER_PID"; exit 1; }
python - "$(cat "$WORK/serve_port")" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
req = urllib.request.Request(
    f"http://127.0.0.1:{port}/v1/generate",
    data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6}).encode(),
)
with urllib.request.urlopen(req, timeout=120) as resp:
    events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
assert events[-1].strip() == b"[DONE]", events
final = json.loads(events[-2])
assert final["finish_reason"] == "length" and len(final["tokens"]) == 6, final
metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
assert "relora_serve_tokens_generated_total 6" in metrics, metrics
print("HTTP stream OK:", final["tokens"])
EOF
kill -TERM "$SERVER_PID"
wait "$SERVER_PID"   # exit 0 = SIGTERM drain completed cleanly

echo "=== 9b. paged KV server (chunked prefill, long+short prompt mix) ==="
rm -f "$WORK/paged_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/paged_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 6 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --run-dir "$WORK/paged_run" &
PAGED_PID=$!
for _ in $(seq 300); do [ -s "$WORK/paged_port" ] && break; sleep 0.2; done
[ -s "$WORK/paged_port" ] || { echo "paged server never wrote its port"; kill "$PAGED_PID"; exit 1; }
python - "$(cat "$WORK/paged_port")" "$WORK/paged_tokens.json" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
assert "paging" in health, health
assert health["paging"]["kv_pages_used"] == 0, health["paging"]

def generate(prompt):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 6}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
    final = json.loads(events[-2])
    assert final["finish_reason"] == "length" and len(final["tokens"]) == 6, final
    return final["tokens"]

# long prompt (spans several chunks + pages) and short prompts interleaved
long_prompt = [(i % 100) + 1 for i in range(40)]
first = generate(long_prompt)
generate([1, 2, 3])
# identical long prompt again: served through the prefix cache, same tokens
assert generate(long_prompt) == first, "prefix-cache replay diverged"
health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
paging = health["paging"]
assert paging["kv_pages_used"] > 0, paging  # prefix entries hold pages
assert paging["prefix_cache"]["hits"] >= 1, paging
json.dump(first, open(sys.argv[2], "w"))  # 9c compares the int8 pool to these
print("paged HTTP OK:", first, "| paging:", paging)
EOF
kill -TERM "$PAGED_PID"
wait "$PAGED_PID"
grep -q "serve/kv_pages_used" "$WORK/paged_run/metrics.jsonl"
grep -q "serve/prefix_cache_hit_rate" "$WORK/paged_run/metrics.jsonl"

echo "=== 9c. int8 paged KV server (quantized pool, greedy token parity vs 9b) ==="
rm -f "$WORK/int8_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/int8_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 6 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --kv-dtype int8 \
    --run-dir "$WORK/int8_run" &
INT8_PID=$!
for _ in $(seq 300); do [ -s "$WORK/int8_port" ] && break; sleep 0.2; done
[ -s "$WORK/int8_port" ] || { echo "int8 server never wrote its port"; kill "$INT8_PID"; exit 1; }
python - "$(cat "$WORK/int8_port")" "$WORK/paged_tokens.json" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
paging = health["paging"]
assert paging["kv_dtype"] == "int8", paging
# int8 codes + per-page scales undercut half the unquantized pool bytes
assert paging["kv_bytes_per_token"] > 0, paging

def generate(prompt):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 6}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
    final = json.loads(events[-2])
    assert final["finish_reason"] == "length" and len(final["tokens"]) == 6, final
    return final["tokens"]

# the 9b prompts again: greedy decode from the quantized pool must produce
# the exact tokens the unquantized pool produced
want = json.load(open(sys.argv[2]))
long_prompt = [(i % 100) + 1 for i in range(40)]
got = generate(long_prompt)
assert got == want, f"int8 diverged from bf16 pool: {got} != {want}"
assert generate(long_prompt) == want, "int8 prefix-cache replay diverged"
print("int8 paged HTTP OK:", got, "| kv_bytes_per_token:", paging["kv_bytes_per_token"])
EOF
kill -TERM "$INT8_PID"
wait "$INT8_PID"
grep -q "serve/kv_cache_bytes" "$WORK/int8_run/metrics.jsonl"
grep -q "serve/kv_bytes_per_token" "$WORK/int8_run/metrics.jsonl"

echo "=== 9d. speculative paged server (--spec ngram, greedy token parity vs 9b) ==="
rm -f "$WORK/spec_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/spec_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 6 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --spec ngram --spec-k 4 \
    --run-dir "$WORK/spec_run" &
SPEC_PID=$!
for _ in $(seq 300); do [ -s "$WORK/spec_port" ] && break; sleep 0.2; done
[ -s "$WORK/spec_port" ] || { echo "spec server never wrote its port"; kill "$SPEC_PID"; exit 1; }
python - "$(cat "$WORK/spec_port")" "$WORK/paged_tokens.json" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
spec = health["paging"]["spec"]
assert spec["mode"] == "ngram" and spec["k"] == 4, spec

def generate(prompt):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 6}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
    final = json.loads(events[-2])
    assert final["finish_reason"] == "length" and len(final["tokens"]) == 6, final
    return final["tokens"]

# the 9b prompt again: greedy speculative decode must produce exactly the
# tokens the non-speculative paged server produced (the parity contract)
want = json.load(open(sys.argv[2]))
long_prompt = [(i % 100) + 1 for i in range(40)]
got = generate(long_prompt)
assert got == want, f"speculative decode diverged: {got} != {want}"
# a self-repeating prompt gives the prompt-lookup drafter material to match
generate([3, 5, 7] * 10)
metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
assert "relora_serve_spec_drafted_total" in metrics, metrics
assert "relora_serve_spec_accept_rate" in metrics, metrics
health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
print("spec paged HTTP OK:", got, "| spec:", health["paging"]["spec"])
EOF
kill -TERM "$SPEC_PID"
wait "$SPEC_PID"
grep -q "serve/spec_drafted_total" "$WORK/spec_run/metrics.jsonl"
grep -q "serve/spec_accept_rate" "$WORK/spec_run/metrics.jsonl"

echo "=== 9e. multi-tenant adapter serving (pytest -m adapters, then the CLI drill) ==="
# the compile-heavy multi-tenant integration tests (per-tenant token parity
# on both model families, scheduler contention, churn-no-retrace, HTTP end
# to end) are slow-marked out of tier-1 and run here, like stage 6b
python -m pytest tests/test_adapters.py -q -m "adapters and slow" -p no:cacheprovider
# tenant A: a short hot-lr continuation of run 2, saved MID-cycle (step 44;
# resets land on 40/48) so its factors are nonzero and actually steer greedy
# decode — checkpoints at reset boundaries (model_8..model_40) have freshly
# reinitialized factors whose contribution is exactly zero.  tenant B is one
# of those boundary checkpoints: a valid, loadable identity-contribution
# adapter that must reproduce the base stream.
python main.py --megatron_dataset_config "$WORK/mega.yaml" --model_config llama_9m \
    --batch_size 4 --total_batch_size 8 --max_length 32 --dp_size 2 \
    --warmup_steps 2 --eval_every 1000 --seed 1 \
    --lr 0.1 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --warmed_up_model "$WORK/relora/model_40" \
    --num_training_steps 48 --save_every 4 --save_dir "$WORK/tenant_a"
mkdir -p "$WORK/adapters"
ln -sfn "$WORK/tenant_a/model_44" "$WORK/adapters/tA"
ln -sfn "$WORK/relora/model_16" "$WORK/adapters/tB"
rm -f "$WORK/adapter_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/adapter_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 8 --eos-id -1 \
    --no-merge --adapter-dir "$WORK/adapters" --adapters tA,tB --adapter-slots 3 \
    --run-dir "$WORK/adapter_run" &
ADPT_PID=$!
for _ in $(seq 300); do [ -s "$WORK/adapter_port" ] && break; sleep 0.2; done
[ -s "$WORK/adapter_port" ] || { echo "adapter server never wrote its port"; kill "$ADPT_PID"; exit 1; }
python - "$(cat "$WORK/adapter_port")" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
adapters = health["adapters"]
assert adapters["num_slots"] == 3, adapters
assert set(adapters["resident"]) == {"tA", "tB"}, adapters

def generate(adapter=None):
    body = {"prompt": [(i % 50) + 1 for i in range(12)], "max_new_tokens": 8}
    if adapter is not None:
        body["adapter"] = adapter
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate", data=json.dumps(body).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
    final = json.loads(events[-2])
    assert final["finish_reason"] == "length" and len(final["tokens"]) == 8, final
    return final["tokens"]

base, ta, tb = generate(), generate("tA"), generate("tB")
# tenant A's hot-lr factors must steer greedy decode away from the base;
# tenant B's boundary-checkpoint factors contribute zero and must not
assert ta != base, f"tenant stream identical to base: {ta}"
assert tb == base, f"identity-factor tenant diverged from base: {tb}"
# greedy + resident slot: the same tenant must decode deterministically
assert generate("tA") == ta, "tenant decode not deterministic"
metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
for want in (
    'relora_serve_adapter_requests_total{adapter="base"} 1',
    'relora_serve_adapter_requests_total{adapter="tA"} 2',
    'relora_serve_adapter_requests_total{adapter="tB"} 1',
    "relora_serve_adapter_slots_used 3",
    "relora_serve_adapter_evictions_total 0",
    "relora_serve_adapter_load_seconds_count 0",  # preloads; zero runtime loads
):
    assert want in metrics, f"missing from /metrics: {want}"
print("multi-tenant HTTP OK: base", base, "| tA", ta, "| tB", tb)
EOF
kill -TERM "$ADPT_PID"
wait "$ADPT_PID"
grep -q "serve/adapter_slots_used" "$WORK/adapter_run/metrics.jsonl"
grep -q "serve/adapter_hit_rate" "$WORK/adapter_run/metrics.jsonl"

echo "=== 9f. packed paged server (--packed, one dispatch per round, token parity vs 9b) ==="
rm -f "$WORK/packed_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/packed_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 6 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --packed \
    --run-dir "$WORK/packed_run" &
PACKED_PID=$!
for _ in $(seq 300); do [ -s "$WORK/packed_port" ] && break; sleep 0.2; done
[ -s "$WORK/packed_port" ] || { echo "packed server never wrote its port"; kill "$PACKED_PID"; exit 1; }
python - "$(cat "$WORK/packed_port")" "$WORK/paged_tokens.json" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
dispatch = health["paging"]["dispatch"]
assert dispatch["mode"] == "packed", dispatch
assert dispatch["token_budget"] > 0 and dispatch["buckets"], dispatch

def generate(prompt):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 6}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
    final = json.loads(events[-2])
    assert final["finish_reason"] == "length" and len(final["tokens"]) == 6, final
    return final["tokens"]

# the 9b prompt set again: the packed single-dispatch round must produce
# exactly the tokens the sequential paged server produced
want = json.load(open(sys.argv[2]))
long_prompt = [(i % 100) + 1 for i in range(40)]
got = generate(long_prompt)
assert got == want, f"packed step diverged from sequential: {got} != {want}"
generate([1, 2, 3])
assert generate(long_prompt) == want, "packed prefix-cache replay diverged"
health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
dispatch = health["paging"]["dispatch"]
# the tentpole invariant: every round that dispatched, dispatched once
assert dispatch["rounds"] > 0, dispatch
assert dispatch["dispatches_per_round"] == 1.0, dispatch
assert 0.0 < dispatch["packed_token_utilization"] <= 1.0, dispatch
metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
assert "relora_serve_dispatches_per_round" in metrics, metrics
assert "relora_serve_tokens_per_dispatch" in metrics, metrics
assert "relora_serve_packed_token_utilization" in metrics, metrics
assert "relora_serve_model_dispatches_total" in metrics, metrics
print("packed paged HTTP OK:", got, "| dispatch:", dispatch)
EOF
kill -TERM "$PACKED_PID"
wait "$PACKED_PID"
grep -q "serve/dispatches_per_round" "$WORK/packed_run/metrics.jsonl"
grep -q "serve/tokens_per_dispatch" "$WORK/packed_run/metrics.jsonl"
grep -q "serve/packed_token_utilization" "$WORK/packed_run/metrics.jsonl"

echo "=== 10. traced run + SIGTERM flight dump (obs subsystem) ==="
# fault injection fires a real SIGTERM at update 4; the PreemptionGuard
# handler dumps the span flight recorder before the emergency checkpoint
RELORA_TPU_TRACE_DIR="$WORK/traces" RELORA_TPU_FAULTS="preempt:at=4" \
python main.py "${common[@]}" --lr 3e-3 --scheduler cosine --cycle_length 8 \
    --num_training_steps 16 --save_every 100 --save_dir "$WORK/traced"
ls "$WORK"/traced/flight_sigterm_*.json >/dev/null
# the report must parse the dump and see the trainer's span structure
python tools/trace_report.py "$WORK"/traced/flight_sigterm_*.json | tee "$WORK/trace_report.txt" | head -12
grep -q "update_step" "$WORK/trace_report.txt"
grep -q "dispatch" "$WORK/trace_report.txt"
# the JSONL sink recorded the same spans and renders too
python tools/trace_report.py "$WORK/traces/train_spans.jsonl" --max-traces 1 | grep -q "update_step"

echo "=== 11. perf attribution report + bench regression gate ==="
# a short clean traced run (no fault injection): the report must render the
# MFU-gap waterfall and HBM plan, and the steady state must be retrace-free
RELORA_TPU_TRACE_DIR="$WORK/traces11" RELORA_TPU_MEM_PLAN=1 \
python main.py "${common[@]}" --lr 3e-3 --scheduler cosine --cycle_length 8 \
    --num_training_steps 8 --log_every 4 --save_every 100 --save_dir "$WORK/perf"
python tools/perf_report.py "$WORK/perf" --traces "$WORK/traces11/train_spans.jsonl" \
    --assert-no-retraces | tee "$WORK/perf_report.txt"
grep -q "MFU-gap waterfall" "$WORK/perf_report.txt"
grep -q "per-pytree" "$WORK/perf_report.txt"
grep -q "steady-state retraces: 0" "$WORK/perf_report.txt"
# the gate passes on the committed BENCH trajectory; warn-only off-TPU
# because CPU numbers swing with machine load
python tools/bench_gate.py --check --warn-only

echo "=== 12. multi-replica fleet: supervisor + router, SIGKILL failover, rolling drain ==="
FLEET="$WORK/fleet"
rm -rf "$FLEET"; mkdir -p "$FLEET"
rm -f "$WORK/router_port"
# two serve.py replicas behind the health-aware router, one front-end process;
# the supervisor appends --port 0 --port-file <workdir>/replica_<i>.port
python -m relora_tpu.serve.supervisor --replicas 2 --workdir "$FLEET" \
    --router-port 0 --router-port-file "$WORK/router_port" \
    --backoff-base-s 0.2 --probe-interval-s 0.1 -- \
    python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --max-batch 2 --max-queue 8 --cache-size 64 --eos-id -1 &
SUP_PID=$!
for _ in $(seq 600); do [ -s "$WORK/router_port" ] && break; sleep 0.2; done
[ -s "$WORK/router_port" ] || { echo "router never wrote its port"; kill "$SUP_PID"; exit 1; }
python - "$(cat "$WORK/router_port")" "$FLEET" <<'EOF'
import json, os, signal, sys, time, urllib.error, urllib.request

port, fleet = sys.argv[1], sys.argv[2]
base = f"http://127.0.0.1:{port}"

def healthz():
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            return json.load(r)
    except urllib.error.HTTPError as e:  # 503 while < 1 replica routable
        return json.loads(e.read().decode())

def wait_healthy(n, tries=600):
    h = {}
    for _ in range(tries):
        h = healthz()
        if h.get("healthy_replicas", 0) >= n:
            return h
        time.sleep(0.2)
    raise SystemExit(f"fleet never reached {n} healthy replicas: {h}")

def stream(max_new_tokens, kill_mid_stream=False):
    """One /v1/generate stream through the router -> (replica_id, events)."""
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": max_new_tokens}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        rid = resp.headers["X-Relora-Replica"]
        events = []
        for line in resp:
            if not line.startswith(b"data: "):
                continue
            events.append(line[len(b"data: "):].strip())
            if kill_mid_stream and len(events) == 1:
                pid = int(open(os.path.join(fleet, f"replica_{rid[1:]}.pid")).read())
                os.kill(pid, signal.SIGKILL)
    return rid, events

wait_healthy(2)
# warm both replicas (a replica's first request compiles the decode graph);
# equal-load ties round-robin, so a few sequential streams cover the fleet
seen = set()
for _ in range(8):
    rid, events = stream(4)
    assert events[-1] == b"[DONE]", events
    seen.add(rid)
    if len(seen) == 2:
        break
assert len(seen) == 2, f"router never spread load across both replicas: {seen}"

# SIGKILL the serving replica mid-stream: bytes already reached the client, so
# no silent replay — the stream must end with a typed error, never a hang
victim, events = stream(32, kill_mid_stream=True)
if events[-1] == b"[DONE]":
    print("note: victim finished its stream before the SIGKILL landed")
else:
    err = json.loads(events[-1]).get("error", {})
    assert err.get("type") == "stream_interrupted", events[-3:]
    assert err.get("retryable") is False, err

# the survivor keeps serving while the victim restarts
other, events = stream(4)
assert other != victim and events[-1] == b"[DONE]", (other, victim, events[-3:])

# the supervisor restarts the victim and the router routes to it again
wait_healthy(2)
for _ in range(60):
    got, events = stream(4)
    assert events[-1] == b"[DONE]", events
    if got == victim:
        break
else:
    raise SystemExit(f"restarted replica {victim} never served traffic again")

metrics = urllib.request.urlopen(f"{base}/metrics", timeout=30).read().decode()
assert "relora_router_healthy_replicas 2" in metrics, metrics
assert "relora_router_requests_total" in metrics, metrics
print(f"router failover OK: {victim} killed mid-stream, restarted, serving again")
EOF
kill -TERM "$SUP_PID"
wait "$SUP_PID"   # exit 0 = rolling drain + router shutdown completed cleanly

echo "=== 13. fleet observability plane: collector, SLO burn drill, fleet report ==="
OBS_FLEET="$WORK/obs_fleet"
rm -rf "$OBS_FLEET"; mkdir -p "$OBS_FLEET"
rm -f "$WORK/obs_router_port"
# compressed burn windows so the drill fires/clears in seconds, not hours
cat > "$WORK/slo_drill.json" <<'JSON'
{"slos": [{"name": "availability", "series": "up", "threshold": 1.0,
           "bad_when": "lt", "objective": 0.9, "windows": [[20.0, 3.0, 2.0]]}]}
JSON
# replica 0's first incarnation is armed to os._exit mid-decode (the serving
# fault drill); env_overrides_respawn=False means its respawn comes back clean
python -m relora_tpu.serve.supervisor --replicas 2 --workdir "$OBS_FLEET" \
    --router-port 0 --router-port-file "$WORK/obs_router_port" \
    --backoff-base-s 0.2 --probe-interval-s 0.1 \
    --fleet-cadence-s 0.2 --slo-config "$WORK/slo_drill.json" \
    --replica-env "0:RELORA_TPU_FAULTS=serve_crash:at_token=6" -- \
    python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --max-batch 2 --max-queue 8 --cache-size 64 --eos-id -1 &
OBS_SUP_PID=$!
for _ in $(seq 600); do [ -s "$WORK/obs_router_port" ] && break; sleep 0.2; done
[ -s "$WORK/obs_router_port" ] || { echo "router never wrote its port"; kill "$OBS_SUP_PID"; exit 1; }
python - "$(cat "$WORK/obs_router_port")" "$OBS_FLEET" <<'EOF'
import json, sys, time, urllib.error, urllib.request

port, fleet = sys.argv[1], sys.argv[2]
base = f"http://127.0.0.1:{port}"
series_path = f"{fleet}/fleet_series.jsonl"

def healthz():
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            return json.load(r)
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode())

def wait_healthy(n, tries=600):
    h = {}
    for _ in range(tries):
        h = healthz()
        if h.get("healthy_replicas", 0) >= n:
            return
        time.sleep(0.2)
    raise SystemExit(f"fleet never reached {n} healthy replicas: {h}")

def availability_transitions():
    """(state, _time) of persisted r0 availability burn transitions."""
    out = []
    try:
        with open(series_path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail
                if (rec.get("_event") == "slo_burn_alert"
                        and rec.get("slo") == "availability"
                        and rec.get("_source") == "r0"):
                    out.append((rec["state"], rec["_time"]))
    except OSError:
        pass
    return out

def stream(max_new_tokens):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": max_new_tokens}).encode(),
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            resp.read()
            return True
    except (urllib.error.URLError, ConnectionError, OSError):
        return False  # mid-crash stream errors are the drill, not a failure

wait_healthy(2)
time.sleep(6)  # boot-time burn (replicas down while compiling) must clear
fires0 = sum(1 for s, _ in availability_transitions() if s == "fire")

# drive tokens until replica 0's armed crash lands (at_token=6)
t_crash = None
for _ in range(100):
    stream(4)
    if healthz().get("healthy_replicas", 2) < 2:
        t_crash = time.time()
        break
    time.sleep(0.1)
assert t_crash is not None, "armed replica never crashed"

# the burn alert must FIRE while the replica is down...
for _ in range(200):
    fires = [(s, t) for s, t in availability_transitions() if s == "fire"]
    if len(fires) > fires0:
        break
    time.sleep(0.2)
else:
    raise SystemExit(f"SLO burn alert never fired after the crash: {availability_transitions()}")

# ...and CLEAR once the supervisor's respawn is healthy again
wait_healthy(2)
for _ in range(300):
    trans = availability_transitions()
    if trans and trans[-1][0] == "clear":
        break
    time.sleep(0.2)
else:
    raise SystemExit(f"SLO burn alert never cleared after recovery: {availability_transitions()}")

# the collector's plane is mounted on the router front-end
fm = urllib.request.urlopen(f"{base}/fleet/metrics", timeout=30).read().decode()
assert "relora_fleet_scrape_rounds_total" in fm, fm[:400]
assert "relora_fleet_source_r0_up 1" in fm, fm[:400]
fs = json.load(urllib.request.urlopen(f"{base}/fleet/series?source=r0&series=up", timeout=30))
assert fs["sources"]["r0"]["up"], fs
assert any(o["slo"] == "availability" for o in fs["slo"]["objectives"]), fs["slo"]
print("fleet drill OK: burn alert fired on crash, cleared after respawn")
EOF
kill -TERM "$OBS_SUP_PID"
wait "$OBS_SUP_PID"
# post-mortem: rebuild the fleet picture from the persisted store alone
python tools/fleet_report.py "$OBS_FLEET/fleet_series.jsonl" --window-s 60 > "$WORK/fleet_report.txt"
grep -q "== fleet health ==" "$WORK/fleet_report.txt"
grep -q "== SLO / error budget ==" "$WORK/fleet_report.txt"
grep -q "slo_burn_alert" "$WORK/fleet_report.txt"
grep -q "supervisor_" "$WORK/fleet_report.txt"   # lifecycle events on the timeline
head -40 "$WORK/fleet_report.txt"

echo "=== 14. continuous deployment: watcher hot-swap, corrupt reject, canary rollback ==="
DEPLOY_FLEET="$WORK/deploy_fleet"
rm -rf "$DEPLOY_FLEET"; mkdir -p "$DEPLOY_FLEET"
rm -f "$WORK/deploy_router_port"
# the trainer's manifest commit already published latest -> model_40; prove
# that, then re-pin to model_32 so the fleet boots one version behind and the
# watcher has a verified newer checkpoint to roll forward to
python - "$WORK/relora" <<'EOF'
import json, sys
with open(f"{sys.argv[1]}/latest") as f:
    rec = json.load(f)
assert rec["path"] == "model_40", f"trainer did not publish latest: {rec}"
print(f"trainer published latest -> {rec['path']} (step {rec['step']})")
EOF
python -m relora_tpu.serve.deploy publish "$WORK/relora/model_32"
# drill artifacts: a corrupt copy (the watcher must refuse it) and a valid
# checkpoint shipping a deliberately wrong canary baseline (the canary gate
# must yank the fleet back)
rm -rf "$WORK/relora/model_48" "$WORK/relora/model_9924"
cp -r "$WORK/relora/model_40" "$WORK/relora/model_48"
cp -r "$WORK/relora/model_24" "$WORK/relora/model_9924"
python - "$WORK/relora/model_48" "$WORK/relora/model_9924" <<'EOF'
import json, os, sys
corrupt, bad_canary = sys.argv[1], sys.argv[2]
for dirpath, _, names in os.walk(os.path.join(corrupt, "state")):
    for name in sorted(names):
        p = os.path.join(dirpath, name)
        if os.path.getsize(p):
            with open(p, "r+b") as f:
                b = f.read(1)
                f.seek(0)
                f.write(bytes([b[0] ^ 0xFF]))
            break
    else:
        continue
    break
else:
    raise SystemExit(f"no state file to corrupt under {corrupt}")
with open(os.path.join(bad_canary, "canary.json"), "w") as f:
    json.dump({"prompts": [[1, 2, 3]], "tokens": [[255, 255, 255, 255]],
               "max_new_tokens": 4}, f)
EOF
python -m relora_tpu.serve.supervisor --replicas 2 --workdir "$DEPLOY_FLEET" \
    --router-port 0 --router-port-file "$WORK/deploy_router_port" \
    --backoff-base-s 0.2 --probe-interval-s 0.1 --fleet-cadence-s 0.2 \
    --watch-checkpoints "$WORK/relora" --watch-interval-s 0.3 \
    --canary-max-new-tokens 4 -- \
    python serve.py --checkpoint "$WORK/relora/model_32" --model_config llama_9m \
    --max-batch 2 --max-queue 16 --cache-size 64 --eos-id -1 &
DEPLOY_SUP_PID=$!
for _ in $(seq 600); do [ -s "$WORK/deploy_router_port" ] && break; sleep 0.2; done
[ -s "$WORK/deploy_router_port" ] || { echo "router never wrote its port"; kill "$DEPLOY_SUP_PID"; exit 1; }
python - "$(cat "$WORK/deploy_router_port")" "$DEPLOY_FLEET" "$WORK/relora" <<'EOF'
import json, subprocess, sys, threading, time, urllib.error, urllib.request

port, fleet, save_dir = sys.argv[1], sys.argv[2], sys.argv[3]
base = f"http://127.0.0.1:{port}"
series_path = f"{fleet}/fleet_series.jsonl"

def healthz():
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            return json.load(r)
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode())

def wait_healthy(n, tries=600):
    h = {}
    for _ in range(tries):
        h = healthz()
        if h.get("healthy_replicas", 0) >= n:
            return
        time.sleep(0.2)
    raise SystemExit(f"fleet never reached {n} healthy replicas: {h}")

def deploy_events():
    out = []
    try:
        with open(series_path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail
                if rec.get("_event", "").startswith("deploy_"):
                    out.append(rec)
    except OSError:
        pass
    return out

def wait_event(name, want_detail="", tries=600):
    for _ in range(tries):
        evs = [e for e in deploy_events()
               if e["_event"] == name and want_detail in e.get("detail", "")]
        if evs:
            return evs
        time.sleep(0.2)
    raise SystemExit(f"never saw {name} ({want_detail!r}) in the fleet store")

def publish(ckpt, force=False):
    cmd = [sys.executable, "-m", "relora_tpu.serve.deploy", "publish", ckpt]
    if force:
        cmd.append("--force")
    subprocess.run(cmd, check=True)

# continuous 8-way load for the whole drill; EVERY request must finish
dropped, lock = [], threading.Lock()
last_weights = {}  # replica rid -> last X-Relora-Weights it answered with
stop = threading.Event()

def worker(wid):
    while not stop.is_set():
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4,
                             "temperature": 0.0, "stream": False}).encode(),
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                body = json.load(resp)
                rid = resp.headers.get("X-Relora-Replica")
                weights = resp.headers.get("X-Relora-Weights")
                if body.get("finish_reason") not in ("eos", "length"):
                    raise ValueError(f"bad finish: {body}")
                with lock:
                    if rid and weights:
                        last_weights[rid] = weights
        except Exception as e:
            with lock:
                dropped.append(f"worker {wid}: {e!r}")
            return

def wait_fleet_on(version, tries=600):
    for _ in range(tries):
        with lock:
            vals = dict(last_weights)
        if len(vals) >= 2 and all(v == str(version) for v in vals.values()):
            return
        time.sleep(0.2)
    raise SystemExit(f"fleet never converged on weights {version}: {last_weights}")

wait_healthy(2)
workers = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
for t in workers:
    t.start()

# 1. rolling hot-swap under load: publish model_40; the watcher verifies it
#    and walks the fleet one replica at a time behind the canary gate
publish(f"{save_dir}/model_40")
wait_event("deploy_complete", "model_40")
wait_fleet_on(40)
assert not dropped, dropped
print("rolling hot-swap 32 -> 40 complete, zero dropped requests")

# 2. corrupt publish: the watcher must refuse it and the fleet must hold 40
publish(f"{save_dir}/model_48", force=True)
wait_event("deploy_reject", "model_48")
assert not any("model_48" in e.get("detail", "")
               for e in deploy_events() if e["_event"] == "deploy_begin"), \
    "corrupt checkpoint reached the fleet"
wait_fleet_on(40)
print("corrupt publish rejected at the watcher, fleet held version 40")

# 3. canary rollback: model_9924 verifies clean but ships a wrong canary
#    baseline -- the gate must roll the whole fleet back to model_40
publish(f"{save_dir}/model_9924")
wait_event("deploy_canary_fail")
wait_event("deploy_rollback")
publish(f"{save_dir}/model_40")  # re-pin: end the (by-design) retry loop
wait_fleet_on(40)
print("canary mismatch rolled the fleet back to 40")

stop.set()
for t in workers:
    t.join()
assert not dropped, dropped
h = healthz()
assert h.get("healthy_replicas", 0) == 2, h
# crosscheck through the collector: both replicas' scraped healthz agree
fs = json.load(urllib.request.urlopen(
    f"{base}/fleet/series?series=healthz_weights_version", timeout=30))
for rid in ("r0", "r1"):
    pts = fs["sources"].get(rid, {}).get("healthz_weights_version") or []
    assert pts and pts[-1][1] == 40.0, (rid, pts[-2:])
print("deploy drill OK: hot-swap, corrupt reject, and canary rollback "
      "all converged on one healthy version")
EOF
kill -TERM "$DEPLOY_SUP_PID"
wait "$DEPLOY_SUP_PID"
# post-mortem: the whole deployment story must be reconstructible from the
# persisted fleet store alone (and the stale-bench banner must fire on this
# repo's replayed BENCH rounds)
python tools/fleet_report.py "$DEPLOY_FLEET/fleet_series.jsonl" --window-s 600 \
    --events 200 > "$WORK/deploy_report.txt"
grep -q "deploy_complete" "$WORK/deploy_report.txt"
grep -q "deploy_reject" "$WORK/deploy_report.txt"
grep -q "deploy_canary_fail" "$WORK/deploy_report.txt"
grep -q "deploy_rollback" "$WORK/deploy_report.txt"
grep -q "BENCH STALENESS" "$WORK/deploy_report.txt"
grep "deploy_" "$WORK/deploy_report.txt" | head -20

echo "=== 15. elastic fleet: SLO-driven 1->2->1 autoscale under load ==="
AS_FLEET="$WORK/as_fleet"
rm -rf "$AS_FLEET"; mkdir -p "$AS_FLEET"
rm -f "$WORK/as_router_port"
# one replica to start; the autoscaler reads the collector's store and may
# grow to 2 under sustained queue burn, shrinking back after the idle window
python -m relora_tpu.serve.supervisor --replicas 1 --workdir "$AS_FLEET" \
    --router-port 0 --router-port-file "$WORK/as_router_port" \
    --backoff-base-s 0.2 --probe-interval-s 0.1 \
    --fleet-cadence-s 0.2 \
    --autoscale --min-replicas 1 --max-replicas 2 \
    --queue-depth-high 2 --burn-window-s 1.5 --idle-window-s 6 \
    --cooldown-s 3 --autoscale-interval-s 0.25 -- \
    python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --max-batch 2 --max-queue 16 --cache-size 64 --eos-id -1 &
AS_SUP_PID=$!
for _ in $(seq 600); do [ -s "$WORK/as_router_port" ] && break; sleep 0.2; done
[ -s "$WORK/as_router_port" ] || { echo "router never wrote its port"; kill "$AS_SUP_PID"; exit 1; }
python - "$(cat "$WORK/as_router_port")" "$AS_FLEET" <<'EOF'
import json, sys, threading, time, urllib.error, urllib.request

port, fleet = sys.argv[1], sys.argv[2]
base = f"http://127.0.0.1:{port}"
series_path = f"{fleet}/fleet_series.jsonl"

def healthz():
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            return json.load(r)
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode())

def wait_healthy(n, tries=1500):
    h = {}
    for _ in range(tries):
        h = healthz()
        if h.get("healthy_replicas", 0) >= n:
            return
        time.sleep(0.2)
    raise SystemExit(f"fleet never reached {n} healthy replicas: {h}")

def autoscale_events():
    out = []
    try:
        with open(series_path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail
                if str(rec.get("_event", "")).startswith("autoscale_"):
                    out.append(rec)
    except OSError:
        pass
    return out

wait_healthy(1)

# burst: enough concurrent streams to hold queue_depth over the burn window
stop = threading.Event()
dropped = []
def worker(wid):
    while not stop.is_set():
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 8}).encode(),
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                resp.read()
        except urllib.error.HTTPError:
            pass  # 429/503 is typed backpressure, not a drop
        except Exception as e:
            dropped.append(f"worker {wid}: {e!r}")
            return

workers = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
for t in workers:
    t.start()

deadline = time.time() + 120
while time.time() < deadline:
    if any(e.get("_event") == "autoscale_up" for e in autoscale_events()):
        break
    time.sleep(0.2)
else:
    raise SystemExit(f"autoscaler never scaled up: {autoscale_events()[-5:]}")
# the new replica pays its compile warmup (healthz "warming", unroutable)
# before the router counts it healthy
wait_healthy(2)
print("burst scaled the fleet 1 -> 2 (new replica warmed and routable)")

# quiet tail: idle window + cooldown must bring the fleet back to the floor
stop.set()
for t in workers:
    t.join()
assert not dropped, dropped
deadline = time.time() + 180
while time.time() < deadline:
    if any(e.get("_event") == "autoscale_down_complete" for e in autoscale_events()):
        break
    time.sleep(0.2)
else:
    raise SystemExit(
        f"autoscaler never scaled back down: {autoscale_events()[-5:]}")
for _ in range(300):
    h = healthz()
    if h.get("healthy_replicas", 0) == 1:
        break
    time.sleep(0.2)
else:
    raise SystemExit(f"fleet never settled back to 1 replica: {healthz()}")
kinds = [e.get("_event") for e in autoscale_events()]
assert "autoscale_decision" in kinds, kinds
print("idle scaled the fleet 2 -> 1; zero dropped requests across the resize")
EOF
kill -TERM "$AS_SUP_PID"
wait "$AS_SUP_PID"   # exit 0 = rolling drain wins over any pending scale-up
# the elastic history must be reconstructible from the persisted store
python tools/fleet_report.py "$AS_FLEET/fleet_series.jsonl" --window-s 600 \
    --events 200 > "$WORK/as_report.txt"
grep -q "== autoscale ==" "$WORK/as_report.txt"
grep -q "autoscale_up" "$WORK/as_report.txt"
grep -q "autoscale_down_complete" "$WORK/as_report.txt"
grep -q "replicas:" "$WORK/as_report.txt"
grep "autoscale_" "$WORK/as_report.txt" | head -12

echo "=== 16. disaggregated fleet: prefill/decode roles, KV page migration, prefix directory ==="
# reference first: one *mixed* paged replica records the greedy tokens the
# disaggregated fleet must reproduce exactly (same checkpoint, same pool)
rm -f "$WORK/dg_ref_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/dg_ref_port" --max-batch 2 --max-queue 8 \
    --cache-size 64 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --kv-dtype int8 &
DG_REF_PID=$!
for _ in $(seq 300); do [ -s "$WORK/dg_ref_port" ] && break; sleep 0.2; done
[ -s "$WORK/dg_ref_port" ] || { echo "reference server never wrote its port"; kill "$DG_REF_PID"; exit 1; }
python - "$(cat "$WORK/dg_ref_port")" "$WORK/dg_ref.json" <<'EOF'
import json, sys, time, urllib.error, urllib.request
port = sys.argv[1]
deadline = time.time() + 600
while True:
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
assert health["role"] == "mixed", health

def generate(prompt):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 8}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [
            line[len(b"data: "):].strip()
            for line in resp
            if line.startswith(b"data: ")
        ]
    assert events[-1] == b"[DONE]", events[-3:]
    return json.loads(events[-2])["tokens"]

short = [(i % 100) + 1 for i in range(8)]
long1 = [(i % 100) + 1 for i in range(40)]
long2 = long1[:32] + [7, 8, 9, 10, 11, 12, 13, 14]  # shared 4-page prefix
json.dump(
    {"short": generate(short), "long1": generate(long1), "long2": generate(long2)},
    open(sys.argv[2], "w"),
)
print("disagg reference tokens recorded")
EOF
kill -TERM "$DG_REF_PID"
wait "$DG_REF_PID"

# the disaggregated fleet: replica 0 prefill, replica 1 decode, replica 2
# mixed (the fallback pool), router classifying at 24 prompt tokens, the
# collector (0.2s cadence) feeding the fleet prefix-page directory
DG_FLEET="$WORK/dg_fleet"
rm -rf "$DG_FLEET"; mkdir -p "$DG_FLEET"
rm -f "$WORK/dg_router_port"
python -m relora_tpu.serve.supervisor --replicas 3 \
    --prefill-replicas 1 --decode-replicas 1 --classify-threshold 24 \
    --workdir "$DG_FLEET" \
    --router-port 0 --router-port-file "$WORK/dg_router_port" \
    --backoff-base-s 0.2 --probe-interval-s 0.1 --fleet-cadence-s 0.2 -- \
    python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --max-batch 2 --max-queue 8 --cache-size 64 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --kv-dtype int8 &
DG_SUP_PID=$!
for _ in $(seq 600); do [ -s "$WORK/dg_router_port" ] && break; sleep 0.2; done
[ -s "$WORK/dg_router_port" ] || { echo "router never wrote its port"; kill "$DG_SUP_PID"; exit 1; }
python - "$(cat "$WORK/dg_router_port")" "$DG_FLEET" "$WORK/dg_ref.json" <<'EOF'
import json, os, signal, sys, time, urllib.error, urllib.request

port, fleet, want = sys.argv[1], sys.argv[2], json.load(open(sys.argv[3]))
base = f"http://127.0.0.1:{port}"

def healthz(p=None, b=None):
    url = b or (f"http://127.0.0.1:{p}" if p else base)
    try:
        with urllib.request.urlopen(f"{url}/healthz", timeout=10) as r:
            return json.load(r)
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode())

def wait_healthy(n, tries=1500):
    h = {}
    for _ in range(tries):
        h = healthz()
        if h.get("healthy_replicas", 0) >= n:
            return h
        time.sleep(0.2)
    raise SystemExit(f"fleet never reached {n} healthy replicas: {h}")

def stream(prompt, kill_mid_stream=False):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 8}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        rid = resp.headers["X-Relora-Replica"]
        events = []
        for line in resp:
            if not line.startswith(b"data: "):
                continue
            events.append(line[len(b"data: "):].strip())
            if kill_mid_stream and len(events) == 1:
                pid = int(open(os.path.join(fleet, f"replica_{rid[1:]}.pid")).read())
                os.kill(pid, signal.SIGKILL)
    return rid, events

wait_healthy(3)
# replica roles come up exactly as assigned (healthz is the role advertisement)
role_of = {}
for i in range(3):
    rp = open(os.path.join(fleet, f"replica_{i}.port")).read().strip()
    role_of[f"r{i}"] = healthz(p=rp)["role"]
assert sorted(role_of.values()) == ["decode", "mixed", "prefill"], role_of
assert role_of["r0"] == "prefill" and role_of["r1"] == "decode", role_of

short, long1, long2 = (
    [(i % 100) + 1 for i in range(8)],
    [(i % 100) + 1 for i in range(40)],
    [(i % 100) + 1 for i in range(32)] + [7, 8, 9, 10, 11, 12, 13, 14],
)

def final(events):
    assert events[-1] == b"[DONE]", events[-3:]
    return json.loads(events[-2])["tokens"]

# short prompt -> decode pool; long prompt -> prefill pool, whose finished
# page run migrates to the decode peer mid-stream.  Either way the tokens
# must be exactly what the single mixed replica produced.
rid, events = stream(short)
assert role_of[rid] == "decode", (rid, role_of)
assert final(events) == want["short"], (final(events), want["short"])
rid, events = stream(long1)
assert role_of[rid] == "prefill", (rid, role_of)
assert final(events) == want["long1"], (final(events), want["long1"])
rid, events = stream(long2)
assert final(events) == want["long2"], (final(events), want["long2"])

# the long streams really were handed off: donor-side migration counters
prefill_port = open(os.path.join(fleet, "replica_0.port")).read().strip()
for _ in range(100):
    m = urllib.request.urlopen(f"http://127.0.0.1:{prefill_port}/metrics", timeout=10).read().decode()
    migrated = [l for l in m.splitlines() if l.startswith("relora_serve_pages_migrated_total")]
    if migrated and float(migrated[0].split()[-1]) > 0:
        break
    time.sleep(0.2)
else:
    raise SystemExit(f"prefill replica never migrated a page run: {migrated}")
router_metrics = urllib.request.urlopen(f"{base}/metrics", timeout=10).read().decode()
assert "relora_router_routed_prefill_total" in router_metrics, router_metrics
assert "relora_router_routed_decode_total" in router_metrics, router_metrics

# fleet prefix-page directory: the collector scraped the prefill replica's
# digest advertisement; the router resolves a digest to its holder
digests = healthz(p=prefill_port).get("prefix_digests") or []
assert digests, "prefill replica advertises no prefix digests after long prompts"
holder = None
for _ in range(100):  # collector cadence: the next scrape feeds the directory
    try:
        with urllib.request.urlopen(f"{base}/fleet/prefix?d={digests[0]}", timeout=10) as r:
            holder = json.load(r)
            break
    except urllib.error.HTTPError:
        time.sleep(0.2)
assert holder and holder["digest"] == digests[0] and holder["port"], holder
print(f"prefix directory resolves {digests[0][:12]}... -> {holder['replica']}")

# SIGKILL the prefill replica mid-stream: bytes already reached the client,
# so the stream must end with a typed error (never a hang, never a replay)
victim, events = stream(long1, kill_mid_stream=True)
assert role_of[victim] == "prefill", (victim, role_of)
if events[-1] == b"[DONE]":
    print("note: victim finished its stream before the SIGKILL landed")
else:
    err = json.loads(events[-1]).get("error", {})
    assert err.get("type") == "stream_interrupted", events[-3:]
    assert err.get("retryable") is False, err

# with the prefill pool empty the router falls back to the mixed replica —
# same tokens, zero dropped requests
rid, events = stream(long1)
assert role_of[rid] == "mixed", (rid, role_of)
assert final(events) == want["long1"], (final(events), want["long1"])

# the supervisor restarts the victim; the rearmed prefill pool serves again
wait_healthy(3)
for _ in range(60):
    rid, events = stream(long2)
    assert final(events) == want["long2"], (final(events), want["long2"])
    if rid == victim:
        break
else:
    raise SystemExit(f"restarted prefill replica {victim} never served traffic again")
print("disagg fleet OK: role routing, token-identical migration, typed SIGKILL fallback")
EOF
kill -TERM "$DG_SUP_PID"
wait "$DG_SUP_PID"   # exit 0 = rolling drain across all three roles

echo "=== 17. compression: prune-retrain, draft export, --spec model parity vs 9b ==="
# (a) prune mid-training: ReLoRA from the stage-1 warmup fixes the keep-mask
# at the first merge past prune_start_step, then every later cycle re-zeroes
# the holes before requant and retrains the fresh factors around them
python main.py "${common[@]}" --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --warmed_up_model "$WORK/full/model_8" \
    --prune_sparsity 0.5 --prune_scope per_matrix --prune_start_step 2 \
    --reset_init magnitude \
    --num_training_steps 24 --save_every 8 --save_dir "$WORK/prune"
grep -q "prune_mask_computed" "$WORK/prune/metrics.jsonl"
[ -f "$WORK/prune/model_24/prune_mask.npz" ]   # sidecar rides the checkpoint
[ -f "$WORK/prune/model_24/prune_meta.json" ]

# (b) resume the retrain cycle: autoresume restores the sidecar mask (no
# recompute — the event count stays 1) and training continues through
# another merge with the holes intact
python main.py "${common[@]}" --lr 5e-3 --use_peft true --relora 8 --cycle_length 8 \
    --scheduler cosine_restarts --restart_warmup_steps 2 \
    --prune_sparsity 0.5 --prune_scope per_matrix --prune_start_step 2 \
    --reset_init magnitude \
    --num_training_steps 32 --save_every 8 --save_dir "$WORK/prune" \
    --autoresume true
[ "$(grep -c prune_mask_computed "$WORK/prune/metrics.jsonl")" = 1 ]
[ -f "$WORK/prune/model_32/prune_mask.npz" ]
python - "$WORK/prune/model_32" <<'EOF'
# the stored base kernels stay exactly zero on the pruned positions across
# prune -> retrain -> resume -> merge (the factors are dense, the base is not)
import sys
import numpy as np
from relora_tpu.compress import prune
from relora_tpu.train.checkpoint import restore_serving_params
mask, meta = prune.load_mask(sys.argv[1])
assert mask is not None and meta["sparsity"] > 0.4, meta
# draft-export the resumed checkpoint: the sidecar mask is reused verbatim
out = __import__("relora_tpu.compress.draft", fromlist=["export_draft_checkpoint"])
path = out.export_draft_checkpoint(sys.argv[1], sys.argv[1] + "_draft")
params = restore_serving_params(path)
checked = 0
for mpath, keep in prune._mask_items(mask):
    mod = prune._module_at(params, mpath)
    w = np.asarray(mod["kernel"], np.float32)
    assert not np.any(w[~np.asarray(keep)]), mpath
    checked += 1
assert checked > 0
print(f"prune-retrain OK: {meta['sparsity']*100:.1f}% sparsity exact-zero in {checked} modules")
EOF

# (c) export a light draft from the 9b checkpoint and serve it as the
# --spec model drafter: greedy output must replay the 9b tokens exactly
# (the parity contract — a pruned draft can only lower acceptance, never
# change what the server says)
python -m relora_tpu.compress.draft "$WORK/relora/model_40" "$WORK/draft" \
    --sparsity 0.3 --scope per_matrix
rm -f "$WORK/mspec_port"
python serve.py --checkpoint "$WORK/relora/model_40" --model_config llama_9m \
    --port 0 --port-file "$WORK/mspec_port" --max-batch 2 --max-queue 4 \
    --cache-size 64 --max-new-tokens 6 --eos-id -1 \
    --paged --page-size 8 --chunk-size 16 --spec model --spec-k 4 \
    --draft-checkpoint "$WORK/draft/model_40" --run-dir "$WORK/mspec_run" &
MSPEC_PID=$!
for _ in $(seq 300); do [ -s "$WORK/mspec_port" ] && break; sleep 0.2; done
[ -s "$WORK/mspec_port" ] || { echo "model-spec server never wrote its port"; kill "$MSPEC_PID"; exit 1; }
python - "$(cat "$WORK/mspec_port")" "$WORK/paged_tokens.json" <<'EOF'
import json, sys, urllib.request
port = sys.argv[1]
import time, urllib.error
deadline = time.time() + 600
while True:  # cold replica: healthz is 503 "warming" until compile warmup completes
    try:
        health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
    except urllib.error.HTTPError as e:
        health = json.load(e)
    if health["status"] == "ok":
        break
    assert health["status"] == "warming" and time.time() < deadline, health
    time.sleep(0.5)
spec = health["paging"]["spec"]
assert spec["mode"] == "model" and spec["k"] == 4, spec

def generate(prompt):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt": prompt, "max_new_tokens": 6}).encode(),
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        events = [line[len(b"data: "):] for line in resp if line.startswith(b"data: ")]
    final = json.loads(events[-2])
    assert final["finish_reason"] == "length" and len(final["tokens"]) == 6, final
    return final["tokens"]

# the 9b prompts again: greedy model-drafted decode must produce exactly
# the tokens the non-speculative paged server produced
want = json.load(open(sys.argv[2]))
long_prompt = [(i % 100) + 1 for i in range(40)]
got = generate(long_prompt)
assert got == want, f"model-drafted decode diverged: {got} != {want}"
generate([1, 2, 3])
health = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30))
spec = health["paging"]["spec"]
assert spec["drafted"] > 0, spec  # the model drafter always proposes
metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
assert "relora_serve_spec_mode_model 1" in metrics, metrics
assert "relora_serve_spec_drafted_total" in metrics, metrics
print("model-spec HTTP OK:", got, "| spec:", spec)
EOF
kill -TERM "$MSPEC_PID"
wait "$MSPEC_PID"
grep -q "serve/spec_mode_model" "$WORK/mspec_run/metrics.jsonl"

echo "SMOKE OK"
