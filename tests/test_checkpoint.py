"""Checkpoint layer unit tests: async save/restore roundtrip, resharding
restore under a different device layout, and commit-awareness of the
autoresume probe."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from relora_tpu.parallel.mesh import MeshSpec, make_mesh
from relora_tpu.train import checkpoint as ckpt
from relora_tpu.train.state import TrainState


def make_state(mesh, fsdp_axis_parts):
    sharding = NamedSharding(mesh, P("fsdp", None))
    params = {
        "layer": {
            "kernel": jax.device_put(
                jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8), sharding
            ),
            "bias": jnp.ones((8,), jnp.float32),
        }
    }
    opt_state = {"mu": jax.tree_util.tree_map(jnp.zeros_like, params)}
    return TrainState.create(params, opt_state)


def test_async_save_restore_roundtrip(tmp_path, devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh, 8)
    path = ckpt.save_checkpoint(
        str(tmp_path), 10, state, {"update_step": 10, "global_step": 10}
    )
    # async write: the JSON lands immediately, the state dir commits in the
    # background; wait_for_save fences it
    ckpt.wait_for_save()
    assert os.path.isdir(os.path.join(path, ckpt.STATE_SUBDIR))

    restored = ckpt.restore_checkpoint(path, jax.eval_shape(lambda: state))
    np.testing.assert_array_equal(
        np.asarray(restored.params["layer"]["kernel"]),
        np.asarray(state.params["layer"]["kernel"]),
    )


def test_restore_under_different_device_layout(tmp_path, devices):
    """Save sharded fsdp=8, restore onto an fsdp=2 mesh (the device-count
    change scenario: pod resize between save and resume)."""
    mesh8 = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh8, 8)
    path = ckpt.save_checkpoint(str(tmp_path), 5, state, {"update_step": 5})
    ckpt.wait_for_save()

    mesh2 = make_mesh(MeshSpec(data=1, fsdp=2))
    target_sharding = NamedSharding(mesh2, P("fsdp", None))

    def abstract():
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=target_sharding)
            if x.ndim == 2
            else jax.ShapeDtypeStruct(x.shape, x.dtype),
            state,
        )

    restored = ckpt.restore_checkpoint(path, abstract())
    kernel = restored.params["layer"]["kernel"]
    assert kernel.sharding.mesh.shape["fsdp"] == 2
    np.testing.assert_array_equal(
        np.asarray(kernel), np.arange(64.0, dtype=np.float32).reshape(8, 8)
    )

    # topology-free host restore also works (warm starts / offline tools)
    host = ckpt.restore_state_host(path)
    np.testing.assert_array_equal(
        np.asarray(host["params"]["layer"]["kernel"]),
        np.arange(64.0, dtype=np.float32).reshape(8, 8),
    )


def test_get_last_checkpoint_skips_uncommitted(tmp_path, devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh, 8)
    ckpt.save_checkpoint(str(tmp_path), 3, state, {"update_step": 3})
    ckpt.wait_for_save()

    # a newer dir with JSON but no committed state/ (died mid-async-write)
    dead = os.path.join(str(tmp_path), "model_7")
    os.makedirs(dead)
    with open(os.path.join(dead, ckpt.TRAINING_STATE_FILE), "w") as f:
        json.dump({"update_step": 7}, f)

    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3
    assert path.endswith("model_3")

    # retention must neither count nor delete the uncommitted dir — with
    # keep=1 the committed model_3 survives (deleting it against an
    # in-flight model_7 would leave nothing restorable)
    ckpt.delete_old_checkpoints(str(tmp_path), keep=1)
    assert os.path.isdir(os.path.join(str(tmp_path), "model_3", ckpt.STATE_SUBDIR))
