"""relora_tpu.obs — unified observability: span tracing, shared metrics
registry, flight recorder, MFU helpers, HBM accounting, and compile
telemetry.

Stdlib-only at import time (``mfu`` / ``memory`` / ``compile`` import jax
lazily, inside calls); safe to import from the serving front-end, the
trainer, and signal handlers.  See docs/observability.md.
"""

from relora_tpu.obs.compile import CompileEvent, CompileWatcher, abstract_signature, signature_diff
from relora_tpu.obs.fleet import (
    FleetCollector,
    SeriesStore,
    histogram_quantile,
    load_series_jsonl,
    parse_prometheus,
)
from relora_tpu.obs.flight import FlightRecorder, configure, default_recorder, dump_on_fault
from relora_tpu.obs.memory import (
    MemoryPoller,
    hbm_peak_gb,
    live_memory_stats,
    plan_for,
    pytree_breakdown,
    pytree_bytes,
    reconcile,
    xla_memory_plan,
)
from relora_tpu.obs.metrics import LATENCY_BUCKETS, Histogram, MetricsRegistry
from relora_tpu.obs.mfu import peak_flops, step_flops_from_cost_analysis
from relora_tpu.obs.slo import (
    SLO,
    Alert,
    AnomalySpec,
    SeriesAnomalyDetector,
    SLOEngine,
    default_slos,
    load_slo_config,
)
from relora_tpu.obs.tracer import (
    NoopTracer,
    Span,
    Tracer,
    chrome_trace_events,
    default_tracer,
    new_trace_id,
    set_default_tracer,
)

__all__ = [
    "CompileEvent",
    "CompileWatcher",
    "abstract_signature",
    "signature_diff",
    "MemoryPoller",
    "hbm_peak_gb",
    "live_memory_stats",
    "plan_for",
    "pytree_breakdown",
    "pytree_bytes",
    "reconcile",
    "xla_memory_plan",
    "FleetCollector",
    "SeriesStore",
    "histogram_quantile",
    "load_series_jsonl",
    "parse_prometheus",
    "SLO",
    "Alert",
    "AnomalySpec",
    "SeriesAnomalyDetector",
    "SLOEngine",
    "default_slos",
    "load_slo_config",
    "FlightRecorder",
    "configure",
    "default_recorder",
    "dump_on_fault",
    "LATENCY_BUCKETS",
    "Histogram",
    "MetricsRegistry",
    "peak_flops",
    "step_flops_from_cost_analysis",
    "NoopTracer",
    "Span",
    "Tracer",
    "chrome_trace_events",
    "default_tracer",
    "new_trace_id",
    "set_default_tracer",
]
