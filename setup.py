"""Packaging (parity: reference setup.py — pip-installable package).

The native dataset helpers (relora_tpu/data/native/helpers.cpp) are compiled
at first use with g++ (see native/build hook in __init__.py), so no build
step is required at install time.
"""

from setuptools import find_packages, setup

setup(
    name="relora_tpu",
    version="0.1.0",
    description=(
        "TPU-native ReLoRA pretraining: high-rank training through low-rank "
        "updates on JAX/XLA/pallas/pjit"
    ),
    packages=find_packages(include=["relora_tpu", "relora_tpu.*"]),
    package_data={"relora_tpu.data.native": ["helpers.cpp"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "orbax-checkpoint",
        "numpy",
        "pyyaml",
        "einops",
    ],
    extras_require={
        "data": ["datasets", "transformers", "tokenizers"],
        "dev": ["pytest", "chex"],
    },
)
