"""Layer-wise magnitude pruning of the frozen base (PERP, arXiv:2312.15230).

ReLoRA's merge step makes the frozen base a living artifact: every cycle
folds the low-rank update into the kernel and re-draws the factors.  PERP's
observation is that this is exactly the right moment to prune — magnitude-
prune the merged base, then let the *next* cycle's LoRA factors (the only
trainable weights) recover the damage.  The mask is computed **once**, at
the first merge past ``prune_start_step``, and re-applied after every later
merge so pruned positions stay exactly zero for the rest of the run.

Mask format
-----------
A nested dict mirroring the params tree's module structure, holding a
single boolean ``kernel``-shaped leaf (True = keep) at every pruned module
and nothing anywhere else.  The same tree walks alongside ``params`` inside
:func:`relora_tpu.core.relora.merge_and_reinit` (mask applied to the merged
f32 values *before* requant — one quantization, no double-rounding) and is
persisted as a checkpoint sidecar (``prune_mask.npz`` + ``prune_meta.json``)
covered by the manifest's size+crc32 walk.

Exact-zero invariance across storage formats:

- dense (f32/bf16): ``0.0`` casts to ``0.0``;
- int8: symmetric zero-point — code 0 dequantizes to exactly 0;
- nf4: the codebook's index-7 level is exactly 0.0 and the midpoint encoder
  maps 0 to it, so ``0 * bscale == 0.0`` regardless of double-quant.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from relora_tpu.core.relora import LORA_A

PyTree = Any

PRUNE_MASK_FILE = "prune_mask.npz"
PRUNE_META_FILE = "prune_meta.json"

_VALID_SCOPES = ("global", "per_matrix")


class PruneMaskMismatchError(ValueError):
    """A prune mask does not line up with the weight tree it is applied to
    (missing module, extra module, or a shape mismatch) — named so callers
    (export_hf --pruned, serve draft loading) can refuse loudly."""


def parse_nm(nm: Union[str, Tuple[int, int], None]) -> Optional[Tuple[int, int]]:
    """``"2:4"`` -> ``(2, 4)``; validates N < M, both positive."""
    if nm is None:
        return None
    if isinstance(nm, str):
        parts = nm.split(":")
        if len(parts) != 2:
            raise ValueError(f"nm must look like 'N:M', got {nm!r}")
        n, m = (int(p) for p in parts)
    else:
        n, m = nm
    if not (0 < n < m):
        raise ValueError(f"N:M sparsity needs 0 < N < M, got {n}:{m}")
    return n, m


def _module_base(node: Dict[str, Any]) -> Optional[jax.Array]:
    """The module's frozen base as an f32 dense array (dequantized when the
    storage is int8/nf4), or None for lora_only modules with no base."""
    if "kernel" in node:
        return node["kernel"].astype(jnp.float32)
    if "kernel_q" in node:
        from relora_tpu.ops.quant import dequantize_int8

        return dequantize_int8(node["kernel_q"], node["kernel_scale"])
    if "kernel_codes" in node:
        from relora_tpu.ops.quant import dequantize_nf4, nf4_leaves_from_module

        return dequantize_nf4(nf4_leaves_from_module(node))
    return None


def _walk_prunable(params: PyTree, path: Tuple[str, ...] = ()):
    """Yield ``(path, module_dict)`` for every LoRA-wrapped module that owns
    a base kernel, in deterministic tree order (the same order
    ``merge_and_reinit`` walks)."""
    if not isinstance(params, dict):
        return
    if LORA_A in params:
        if _module_base(params) is not None:
            yield path, params
        return
    for k in params:
        yield from _walk_prunable(params[k], path + (k,))


def _module_at(params: PyTree, path: Tuple[str, ...]) -> Optional[Dict[str, Any]]:
    """The module dict at ``path``, or None when the path does not resolve."""
    node = params
    for k in path:
        if not isinstance(node, dict) or k not in node:
            return None
        node = node[k]
    return node if isinstance(node, dict) else None


def _nm_mask(mags: jax.Array, n: int, m: int) -> jax.Array:
    """Structured N:M keep-mask: within every group of M consecutive rows
    along the input (reduction) axis, keep the N largest magnitudes."""
    *lead, in_f, out_f = mags.shape
    if in_f % m:
        raise ValueError(f"N:M pruning needs in_features % M == 0, got {in_f} % {m}")
    groups = mags.reshape(*lead, in_f // m, m, out_f)
    # rank of each element within its group (0 = smallest)
    order = jnp.argsort(groups, axis=-2)
    ranks = jnp.argsort(order, axis=-2)
    keep = ranks >= (m - n)
    return keep.reshape(mags.shape)


def magnitude_mask(
    params: PyTree,
    sparsity: float,
    *,
    scope: str = "global",
    nm: Union[str, Tuple[int, int], None] = None,
    paths: Optional[list] = None,
) -> PyTree:
    """Build a keep-mask over every frozen base kernel.

    ``scope="global"`` ranks magnitudes across all prunable kernels with one
    threshold; ``"per_matrix"`` applies the sparsity level to each kernel
    independently.  ``nm`` switches to structured N:M sparsity (N kept per
    group of M along the input axis) and ignores ``sparsity``/``scope``.

    ``paths`` overrides module discovery with an explicit list of module
    paths — how the draft exporter prunes an already-*merged* tree (no
    ``lora_a`` leaves to walk) using the paths recorded from the unmerged
    training checkpoint.

    Magnitudes are taken on the *dequantized* base for int8/nf4 storage, so
    the mask means the same thing whatever the storage format.
    """
    if scope not in _VALID_SCOPES:
        raise ValueError(f"scope must be one of {_VALID_SCOPES}, got {scope!r}")
    nm_t = parse_nm(nm)
    if nm_t is None and not 0.0 <= sparsity < 1.0:
        raise ValueError(f"sparsity must be in [0, 1), got {sparsity}")

    if paths is not None:
        modules = []
        for path in paths:
            path = tuple(path)
            mod = _module_at(params, path)
            if mod is None or _module_base(mod) is None:
                raise PruneMaskMismatchError(
                    f"requested prune path {'/'.join(path)} has no base kernel "
                    "in this weight tree"
                )
            modules.append((path, mod))
    else:
        modules = list(_walk_prunable(params))
    if not modules:
        raise ValueError("no prunable modules found (is this a LoRA param tree?)")

    if nm_t is not None:
        n, m = nm_t
        return _build_tree(
            {path: _nm_mask(jnp.abs(_module_base(mod)), n, m) for path, mod in modules}
        )

    if sparsity == 0.0:
        return _build_tree(
            {path: jnp.ones(_module_base(mod).shape, bool) for path, mod in modules}
        )

    mags = {path: jnp.abs(_module_base(mod)) for path, mod in modules}
    if scope == "global":
        flat = jnp.concatenate([m.ravel() for m in mags.values()])
        thresh = jnp.quantile(flat, sparsity)
        masks = {path: m > thresh for path, m in mags.items()}
    else:
        masks = {path: m > jnp.quantile(m.ravel(), sparsity) for path, m in mags.items()}
    return _build_tree(masks)


def _build_tree(masks: Dict[Tuple[str, ...], jax.Array]) -> PyTree:
    """``{path: array}`` -> nested dict with a ``kernel`` leaf per module."""
    tree: Dict[str, Any] = {}
    for path, arr in masks.items():
        node = tree
        for k in path:
            node = node.setdefault(k, {})
        node["kernel"] = arr
    return tree


def _mask_items(mask: PyTree, path: Tuple[str, ...] = ()):
    """Yield ``(path, keep_array)`` for every mask leaf, deterministic order."""
    if not isinstance(mask, dict):
        return
    for k in sorted(mask):
        v = mask[k]
        if k == "kernel" and not isinstance(v, dict):
            yield path, v
        elif isinstance(v, dict):
            yield from _mask_items(v, path + (k,))


def apply_mask(params: PyTree, mask: PyTree) -> PyTree:
    """Zero the pruned positions of every masked base kernel.

    Validates the mask against the tree first: a module the mask names that
    the tree lacks, or a shape mismatch, raises
    :class:`PruneMaskMismatchError` (nothing partially applied).  Quantized
    bases go dequant -> mask -> requant; requantization is idempotent on
    already-quantized values, so repeated application is safe (the hot-swap
    and merge-cycle invariance tests rely on this).

    The walk is path-directed (not LoRA-directed), so the same mask applies
    to the unmerged training tree and to a merged serving/draft tree whose
    ``lora_a`` leaves are gone.
    """
    by_path = dict(_mask_items(mask))
    missing = sorted(
        path
        for path in by_path
        if (mod := _module_at(params, path)) is None or _module_base(mod) is None
    )
    if missing:
        raise PruneMaskMismatchError(
            f"prune mask names modules absent from the weight tree: "
            f"{['/'.join(p) for p in missing]}"
        )

    def walk(node, path=()):
        if not isinstance(node, dict):
            return node
        keep = by_path.get(path)
        if keep is not None:
            base = _module_base(node)
            if base.shape != keep.shape:
                raise PruneMaskMismatchError(
                    f"prune mask shape {tuple(keep.shape)} != kernel shape "
                    f"{tuple(base.shape)} at {'/'.join(path)}"
                )
            masked = jnp.where(keep, base, 0.0)
            out = dict(node)
            if "kernel" in node:
                out["kernel"] = masked.astype(node["kernel"].dtype)
            elif "kernel_q" in node:
                from relora_tpu.ops.quant import quantize_int8

                out["kernel_q"], out["kernel_scale"] = quantize_int8(masked)
            else:
                from relora_tpu.ops.quant import nf4_leaves_to_module, quantize_nf4

                out.update(
                    nf4_leaves_to_module(
                        quantize_nf4(
                            masked,
                            double_quant=node["kernel_bscale_q"].dtype == jnp.int8,
                        )
                    )
                )
            return out
        return {k: walk(v, path + (k,)) for k, v in node.items()}

    return walk(params)


def sparsity_stats(mask: PyTree) -> Dict[str, Any]:
    """Fraction pruned, overall and per module (host scalars, for logging
    and the prune_meta sidecar)."""
    per_module = {}
    pruned = total = 0
    for path, keep in _mask_items(mask):
        k = np.asarray(keep)
        per_module["/".join(path)] = float(1.0 - k.mean())
        pruned += int(k.size - k.sum())
        total += int(k.size)
    return {
        "sparsity": pruned / total if total else 0.0,
        "pruned": pruned,
        "total": total,
        "per_module": per_module,
    }


def mask_checksum(mask: PyTree) -> int:
    """crc32 over the packed mask bits in deterministic path order — the
    identity recorded in checkpoint manifests and export sidecars."""
    crc = 0
    for path, keep in _mask_items(mask):
        crc = zlib.crc32("/".join(path).encode(), crc)
        crc = zlib.crc32(np.packbits(np.asarray(keep, dtype=bool)).tobytes(), crc)
    return crc


def save_mask(dir_path: str, mask: PyTree, meta: Optional[dict] = None) -> dict:
    """Write the sidecar pair into a checkpoint dir; returns the meta dict
    (stats + checksum + whatever the caller passed)."""
    arrays = {
        "/".join(path): np.asarray(keep, dtype=bool) for path, keep in _mask_items(mask)
    }
    full_meta = dict(meta or {})
    full_meta.update(sparsity_stats(mask))
    full_meta["mask_crc32"] = mask_checksum(mask)
    os.makedirs(dir_path, exist_ok=True)
    np.savez_compressed(os.path.join(dir_path, PRUNE_MASK_FILE), **arrays)
    with open(os.path.join(dir_path, PRUNE_META_FILE), "w") as f:
        json.dump(full_meta, f, indent=2)
    return full_meta


def load_mask(dir_path: str) -> Tuple[Optional[PyTree], Optional[dict]]:
    """Read the sidecar pair back; ``(None, None)`` when the checkpoint was
    never pruned.  Verifies the recorded crc32 against the reloaded bits."""
    mask_path = os.path.join(dir_path, PRUNE_MASK_FILE)
    if not os.path.exists(mask_path):
        return None, None
    with np.load(mask_path) as z:
        masks = {tuple(name.split("/")): jnp.asarray(z[name]) for name in z.files}
    mask = _build_tree(masks)
    meta = None
    meta_path = os.path.join(dir_path, PRUNE_META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        want = meta.get("mask_crc32")
        if want is not None and mask_checksum(mask) != want:
            raise PruneMaskMismatchError(
                f"prune mask at {dir_path} fails its recorded crc32 ({want})"
            )
    return mask, meta
