"""Rule engine for the RTL footgun linter (stdlib-``ast``, no deps).

The analysis is organized as *checkers* — functions ``(FileContext) ->
Iterable[Finding]`` registered with :func:`checker` — each of which may emit
findings for one or more rule codes declared in :data:`RULE_CATALOG`.  A
finding is identified for suppression purposes by ``(relpath, code,
stripped source line)``: line *text*, not line *number*, so baselines
survive unrelated edits above the finding.

Two suppression layers:

- inline ``# noqa: RTL###`` (or a bare ``# noqa``) on the offending line,
  for one-off intentional violations that a reader of the code should see;
- the checked-in baseline file (``tools/lint_baseline.txt``) for
  grandfathered findings, one per line with a mandatory justification::

      relora_tpu/train/trainer.py | RTL203 | jax.block_until_ready(...) | merge cadence, timed for logging

  New findings (not baselined, not noqa'd) fail the lint.  Baseline entries
  that no longer match anything are reported as stale so the file must
  shrink as violations are fixed, never silently rot.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Sequence, Union

# code -> one-line summary; every Finding.code must be declared here
RULE_CATALOG: Dict[str, str] = {}
CHECKERS: List[Callable[["FileContext"], Iterable["Finding"]]] = []
#: project-wide checkers ``(ProjectIndex) -> Iterable[Finding]``; run once per
#: lint_paths invocation when the scan covers the package (see lint_paths)
PROJECT_CHECKERS: List[Callable[["ProjectIndex"], Iterable["Finding"]]] = []

#: sentinel for a bare ``# noqa`` (suppresses every rule on that line)
ALL_CODES: FrozenSet[str] = frozenset({"*"})

_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>RTL\d+(?:\s*,\s*RTL\d+)*))?", re.IGNORECASE
)


def catalog(**rules: str) -> None:
    """Declare rule codes (``RTL101="summary"``); called at module import."""
    for code, summary in rules.items():
        RULE_CATALOG[code] = summary


def checker(fn: Callable[["FileContext"], Iterable["Finding"]]):
    CHECKERS.append(fn)
    return fn


def project_checker(fn: Callable[["ProjectIndex"], Iterable["Finding"]]):
    PROJECT_CHECKERS.append(fn)
    return fn


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, posix separators
    line: int
    code: str
    message: str
    line_text: str  # stripped source of the offending line (baseline identity)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class FileContext:
    """One parsed file plus the per-line suppression map."""

    def __init__(self, path: str, relpath: str, text: str, force_hot: bool = False):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        self.force_hot = force_hot
        self._noqa: Dict[int, FrozenSet[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _NOQA_RE.search(line)
            if m:
                codes = m.group("codes")
                self._noqa[i] = (
                    frozenset(c.strip().upper() for c in codes.split(","))
                    if codes
                    else ALL_CODES
                )

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno: int, code: str) -> bool:
        codes = self._noqa.get(lineno)
        return codes is not None and (codes is ALL_CODES or code in codes)

    def finding(self, node: ast.AST, code: str, message: str) -> Finding:
        assert code in RULE_CATALOG, f"undeclared rule code {code}"
        lineno = getattr(node, "lineno", 1)
        return Finding(self.relpath, lineno, code, message, self.line_text(lineno))


# ---------------------------------------------------------------------------
# baseline


@dataclasses.dataclass
class BaselineEntry:
    path: str
    code: str
    snippet: str
    justification: str
    lineno: int  # line in the baseline file (for stale reports)

    def matches(self, f: Finding) -> bool:
        return (
            f.path == self.path and f.code == self.code and f.line_text == self.snippet
        )


def load_baseline(path: str) -> List[BaselineEntry]:
    entries: List[BaselineEntry] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split("|", 3)]
            if len(parts) != 4 or not parts[3]:
                raise ValueError(
                    f"{path}:{lineno}: baseline entries are "
                    f"'path | RTL### | source line | justification' "
                    f"(justification is mandatory)"
                )
            entries.append(BaselineEntry(parts[0], parts[1], parts[2], parts[3], lineno))
    return entries


def format_baseline_entry(f: Finding, justification: str = "TODO: justify") -> str:
    return f"{f.path} | {f.code} | {f.line_text} | {justification}"


# ---------------------------------------------------------------------------
# runner


@dataclasses.dataclass
class Report:
    findings: List[Finding]  # everything the rules produced (pre-suppression)
    new: List[Finding]  # not noqa'd, not baselined -> these fail the lint
    noqa_suppressed: int
    baselined: int
    stale_baseline: List[BaselineEntry]
    files_scanned: int
    parse_errors: List[str]

    @property
    def rule_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        return dict(sorted(counts.items()))


def lint_context(ctx: FileContext) -> List[Finding]:
    found: List[Finding] = []
    for check in CHECKERS:
        found.extend(check(ctx))
    return sorted(found, key=lambda f: (f.path, f.line, f.code))


def lint_text(
    text: str, relpath: str = "<text>", *, force_hot: bool = False
) -> List[Finding]:
    """Lint a source string (fixture/test entry point).  Returns raw
    findings; ``# noqa`` suppression is applied, the baseline is not."""
    ctx = FileContext(relpath, relpath, text, force_hot=force_hot)
    return [f for f in lint_context(ctx) if not ctx.suppressed(f.line, f.code)]


def _iter_py_files(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        yield path
        return
    skip = {".git", "__pycache__", ".venv", "node_modules", "build", "dist"}
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(d for d in dirnames if d not in skip)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    baseline: Union[str, Sequence[BaselineEntry], None] = None,
    project: Optional[bool] = None,
) -> Report:
    """Lint files/trees; relpaths (finding + baseline identity) are taken
    relative to ``root`` (default: cwd).

    ``project`` controls the whole-repo pass (PROJECT_CHECKERS: call graph +
    RTL7xx fleet consistency).  The default (None) auto-enables it when the
    scan set includes the fleet plane (:data:`PROJECT_SENTINEL`) — i.e. a
    real package scan, not a one-off fixture file — because the consistency
    rules are meaningless against a partial producer/consumer universe."""
    root = os.path.abspath(root or os.getcwd())
    entries: List[BaselineEntry] = []
    if isinstance(baseline, str):
        entries = load_baseline(baseline)
    elif baseline:
        entries = list(baseline)

    all_findings: List[Finding] = []
    new: List[Finding] = []
    noqa_count = 0
    baselined_count = 0
    used = [False] * len(entries)
    files = 0
    parse_errors: List[str] = []
    contexts: Dict[str, FileContext] = {}

    def classify(f: Finding, ctx: Optional[FileContext]) -> None:
        nonlocal noqa_count, baselined_count
        all_findings.append(f)
        if ctx is not None and ctx.suppressed(f.line, f.code):
            noqa_count += 1
            return
        for i, entry in enumerate(entries):
            if entry.matches(f):
                used[i] = True
                baselined_count += 1
                return
        new.append(f)

    for path in paths:
        for fpath in _iter_py_files(path):
            abspath = os.path.abspath(fpath)
            relpath = os.path.relpath(abspath, root)
            try:
                with open(abspath, encoding="utf-8") as fh:
                    text = fh.read()
                ctx = FileContext(abspath, relpath, text)
            except (SyntaxError, UnicodeDecodeError) as e:
                parse_errors.append(f"{relpath}: {e}")
                continue
            files += 1
            contexts[ctx.relpath] = ctx
            for f in lint_context(ctx):
                classify(f, ctx)

    if project is None:
        project = PROJECT_SENTINEL in contexts
    if project and PROJECT_CHECKERS:
        extra: Dict[str, FileContext] = {}
        for name in PROJECT_CONTEXT_GLOBS:
            for fpath in _iter_py_files(os.path.join(root, name)):
                relpath = os.path.relpath(fpath, root).replace(os.sep, "/")
                if relpath in contexts:
                    continue
                try:
                    with open(fpath, encoding="utf-8") as fh:
                        extra[relpath] = FileContext(fpath, relpath, fh.read())
                except (OSError, SyntaxError, UnicodeDecodeError):
                    continue  # context files are best-effort, never fatal
        index = ProjectIndex(contexts, extra)
        by_path = index.contexts
        project_findings: List[Finding] = []
        for check in PROJECT_CHECKERS:
            project_findings.extend(check(index))
        for f in sorted(project_findings, key=lambda f: (f.path, f.line, f.code)):
            classify(f, by_path.get(f.path))

    stale = [e for e, u in zip(entries, used) if not u]
    return Report(
        findings=all_findings,
        new=sorted(new, key=lambda f: (f.path, f.line, f.code)),
        noqa_suppressed=noqa_count,
        baselined=baselined_count,
        stale_baseline=stale,
        files_scanned=files,
        parse_errors=parse_errors,
    )


# ---------------------------------------------------------------------------
# shared AST helpers used by the rule modules


def dotted_name(node: ast.AST) -> str:
    """'jax.random.PRNGKey' for nested Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def target_path(node: ast.AST) -> str:
    """Dotted path for assignable/loadable chains rooted at a Name
    ('self.state.params'); '' for anything else (calls, subscripts...)."""
    return dotted_name(node)


def const_int_set(node: ast.AST) -> Optional[FrozenSet[int]]:
    """The set of ints in a literal int / tuple-or-list-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                vals.add(elt.value)
            else:
                return None
        return frozenset(vals)
    return None


def const_str_set(node: ast.AST) -> Optional[FrozenSet[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.add(elt.value)
            else:
                return None
        return frozenset(vals)
    return None


def get_kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


JIT_NAMES = frozenset({"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"})


def is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in JIT_NAMES


def unwrap_partial(node: ast.AST) -> Optional[ast.Call]:
    """``functools.partial(jax.jit, ...)`` / ``partial(jit, ...)`` as a
    pseudo jit-Call (kwargs of the partial are the jit kwargs)."""
    if (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in ("partial", "functools.partial")
        and node.args
        and dotted_name(node.args[0]) in JIT_NAMES
    ):
        return node
    return None


class QualnameVisitor(ast.NodeVisitor):
    """Base visitor tracking the dotted qualname of the enclosing
    function/class scope ('Trainer.fit.flush_pending')."""

    def __init__(self) -> None:
        self.stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self.stack)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


# ---------------------------------------------------------------------------
# module index: per-file symbol table, call graph, thread roots
#
# The interprocedural layer under the RTL6xx/RTL7xx families and the
# one-level RTL2xx propagation.  Resolution is deliberately conservative
# (module-qualified names only, no MRO, no data flow): an unresolved call is
# simply not an edge, so the derived facts (reachability, thread roots) err
# toward missing edges rather than inventing them — precision over recall,
# per docs/static-analysis.md.

THREAD_FACTORIES = frozenset({"threading.Thread", "Thread", "threading.Timer", "Timer"})
LOCK_FACTORIES = frozenset(
    {
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "Lock",
        "RLock",
        "Condition",
    }
)

#: root kinds that run on their own OS thread (vs the main/event-loop thread)
SPAWNED_ROOT_KINDS = frozenset({"thread", "executor"})


@dataclasses.dataclass
class FunctionInfo:
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    owner_class: str  # qualname of the innermost enclosing class, "" if none
    lineno: int


class _ModuleIndexBuilder(QualnameVisitor):
    def __init__(self) -> None:
        super().__init__()
        self.class_stack: List[str] = []
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        # (caller_qualname, dotted_callee, unconditional)
        self.calls_raw: List = []
        # (dotted_target, kind, lineno, registering caller qualname)
        self.root_targets_raw: List = []
        # class qualname -> attr -> dotted factory name of `self.X = Factory()`
        self.attr_types: Dict[str, Dict[str, str]] = {}
        # module-level `name = Factory()`
        self.module_types: Dict[str, str] = {}
        self.imports: Dict[str, str] = {}  # alias -> module dotted path
        self.from_imports: Dict[str, tuple] = {}  # name -> (module, orig name)
        self._branch_depth = 0
        self._func_entry_depth: List[int] = []

    # -- scope bookkeeping ---------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.class_stack.append(self.qualname)
        self.classes[self.qualname] = node
        self.generic_visit(node)
        self.class_stack.pop()
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        qn = self.qualname
        is_async = isinstance(node, ast.AsyncFunctionDef)
        self.functions[qn] = FunctionInfo(
            qualname=qn,
            node=node,
            is_async=is_async,
            owner_class=self.class_stack[-1] if self.class_stack else "",
            lineno=node.lineno,
        )
        if is_async:
            self.root_targets_raw.append((qn, "async", node.lineno, qn))
        self._func_entry_depth.append(self._branch_depth)
        self.generic_visit(node)
        self._func_entry_depth.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_If(self, node: ast.If) -> None:
        self._branch_depth += 1
        self.generic_visit(node)
        self._branch_depth -= 1

    # -- facts ---------------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.imports[alias.asname or alias.name.split(".")[0]] = alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and not node.level:
            for alias in node.names:
                self.from_imports[alias.asname or alias.name] = (
                    node.module,
                    alias.name,
                )

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            factory = dotted_name(node.value.func)
            if factory:
                for tgt in node.targets:
                    path = target_path(tgt)
                    if path.startswith("self.") and path.count(".") == 1:
                        cls = self.class_stack[-1] if self.class_stack else ""
                        if cls:
                            self.attr_types.setdefault(cls, {})[
                                path.split(".", 1)[1]
                            ] = factory
                    elif path and "." not in path and not self.stack:
                        self.module_types[path] = factory
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        caller = self.qualname
        dotted = dotted_name(node.func)
        if dotted:
            uncond = (
                bool(self._func_entry_depth)
                and self._branch_depth == self._func_entry_depth[-1]
            )
            self.calls_raw.append((caller, dotted, uncond))
        # thread/executor/signal entry points
        basename = dotted.rsplit(".", 1)[-1] if dotted else (
            node.func.attr if isinstance(node.func, ast.Attribute) else ""
        )
        target: Optional[ast.AST] = None
        kind = ""
        if dotted in THREAD_FACTORIES:
            target, kind = get_kwarg(node, "target"), "thread"
        elif basename == "run_in_executor" and len(node.args) >= 2:
            target, kind = node.args[1], "executor"
        elif dotted == "signal.signal" and len(node.args) >= 2:
            target, kind = node.args[1], "signal"
        elif basename == "add_signal_handler" and len(node.args) >= 2:
            target, kind = node.args[1], "signal"
        if target is not None and kind:
            tgt_dotted = dotted_name(target)
            if tgt_dotted:
                self.root_targets_raw.append((tgt_dotted, kind, node.lineno, caller))
        self.generic_visit(node)


class ModuleIndex:
    """Symbol table + call graph for one parsed module."""

    def __init__(self, ctx: FileContext) -> None:
        b = _ModuleIndexBuilder()
        b.visit(ctx.tree)
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.functions = b.functions
        self.classes = b.classes
        self.attr_types = b.attr_types
        self.module_types = b.module_types
        self.imports = b.imports
        self.from_imports = b.from_imports
        self.calls: Dict[str, set] = {}  # caller -> resolved local callees
        self.uncond_calls: Dict[str, set] = {}
        self.raw_calls: Dict[str, set] = {}  # caller -> dotted callee names
        for caller, dotted, uncond in b.calls_raw:
            self.raw_calls.setdefault(caller, set()).add(dotted)
            resolved = self.resolve_local(dotted, caller)
            if resolved is not None:
                self.calls.setdefault(caller, set()).add(resolved)
                if uncond:
                    self.uncond_calls.setdefault(caller, set()).add(resolved)
        #: qualname -> root kind ("thread" | "executor" | "signal" | "async")
        self.thread_roots: Dict[str, str] = {}
        for tgt, kind, _lineno, caller in b.root_targets_raw:
            if kind == "async":
                self.thread_roots.setdefault(tgt, "async")
                continue
            resolved = self.resolve_local(tgt, caller)
            if resolved is None and tgt in self.functions:
                resolved = tgt
            if resolved is not None:
                self.thread_roots[resolved] = kind

    def resolve_local(self, dotted: str, caller: str) -> Optional[str]:
        """Module-local qualname for a dotted callee, or None.  Handles
        ``self.m``/``cls.m`` (innermost enclosing class of *caller*), bare
        names (lexical scope chain, then module level), and already-qualified
        ``Class.method`` paths."""
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] in ("self", "cls"):
            info = self.functions.get(caller)
            cls = info.owner_class if info else ""
            if cls and len(parts) == 2:
                cand = f"{cls}.{parts[1]}"
                if cand in self.functions:
                    return cand
            return None
        if len(parts) == 1:
            scope = caller
            while scope:
                cand = f"{scope}.{parts[0]}"
                if cand in self.functions:
                    return cand
                scope = scope.rsplit(".", 1)[0] if "." in scope else ""
            return parts[0] if parts[0] in self.functions else None
        return dotted if dotted in self.functions else None

    def reachable(self, roots: Iterable[str]) -> set:
        """Transitive closure over resolved module-local call edges."""
        seen = set()
        work = [r for r in roots if r in self.functions]
        while work:
            qn = work.pop()
            if qn in seen:
                continue
            seen.add(qn)
            work.extend(self.calls.get(qn, ()))
        return seen


def get_module_index(ctx: FileContext) -> ModuleIndex:
    """Build (and cache on the context) the module's symbol table."""
    idx = getattr(ctx, "_module_index", None)
    if idx is None:
        idx = ModuleIndex(ctx)
        ctx._module_index = idx  # type: ignore[attr-defined]
    return idx


# ---------------------------------------------------------------------------
# project index: the whole-repo pass the RTL7xx family runs over


def _module_relpath(dotted: str) -> str:
    return dotted.replace(".", "/") + ".py"


class ProjectIndex:
    """All scanned modules plus read-only *context* modules (tools/, tests/,
    bench.py): consumer surfaces the fleet-consistency rules must see even
    though only the package itself is being linted.  Findings may anchor in
    either set; ``# noqa`` works in both."""

    def __init__(
        self,
        contexts: Dict[str, FileContext],
        extra: Optional[Dict[str, FileContext]] = None,
    ) -> None:
        self.scanned = dict(contexts)
        self.extra = dict(extra or {})

    @property
    def contexts(self) -> Dict[str, FileContext]:
        merged = dict(self.scanned)
        merged.update(self.extra)
        return merged

    def module(self, relpath: str) -> Optional[ModuleIndex]:
        ctx = self.scanned.get(relpath) or self.extra.get(relpath)
        return get_module_index(ctx) if ctx else None

    def modules(self) -> Iterable[ModuleIndex]:
        for relpath in sorted(self.contexts):
            idx = self.module(relpath)
            if idx is not None:
                yield idx

    def resolve_import(self, relpath: str, dotted: str):
        """Cross-module resolution of ``alias.func`` / from-imported names:
        returns ``(target_relpath, qualname)`` or None."""
        idx = self.module(relpath)
        if idx is None or not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] in idx.from_imports and len(parts) <= 2:
            mod, orig = idx.from_imports[parts[0]]
            target_rel = _module_relpath(mod)
            target = self.module(target_rel)
            qual = ".".join([orig] + parts[1:])
            if target is not None and qual in target.functions:
                return target_rel, qual
        if parts[0] in idx.imports and len(parts) >= 2:
            mod = idx.imports[parts[0]]
            target_rel = _module_relpath(mod)
            target = self.module(target_rel)
            qual = ".".join(parts[1:])
            if target is not None and qual in target.functions:
                return target_rel, qual
        return None

    def call_graph_dump(self) -> str:
        """Debug rendering for ``--call-graph-dump``: thread roots and
        resolved edges per module."""
        out: List[str] = []
        for idx in self.modules():
            if not idx.functions:
                continue
            out.append(f"== {idx.relpath} ==")
            for qn, kind in sorted(idx.thread_roots.items()):
                out.append(f"  root[{kind}] {qn}")
            for caller in sorted(idx.calls):
                for callee in sorted(idx.calls[caller]):
                    out.append(f"  {caller or '<module>'} -> {callee}")
        return "\n".join(out)


def build_project_index(files: Dict[str, str]) -> ProjectIndex:
    """Fixture entry point: build a ProjectIndex from {relpath: source}."""
    contexts = {
        rel: FileContext(rel, rel, text) for rel, text in sorted(files.items())
    }
    return ProjectIndex(contexts)


#: repo-root files/dirs pulled in as read-only context for the project pass
PROJECT_CONTEXT_GLOBS = ("tools", "tests", "bench.py")
#: the project pass only makes sense when the fleet plane is in the scan set
PROJECT_SENTINEL = "relora_tpu/obs/fleet.py"
