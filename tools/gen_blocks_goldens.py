"""Generate bit-parity goldens for build_blocks_mapping from the REFERENCE's
own compiled module.

Compiles /root/reference/peft_pretraining/megatron_dataset/helpers.cpp (in a
temp dir, against the pybind11 headers torch ships) and records its
build_blocks_mapping outputs for a spread of configurations into
tests/golden/blocks_mapping_*.npz.  The committed goldens let the test suite
assert byte-identity without needing the reference or a compiler at test
time.

Usage: python tools/gen_blocks_goldens.py
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import sysconfig
import tempfile

import numpy as np

REF_SRC = "/root/reference/peft_pretraining/megatron_dataset/helpers.cpp"
OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "golden")


def compile_reference(tmp: str):
    import torch

    torch_inc = os.path.join(os.path.dirname(torch.__file__), "include")
    np_inc = np.get_include()
    py_inc = sysconfig.get_paths()["include"]
    # the reference's PYBIND11_MODULE name is "helpers" — the .so and the
    # import name must match it
    so = os.path.join(tmp, "helpers.so")
    src = os.path.join(tmp, "helpers.cpp")
    shutil.copy(REF_SRC, src)
    subprocess.run(
        [
            "g++", "-O3", "-Wall", "-shared", "-std=c++11", "-fPIC", src, "-o", so,
            f"-I{torch_inc}", f"-I{np_inc}", f"-I{py_inc}",
        ],
        check=True,
    )
    import importlib.util

    spec = importlib.util.spec_from_file_location("helpers", so)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def cases():
    rs = np.random.RandomState(42)
    # (name, n_docs, sent range, size range, epochs, max_samples, seq, seed, one_sent, titles)
    yield "basic", 30, (2, 9), (5, 80), 2, 10_000, 128, 7, False, (0, 1)
    yield "titles", 25, (1, 7), (5, 60), 3, 10_000, 96, 13, False, (0, 30)
    yield "one_sent", 40, (1, 5), (5, 50), 2, 10_000, 64, 101, True, (0, 8)
    yield "budget", 50, (3, 10), (10, 100), 5, 40, 256, 3, False, (0, 5)
    yield "long_sent", 20, (2, 6), (400, 600), 2, 10_000, 1024, 9, False, (0, 2)


def main() -> None:
    os.makedirs(OUT_DIR, exist_ok=True)
    with tempfile.TemporaryDirectory() as tmp:
        ref = compile_reference(tmp)
        rs = np.random.RandomState(0)
        for name, n_docs, sents, szs, epochs, max_s, seq, seed, one_sent, trange in cases():
            counts = rs.randint(*sents, size=n_docs)
            docs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
            sizes = rs.randint(*szs, size=int(docs[-1])).astype(np.int32)
            titles = rs.randint(*trange, size=n_docs).astype(np.int32)
            expected = np.asarray(
                ref.build_blocks_mapping(
                    docs, sizes, titles, epochs, max_s, seq, seed, False, one_sent
                )
            )
            out = os.path.join(OUT_DIR, f"blocks_mapping_{name}.npz")
            np.savez_compressed(
                out,
                docs=docs, sizes=sizes, titles=titles,
                num_epochs=epochs, max_num_samples=max_s, max_seq_length=seq,
                seed=seed, use_one_sent_blocks=one_sent, expected=expected,
            )
            print(f"{name}: {expected.shape[0]} rows dtype={expected.dtype} -> {out}")


if __name__ == "__main__":
    main()
