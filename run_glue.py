"""GLUE fine-tuning CLI — the reference run_glue.py equivalent.

Fine-tunes a (ReLoRA-)pretrained checkpoint on a GLUE task and reports the
task metrics.  Example::

    python run_glue.py --task_name sst2 --model_config llama_250m \
        --checkpoint ckpts/relora/model_20000 --tokenizer t5-base \
        --batch_size 32 --num_epochs 3 --max_length 128
"""

from __future__ import annotations

import argparse
import json
import os


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--task_name", required=True)
    p.add_argument("--model_config", required=True)
    p.add_argument("--checkpoint", default=None, help="relora-tpu checkpoint dir (model_N)")
    p.add_argument("--tokenizer", required=True)
    p.add_argument("--lr", type=float, default=2e-5)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--num_epochs", type=int, default=3)
    p.add_argument("--max_length", type=int, default=128)
    p.add_argument("--weight_decay", type=float, default=0.01)
    p.add_argument("--use_lora", default=False, type=lambda x: str(x).lower() == "true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_train_samples", type=int, default=None)
    args = p.parse_args(argv)

    from relora_tpu.utils.logging import honor_platform_request

    honor_platform_request()

    import datasets
    import numpy as np
    from transformers import AutoTokenizer

    from relora_tpu.config.model import load_model_config
    from relora_tpu.eval.glue import GlueConfig, TASK_TO_KEYS, finetune

    model_cfg = load_model_config(args.model_config)
    gcfg = GlueConfig(
        task=args.task_name,
        lr=args.lr,
        batch_size=args.batch_size,
        num_epochs=args.num_epochs,
        max_length=args.max_length,
        weight_decay=args.weight_decay,
        use_lora=args.use_lora,
        seed=args.seed,
    )

    tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)
    if tokenizer.pad_token_id is None:
        tokenizer.pad_token = tokenizer.eos_token
    key1, key2 = TASK_TO_KEYS[args.task_name]
    raw = datasets.load_dataset("glue", args.task_name)
    eval_split = "validation_matched" if args.task_name == "mnli" else "validation"

    def encode(split, limit=None):
        ds = raw[split]
        if limit:
            ds = ds.select(range(min(limit, len(ds))))
        enc = tokenizer(
            *( [ds[key1], ds[key2]] if key2 else [ds[key1]] ),
            truncation=True,
            max_length=args.max_length,
            padding="max_length",
        )
        ids = np.asarray(enc["input_ids"], dtype=np.int32)
        labels = np.asarray(ds["label"])
        return ids, labels

    train_ids, train_labels = encode("train", args.max_train_samples)
    eval_ids, eval_labels = encode(eval_split)

    bs = args.batch_size
    steps_per_epoch = len(train_ids) // bs

    def train_batches():
        rs = np.random.RandomState(args.seed)
        order = rs.permutation(len(train_ids))
        for i in range(steps_per_epoch):
            sel = order[i * bs : (i + 1) * bs]
            yield train_ids[sel], train_labels[sel]

    def eval_batches():
        for i in range(0, len(eval_ids) - bs + 1, bs):
            yield eval_ids[i : i + bs], eval_labels[i : i + bs]

    pretrained = None
    if args.checkpoint:
        from relora_tpu.train.checkpoint import restore_params_host

        pretrained = restore_params_host(args.checkpoint)

    metrics = finetune(
        model_cfg,
        gcfg,
        train_batches,
        eval_batches,
        steps_per_epoch,
        pad_token_id=tokenizer.pad_token_id,
        pretrained_backbone=pretrained,
    )
    print(json.dumps({"task": args.task_name, **metrics}))


if __name__ == "__main__":
    main()
