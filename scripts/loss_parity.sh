#!/usr/bin/env bash
# Loss-parity experiment (BASELINE.md quality target): ReLoRA vs full-rank
# at matched tokens, llama_35m on a ~100M-token local corpus.
#
# Mirrors the reference recipe structure (README.md:69-89): a shared
# full-rank warmup, then two branches from the same checkpoint —
#   A) full-rank continuation, lr 1e-3 cosine
#   B) ReLoRA r=128, merge+reset every 1000 steps, lr 2e-3 cosine_restarts
#      (the "2x full-rank lr" rule, README.md:19-20)
# Both train to the same total step count / token count; compare eval loss.
#
# Prereq: python tools/build_text_corpus.py --out $CORPUS ... (see README)
set -euo pipefail
cd "$(dirname "$0")/.."

CORPUS="${CORPUS:-/tmp/corpus/local400}"
WORK="${WORK:-/tmp/loss_parity}"
STEPS_WARMUP="${STEPS_WARMUP:-1000}"
STEPS_TOTAL="${STEPS_TOTAL:-8000}"
BATCH="${BATCH:-24}"
SEQ="${SEQ:-512}"
mkdir -p "$WORK"

cat > "$WORK/data.yaml" <<EOF
data_path: $CORPUS
split: "95,4,1"
seq_length: $SEQ
seed: 0
data_impl: mmap
EOF

common=(--megatron_dataset_config "$WORK/data.yaml" --model_config llama_35m
        --batch_size "$BATCH" --total_batch_size "$BATCH" --max_length "$SEQ"
        --dtype bfloat16 --eval_every 500 --eval_tokens_during_training 500000
        --keep_checkpoints 2 --seed 0)

if [ ! -d "$WORK/warmup/model_$STEPS_WARMUP" ]; then
  echo "=== stage 1: shared full-rank warmup ($STEPS_WARMUP steps) ==="
  python main.py "${common[@]}" --lr 1e-3 --scheduler cosine \
      --warmup_steps 250 --cycle_length "$STEPS_WARMUP" --min_lr_ratio 0.9 \
      --num_training_steps "$STEPS_WARMUP" --save_every "$STEPS_WARMUP" \
      --save_dir "$WORK/warmup"
fi

echo "=== stage 2a: full-rank branch (to $STEPS_TOTAL steps) ==="
# warm-started schedules run over the REMAINING steps (trainer.py:242-251)
python main.py "${common[@]}" --lr 1e-3 --scheduler cosine \
    --warmup_steps 250 --cycle_length "$((STEPS_TOTAL - STEPS_WARMUP))" \
    --warmed_up_model "$WORK/warmup/model_$STEPS_WARMUP" \
    --num_training_steps "$STEPS_TOTAL" --save_every 4000 \
    --save_dir "$WORK/full_rank" --autoresume true

echo "=== stage 2b: ReLoRA branch (to $STEPS_TOTAL steps) ==="
python main.py "${common[@]}" --lr 2e-3 --use_peft true --lora_r 128 \
    --relora 1000 --cycle_length 1000 --scheduler cosine_restarts \
    --warmup_steps 250 --restart_warmup_steps 100 \
    --reset_optimizer_on_relora true \
    --warmed_up_model "$WORK/warmup/model_$STEPS_WARMUP" \
    --num_training_steps "$STEPS_TOTAL" --save_every 4000 \
    --save_dir "$WORK/relora" --autoresume true

echo "=== results ==="
python tools/compare_runs.py full_rank="$WORK/full_rank" relora="$WORK/relora"
