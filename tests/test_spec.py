"""Speculative decoding on the paged engine: draft K via prompt lookup,
verify in one ``(batch, K+1)`` forward, commit the longest accepted prefix.

The contract under test is the parity oracle: a greedy drain through
``spec="ngram"`` must be **token-identical** to the non-speculative paged
drain for the same request stream — acceptance is argmax match, so every
committed token is exactly what step-by-step decode would have produced.
Sampled rows are not token-pinned (the residual/bonus draws consume a
different fold of the same ``(uid, token_index)`` key) but their committed
marginal must equal the filtered target distribution ``sample()`` draws
from, which ``test_spec_verify_draws_sampled_marginal`` pins by Monte Carlo.
Page-accounting invariants under speculation live in tests/test_paging.py.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import init_params
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.sampling import spec_verify_draws, top_k_mask, top_p_mask
from relora_tpu.serve.scheduler import PagedContinuousBatchingScheduler, Request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.serve, pytest.mark.spec]

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)


def make_paged_pair(cfg, *, cache_size=32, spec_k=4, page_size=8, chunk_size=8):
    """Two paged engines over the SAME params: plain, and spec_k-enabled."""
    model = build_decode_model(cfg, cache_size=cache_size)
    base = type(model)(cfg, lora=None, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    kw = dict(
        cache_size=cache_size,
        page_size=page_size,
        num_pages=3 * (cache_size // page_size) + 1,
        chunk_size=chunk_size,
    )
    plain = InferenceEngine(cfg, params, **kw)
    spec = InferenceEngine(cfg, params, spec_k=spec_k, **kw)
    return plain, spec


def spec_requests(vocab):
    """Greedy rows with self-repeating prompts (the prompt-lookup regime),
    one greedy random prompt (drafting may never fire: fallback shape), and
    a sampled row — staggered through max_batch=2 slots."""
    rng = np.random.default_rng(7)
    return [
        Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=8),
        Request(uid=2, prompt=rng.integers(1, vocab, 13).tolist(), max_new_tokens=6),
        Request(uid=3, prompt=[2, 4] * 6, max_new_tokens=7, temperature=0.8, top_p=0.9),
        Request(uid=4, prompt=rng.integers(1, vocab, 5).tolist(), max_new_tokens=5),
    ]


def drain(engine, reqs, **kwargs):
    sched = PagedContinuousBatchingScheduler(
        engine, max_batch=2, eos_id=9, key=jax.random.PRNGKey(42), **kwargs
    )
    completions = sched.run(reqs)
    return sched, {uid: c.tokens for uid, c in completions.items()}


# -- the drafter --------------------------------------------------------------


def test_ngram_draft_prompt_lookup():
    _, eng = make_paged_pair(TINY_LLAMA)
    sched = PagedContinuousBatchingScheduler(eng, max_batch=2, spec="ngram")
    # longest suffix n-gram that recurs wins; proposal is what followed it
    assert sched._ngram_draft([1, 2, 3, 4, 2, 3], 3) == [4, 2, 3]
    # most recent earlier occurrence wins over an older one
    assert sched._ngram_draft([7, 9, 1, 7, 9, 2, 7, 9], 2) == [2, 7]
    # proposal is capped at k and at the end of the context
    assert sched._ngram_draft([1, 2, 3, 4, 2, 3], 1) == [4]
    assert sched._ngram_draft([5, 6, 5, 6], 8) == [5, 6]
    # no recurrence, no draft — and degenerate inputs stay empty
    assert sched._ngram_draft([1, 2, 3, 4, 5], 4) == []
    assert sched._ngram_draft([1, 2, 3], 0) == []
    assert sched._ngram_draft([1], 4) == []


# -- the verify sampler -------------------------------------------------------


def test_spec_verify_draws_greedy_exact():
    """temperature<=0 rows: accept iff the draft equals the row argmax, and
    the corrective token is the argmax — no randomness anywhere."""
    key = jax.random.PRNGKey(5)
    logits = jax.random.normal(key, (2, 3, 16), jnp.float32)
    am = np.asarray(jnp.argmax(logits, axis=-1))
    draft = np.array([[am[0, 0], 11], [3, am[1, 1]]], np.int32)  # mixed hits
    accept, alt = spec_verify_draws(
        logits,
        jnp.asarray(draft),
        jax.random.PRNGKey(42),
        jnp.array([1, 2], jnp.int32),
        jnp.array([0, 4], jnp.int32),
        jnp.array([2, 2], jnp.int32),
        temperature=jnp.zeros(2),
    )
    np.testing.assert_array_equal(np.asarray(accept), am[:, :2] == draft)
    np.testing.assert_array_equal(np.asarray(alt), am)


def test_spec_verify_draws_sampled_marginal():
    """Rejection sampling with a deterministic proposal: committed token =
    draft if u < p(draft) else residual sample — the marginal over many
    independent (uid, index) streams must equal the filtered target
    distribution, and never land outside its support."""
    V, N = 12, 20000
    row = jax.random.normal(jax.random.PRNGKey(9), (V,), jnp.float32) * 2.0
    temp, top_k, top_p = 0.7, 5, 0.9
    # the target distribution exactly as sample() builds it
    filtered = top_p_mask(top_k_mask(row[None, :], top_k), jnp.asarray([top_p]))
    target = np.asarray(jax.nn.softmax(filtered / temp, axis=-1))[0]
    d = int(np.argsort(target)[-2])  # a mid-probability in-support draft

    logits = jnp.broadcast_to(row, (N, 2, V))
    accept, alt = spec_verify_draws(
        logits,
        jnp.full((N, 1), d, jnp.int32),
        jax.random.PRNGKey(0),
        jnp.arange(N, dtype=jnp.int32),
        jnp.zeros(N, jnp.int32),
        jnp.ones(N, jnp.int32),
        temperature=jnp.full(N, temp),
        top_k=top_k,
        top_p=top_p,
    )
    committed = np.where(np.asarray(accept)[:, 0], d, np.asarray(alt)[:, 0])
    emp = np.bincount(committed, minlength=V) / N
    # accept rate is p(draft) itself (deterministic proposal), marginal is
    # the target; 20k draws put the per-token noise well under 0.02
    assert np.asarray(accept)[:, 0].mean() == pytest.approx(target[d], abs=0.02)
    np.testing.assert_allclose(emp, target, atol=0.02)
    assert emp[target < 1e-12].sum() == 0.0  # filtered-out tokens never appear


# -- the parity oracle --------------------------------------------------------


@pytest.mark.parametrize("cfg", [TINY_LLAMA, TINY_NEOX], ids=["llama", "neox"])
def test_greedy_spec_drain_token_identical(cfg):
    """Acceptance: greedy requests through the speculative scheduler emit
    exactly the tokens the non-speculative paged drain emits — staggered
    admissions, drafting rows sharing batches with fallback rows."""
    plain, spec_eng = make_paged_pair(cfg)
    reqs = spec_requests(cfg.vocab_size)
    _, want = drain(plain, reqs)
    sched, got = drain(spec_eng, reqs, spec="ngram")
    for uid in (1, 2, 4):  # the greedy rows are token-pinned
        assert got[uid] == want[uid], f"uid {uid}"
    # the sampled row is distribution-pinned, not token-pinned: just sane
    assert got[3] and all(0 <= t < cfg.vocab_size for t in got[3])
    stats = sched.spec_stats()
    assert stats["mode"] == "ngram" and stats["k"] == 4
    assert stats["drafted"] > 0  # the repetitive prompts did draft
    assert 0 <= stats["accepted"] <= stats["drafted"]
    assert stats["accept_rate"] == pytest.approx(
        stats["accepted"] / max(stats["drafted"], 1), abs=1e-3
    )
    # every request page released once the prefix cache lets go
    if sched.prefix_cache is not None:
        sched.prefix_cache.clear()
    assert sched.allocator.used_pages == 0


@pytest.mark.slow
def test_spec_multi_token_commits_on_repetitive_generation():
    """A prompt the model answers with a loop: speculation must actually
    accept (multi-token commits), and the output still matches non-spec."""
    plain, spec_eng = make_paged_pair(TINY_LLAMA, cache_size=64)
    reqs = [
        Request(uid=1, prompt=[3, 5, 7] * 5, max_new_tokens=40),
        Request(uid=2, prompt=[2, 4] * 7, max_new_tokens=40),
    ]
    _, want = drain(plain, reqs)
    sched, got = drain(spec_eng, reqs, spec="ngram")
    assert got == want
    stats = sched.spec_stats()
    assert stats["accepted"] > 0, stats  # real multi-token commits happened
    # accepted drafts shrink the step count below one-per-token
    total = sum(len(t) for t in want.values())
    assert sched._step_count < total / 2 + len(reqs) * 4


@pytest.mark.slow
def test_request_spec_false_opts_out():
    """Per-request opt-out: spec=False rows never draft, so the round takes
    the plain decode shape and output matches non-spec exactly (sampled
    included — same keys, same sampler)."""
    plain, spec_eng = make_paged_pair(TINY_LLAMA)
    reqs = [
        Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=6, spec=False),
        Request(uid=2, prompt=[2, 4] * 5, max_new_tokens=6, temperature=0.9, spec=False),
    ]
    _, want = drain(plain, reqs)
    sched, got = drain(spec_eng, reqs, spec="ngram")
    assert got == want
    assert sched.spec_stats()["drafted"] == 0


# -- compile discipline -------------------------------------------------------


def test_spec_warmup_shapes_and_no_retrace():
    """Warmup compiles all three shapes (chunk, decode, verify); a drain
    mixing drafting rounds with fallback rounds then retraces nothing."""
    _, spec_eng = make_paged_pair(TINY_LLAMA)
    report = spec_eng.warmup(2)
    assert report["shapes"]["decode_paged"] == [2, 1]
    assert report["shapes"]["verify_paged"] == [2, 5]
    assert report["spec_k"] == 4
    sched = PagedContinuousBatchingScheduler(
        spec_eng, max_batch=2, eos_id=9, key=jax.random.PRNGKey(42), spec="ngram"
    )
    # one prompt-lookup row (drafts -> verify shape) + one random row
    # (never drafts -> fallback decode shape) is the full shape mix
    sched.run(spec_requests(TINY_LLAMA.vocab_size)[:2])
    assert spec_eng.compile_watcher.steady_state_retraces == 0


@pytest.mark.slow
def test_spec_memory_plans_include_verify():
    _, spec_eng = make_paged_pair(TINY_LLAMA)
    plans = spec_eng.memory_plans(2)
    assert "verify_paged" in plans


# -- configuration guards -----------------------------------------------------


def test_spec_configuration_guards():
    plain, spec_eng = make_paged_pair(TINY_LLAMA)
    with pytest.raises(ValueError, match="spec_k >= 1"):
        PagedContinuousBatchingScheduler(plain, max_batch=2, spec="ngram")
    with pytest.raises(ValueError, match="spec must be"):
        PagedContinuousBatchingScheduler(spec_eng, max_batch=2, spec="lookahead")
    with pytest.raises(ValueError, match="requires the paged engine"):
        InferenceEngine(TINY_LLAMA, spec_eng.params, cache_size=32, spec_k=4)


@pytest.mark.slow
def test_cli_spec_requires_paged():
    """serve.py refuses --spec without --paged (the verify window writes
    through block tables), and --spec with a degenerate --spec-k."""
    sys.path.insert(0, ROOT)
    import serve

    common = [
        "--model_config", "llama_9m",
        "--random-init",
        "--cache-size", "64",
        "--prompt", "1 2 3",
        "--max-new-tokens", "2",
    ]
    with pytest.raises(SystemExit, match="requires --paged"):
        serve.main(common + ["--spec", "ngram"])
    with pytest.raises(SystemExit, match="spec-k"):
        serve.main(common + ["--paged", "--spec", "ngram", "--spec-k", "0"])
