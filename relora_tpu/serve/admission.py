"""Bounded admission and serving metrics for the HTTP front-end.

The scheduler (serve/scheduler.py) is single-threaded: one model thread owns
``submit``/``step``/``cancel``.  This module is everything that crosses the
thread boundary between the asyncio request handlers and that model thread:

- ``AdmissionController`` — the *only* waiting room between the network and
  the decode slots.  A ``queue.Queue(maxsize=max_queue)`` holds tickets the
  model thread has not yet claimed; when it is full, ``try_admit`` raises
  ``QueueFull`` and the server answers **429 + Retry-After** instead of
  buffering without bound.  ``begin_drain()`` flips the controller into
  drain mode (SIGTERM): new admissions raise ``Draining`` (**503**) while
  already-accepted tickets keep flowing to the model thread — the same
  request-a-stop-honor-it-at-the-boundary shape as
  ``train/resilience.PreemptionGuard``, with the decode step as the
  boundary.
- ``Ticket`` — one accepted request plus its cross-thread plumbing: token /
  finish callbacks (which hop onto the event loop via
  ``loop.call_soon_threadsafe``) and a ``cancelled`` event the handler sets
  on client disconnect so the model thread can free the slot.
- ``ServeMetrics`` — the serving-flavoured view of the shared
  :class:`relora_tpu.obs.metrics.MetricsRegistry` (thread-safe counters,
  gauges, and fixed-bucket histograms behind the ``/metrics`` endpoint),
  fed from both sides: handlers count requests and rejects, the model
  thread observes TTFT / per-token latency and updates the queue/slot
  gauges every step.

Everything here is stdlib-only and jax-free, like relora_tpu/analysis — the
front-end must import fast and run anywhere the linter runs.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Callable, Optional

from relora_tpu.obs.metrics import LATENCY_BUCKETS, Histogram, MetricsRegistry
from relora_tpu.serve.scheduler import Completion, Request

__all__ = [
    "QueueFull",
    "Draining",
    "Ticket",
    "AdmissionController",
    "ServeMetrics",
    "LATENCY_BUCKETS",  # re-exported from obs.metrics for existing importers
    "Histogram",
]


class QueueFull(Exception):
    """Admission queue at capacity — shed load (HTTP 429)."""


class Draining(Exception):
    """Server is draining (SIGTERM) — reject new work (HTTP 503)."""


@dataclasses.dataclass
class Ticket:
    """One accepted request en route to the model thread."""

    uid: int
    request: Request
    deadline: Optional[float]  # absolute time.monotonic(), None = no limit
    on_token: Callable[[int, int, int], None]
    on_finish: Callable[[Completion], None]
    cancelled: threading.Event = dataclasses.field(default_factory=threading.Event)
    t_enqueue: float = dataclasses.field(default_factory=time.monotonic)
    t_last_token: Optional[float] = None  # model thread only; TPOT bookkeeping
    trace_id: Optional[str] = None  # request id; X-Request-Id + span trace_id
    span: Optional[Any] = None  # root "request" span; ended at finish
    queue_span: Optional[Any] = None  # "queue_wait": admit -> model-thread claim


class AdmissionController:
    """Bounded, drain-aware handoff from request handlers to the model thread.

    ``try_admit`` (any thread) assigns the uid, enforces the bound, and
    enqueues; ``pop`` (model thread) claims the next ticket.  The bound
    covers only requests *waiting* for a slot — the model thread claims a
    ticket when a decode slot is free, so total in-system work is
    ``max_batch`` decoding + ``max_queue`` waiting, both fixed.
    """

    #: Retry-After never exceeds this; a longer hint just loses the client.
    RETRY_AFTER_CAP_S = 30.0

    def __init__(
        self, max_queue: int, *, retry_after_s: float = 1.0, uid_base: int = 0
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.retry_after_floor_s = retry_after_s
        self._q: "queue.Queue[Ticket]" = queue.Queue(maxsize=max_queue)
        # uid_base makes fleet replicas' uid spaces disjoint: a migrated
        # request keeps its donor uid (the sampling keys fold it in), so the
        # receiver's own counter must never mint the same value
        self._uids = itertools.count(uid_base)
        self._draining = threading.Event()
        self._tpot_ewma: Optional[float] = None  # model thread writes, any reads

    @property
    def retry_after_s(self) -> float:
        """Load-aware Retry-After hint: the time for the current queue to
        clear at the observed decode rate (queue depth × rolling TPOT),
        clamped to ``[max(1, floor), RETRY_AFTER_CAP_S]``.  Before any token
        has been observed (cold server) it falls back to the floor — the old
        fixed behaviour."""
        floor = max(1.0, self.retry_after_floor_s)
        if self._tpot_ewma is None:
            return floor
        estimate = self._q.qsize() * self._tpot_ewma
        return min(max(floor, estimate), self.RETRY_AFTER_CAP_S)

    def note_tpot(self, seconds: float) -> None:
        """Model thread: fold one observed per-token latency into the rolling
        TPOT estimate behind :attr:`retry_after_s`."""
        if seconds <= 0.0:
            return
        if self._tpot_ewma is None:
            self._tpot_ewma = seconds
        else:
            self._tpot_ewma = 0.8 * self._tpot_ewma + 0.2 * seconds

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        self._draining.set()

    def depth(self) -> int:
        return self._q.qsize()

    def next_uid(self) -> int:
        return next(self._uids)

    def try_admit(self, ticket: Ticket) -> Ticket:
        """Enqueue or reject — never block, never buffer beyond the bound."""
        if self._draining.is_set():
            raise Draining("server is draining; not accepting new requests")
        try:
            self._q.put_nowait(ticket)
        except queue.Full:
            raise QueueFull(
                f"admission queue full ({self.max_queue} waiting); retry after "
                f"{self.retry_after_s:.0f}s"
            ) from None
        return ticket

    def pop(self, timeout: Optional[float] = None) -> Optional[Ticket]:
        """Model thread: claim the next waiting ticket, or None on timeout
        (``timeout=None`` polls without blocking)."""
        try:
            if timeout is None:
                return self._q.get_nowait()
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


# -- metrics -----------------------------------------------------------------
# Histogram / LATENCY_BUCKETS / the registry implementation live in
# relora_tpu.obs.metrics (shared with the trainer); re-exported above.


class ServeMetrics(MetricsRegistry):
    """Serving metrics: the shared registry under the ``relora_serve``
    namespace.  ``render()``/``snapshot()``/counter semantics are the
    registry's — the ``/metrics`` body is byte-identical to the
    pre-extraction renderer (pinned by tests/test_obs.py's golden test)."""

    def __init__(self, namespace: str = "relora_serve"):
        super().__init__(namespace=namespace)
