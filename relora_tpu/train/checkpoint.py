"""Checkpoint / resume: Orbax-backed sharded state + reference-schema JSON.

Reference checkpoint dir ``model_{update_step}`` holds the HF model files,
``optimizer.pt``, ``relora_config.json`` and ``training_state.json``
(torchrun_main.py:192-225, 256-273).  Here each ``model_{step}`` dir holds:

- ``state/``               — Orbax checkpoint of the full TrainState
  (params + optimizer state + step counters), saved **sharded**: every host
  writes its own shards (the reference funnels everything through rank 0 and
  notes it as a limitation, torchrun_main.py:508).
- ``training_state.json``  — the reference's counter schema, unchanged
  (global_step, update_step, tokens_seen, tokens_seen_before,
  n_lora_restarts, n_optimizer_resets, update_time, wandb_id).
- ``relora_config.json``   — LoraSpec (parity: relora.py:149-152).

Resume modes (parity: §3.5 of SURVEY.md):
- ``autoresume``    — find latest ``model_*`` in save_dir
  (training_utils.py:248-264).
- ``resume_from``   — explicit dir: full state restore.
- ``warmed_up_model`` — weights + counters only, fresh optimizer
  (torchrun_main.py:505-527).
Retention: ``delete_old_checkpoints`` keeps the newest N
(training_utils.py:406-418).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Mapping, Optional, Tuple

import jax

from relora_tpu.core.relora import LoraSpec
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PyTree = Any

STATE_SUBDIR = "state"
TRAINING_STATE_FILE = "training_state.json"
RELORA_CONFIG_FILE = "relora_config.json"


_CKPTR = None


def _checkpointer():
    # one process-wide async checkpointer: StandardCheckpointer is an
    # AsyncCheckpointer — save() returns after the (blocking) device->host
    # copy and writes to disk in a background thread, so the train loop only
    # stalls for the copy, not the serialize+write (SURVEY.md §7: Orbax
    # async).  A singleton keeps one background thread pool and lets
    # wait_for_save() fence all pending writes.
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def wait_for_save() -> None:
    """Block until every initiated async checkpoint write has committed."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()


def checkpoint_dir(save_dir: str, update_step: int) -> str:
    return os.path.join(save_dir, f"model_{update_step}")


def save_checkpoint(
    save_dir: str,
    update_step: int,
    state: PyTree,
    training_state: dict,
    lora_spec: Optional[LoraSpec] = None,
) -> str:
    """Write one checkpoint dir; returns its path.  Safe to call from every
    process — Orbax coordinates the multi-host write; JSON goes from
    process 0 only."""
    path = checkpoint_dir(save_dir, update_step)
    os.makedirs(path, exist_ok=True)
    ckptr = _checkpointer()
    # fence the previous in-flight save (usually a no-op: saves are far
    # apart), then initiate this one — save() returns after the d2h copy,
    # the disk write proceeds in the background.  Orbax writes to a tmp dir
    # and renames on commit, so ``state/`` appears atomically.
    ckptr.wait_until_finished()
    state_path = os.path.abspath(os.path.join(path, STATE_SUBDIR))
    if os.path.exists(state_path):
        shutil.rmtree(state_path)
    ckptr.save(state_path, state)
    if jax.process_index() == 0:
        with open(os.path.join(path, TRAINING_STATE_FILE), "w") as f:
            json.dump(training_state, f, indent=2)
        if lora_spec is not None:
            with open(os.path.join(path, RELORA_CONFIG_FILE), "w") as f:
                json.dump(dataclasses.asdict(lora_spec), f, indent=2)
    logger.info(f"Saving checkpoint to {path} (async)")
    return path


def restore_checkpoint(path: str, abstract_state: PyTree) -> PyTree:
    """Restore a TrainState saved by ``save_checkpoint``.

    ``abstract_state`` — e.g. ``jax.eval_shape(lambda: state)`` with sharding
    annotations — tells Orbax the target shapes/shardings, so restore places
    shards directly on the mesh."""
    ckptr = _checkpointer()
    ckptr.wait_until_finished()  # same-process restore right after a save
    return ckptr.restore(os.path.abspath(os.path.join(path, STATE_SUBDIR)), abstract_state)


def restore_state_host(path: str) -> PyTree:
    """Template-free restore of the full saved state as host numpy arrays.

    Works regardless of the current device topology (every leaf is forced to
    numpy instead of the recorded shardings) — for warm starts and offline
    tools."""
    import numpy as np
    import orbax.checkpoint as ocp

    wait_for_save()  # same-process restore right after a save
    state_path = os.path.abspath(os.path.join(path, STATE_SUBDIR))
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"no checkpoint state at {state_path}")
    ckptr = ocp.PyTreeCheckpointer()
    item_metadata = ckptr.metadata(state_path).item_metadata
    if item_metadata is None:
        raise FileNotFoundError(f"checkpoint at {state_path} has no readable metadata")
    restore_args = jax.tree_util.tree_map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), item_metadata.tree
    )
    return ckptr.restore(state_path, restore_args=restore_args)


def restore_params_host(path: str) -> PyTree:
    """Just the params subtree of ``restore_state_host`` (the saved tree —
    e.g. full-rank with its own optimizer — may deliberately differ from the
    new run's state shape)."""
    restored = restore_state_host(path)
    if isinstance(restored, Mapping) and "params" in restored:
        return restored["params"]
    return restored


def load_training_state(path: str) -> dict:
    with open(os.path.join(path, TRAINING_STATE_FILE)) as f:
        return json.load(f)


def load_lora_spec(path: str) -> Optional[LoraSpec]:
    p = os.path.join(path, RELORA_CONFIG_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return LoraSpec(**json.load(f))


def get_last_checkpoint(save_dir: str) -> Tuple[Optional[dict], Optional[str]]:
    """Find the newest ``model_{step}`` dir and its training_state.json
    (parity: training_utils.get_last_training_state :248-264)."""
    if not os.path.isdir(save_dir):
        return None, None
    dirs = _committed_checkpoints(save_dir)
    if not dirs:
        logger.warning(f"Save directory {save_dir} exists but has no checkpoints; starting fresh")
        return None, None
    path = os.path.join(save_dir, dirs[-1])
    return load_training_state(path), path


def _committed_checkpoints(save_dir: str) -> list:
    """``model_*`` dirs with a committed ``state/`` (Orbax renames the tmp dir
    into place on commit), sorted by step.  An async save that died mid-write
    leaves the JSON but no ``state/`` — those are invisible to both the
    autoresume probe and retention."""
    dirs = [
        d
        for d in os.listdir(save_dir)
        if d.startswith("model_")
        and os.path.isdir(os.path.join(save_dir, d, STATE_SUBDIR))
    ]
    dirs.sort(key=lambda d: int(d.split("_")[-1]))
    return dirs


def delete_old_checkpoints(save_dir: str, keep: Optional[int]) -> None:
    """Keep the newest N checkpoint dirs (parity: training_utils.py:406-418).

    Only *committed* checkpoints (renamed ``state/`` present) count toward
    the keep budget and are eligible for deletion — with async saves the
    newest dir may still be in flight, and pruning the last committed one
    against it would leave nothing restorable if the process dies before
    the write commits."""
    if keep is None or jax.process_index() != 0:
        return
    dirs = _committed_checkpoints(save_dir)
    if len(dirs) <= keep:
        return
    for d in dirs[:-keep]:
        full = os.path.join(save_dir, d)
        logger.info(f"Deleting old checkpoint {full}")
        shutil.rmtree(full)
