"""Paged KV cache tests: allocator/prefix-cache bookkeeping, chunked-prefill
parity, and the acceptance oracle for the paged scheduler — a drain through
``PagedContinuousBatchingScheduler`` must be **token-identical** to the
contiguous ``ContinuousBatchingScheduler`` for the same request stream
(greedy and sampled, staggered admissions, early EOS), because the paged
attention gather reconstructs the contiguous contraction exactly and
sampling keys stay ``(uid, token_index)``.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import init_params
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.paging import NULL_PAGE, PageAllocator, PrefixCache, pages_needed
from relora_tpu.serve.scheduler import (
    ContinuousBatchingScheduler,
    PagedContinuousBatchingScheduler,
    Request,
)
from relora_tpu.utils.logging import MetricsLogger

pytestmark = pytest.mark.serve

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)


# -- host-side bookkeeping ----------------------------------------------------


def test_pages_needed():
    assert pages_needed(1, 8) == 1
    assert pages_needed(8, 8) == 1
    assert pages_needed(9, 8) == 2
    assert pages_needed(0, 8) == 0


class TestPageAllocator:
    def test_null_page_reserved(self):
        alloc = PageAllocator(4, 8)
        pages = alloc.alloc(3)
        assert NULL_PAGE not in pages
        assert sorted(pages) == [1, 2, 3]

    def test_alloc_all_or_nothing(self):
        alloc = PageAllocator(5, 8)  # 4 usable pages
        assert alloc.alloc(3) is not None
        free_before = alloc.free_pages
        assert alloc.alloc(2) is None  # only 1 free: nothing allocated
        assert alloc.free_pages == free_before
        assert alloc.alloc(1) is not None
        assert alloc.free_pages == 0

    def test_decref_frees_incref_shares(self):
        alloc = PageAllocator(4, 8)
        [a, b] = alloc.alloc(2)
        alloc.incref([a])
        assert alloc.refcount(a) == 2
        assert alloc.decref([a, b]) == 1  # only b reached zero
        assert alloc.used_pages == 1
        assert alloc.decref([a]) == 1
        assert alloc.used_pages == 0

    def test_double_free_raises(self):
        alloc = PageAllocator(4, 8)
        [a] = alloc.alloc(1)
        alloc.decref([a])
        with pytest.raises(ValueError, match="double free"):
            alloc.decref([a])
        with pytest.raises(ValueError, match="invalid page"):
            alloc.decref([NULL_PAGE])

    def test_peak_used(self):
        alloc = PageAllocator(6, 8)
        pages = alloc.alloc(4)
        alloc.decref(pages)
        assert alloc.peak_used == 4
        assert alloc.used_pages == 0


class TestPrefixCache:
    def test_lookup_caps_below_full_prompt(self):
        """At least one prompt token must re-prefill: a prompt of exactly
        k pages only ever matches a (k-1)-page prefix."""
        alloc = PageAllocator(8, 4)
        cache = PrefixCache(alloc)
        prompt = list(range(8))  # exactly 2 pages
        pages = alloc.alloc(2)
        cache.register(prompt, pages)
        got, n = cache.lookup(prompt)
        assert n == 4 and got == pages[:1]
        alloc.decref(got)

    def test_register_lookup_roundtrip_increfs(self):
        alloc = PageAllocator(8, 4)
        cache = PrefixCache(alloc)
        prompt = list(range(10))  # 2 full pages + tail
        pages = alloc.alloc(pages_needed(10, 4))
        assert cache.register(prompt, pages) == 2
        got, n = cache.lookup(prompt + [99])
        assert n == 8 and got == pages[:2]
        # owner + the k=1 entry + the k=2 entry + lookup
        assert alloc.refcount(pages[0]) == 4
        assert alloc.refcount(pages[1]) == 3  # owner + k=2 entry + lookup
        # different tokens: no hit
        assert cache.lookup([7] * 10) == ([], 0)
        assert cache.stats()["hits"] == 1 and cache.stats()["lookups"] == 2

    def test_eviction_respects_live_refs(self):
        """Evicting an entry drops only the cache's reference: a page a live
        request still holds stays allocated."""
        alloc = PageAllocator(4, 4)
        cache = PrefixCache(alloc)
        prompt = list(range(5))
        pages = alloc.alloc(2)
        cache.register(prompt, pages)
        shared, _ = cache.lookup(prompt)  # live consumer increfs pages[0]
        freed = cache.clear()
        assert freed == 0  # owner + consumer refs keep everything alive
        alloc.decref(pages)  # owner retires
        assert alloc.refcount(shared[0]) == 1  # consumer still holds it
        assert alloc.decref(shared) == 1

    def test_lru_capacity(self):
        alloc = PageAllocator(16, 2)
        cache = PrefixCache(alloc, max_entries=2)
        for start in (0, 10, 20):
            pages = alloc.alloc(1)
            cache.register([start, start + 1, start + 2], pages)
            alloc.decref(pages)
        assert len(cache) == 2
        assert cache.lookup([0, 1, 2]) == ([], 0)  # oldest evicted
        got, _ = cache.lookup([20, 21, 22])
        assert got
        alloc.decref(got)


# -- engine: chunked prefill and memory --------------------------------------


def make_engines(cfg, *, cache_size=32, page_size=8, num_pages=None, chunk_size=8, spec_k=0):
    model = build_decode_model(cfg, cache_size=cache_size)
    base = type(model)(cfg, lora=None, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    contiguous = InferenceEngine(cfg, params, cache_size=cache_size)
    paged = InferenceEngine(
        cfg,
        params,
        cache_size=cache_size,
        page_size=page_size,
        num_pages=num_pages or 3 * (cache_size // page_size) + 1,
        chunk_size=chunk_size,
        spec_k=spec_k,
    )
    return contiguous, paged


@pytest.mark.parametrize("cfg", [TINY_LLAMA, TINY_NEOX], ids=["llama", "neox"])
@pytest.mark.parametrize("chunk", [4, 8, 32])
def test_chunked_prefill_matches_whole(cfg, chunk):
    """Driving a prompt through fixed-size prefill chunks produces the same
    logits at every real position as one whole contiguous prefill — checked
    at every chunk boundary, including the ragged last chunk."""
    contiguous, paged = make_engines(cfg, chunk_size=chunk)
    L = 13
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (L,), 0, cfg.vocab_size)
    )
    whole, _ = contiguous.prefill(jnp.asarray(prompt[None, :]))

    pool = paged.init_pool()
    table = np.zeros((1, paged.block_table_width), np.int32)
    n_pages = pages_needed(L, paged.page_size)
    table[0, :n_pages] = np.arange(1, n_pages + 1)
    for start in range(0, L, chunk):
        ids = np.zeros((1, chunk), np.int32)
        n_real = min(chunk, L - start)
        ids[0, :n_real] = prompt[start : start + n_real]
        logits, pool = paged.prefill_chunk(jnp.asarray(ids), start, pool, table)
        np.testing.assert_allclose(
            np.asarray(logits[:, :n_real]),
            np.asarray(whole[:, start : start + n_real]),
            atol=1e-5,
        )


def test_memory_plans_pool_scales_with_pages():
    """The paged kv_cache entry is the page pool: bytes scale with num_pages
    and undercut the contiguous max_batch × cache_size reservation."""
    contiguous, paged = make_engines(TINY_LLAMA, num_pages=13)
    small = paged.memory_plans(4)["pytree"]["kv_cache_bytes"]
    _, bigger = make_engines(TINY_LLAMA, num_pages=25)
    big = bigger.memory_plans(4)["pytree"]["kv_cache_bytes"]
    assert big / small == pytest.approx(25 / 13, rel=1e-6)
    contiguous_kv = contiguous.memory_plans(4)["pytree"]["kv_cache_bytes"]
    # 12 usable pages × 8 tokens = 96 cache entries vs 4 × 32 = 128
    assert small < contiguous_kv


def test_warmup_covers_all_shapes_no_retrace():
    """Paged warmup compiles the chunk + decode pair; afterwards a drain of
    mixed prompt lengths (short, page-straddling, multi-chunk) triggers no
    steady-state retrace."""
    _, paged = make_engines(TINY_LLAMA, chunk_size=8)
    report = paged.warmup(2)
    assert report["shapes"] == {"prefill_chunk": [1, 8], "decode_paged": [2, 1]}
    sched = PagedContinuousBatchingScheduler(paged, max_batch=2)
    reqs = [
        Request(uid=i, prompt=list(range(1, L + 1)), max_new_tokens=3)
        for i, L in enumerate((2, 7, 9, 17, 23))
    ]
    sched.run(reqs)
    assert paged.compile_watcher.steady_state_retraces == 0


def test_contiguous_default_warmup_covers_every_bucket():
    """Satellite: warmup's default prompt_buckets covers every power-of-two
    bucket up to capacity, so a long prompt after warmup never retraces."""
    contiguous, _ = make_engines(TINY_LLAMA)
    assert contiguous.default_prompt_buckets() == (16, 32)
    report = contiguous.warmup(2)
    assert report["prompt_buckets"] == [16, 32]
    sched = ContinuousBatchingScheduler(contiguous, max_batch=2)
    sched.run([Request(uid=0, prompt=list(range(1, 25)), max_new_tokens=4)])
    assert contiguous.compile_watcher.steady_state_retraces == 0


# -- scheduler: the token-parity oracle ---------------------------------------


def mixed_requests(vocab):
    """Mixed lengths (page-straddling + multi-chunk), greedy AND sampled,
    staggered through max_batch=2 slots, with uid 4 likely to hit EOS."""
    rng = np.random.default_rng(11)
    mk = lambda uid, L, new, **kw: Request(
        uid=uid, prompt=rng.integers(1, vocab, L).tolist(), max_new_tokens=new, **kw
    )
    return [
        mk(1, 13, 6),
        mk(2, 5, 9, temperature=0.8, top_p=0.9),
        mk(3, 21, 4),
        mk(4, 3, 7, temperature=1.1),
    ]


def drain(sched_cls, engine, reqs, **kwargs):
    sched = sched_cls(engine, max_batch=2, eos_id=9, key=jax.random.PRNGKey(42), **kwargs)
    completions = sched.run(reqs)
    return sched, {uid: c.tokens for uid, c in completions.items()}


@pytest.mark.parametrize("cfg", [TINY_LLAMA, TINY_NEOX], ids=["llama", "neox"])
def test_paged_drain_token_identical_to_contiguous(cfg):
    contiguous, paged = make_engines(cfg)
    reqs = mixed_requests(cfg.vocab_size)
    _, want = drain(ContinuousBatchingScheduler, contiguous, reqs)
    sched, got = drain(PagedContinuousBatchingScheduler, paged, reqs)
    assert got == want
    # all request pages released: only prefix-cache refs remain, and
    # clearing the cache drains the allocator completely
    sched.prefix_cache.clear()
    assert sched.allocator.used_pages == 0


def test_paged_parity_without_prefix_cache():
    contiguous, paged = make_engines(TINY_LLAMA)
    reqs = mixed_requests(TINY_LLAMA.vocab_size)
    _, want = drain(ContinuousBatchingScheduler, contiguous, reqs)
    sched, got = drain(
        PagedContinuousBatchingScheduler, paged, reqs, prefix_cache=False
    )
    assert got == want
    assert sched.allocator.used_pages == 0


def test_cancel_mid_decode_frees_pages():
    _, paged = make_engines(TINY_LLAMA)
    sched = PagedContinuousBatchingScheduler(paged, max_batch=2, prefix_cache=False)
    free0 = sched.allocator.free_pages
    sched.submit(Request(uid=1, prompt=[1, 2, 3, 4, 5], max_new_tokens=8))
    sched.submit(Request(uid=2, prompt=[6, 7, 8], max_new_tokens=8))
    for _ in range(3):  # both prefilled, a few decode steps in
        sched.step()
    assert sched.active_slots == 2
    completion = sched.cancel(1)
    assert completion.finish_reason == "cancelled" and completion.tokens
    assert sched.allocator.free_pages == free0 - pages_needed(
        3 + 8, paged.page_size
    )
    while sched.has_work():
        sched.step()
    assert sched.allocator.free_pages == free0  # pinned: no page leaked


def test_pool_exhaustion_queues_fifo():
    """When the pool cannot cover the queue head, it stays queued — FIFO, no
    skip-ahead — and admits once the running request retires."""
    # 5 usable pages of 8: one request reserves ceil((13+6)/8)=3
    _, paged = make_engines(TINY_LLAMA, num_pages=6)
    sched = PagedContinuousBatchingScheduler(paged, max_batch=2, prefix_cache=False)
    sched.submit(Request(uid=1, prompt=list(range(1, 14)), max_new_tokens=6))
    sched.submit(Request(uid=2, prompt=list(range(1, 14)), max_new_tokens=6))
    sched.submit(Request(uid=3, prompt=[1, 2], max_new_tokens=2))  # would fit!
    sched.step()
    # head (uid 2) needs 3 pages, only 2 free: stays queued, and uid 3 does
    # NOT jump the line even though its 1 page would fit
    assert sched.active_slots == 1 and sched.queue_depth == 2
    done = {}
    while sched.has_work():
        for c in sched.step():
            done[c.uid] = c
    assert set(done) == {1, 2, 3}
    assert done[1].tokens == done[2].tokens  # same prompt, both greedy
    assert sched.allocator.used_pages == 0


def test_prefix_hit_serves_identical_tokens():
    """A prompt served through shared prefix pages produces exactly the
    tokens the cold run produced — and the shared pages survive the donor
    retiring (refcounts, not ownership)."""
    _, paged = make_engines(TINY_LLAMA)
    sched = PagedContinuousBatchingScheduler(paged, max_batch=2)
    prompt = list(range(1, 22))  # 21 tokens: 2 full shareable pages
    cold = sched.run([Request(uid=1, prompt=prompt, max_new_tokens=5)])[1].tokens
    assert sched.prefix_cache.stats()["entries"] > 0
    # donor finished; its pages persist only through the cache's refs
    warm = sched.run([Request(uid=2, prompt=prompt, max_new_tokens=5)])[2].tokens
    assert warm == cold
    assert sched.prefix_cache.hits >= 1
    # a longer prompt sharing the prefix also matches its cold equivalent
    longer = prompt + [30, 31, 32]
    warm_long = sched.run([Request(uid=3, prompt=longer, max_new_tokens=5)])[3].tokens
    fresh = PagedContinuousBatchingScheduler(paged, max_batch=2, prefix_cache=False)
    cold_long = fresh.run([Request(uid=4, prompt=longer, max_new_tokens=5)])[4].tokens
    assert warm_long == cold_long


def test_prefix_eviction_never_corrupts_active_request():
    """Allocation pressure evicts prefix entries while a consumer request is
    mid-decode on those shared pages; its output must not change."""
    # 5 usable pages: uid2 (2 shared + 1 fresh) + uid3 (3 fresh) overflows,
    # so uid3's admission forces prefix eviction while uid2 is live
    _, paged = make_engines(TINY_LLAMA, num_pages=6)
    reference = PagedContinuousBatchingScheduler(paged, max_batch=2, prefix_cache=False)
    prompt = list(range(1, 18))  # 17 tokens: 2 shareable pages of 8
    want = reference.run([Request(uid=0, prompt=prompt, max_new_tokens=6)])[0].tokens

    sched = PagedContinuousBatchingScheduler(paged, max_batch=2)
    assert sched.run([Request(uid=1, prompt=prompt, max_new_tokens=6)])[1].tokens == want
    # consumer admits on the shared pages, then pressure from uid 3 forces
    # prefix eviction mid-flight (9 usable pages: 3+3 live + 2 cached > 9)
    sched.submit(Request(uid=2, prompt=prompt, max_new_tokens=6))
    sched.step()  # admit + first chunk; holds the shared pages
    assert sched.prefix_cache.hits >= 1
    sched.submit(Request(uid=3, prompt=list(range(40, 57)), max_new_tokens=6))
    done = {}
    while sched.has_work():
        for c in sched.step():
            done[c.uid] = c
    assert done[2].tokens == want  # eviction dropped refs, not live pages
    sched.prefix_cache.clear()
    assert sched.allocator.used_pages == 0


def test_paged_metrics_records(tmp_path):
    """Satellite: the paged scheduler's per-step records carry the pool and
    prefix gauges, and the request records still appear."""
    _, paged = make_engines(TINY_LLAMA)
    metrics = MetricsLogger(run_dir=str(tmp_path))
    sched = PagedContinuousBatchingScheduler(paged, max_batch=2, metrics=metrics)
    sched.run([Request(uid=1, prompt=list(range(1, 14)), max_new_tokens=4)])
    metrics.finish()
    records = [
        json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
        if line.strip()
    ]
    steps = [r for r in records if "serve/decode_step" in r]
    assert steps, records
    for key in (
        "serve/kv_pages_used",
        "serve/kv_pages_free",
        "serve/prefix_cache_hit_rate",
        "serve/prefill_pad_share",
        "serve/batch_fill",
        "serve/prefill_stall_share",
    ):
        assert key in steps[-1], key
    assert steps[-1]["serve/kv_pages_used"] >= 0
    assert any("serve_request" in r for r in records)


def test_paged_scheduler_rejects_contiguous_engine():
    contiguous, _ = make_engines(TINY_LLAMA)
    with pytest.raises(ValueError, match="page_size"):
        PagedContinuousBatchingScheduler(contiguous, max_batch=2)


# -- speculative rounds never move page accounting ----------------------------
#
# The design invariant under test: every verify-window write (accepted OR
# rejected) lands inside the request's worst-case admission allocation or the
# null page, so draft/verify/reject sequences are invisible to the allocator —
# rollback is host-side bookkeeping only.  tests/test_spec.py pins output
# parity; these pin the page accounting under mid-stream disruption.


def spec_sched(paged):
    return PagedContinuousBatchingScheduler(
        paged,
        max_batch=2,
        eos_id=9,
        key=jax.random.PRNGKey(42),
        prefix_cache=False,
        spec="ngram",
    )


def _step_until_drafting(sched, cap=10):
    for _ in range(cap):
        sched.step()
        if sched.spec_stats()["drafted"] > 0:
            return
    raise AssertionError("no draft fired within the step cap")


@pytest.mark.spec
def test_spec_rounds_restore_allocator_exactly():
    """Property: after a full drain with drafting rounds the free count
    returns exactly to its pre-request value — speculation allocates and
    frees nothing of its own."""
    _, paged = make_engines(TINY_LLAMA, spec_k=4)
    sched = spec_sched(paged)
    free0 = sched.allocator.free_pages
    rng = np.random.default_rng(3)
    sched.run(
        [
            Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=8),
            Request(uid=2, prompt=rng.integers(1, 256, 13).tolist(), max_new_tokens=6),
            Request(uid=3, prompt=[2, 4] * 6, max_new_tokens=7),
        ]
    )
    assert sched.spec_stats()["drafted"] > 0
    assert sched.allocator.free_pages == free0
    assert sched.allocator.used_pages == 0


@pytest.mark.spec
@pytest.mark.slow
def test_cancel_mid_verify_frees_only_victim_pages():
    """Cancelling a request between verify rounds frees exactly its own
    reservation; the surviving slot's pages stay live and its greedy output
    still matches a solo non-speculative run."""
    _, paged = make_engines(TINY_LLAMA, spec_k=4)
    sched = spec_sched(paged)
    free0 = sched.allocator.free_pages
    survivor_prompt = [2, 4] * 5
    sched.submit(Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=10))
    sched.submit(Request(uid=2, prompt=survivor_prompt, max_new_tokens=10))
    _step_until_drafting(sched)
    assert sched.active_slots == 2
    completion = sched.cancel(1)
    assert completion.finish_reason == "cancelled"
    # the victim's full worst-case reservation came back, nothing else
    assert sched.allocator.free_pages == free0 - pages_needed(
        len(survivor_prompt) + 10, paged.page_size
    )
    done = {}
    while sched.has_work():
        for c in sched.step():
            done[c.uid] = c
    assert sched.allocator.free_pages == free0  # pinned: no page leaked
    reference = PagedContinuousBatchingScheduler(
        paged, max_batch=2, eos_id=9, key=jax.random.PRNGKey(42), prefix_cache=False
    )
    want = reference.run(
        [Request(uid=2, prompt=survivor_prompt, max_new_tokens=10)]
    )[2].tokens
    assert done[2].tokens == want  # live pages untouched by the cancel


@pytest.mark.spec
@pytest.mark.slow
def test_deadline_expiry_mid_spec_restores_free_count():
    """A deadline expiring between verify rounds retires the slot with its
    partial output and returns its pages — the draft/verify machinery holds
    no page state that could leak across the expiry."""
    _, paged = make_engines(TINY_LLAMA, spec_k=4)
    sched = spec_sched(paged)
    free0 = sched.allocator.free_pages
    sched.submit(
        Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=10),
        deadline=time.monotonic() + 60.0,
    )
    sched.submit(Request(uid=2, prompt=[2, 4] * 5, max_new_tokens=8))
    _step_until_drafting(sched)
    # yank the running deadline into the past: the next round expires it
    slot = next(s for s in sched._slots if s is not None and s.request.uid == 1)
    slot.deadline = time.monotonic() - 1.0
    done = {}
    while sched.has_work():
        for c in sched.step():
            done[c.uid] = c
    assert done[1].finish_reason == "timeout" and done[1].tokens
    assert done[2].finish_reason in ("eos", "length")
    assert sched.allocator.free_pages == free0
    assert sched.allocator.used_pages == 0


# -- int8 KV pool: the quantization dial ---------------------------------------


def make_paged_pair(cfg, *, cache_size=32, page_size=8, num_pages=None, chunk_size=8):
    """Same params, same pool geometry, two kv_dtype settings: the stored
    pool (bf16 = compute dtype) vs int8 codes + per-page scales."""
    model = build_decode_model(cfg, cache_size=cache_size)
    base = type(model)(cfg, lora=None, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    kw = dict(
        cache_size=cache_size,
        page_size=page_size,
        num_pages=num_pages or 3 * (cache_size // page_size) + 1,
        chunk_size=chunk_size,
    )
    stored = InferenceEngine(cfg, params, **kw)
    quant = InferenceEngine(cfg, params, kv_dtype="int8", **kw)
    return stored, quant


@pytest.mark.parametrize("cfg", [TINY_LLAMA, TINY_NEOX], ids=["llama", "neox"])
def test_int8_greedy_tokens_identical_to_bf16(cfg):
    """Acceptance: the int8 pool serves token-identical greedy completions.
    Per-(page, kv_head) scales keep the logit perturbation far below the
    greedy argmax margin on these prompts (pinned — a regression here means
    the quantizer or the in-kernel dequant changed)."""
    stored, quant = make_paged_pair(cfg)
    reqs = [r for r in mixed_requests(cfg.vocab_size) if r.temperature == 0.0]
    assert len(reqs) == 2  # uids 1 and 3: page-straddling + multi-chunk
    _, want = drain(PagedContinuousBatchingScheduler, stored, reqs)
    sched, got = drain(PagedContinuousBatchingScheduler, quant, reqs)
    assert got == want
    sched.prefix_cache.clear()
    assert sched.allocator.used_pages == 0


def test_int8_sampled_tokens_track_bf16():
    """Sampled requests see quantization through the softmax, so exact
    parity is not guaranteed — but on these short completions the perturbed
    logits must keep the same sampling decisions (same keys, same
    temperature): any divergence beyond a token or two means the
    quantization error grew out of its design envelope."""
    stored, quant = make_paged_pair(TINY_LLAMA)
    reqs = [r for r in mixed_requests(TINY_LLAMA.vocab_size) if r.temperature != 0.0]
    _, want = drain(PagedContinuousBatchingScheduler, stored, reqs)
    _, got = drain(PagedContinuousBatchingScheduler, quant, reqs)
    assert set(got) == set(want)
    for uid in want:
        a, b = want[uid], got[uid]
        agree = sum(x == y for x, y in zip(a, b))
        assert agree >= max(1, len(a) - 2), (uid, a, b)


def test_memory_plans_int8_halves_cache_bytes():
    """Acceptance: at equal num_pages the int8 pool (codes + f32 per-page
    scales) costs at most 0.55x the bf16-engine pool bytes.  (This tiny
    engine stores at f32 compute dtype, so the measured ratio is ~0.26;
    against a true bf16 pool the same leaves give ~0.51.)"""
    stored, quant = make_paged_pair(TINY_LLAMA, num_pages=13)
    stored_kv = stored.memory_plans(4)["pytree"]["kv_cache_bytes"]
    quant_kv = quant.memory_plans(4)["pytree"]["kv_cache_bytes"]
    assert quant_kv <= 0.55 * stored_kv
    assert quant.pool_bytes() == quant_kv
    assert quant.kv_bytes_per_token() == pytest.approx(
        quant_kv / (13 * 8), rel=1e-6
    )
    # int8 codes dominate; scales are the small remainder
    n_scales = 2 * TINY_LLAMA.num_hidden_layers * 13 * TINY_LLAMA.num_attention_heads
    assert quant_kv == stored_kv // 4 + n_scales * 4


def test_int8_warmup_covers_all_shapes_no_retrace():
    """The quantized write path (gather-requantize-scatter + scale updates)
    must not add steady-state retraces: warmup's two shapes still cover a
    mixed drain."""
    _, quant = make_paged_pair(TINY_LLAMA, chunk_size=8)
    report = quant.warmup(2)
    assert report["shapes"] == {"prefill_chunk": [1, 8], "decode_paged": [2, 1]}
    assert report["kv_dtype"] == "int8"
    sched = PagedContinuousBatchingScheduler(quant, max_batch=2)
    reqs = [
        Request(uid=i, prompt=list(range(1, L + 1)), max_new_tokens=3)
        for i, L in enumerate((2, 7, 9, 17, 23))
    ]
    sched.run(reqs)
    assert quant.compile_watcher.steady_state_retraces == 0


def test_paged_metrics_kv_bytes_gauges(tmp_path):
    """Satellite: decode-step records carry the HBM dial gauges, and the
    int8 engine reports the smaller pool."""
    stored, quant = make_paged_pair(TINY_LLAMA)
    values = {}
    for name, engine in (("stored", stored), ("int8", quant)):
        metrics = MetricsLogger(run_dir=str(tmp_path / name))
        sched = PagedContinuousBatchingScheduler(engine, max_batch=2, metrics=metrics)
        sched.run([Request(uid=1, prompt=list(range(1, 14)), max_new_tokens=4)])
        metrics.finish()
        records = [
            json.loads(line)
            for line in (tmp_path / name / "metrics.jsonl").read_text().splitlines()
            if line.strip()
        ]
        step = [r for r in records if "serve/decode_step" in r][-1]
        assert step["serve/kv_cache_bytes"] == engine.pool_bytes()
        assert step["serve/kv_bytes_per_token"] == pytest.approx(
            engine.kv_bytes_per_token(), rel=1e-3
        )
        assert sched.paging_stats()["kv_dtype"] == ("int8" if name == "int8" else "bf16")
        # byte accounting tracks the page accounting exactly (prefix-cache
        # refs keep some pages resident after the drain)
        page_bytes = engine.pool_bytes() // engine.num_pages
        assert sched.allocator.used_bytes == sched.allocator.used_pages * page_bytes
        sched.prefix_cache.clear()
        assert sched.allocator.used_bytes == 0
        values[name] = step["serve/kv_cache_bytes"]
    assert values["int8"] < 0.55 * values["stored"]
