#!/usr/bin/env python
"""Thresholded regression gate over the committed BENCH_* trajectory.

Twelve rules, each skipped gracefully when its input files are absent:

1. **train tok/s** (``BENCH_r*.json``): the latest round with a real
   measurement (``parsed.value > 0`` — watchdog rounds report 0 and are
   ignored, as are stale replays with ``detail.stale``) must be within
   ``--tolerance`` (default 10%) of the best previous real round.  A
   fresh regression shows up as the newest value dropping below
   ``best * (1 - tolerance)``.
2. **MFU floor** (``BENCH_r*.json``): the newest non-stale on-TPU round
   must report ``detail.mfu >= --mfu-floor`` (default 0.25, or the
   ``mfu_floor`` key in the baselines file).  Skipped for stale replays
   and CPU rounds — off-TPU numbers say nothing about chip utilization.
3. **serving latency** (``BENCH_http.json`` vs ``tools/bench_baselines.json``):
   per-level ``ttft_p95_ms`` / ``tpot_p95_ms`` must stay under the committed
   caps (baseline p95 x (1 + tolerance), pre-expanded in the baselines file
   with generous CPU-noise margins).
4. **router failover** (``BENCH_http.json`` ``detail.router``): zero hung
   requests under a mid-run replica SIGKILL, the killed replica restarted,
   and clean/kill ``ttft_p95_ms`` under the committed router caps.
5. **obs overhead** (``BENCH_obs.json``): ``detail.within_budget`` must be
   true — the span tracer's measured overhead stayed inside its budget_pct.
6. **attention kernel** (``BENCH_attn.json``): on TPU the fused paged-decode
   arm must not lose to the naive gather arm by more than ``--tolerance``
   on any decode bucket, and the roofline's ``model_choice`` must agree
   with ``measured_best`` on the arm family.  Skipped entirely when the
   artifact was recorded in interpreter mode (``detail.is_interpret`` —
   off-TPU the pallas arm runs the pallas interpreter, a correctness
   record whose timings carry no performance signal).
7. **speculative decoding** (``BENCH_http.json`` ``detail.spec_runs``): on
   TPU every ngram sweep level must hold its accept rate at or above the
   committed ``spec_accept_rate_floor`` and its effective tok/s within
   ``--tolerance`` of the non-speculative "off" level.  Skipped off-TPU —
   CPU timings and random-token bench prompts carry no speculation signal.
8. **packed step** (``BENCH_http.json`` ``detail.packed_run``): the packed
   token-budget run must issue exactly one model dispatch per scheduler
   round, and on TPU its peak-level ``ttft_p95_ms`` must stay within
   ``--tolerance`` of the sequential headline — packing decode and prefill
   into one forward must not starve first tokens.  The latency half is
   skipped off-TPU.
9. **autoscale** (``BENCH_http.json`` ``detail.autoscale_run``): across the
   1→2→1 elastic resize driven by ``bench.py --mode autoscale``, zero
   requests may be dropped (rejected-with-429 is typed backpressure and
   allowed; vanishing mid-stream is not), the burst must have scaled the
   fleet up, and the quiet tail must have scaled it back down.  Structural
   — counts requests and replicas, not time — so it runs everywhere.
10. **grouped LoRA** (``BENCH_lora.json`` ``detail.grouped_buckets``): on TPU
   the grouped multi-tenant arm on a degenerate single-adapter batch
   (``distinct_adapters == 1``) must stay within ``--tolerance`` of the
   single-adapter fused arm on the same (B, K, N, r) bucket — the grouped
   kernel's scalar-prefetch indirection must be ~free when every row hits
   one slot.  Skipped when the artifact was recorded in interpreter mode
   (``detail.fused_is_interpret``).
11. **disaggregated handoff** (``BENCH_http.json`` ``detail.disagg_run``):
   the prefill→decode scheduler pair draining the long+short mix through
   the migration wire must finish token-identical to the single mixed
   scheduler with zero dropped requests on every kv_dtype arm, and the
   int8 arm's migrated bytes must be at most 0.3x the bf16 arm's — the
   quantized page payload is the whole point of migrating int8 pools.
   Structural — counts and parity, not time — so it runs everywhere.
12. **compression** (``BENCH_compress.json``): the prune-retrain ladder must
   cover at least the committed ``compress.min_levels`` sparsity levels, every
   level must report its GLUE score and draft accept rate, greedy ``--spec
   model`` output must be token-identical to the non-speculative run at every
   sparsity level, and the lightest level's accept rate must clear
   ``compress.accept_rate_floor`` — a near-dense draft that stops agreeing
   with its own base means the draft KV lockstep or the verify walk broke.
   Structural (parity, counts, deterministic greedy accept math — not wall
   time), so it runs everywhere, off-TPU included.

Exit codes: 0 = all rules pass (or skipped), 1 = regression, 2 = usage error.
``--warn-only`` reports failures but exits 0 — CI uses it off-TPU where the
numbers are load-noisy.

    python tools/bench_gate.py --check
    python tools/bench_gate.py --check --dir /path/to/benches --tolerance 0.15
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

BASELINES_PATH = Path(__file__).resolve().parent / "bench_baselines.json"


def _load(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def real_rounds(bench_dir: str) -> List[Tuple[int, float]]:
    """(round_n, tok/s) for every round with a real measurement, sorted by n.
    Watchdog/stalled rounds (value <= 0) carry no signal and are dropped, as
    are stale replays (``detail.stale`` — an outage round re-emitting the
    last on-chip number is provenance, not a fresh measurement: comparing it
    against itself would mask a real regression on the next live round)."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r[0-9]*.json")):
        doc = _load(path)
        if not doc:
            continue
        parsed = doc.get("parsed") or {}
        if (parsed.get("detail") or {}).get("stale"):
            continue
        value = parsed.get("value")
        if isinstance(value, (int, float)) and value > 0:
            rounds.append((int(doc.get("n", 0)), float(value)))
    rounds.sort()
    return rounds


def check_train(bench_dir: str, tolerance: float) -> List[str]:
    rounds = real_rounds(bench_dir)
    if len(rounds) < 2:
        return []  # nothing to compare against yet
    *prev, (latest_n, latest) = rounds
    best_n, best = max(prev, key=lambda r: r[1])
    floor = best * (1.0 - tolerance)
    if latest < floor:
        return [
            f"train tok/s: round {latest_n} = {latest:,.1f} is "
            f"{(1 - latest / best) * 100:.1f}% below best round {best_n} "
            f"({best:,.1f}); floor at {tolerance * 100:.0f}% is {floor:,.1f}"
        ]
    return []


def check_mfu(bench_dir: str, floor: float) -> List[str]:
    """MFU floor over the train rounds: the newest non-stale on-TPU round
    reporting ``detail.mfu`` must meet ``floor``.  Stale replays and CPU
    rounds are skipped — a tunnel outage or an off-TPU CI run says nothing
    about chip utilization.  The floor is a ratchet guard under the 50%
    north star: it holds the measured band, it is not the target itself."""
    latest: Optional[Tuple[int, float]] = None
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r[0-9]*.json")):
        doc = _load(path)
        if not doc:
            continue
        parsed = doc.get("parsed") or {}
        detail = parsed.get("detail") or {}
        mfu = detail.get("mfu")
        if detail.get("stale") or not isinstance(mfu, (int, float)) or mfu <= 0:
            continue
        if "cpu" in str(detail.get("device", "")).lower():
            continue
        n = int(doc.get("n", 0))
        if latest is None or n > latest[0]:
            latest = (n, float(mfu))
    if latest is None:
        return []
    n, mfu = latest
    if mfu < floor:
        return [
            f"mfu: round {n} measured {mfu * 100:.1f}% MFU, below the "
            f"{floor * 100:.0f}% floor (north star is >= 50%)"
        ]
    return []


def check_http(bench_dir: str, baselines: Optional[Dict[str, Any]]) -> List[str]:
    doc = _load(os.path.join(bench_dir, "BENCH_http.json"))
    if not doc or not baselines:
        return []
    caps = baselines.get("http_p95_caps_ms") or {}
    failures = []
    for level in (doc.get("detail") or {}).get("levels") or []:
        cap = caps.get(str(level.get("offered")))
        if not cap:
            continue
        for key in ("ttft_p95_ms", "tpot_p95_ms"):
            got, limit = level.get(key), cap.get(key)
            if isinstance(got, (int, float)) and isinstance(limit, (int, float)) and got > limit:
                failures.append(
                    f"http {level['offered']}: {key} = {got:.1f}ms exceeds cap {limit:.1f}ms"
                )
    return failures


def check_router(bench_dir: str, baselines: Optional[Dict[str, Any]]) -> List[str]:
    """Multi-replica failover rules over ``detail.router`` in BENCH_http.json
    (present only for ``bench.py --mode serve_load --router`` runs):

    - hung_requests must be 0 — a crash degrades to retried or typed-error,
      never to a client waiting forever;
    - the SIGKILLed replica must have been restarted inside the bench window;
    - per-run ttft_p95_ms must stay under the committed router caps.
    """
    doc = _load(os.path.join(bench_dir, "BENCH_http.json"))
    router = ((doc or {}).get("detail") or {}).get("router")
    if not router:
        return []
    failures = []
    hung = router.get("hung_requests", 0)
    if hung:
        failures.append(
            f"router: {hung} hung request(s) under replica failure — every "
            "accepted request must terminate (finish record or typed error)"
        )
    if router.get("replica0_restarted") is False:
        failures.append("router: SIGKILLed replica was not restarted during the bench")
    caps = (baselines or {}).get("router_p95_caps_ms") or {}
    for run in ("clean", "kill"):
        cap = caps.get(run)
        row = router.get(run) or {}
        if not cap:
            continue
        got, limit = row.get("ttft_p95_ms"), cap.get("ttft_p95_ms")
        if isinstance(got, (int, float)) and isinstance(limit, (int, float)) and got > limit:
            failures.append(
                f"router {run}: ttft_p95_ms = {got:.1f}ms exceeds cap {limit:.1f}ms"
            )
    return failures


def check_obs(bench_dir: str) -> List[str]:
    doc = _load(os.path.join(bench_dir, "BENCH_obs.json"))
    if not doc:
        return []
    detail = doc.get("detail") or {}
    failures = []
    if detail.get("within_budget") is False:
        failures.append(
            f"obs overhead: {doc.get('value')}% of step time exceeds "
            f"budget {detail.get('budget_pct')}%"
        )
    collector = detail.get("collector") or {}
    if collector.get("within_budget") is False:
        failures.append(
            f"fleet collector overhead: {collector.get('overhead_pct')}% "
            f"serving throughput loss exceeds budget "
            f"{collector.get('budget_pct')}% "
            f"(off {collector.get('off_tok_s')} tok/s -> "
            f"on {collector.get('on_tok_s')} tok/s)"
        )
    return failures


def check_attn(bench_dir: str, tolerance: float) -> List[str]:
    doc = _load(os.path.join(bench_dir, "BENCH_attn.json"))
    if not doc:
        return []
    detail = doc.get("detail") or {}
    if detail.get("is_interpret"):
        return []  # interpreter-mode timings carry no performance signal
    failures = []
    for row in detail.get("buckets") or []:
        if row.get("kind") != "decode":
            continue
        shape = f"B={row.get('B')} S_kv={row.get('S_kv')}"
        for tag in ("bf16", "int8"):
            fused = row.get(f"paged_decode_{tag}_ms")
            naive = row.get(f"naive_{tag}_ms")
            if not (isinstance(fused, (int, float)) and isinstance(naive, (int, float))):
                continue
            if fused > naive * (1.0 + tolerance):
                failures.append(
                    f"attn {shape} {tag}: fused paged-decode {fused:.3f}ms is "
                    f"{(fused / naive - 1) * 100:.0f}% slower than naive {naive:.3f}ms"
                )
            choice = row.get(f"model_choice_{tag}")
            best = row.get("measured_best") or ""
            if choice and best and not best.startswith(choice):
                failures.append(
                    f"attn {shape} {tag}: roofline picked {choice} but measured "
                    f"best arm was {best}"
                )
    return failures


def check_spec(
    bench_dir: str, baselines: Optional[Dict[str, Any]], tolerance: float
) -> List[str]:
    """Speculative-decoding rules over ``detail.spec_runs`` in BENCH_http.json
    (present only for paged ``--mode serve_load`` runs with the spec sweep):

    - every ngram level that drafted anything must hold its cumulative accept
      rate at or above the committed ``spec_accept_rate_floor`` — a collapse
      here means the draft source or the verify/accept walk broke, not noise;
    - each ngram level's effective tok/s must not fall below the "off" level
      by more than ``tolerance`` — speculation that loses throughput to its
      own verify overhead is a regression, the roofline said it should win.

    Skipped entirely off-TPU (like ``check_attn``): CPU timings carry no
    throughput signal, and random-token bench prompts make acceptance a
    property of the model's repetition loops, not the feature.
    """
    doc = _load(os.path.join(bench_dir, "BENCH_http.json"))
    detail = (doc or {}).get("detail") or {}
    spec_runs = detail.get("spec_runs") or {}
    if not spec_runs:
        return []
    if "cpu" in str(detail.get("device", "")).lower():
        return []  # off-TPU: no throughput signal, acceptance is prompt noise
    floor = float((baselines or {}).get("spec_accept_rate_floor", 0.0))
    off_tok_s = (spec_runs.get("off") or {}).get("effective_tokens_per_s")
    failures = []
    for level, run in spec_runs.items():
        if run.get("mode") == "off":
            continue
        drafted = run.get("drafted", 0)
        rate = run.get("accept_rate")
        if drafted and isinstance(rate, (int, float)) and rate < floor:
            failures.append(
                f"spec {level}: accept rate {rate:.3f} below floor {floor:.3f} "
                f"({run.get('accepted', 0)}/{drafted} drafted tokens accepted)"
            )
        got = run.get("effective_tokens_per_s")
        if isinstance(got, (int, float)) and isinstance(off_tok_s, (int, float)):
            if got < off_tok_s * (1.0 - tolerance):
                failures.append(
                    f"spec {level}: effective {got:,.1f} tok/s is "
                    f"{(1 - got / off_tok_s) * 100:.0f}% below non-speculative "
                    f"{off_tok_s:,.1f} tok/s (tolerance {tolerance * 100:.0f}%)"
                )
    return failures


def check_compress(bench_dir: str, baselines: Optional[Dict[str, Any]]) -> List[str]:
    """Compression rules over BENCH_compress.json (``bench.py --mode
    compress`` — the prune-retrain ladder from relora_tpu/compress):

    - the ladder must cover at least ``compress.min_levels`` sparsity levels
      (default 3) — one point is a smoke test, not a quality curve;
    - every level must report a numeric ``glue_score`` and draft
      ``accept_rate`` — a level that silently dropped either half measured
      nothing;
    - greedy ``--spec model`` output must be token-identical to the
      non-speculative run at **every** sparsity level — parity is
      architecture math (``spec_verify_draws`` with temperature 0), so any
      divergence means the draft KV lockstep or the verify/accept walk
      broke, never noise;
    - the lightest level's accept rate must clear
      ``compress.accept_rate_floor`` — with the default ladder the lightest
      draft is the unpruned merge of the same weights, so its acceptance is
      near-total by construction and a collapse is a wiring bug.

    Everything here is structural (parity, counts, deterministic greedy
    accept math — not wall time), so unlike ``check_spec`` the rule runs
    off-TPU too.
    """
    doc = _load(os.path.join(bench_dir, "BENCH_compress.json"))
    detail = (doc or {}).get("detail") or {}
    levels = detail.get("levels") or []
    if not levels:
        return []
    caps = (baselines or {}).get("compress") or {}
    failures = []
    min_levels = int(caps.get("min_levels", 3))
    if len(levels) < min_levels:
        failures.append(
            f"compress: only {len(levels)} sparsity level(s) measured — the "
            f"ladder needs at least {min_levels} to be a quality curve"
        )
    for lv in levels:
        tag = f"compress s={lv.get('sparsity')}"
        spec = lv.get("spec") or {}
        if not isinstance(lv.get("glue_score"), (int, float)):
            failures.append(f"{tag}: missing glue_score — the quality half of the ladder")
        if not isinstance(spec.get("accept_rate"), (int, float)):
            failures.append(f"{tag}: missing draft accept_rate — the serving half of the ladder")
        if spec.get("token_parity") is False:
            failures.append(
                f"{tag}: greedy --spec model output diverged from the "
                "non-speculative run — parity is exact math at temperature 0, "
                "so the draft KV lockstep or the verify walk is broken"
            )
    lightest = min(levels, key=lambda lv: lv.get("sparsity", 1.0))
    floor = float(caps.get("accept_rate_floor", 0.0))
    lspec = lightest.get("spec") or {}
    rate = lspec.get("accept_rate")
    if lspec.get("drafted", 0) and isinstance(rate, (int, float)) and rate < floor:
        failures.append(
            f"compress s={lightest.get('sparsity')}: accept rate {rate:.3f} "
            f"below floor {floor:.3f} on the lightest draft "
            f"({lspec.get('accepted', 0)}/{lspec.get('drafted', 0)} drafted "
            "tokens accepted) — a near-dense draft should track its base"
        )
    return failures


def check_packed(bench_dir: str, tolerance: float) -> List[str]:
    """Packed-step rule over ``detail.packed_run`` in BENCH_http.json
    (present for paged ``--mode serve_load`` runs unless
    ``BENCH_HTTP_PACKED_STEP=0``):

    - the packed run's peak-level ``ttft_p95_ms`` must stay within
      ``tolerance`` of the sequential headline's peak level — token-budget
      scheduling exists to cut dispatch overhead, not to starve first
      tokens behind decode work;
    - the packed run must actually pack: ``dispatches_per_round`` must be
      1.0 (one model dispatch per scheduler round is the whole point).

    The latency comparison is skipped off-TPU (like ``check_attn``): CPU
    wall times carry no performance signal.  The dispatches-per-round
    structural rule runs everywhere — it counts calls, not time.
    """
    doc = _load(os.path.join(bench_dir, "BENCH_http.json"))
    detail = (doc or {}).get("detail") or {}
    packed = detail.get("packed_run") or {}
    if not packed:
        return []
    failures = []
    dpr = (packed.get("dispatch") or {}).get("dispatches_per_round")
    if isinstance(dpr, (int, float)) and dpr > 1.0:
        failures.append(
            f"packed: {dpr:.2f} model dispatches per round — the packed "
            "scheduler must issue exactly one dispatch per round"
        )
    if "cpu" in str(detail.get("device", "")).lower():
        return failures  # off-TPU: no latency signal
    levels = detail.get("levels") or []
    seq_peak = max(
        (lv for lv in levels if isinstance(lv.get("ttft_p95_ms"), (int, float))),
        key=lambda lv: lv.get("throughput_tokens_per_s", 0),
        default=None,
    )
    got = packed.get("ttft_p95_ms_at_peak")
    base = seq_peak.get("ttft_p95_ms") if seq_peak else None
    if isinstance(got, (int, float)) and isinstance(base, (int, float)):
        if got > base * (1.0 + tolerance):
            failures.append(
                f"packed: ttft_p95_ms {got:.1f}ms at peak is "
                f"{(got / base - 1) * 100:.0f}% above the sequential headline "
                f"{base:.1f}ms (tolerance {tolerance * 100:.0f}%)"
            )
    return failures


def check_autoscale(bench_dir: str) -> List[str]:
    """Elastic-fleet rules over ``detail.autoscale_run`` in BENCH_http.json
    (present only for ``bench.py --mode autoscale`` runs):

    - ``dropped_requests`` must be 0 — a scale-up spawn, a warming replica,
      or a scale-down drain must never lose an accepted request (429
      rejections are typed backpressure and do not count);
    - the burst phase must have scaled the fleet up (``scaled_up``), and the
      quiet tail must have brought it back to the floor (``scaled_down``) —
      an autoscaler that never moves is not measuring anything.

    Structural (counts, not wall time), so it runs off-TPU too.
    """
    doc = _load(os.path.join(bench_dir, "BENCH_http.json"))
    run = ((doc or {}).get("detail") or {}).get("autoscale_run")
    if not run:
        return []
    failures = []
    dropped = run.get("dropped_requests", 0)
    if dropped:
        failures.append(
            f"autoscale: {dropped} dropped request(s) across the 1->2->1 "
            "resize — every accepted request must terminate (finish record "
            "or typed error), through spawn, warmup, and drain alike"
        )
    if run.get("scaled_up") is False:
        failures.append(
            "autoscale: the burst phase never scaled the fleet up "
            f"(max_replicas_seen={run.get('max_replicas_seen')})"
        )
    if run.get("scaled_down") is False:
        failures.append(
            "autoscale: the quiet tail never scaled the fleet back down "
            f"(final_replicas={run.get('final_replicas')})"
        )
    return failures


def check_disagg(bench_dir: str) -> List[str]:
    """Disaggregated-handoff rules over ``detail.disagg_run`` in
    BENCH_http.json (present for paged serve_load runs):

    - every kv_dtype arm must finish **token-identical** to the single
      mixed-scheduler baseline — migrating a page run across the wire must
      not perturb a single sampled token;
    - ``dropped_requests`` must be 0 on every arm — a handoff that cannot
      land fails open to donor-local decode, it never loses the request;
    - ``migrated_bytes_ratio_int8_vs_bf16`` must be <= 0.3 — the int8 pool
      ships quantized payloads + per-page scales, so its wire bytes must
      come in well under half the bf16 arm's.

    Structural (parity and byte counts, not wall time), so it runs
    off-TPU too.
    """
    doc = _load(os.path.join(bench_dir, "BENCH_http.json"))
    run = ((doc or {}).get("detail") or {}).get("disagg_run")
    if not run:
        return []
    failures = []
    for dtype, arm in (run.get("runs") or {}).items():
        if arm.get("token_parity") is not True:
            failures.append(
                f"disagg[{dtype}]: prefill->decode drain is not "
                "token-identical to the single mixed scheduler — migration "
                "must preserve the (uid, token_index) sampling stream exactly"
            )
        dropped = arm.get("dropped_requests", 0)
        if dropped:
            failures.append(
                f"disagg[{dtype}]: {dropped} dropped request(s) — a failed "
                "handoff must fail open to local decode, never vanish"
            )
    ratio = run.get("migrated_bytes_ratio_int8_vs_bf16")
    if ratio is None:
        failures.append(
            "disagg: no migrated-bytes ratio recorded (bf16 arm migrated "
            "zero bytes?) — the int8-vs-bf16 comparison needs both arms"
        )
    elif ratio > 0.3:
        failures.append(
            f"disagg: int8 migrated-bytes ratio {ratio:.3f} > 0.3x bf16 — "
            "the quantized page payload is not paying for itself on the wire"
        )
    return failures


def check_grouped_lora(bench_dir: str, tolerance: float) -> List[str]:
    """Grouped multi-tenant LoRA rule over ``detail.grouped_buckets`` in
    BENCH_lora.json: with every row on one adapter (G=1), the grouped
    scalar-prefetch kernel must match the single-adapter fused kernel within
    ``tolerance`` on the same shape — otherwise multi-tenancy taxes
    single-tenant traffic.  Skipped off-TPU (interpreter timings)."""
    doc = _load(os.path.join(bench_dir, "BENCH_lora.json"))
    detail = (doc or {}).get("detail") or {}
    grouped = detail.get("grouped_buckets") or []
    if not grouped or detail.get("fused_is_interpret"):
        return []
    fused_by_shape = {
        (row.get("M"), row.get("K"), row.get("N"), row.get("r")): row.get("fused_ms")
        for row in detail.get("buckets") or []
    }
    failures = []
    for row in grouped:
        if row.get("distinct_adapters") != 1:
            continue
        shape = (row.get("B"), row.get("K"), row.get("N"), row.get("r"))
        fused = fused_by_shape.get(shape)
        got = row.get("grouped_ms")
        if not (isinstance(got, (int, float)) and isinstance(fused, (int, float))):
            continue
        if got > fused * (1.0 + tolerance):
            failures.append(
                f"grouped lora B={shape[0]} K={shape[1]} N={shape[2]} r={shape[3]}: "
                f"grouped arm {got:.3f}ms is {(got / fused - 1) * 100:.0f}% slower "
                f"than single-adapter fused {fused:.3f}ms on a G=1 batch"
            )
    return failures


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true", help="run the gate (the only mode)")
    ap.add_argument(
        "--dir",
        default=str(Path(__file__).resolve().parents[1]),
        help="directory holding BENCH_*.json (default: repo root)",
    )
    ap.add_argument(
        "--tolerance",
        type=float,
        default=0.10,
        help="allowed fractional drop in train tok/s vs the best previous round",
    )
    ap.add_argument(
        "--baselines",
        default=str(BASELINES_PATH),
        help="serving-latency caps JSON ('' disables the http rule)",
    )
    ap.add_argument(
        "--mfu-floor",
        type=float,
        default=None,
        help="minimum MFU for the newest non-stale on-TPU round "
        "(default: baselines 'mfu_floor', else 0.25; 0 disables)",
    )
    ap.add_argument(
        "--warn-only",
        action="store_true",
        help="report regressions but exit 0 (off-TPU CI, where numbers are noisy)",
    )
    args = ap.parse_args(argv)
    if not args.check:
        ap.print_help()
        return 2

    baselines = _load(args.baselines) if args.baselines else None
    mfu_floor = args.mfu_floor
    if mfu_floor is None:
        mfu_floor = float((baselines or {}).get("mfu_floor", 0.25))
    failures = (
        check_train(args.dir, args.tolerance)
        + (check_mfu(args.dir, mfu_floor) if mfu_floor > 0 else [])
        + check_http(args.dir, baselines)
        + check_router(args.dir, baselines)
        + check_obs(args.dir)
        + check_attn(args.dir, args.tolerance)
        + check_spec(args.dir, baselines, args.tolerance)
        + check_packed(args.dir, args.tolerance)
        + check_autoscale(args.dir)
        + check_grouped_lora(args.dir, args.tolerance)
        + check_disagg(args.dir)
        + check_compress(args.dir, baselines)
    )

    rounds = real_rounds(args.dir)
    traj = " -> ".join(f"r{n}:{v:,.0f}" for n, v in rounds) or "no real rounds"
    print(f"bench gate over {args.dir}  (train trajectory: {traj})")
    if failures:
        for f in failures:
            print(f"  REGRESSION: {f}")
        if args.warn_only:
            print("bench gate: FAILURES above (warn-only: exit 0)")
            return 0
        print("bench gate: FAIL")
        return 1
    print("bench gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
