"""Export a relora-tpu checkpoint as an HF-format torch model directory.

The LoRA factors are merged into the base weights first (the equivalent
full-rank model, core.relora.merged_params), so the output loads directly
into transformers' LlamaForCausalLM / GPTNeoXForCausalLM — the path by which
ReLoRA-pretrained models reach downstream HF tooling (the reference does
this through wrapped_model.save_pretrained, relora.py:149-152).

Usage::

    python tools/export_hf.py --checkpoint ckpts/relora/model_20000 \
        --model_config llama_250m --out export/llama_250m_relora
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--checkpoint", required=True)
    p.add_argument("--model_config", required=True)
    p.add_argument("--out", required=True)
    p.add_argument(
        "--dtype",
        choices=["f32", "bf16"],
        default="f32",
        help="storage dtype of the exported tensors (merge math stays f32)",
    )
    p.add_argument(
        "--pruned",
        action="store_true",
        help="apply the checkpoint's prune_mask.npz sidecar to the merged "
        "tree before export (pruned positions stay exactly zero) and record "
        "sparsity + mask checksum in the output config; errors if the "
        "checkpoint has no mask or the mask does not fit the tree",
    )
    args = p.parse_args(argv)

    sys.path.insert(0, ".")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from relora_tpu.config.model import load_model_config
    from relora_tpu.core.relora import LoraSpec, merged_params
    from relora_tpu.models.hf_compat import params_to_hf
    from relora_tpu.train.checkpoint import load_lora_spec, restore_params_host

    cfg = load_model_config(args.model_config)
    params = restore_params_host(args.checkpoint)
    spec = load_lora_spec(args.checkpoint)
    if spec is not None:
        params = jax.tree_util.tree_map(np.asarray, merged_params(params, spec))
        print(f"merged LoRA factors (r={spec.r}) into base weights")

    pruning_block = None
    if args.pruned:
        # apply_mask raises PruneMaskMismatchError (naming the module) on a
        # missing module or shape mismatch — a wrong-architecture mask must
        # fail the export, not silently ship dense weights
        from relora_tpu.compress.prune import (
            apply_mask,
            load_mask,
            mask_checksum,
            sparsity_stats,
        )

        mask, _ = load_mask(args.checkpoint)
        if mask is None:
            raise SystemExit(
                f"--pruned: {args.checkpoint} has no prune_mask.npz sidecar "
                "(not a prune-retrain checkpoint?)"
            )
        params = jax.tree_util.tree_map(np.asarray, apply_mask(params, mask))
        stats = sparsity_stats(mask)
        pruning_block = {
            "sparsity": round(stats["sparsity"], 6),
            "mask_crc32": mask_checksum(mask),
        }
        print(f"applied prune mask: {stats['sparsity']:.1%} sparsity")

    sd = params_to_hf(params, cfg)
    os.makedirs(args.out, exist_ok=True)

    import torch

    # numpy has no native bfloat16: cast on the torch side after the f32
    # merge/transpose work is done
    out_dtype = torch.bfloat16 if args.dtype == "bf16" else torch.float32
    torch.save(
        {
            k: torch.from_numpy(np.ascontiguousarray(np.asarray(v, np.float32))).to(out_dtype)
            for k, v in sd.items()
        },
        os.path.join(args.out, "pytorch_model.bin"),
    )
    hf_config = {
        "architectures": ["LlamaForCausalLM" if cfg.family == "llama" else "GPTNeoXForCausalLM"],
        "model_type": "llama" if cfg.family == "llama" else "gpt_neox",
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_hidden_layers,
        "num_attention_heads": cfg.num_attention_heads,
        "num_key_value_heads": cfg.num_attention_heads,
        "max_position_embeddings": cfg.max_sequence_length,
        "rms_norm_eps": cfg.rms_norm_eps,
        "layer_norm_eps": cfg.layer_norm_eps,
        "rotary_pct": cfg.rotary_pct,
        "rope_theta": cfg.rotary_emb_base,
        "use_parallel_residual": cfg.use_parallel_residual,
        "tie_word_embeddings": cfg.tie_word_embeddings,
        "bos_token_id": cfg.bos_token_id,
        "eos_token_id": cfg.eos_token_id,
        "torch_dtype": "bfloat16" if args.dtype == "bf16" else "float32",
    }
    if pruning_block is not None:
        hf_config["relora_tpu_pruning"] = pruning_block
    with open(os.path.join(args.out, "config.json"), "w") as f:
        json.dump(hf_config, f, indent=2)
    n = sum(v.size for v in sd.values())
    print(f"wrote {len(sd)} tensors ({n/1e6:.1f}M params) to {args.out}")


if __name__ == "__main__":
    main()
