"""HF data pipeline tests, fully offline: a tiny BPE tokenizer is trained
in-process, then tokenize_and_chunk / streaming packing / the pretokenize CLI
are exercised end-to-end (parity surface: dataloader.py + pretokenize.py)."""

import json
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tiny_tokenizer(tmp_path_factory):
    """Train a minimal BPE tokenizer locally and save tokenizers-format json."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        vocab_size=200, special_tokens=["<unk>", "<|endoftext|>"]
    )
    corpus = [
        "the quick brown fox jumps over the lazy dog",
        "pack my box with five dozen liquor jugs",
        "how vexingly quick daft zebras jump",
    ] * 20
    tok.train_from_iterator(corpus, trainer)
    path = tmp_path_factory.mktemp("tok") / "tokenizer.json"
    tok.save(str(path))
    return str(path)


def load_tok(path):
    from pretokenize import load_tokenizer

    return load_tokenizer(path)


def test_tokenizer_json_loading(tiny_tokenizer):
    tok = load_tok(tiny_tokenizer)
    assert tok.eos_token == "<|endoftext|>"
    assert tok.eos_token_id is not None
    ids = tok("the quick brown fox", add_special_tokens=False)["input_ids"]
    assert len(ids) > 0


def test_tokenize_and_chunk(tiny_tokenizer):
    import datasets

    from relora_tpu.data.hf_pipeline import tokenize_and_chunk

    tok = load_tok(tiny_tokenizer)
    ds = datasets.Dataset.from_list(
        [{"text": "the quick brown fox jumps over the lazy dog"} for _ in range(50)]
    )
    out = tokenize_and_chunk(ds, tok, sequence_length=16, num_proc=1)
    assert len(out) > 0
    arr = np.asarray(out[:]["input_ids"])
    assert arr.shape[1] == 16
    # every document boundary carries an EOS; chunked stream contains EOS ids
    assert (arr == tok.eos_token_id).sum() >= len(out) - 1


def test_streaming_iterator_matches_offline(tiny_tokenizer):
    """On-the-fly packing yields the same token stream as pretokenize+chunk
    (PreprocessedIterableDataset parity, dataloader.py:13-54)."""
    import datasets

    from relora_tpu.data.hf_pipeline import StreamingTokenIterator, tokenize_and_chunk

    tok = load_tok(tiny_tokenizer)
    docs = [{"text": f"the quick brown fox number {i} jumps"} for i in range(40)]
    ds = datasets.Dataset.from_list(docs)

    offline = tokenize_and_chunk(ds, tok, sequence_length=8, num_proc=1)
    offline_stream = np.asarray(offline[:]["input_ids"]).reshape(-1)

    stream = StreamingTokenIterator(
        ds, tok, sequence_length=8, microbatch=2, grad_accum=1
    )
    got = np.concatenate([b.reshape(-1) for b in stream])
    n = min(len(got), len(offline_stream))
    np.testing.assert_array_equal(got[:n], offline_stream[:n])


def test_pretokenize_cli_roundtrip(tiny_tokenizer, tmp_path):
    """The offline prep CLI end-to-end: local dataset dir -> chunked dataset
    + args.json provenance (pretokenize.py parity incl. the train-time
    check, torchrun_main.py:452-455)."""
    import datasets

    import pretokenize

    src = tmp_path / "raw"
    datasets.Dataset.from_list(
        [{"text": "pack my box with five dozen liquor jugs"} for _ in range(30)]
    ).save_to_disk(str(src))

    out = tmp_path / "tok"
    pretokenize.main(
        [
            "--dataset", str(src),
            "--tokenizer", tiny_tokenizer,
            "--sequence_length", "16",
            "--num_proc", "1",
            "--save_dir", str(out),
        ]
    )
    cooked = datasets.load_from_disk(str(out))
    assert len(cooked) > 0 and len(cooked[0]["input_ids"]) == 16
    prov = json.load(open(out / "args.json"))
    assert prov["sequence_length"] == 16 and prov["n_sequences"] == len(cooked)
