"""Typed training configuration with full flag parity to the reference CLI.

The reference splits configuration across argparse (torchrun_main.py:54-140),
a YAML full-override path, and post-hoc validation
(peft_pretraining/args_utils.py:8-86).  Here all of it is one dataclass:
every reference flag is a field with the same name and default, `finalize()`
applies the reference's derivation/validation semantics, and YAML configs in
the reference's format (training_configs/1B_v1.0.yaml) load unchanged.

Differences from the reference, by design:
- TPU/mesh fields (``mesh_shape``, axis sizes) replace ``distributed_type``
  (ddp/fsdp), which is kept only as an accepted alias.
- ``quantize`` gates the AQT-style int8 frozen-base path rather than
  bitsandbytes.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def parse_token_count(value) -> Optional[int]:
    """Parse "100M"/"1B"/plain ints (parity: training_utils.max_train_tokens_to_number)."""
    if value is None:
        return None
    if isinstance(value, int):
        return value
    s = str(value)
    if s.endswith("M"):
        return int(s[:-1]) * 1_000_000
    if s.endswith("B"):
        return int(s[:-1]) * 1_000_000_000
    return int(s)


@dataclass
class TrainingConfig:
    # --- model source ---
    model_config: Optional[str] = None  # zoo name or HF-style JSON path
    model_name_or_path: Optional[str] = None
    model_revision: Optional[str] = None
    warmed_up_model: Optional[str] = None  # weights + counters, no optimizer
    resume_from: Optional[str] = None  # full state
    load_optimizer_state_on_resume: bool = True

    # --- data ---
    dataset_path: Optional[str] = None
    megatron_dataset_config: Optional[str] = None
    max_length: int = 512
    workers: int = 8

    # --- batch arithmetic ---
    batch_size: Optional[int] = None  # per-device micro batch
    gradient_accumulation: Optional[int] = None
    total_batch_size: Optional[int] = None

    # --- ReLoRA ---
    use_peft: bool = False
    lora_r: Optional[int] = 128
    lora_alpha: float = 32
    lora_dropout: float = 0.1
    relora: Optional[int] = None  # merge-and-reinit every N update steps
    train_scaling: bool = False
    # LoRA composite execution: "false" = historical unfused path, "true" =
    # fused Pallas kernel (ops/pallas_lora_matmul), "auto" = per-shape
    # dispatch (ops/lora_dispatch).  A string (not bool) so the CLI accepts
    # "auto" — maps onto LoraSpec.fused.
    lora_fused: str = "false"
    reset_optimizer_on_relora: bool = True
    optimizer_random_pruning: float = 0.0
    optimizer_magnitude_pruning: float = 0.0
    force_keep_original: bool = False

    # --- compression (relora_tpu/compress; PERP prune-retrain) ---
    # Base-weight magnitude pruning, applied at ReLoRA merges: at the first
    # merge past prune_start_step the mask is computed from the merged base
    # (fixed from then on) and re-applied after every later merge, so each
    # cycle runs merge -> prune -> re-init A/B -> continue and the LoRA
    # factors retrain around the holes.  0.0 disables pruning entirely.
    prune_sparsity: float = 0.0
    prune_scope: str = "global"  # global | per_matrix magnitude threshold
    prune_nm: Optional[str] = None  # structured "N:M" (overrides sparsity/scope)
    prune_start_step: int = 0  # first update step eligible to compute the mask
    # A/B re-draw flavor at ReLoRA resets (compress/resets.py):
    # "random" = historical kaiming draw (byte-for-byte), "magnitude" =
    # weight-magnitude-aligned init from the merged base
    reset_init: str = "random"

    # --- optimization ---
    optimizer: str = "adam"
    lr: float = 1e-4
    scheduler: str = "cosine"  # linear | cosine | cosine_restarts
    cycle_length: Optional[int] = None
    restart_warmup_steps: Optional[int] = None
    adjust_step: int = 0
    min_lr_ratio: float = 0.1
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    weight_decay: float = 0.0
    warmup_steps: int = 1_000
    clip_grad_norm: float = 1.0
    num_training_steps: int = 10_000
    max_train_tokens: Optional[Any] = None  # accepts "100M"/"1B"

    # --- eval / save ---
    # Metric-materialization cadence: device metrics from the last
    # `log_every` updates are pulled to the host in ONE bulk transfer
    # (metrics stay lagged by at least one step either way).  Raising this
    # trades NaN-abort latency (the check runs on materialized values) for
    # fewer host round trips.
    log_every: int = 1
    eval_every: int = 1_000
    save_every: int = 10_000
    save_dir: Optional[str] = None
    keep_checkpoints: Optional[int] = None
    autoresume: bool = False

    # --- resilience (train/resilience.py, train/checkpoint.py) ---
    handle_preemption: bool = True  # SIGTERM/SIGINT -> emergency checkpoint
    save_retries: int = 3  # checkpoint-save initiation retries
    save_retry_backoff: float = 0.5  # seconds, doubled each retry
    # loss-spike rollback: 0 disables; otherwise the outlier threshold in
    # sigma-equivalents (median + threshold * 1.4826 * MAD over the window)
    spike_threshold: float = 0.0
    spike_window: int = 64  # rolling baseline size (update steps)
    spike_min_history: int = 16  # updates before detection arms
    spike_patience: int = 3  # consecutive outliers before rollback
    spike_rollback_margin: int = 1  # extra batches skipped past the spike
    max_spike_rollbacks: int = 3  # rollback budget per run

    # --- numerics ---
    dtype: str = "bfloat16"
    quantize: Optional[str] = None  # None | "int8" | "nf4"
    # storage dtype for the unquantized frozen base: None = f32 master,
    # "bf16" halves base HBM (merges still compute f32, core/relora.py)
    base_dtype: Optional[str] = None  # None | "bf16"
    # nf4 only: int8-quantize the blockwise scales too (parity:
    # use_double_quant, args flag -> bnb_4bit_use_double_quant)
    use_double_quant: bool = True

    # --- parallelism (TPU-native; replaces distributed_type) ---
    distributed_type: str = "fsdp"  # accepted alias; "ddp" -> pure data axis
    dp_size: Optional[int] = None  # data axis; None = fill remaining devices
    fsdp_size: int = 1  # parameter-sharding axis
    tp_size: int = 1  # tensor axis
    sp_size: int = 1  # sequence (context parallel) axis
    sp_impl: str = "ring"  # ring (streamed K/V) | ulysses (all-to-all heads)
    remat: bool = False  # gradient checkpointing on decoder layers
    remat_policy: str = "full"  # 'full' | 'dots' | 'dots_narrow' | 'dots_all' (params_util.remat_policy)
    bf16_logits: bool = False  # halve the logits HBM footprint; CE still f32
    loss_impl: str = "dense"  # dense | chunked (streamed vocab CE, no full logits)
    vocab_chunk: int = 8192  # chunk size for loss_impl=chunked
    # force the pallas flash kernel unconditionally (bypasses the per-shape
    # roofline dispatch that impl="auto" runs through attention_dispatch.
    # choose_training_arm); off = dispatch decides flash vs xla per shape
    flash_attention: bool = False

    # --- observability / misc ---
    profile: bool = False
    wandb: bool = False
    wandb_watch: bool = False
    tags: Optional[Any] = None
    comment: Optional[str] = None
    skip_batches: Any = None
    seed: int = 0
    eval_tokens_during_training: int = 10_000_000  # torchrun_main.py:144
    # end-of-run eval budget (reference hardcodes 100M, torchrun_main.py:984);
    # configurable so CPU/scaled runs aren't forced through a full-split pass
    final_eval_tokens: int = 100_000_000
    # '' = jax default (threefry); 'rbg' = hardware RNG for dropout bits
    # (cheaper on TPU; cross-host determinism caveats documented in jax)
    prng_impl: str = ""
    nan_abort_fraction: float = 0.05  # torchrun_main.py:820

    # derived (set by finalize)
    _finalized: bool = field(default=False, repr=False)

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml(cls, path: str, **overrides) -> "TrainingConfig":
        """Load a reference-format YAML (training_configs/1B_v1.0.yaml) and finalize."""
        with open(path) as f:
            raw = yaml.safe_load(f)
        cfg = cls()
        known = {f.name for f in dataclasses.fields(cls)}
        for k, v in {**raw, **overrides}.items():
            if k == "lr":
                v = float(v)  # args_utils.py:20 — yaml may parse 4e-4 as str
            if k not in known:
                logger.warning(f"Unknown config key {k!r} ignored")
                continue
            setattr(cfg, k, v)
        return cfg.finalize()

    def finalize(self) -> "TrainingConfig":
        """Derivation + validation, mirroring args_utils.check_args_torchrun_main."""
        if self._finalized:
            return self

        if (self.dataset_path is None) == (self.megatron_dataset_config is None):
            raise ValueError(
                "Exactly one of dataset_path / megatron_dataset_config must be set; "
                f"got dataset_path={self.dataset_path!r}, "
                f"megatron_dataset_config={self.megatron_dataset_config!r}"
            )
        if self.megatron_dataset_config is not None and not os.path.exists(self.megatron_dataset_config):
            raise ValueError(f"megatron_dataset_config {self.megatron_dataset_config!r} does not exist")
        if self.batch_size is None:
            raise ValueError("batch_size must be specified")

        if isinstance(self.tags, str):
            self.tags = self.tags.split(",")

        # Reference semantics (args_utils.py:37-41 runs before the :65-67
        # promotion, making the promotion dead code): relora without use_peft
        # is dropped and the run is full-rank.  We keep that behavior but warn
        # loudly instead of silently.
        if not self.use_peft:
            if self.relora:
                logger.warning(
                    "relora is set but use_peft is false — matching the "
                    "reference, relora is ignored and this run is full-rank. "
                    "Set use_peft=true for ReLoRA training."
                )
            self.relora = None
            self.lora_r = None
            self.force_keep_original = False
        # relora=0 means disabled, exactly like None — normalize here so no
        # consumer (merge cadence, reset cadence, scheduler cycle fallback,
        # lora_only weight decision) has to remember the 0-vs-None convention
        if self.relora == 0:
            self.relora = None

        if self.total_batch_size is None:
            self.gradient_accumulation = self.gradient_accumulation or 1
            self.total_batch_size = self.batch_size * self.gradient_accumulation
        if self.total_batch_size % self.batch_size != 0:
            raise ValueError("total_batch_size must be divisible by batch_size")

        self.max_train_tokens = parse_token_count(self.max_train_tokens)
        if self.max_train_tokens is not None:
            self.num_training_steps = self.max_train_tokens // self.total_batch_size
            logger.info(f"Training for {self.num_training_steps} update steps")

        if self.warmed_up_model is not None and not os.path.exists(self.warmed_up_model):
            raise ValueError(f"warmed_up_model {self.warmed_up_model!r} does not exist")

        if self.dtype in ("fp16", "float16"):
            raise NotImplementedError("fp16 is not supported; use bfloat16 on TPU")

        n_reset_modes = (
            int(bool(self.reset_optimizer_on_relora))
            + int(bool(self.optimizer_random_pruning))
            + int(bool(self.optimizer_magnitude_pruning))
        )
        if n_reset_modes > 1:
            raise ValueError(
                "reset_optimizer_on_relora, optimizer_random_pruning and "
                "optimizer_magnitude_pruning are mutually exclusive"
            )
        if not 0 <= self.optimizer_random_pruning < 1:
            raise ValueError("optimizer_random_pruning must be in [0, 1)")
        if not 0 <= self.optimizer_magnitude_pruning < 1:
            raise ValueError("optimizer_magnitude_pruning must be in [0, 1)")

        if self.optimizer.lower() not in ("adam", "adamw", "adam_zero"):
            raise ValueError(f"Unsupported optimizer {self.optimizer!r}")

        if isinstance(self.skip_batches, str):
            self.skip_batches = set(map(int, self.skip_batches.split(",")))
        self.skip_batches = set(self.skip_batches or ())

        if self.quantize not in (None, "int8", "nf4"):
            raise ValueError(f"quantize must be None, 'int8' or 'nf4', got {self.quantize!r}")
        if str(self.lora_fused).lower() not in ("false", "true", "auto"):
            raise ValueError(
                f"lora_fused must be 'false', 'true' or 'auto', got {self.lora_fused!r}"
            )
        self.lora_fused = str(self.lora_fused).lower()
        if self.base_dtype not in (None, "bf16"):
            raise ValueError(f"base_dtype must be None or 'bf16', got {self.base_dtype!r}")
        if self.base_dtype and self.quantize:
            raise ValueError("base_dtype applies to the unquantized base; drop it or quantize")
        if self.remat_policy not in ("full", "dots", "dots_narrow", "dots_all"):
            raise ValueError(
                "remat_policy must be 'full', 'dots', 'dots_narrow' or 'dots_all', "
                f"got {self.remat_policy!r}"
            )

        if not 0 <= self.prune_sparsity < 1:
            raise ValueError(f"prune_sparsity must be in [0, 1), got {self.prune_sparsity}")
        if self.prune_scope not in ("global", "per_matrix"):
            raise ValueError(
                f"prune_scope must be 'global' or 'per_matrix', got {self.prune_scope!r}"
            )
        if self.prune_nm is not None:
            from relora_tpu.compress.prune import parse_nm

            parse_nm(self.prune_nm)  # raises on malformed "N:M"
        if self.reset_init not in ("random", "magnitude"):
            raise ValueError(
                f"reset_init must be 'random' or 'magnitude', got {self.reset_init!r}"
            )
        if self.prune_start_step < 0:
            raise ValueError("prune_start_step must be >= 0")
        if (self.prune_sparsity or self.prune_nm) and not self.use_peft:
            raise ValueError(
                "base-weight pruning retrains through the LoRA factors; "
                "it requires use_peft=true (PERP regime)"
            )

        if self.log_every < 1:
            raise ValueError("log_every must be >= 1")
        if self.save_retries < 0:
            raise ValueError("save_retries must be >= 0")
        if self.spike_threshold < 0:
            raise ValueError("spike_threshold must be >= 0 (0 disables spike rollback)")
        if self.spike_threshold > 0:
            if self.spike_patience < 1:
                raise ValueError("spike_patience must be >= 1")
            if self.spike_min_history < 4:
                raise ValueError("spike_min_history must be >= 4")
            if self.spike_window < self.spike_min_history:
                raise ValueError("spike_window must be >= spike_min_history")
            if self.spike_rollback_margin < 0:
                raise ValueError("spike_rollback_margin must be >= 0")
            if self.max_spike_rollbacks < 1:
                raise ValueError("max_spike_rollbacks must be >= 1")

        self._finalized = True
        return self

    # ------------------------------------------------------------------
    @property
    def prune_enabled(self) -> bool:
        """True when the prune-retrain pipeline is active (either dial)."""
        return bool(self.prune_sparsity or self.prune_nm)

    @property
    def optimizer_reset_mode(self) -> Optional[str]:
        """Which of the three mutually exclusive reset modes is active."""
        if self.reset_optimizer_on_relora:
            return "zero"
        if self.optimizer_random_pruning:
            return "random"
        if self.optimizer_magnitude_pruning:
            return "magnitude"
        return None

    @property
    def optimizer_reset_ratio(self) -> float:
        if self.optimizer_random_pruning:
            return self.optimizer_random_pruning
        if self.optimizer_magnitude_pruning:
            return self.optimizer_magnitude_pruning
        return 1.0

    def grad_accum_for(self, n_data_parallel: int) -> int:
        """Derive grad-accum from total batch (parity: torchrun_main.py:357-364)."""
        ga = self.total_batch_size // (self.batch_size * n_data_parallel)
        if ga <= 0 or self.total_batch_size != self.batch_size * ga * n_data_parallel:
            raise ValueError(
                f"total_batch_size={self.total_batch_size} must equal "
                f"batch_size={self.batch_size} * grad_accum * dp={n_data_parallel}"
            )
        return ga

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("_finalized", None)
        d["skip_batches"] = sorted(d.get("skip_batches") or ())
        return d

    def save(self, path: str) -> None:
        """Persist resolved config (parity: save_dir/training_config.yaml)."""
        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)


def parse_train_args(argv: Optional[list[str]] = None) -> TrainingConfig:
    """CLI entry: every reference flag, plus a YAML full-override path.

    Like the reference (args_utils.py:9-21), ``--training_config file.yaml``
    replaces all other flags and may not be mixed with them.
    """
    import argparse

    parser = argparse.ArgumentParser(description="relora-tpu training")
    parser.add_argument("--training_config", type=str, default=None)
    bool_t = lambda x: str(x).lower() == "true"
    for f in dataclasses.fields(TrainingConfig):
        if f.name in ("_finalized",):
            continue
        arg = f"--{f.name}"
        if f.name == "training_config":
            continue
        ann = str(f.type)
        if ann == "bool" or isinstance(f.default, bool):
            parser.add_argument(arg, type=bool_t, default=f.default)
        elif "float" in ann or isinstance(f.default, float):
            parser.add_argument(arg, type=float, default=f.default)
        elif "int" in ann or isinstance(f.default, int):
            parser.add_argument(arg, type=int, default=f.default)
        else:
            parser.add_argument(arg, default=f.default)
    ns = parser.parse_args(argv)

    if ns.training_config is not None:
        import sys

        n_extra = len([a for a in (argv if argv is not None else sys.argv[1:]) if a.startswith("--")])
        if n_extra > 1:
            raise RuntimeError(
                "Provide either --training_config or individual flags, not both"
            )
        return TrainingConfig.from_yaml(ns.training_config)

    kwargs = {k: v for k, v in vars(ns).items() if k != "training_config"}
    return TrainingConfig(**kwargs).finalize()
