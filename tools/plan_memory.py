"""Per-device HBM budget planner for a model/mesh/recipe combination.

Answers "does this fit?" before burning pod time — entirely via
``jax.eval_shape`` (abstract shapes, zero allocation), so 7B-scale plans run
on a laptop.  Accounts for:

- frozen base params (bf16/f32, int8 or NF4+double-quant footprints),
- LoRA factors + their Adam moments (the only optimizer state ReLoRA keeps),
- full-rank Adam moments when --rank 0 (the comparison case),
- gradients for trainables,
- activation residuals at the chosen microbatch/seq under the remat policy
  ('full' keeps per-layer boundaries; 'dots' adds the saved matmul outputs;
  'none' estimates the dense residuals incl. the S^2 attention scores XLA
  keeps for backward — measured on-chip, BASELINE.md round-2 finding 2),
- the logits buffer (or its absence with --loss chunked).

Sharding: each param leaf divides by the product of mesh axes its logical
spec maps to (parallel/mesh.LOGICAL_RULES); activations divide by
data*fsdp (batch) and sequence (seq axis).

    python tools/plan_memory.py --model llama_7b --rank 256 --mesh fsdp=32,tensor=2 \
        --micro-batch 8 --seq 2048 --chip v5p
    python tools/plan_memory.py --model llama_1b --rank 128 --micro-batch 8 --seq 1024

``plan()`` is importable (tools/dryrun_at_shape.py asserts live sharded-array
sizes against it at real hidden/vocab dims).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHIP_HBM = {"v5e": 16e9, "v5p": 95e9, "v4": 32e9}


def parse_mesh(mesh: str) -> dict:
    factors = {}
    if mesh:
        for part in mesh.split(","):
            k, v = part.split("=")
            factors[k.strip()] = int(v)
    return factors


def plan(
    model: str,
    *,
    rank: int = 128,
    mesh: str = "",
    micro_batch: int = 8,
    seq: int = 1024,
    dtype: str = "bf16",
    quantize=None,
    base_dtype=None,
    remat: str = "full",
    loss: str = "dense",
    chip: str = "v5e",
    layers: int = 0,
) -> dict:
    """Analytic per-device memory plan.  ``layers`` > 0 overrides the model's
    layer count (used by dryrun_at_shape to compare against a reduced-depth
    live run at real hidden/vocab dims).  Caller is responsible for the JAX
    platform (this only uses eval_shape — no device memory is touched)."""
    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import MODEL_ZOO, load_model_config
    from relora_tpu.core.relora import LoraSpec, frozen_param_mask
    from relora_tpu.models.llama import LlamaForCausalLM
    from relora_tpu.models.params_util import logical_partition_specs
    from relora_tpu.parallel.mesh import LOGICAL_RULES

    mesh_factors = parse_mesh(mesh)
    n_devices = math.prod(mesh_factors.values()) if mesh_factors else 1
    rules = dict(LOGICAL_RULES)

    def shard_div(logical_spec) -> int:
        """How many ways this leaf is split across the mesh."""
        div = 1
        for axis_name in logical_spec or ():
            mesh_axes = rules.get(axis_name)
            if mesh_axes is None:
                continue
            if isinstance(mesh_axes, str):
                mesh_axes = (mesh_axes,)
            for m in mesh_axes:
                div *= mesh_factors.get(m, 1)
        return div

    cfg = MODEL_ZOO[model] if model in MODEL_ZOO else load_model_config(model)
    if layers:
        cfg = dataclasses.replace(cfg, num_hidden_layers=layers)
    # build WITH quantize so the abstract tree carries the real quantized
    # leaves (codes / scales, incl. the odd-width int8 fallback): frozen
    # bytes are then computed exactly from leaf shapes+dtypes instead of an
    # approximate per-element factor model
    spec = (
        LoraSpec(r=rank, alpha=32, dropout=0.0, quantize=quantize, base_dtype=base_dtype)
        if rank
        else None
    )
    jdtype = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    mdl = LlamaForCausalLM(cfg, lora=spec, dtype=jdtype, scan_layers=True)
    sample = jnp.zeros((1, 8), jnp.int32)
    abstract = jax.eval_shape(lambda: mdl.init(jax.random.PRNGKey(0), sample))["params"]
    specs = logical_partition_specs(mdl, sample)

    import flax.linen as nn

    abstract = nn.meta.unbox(abstract)

    # the REAL trainability rule (core/relora.py::trainable_param_mask):
    # everything trains except the frozen base kernels of LoRA-wrapped
    # Denses — embeddings/norms/head carry Adam state too, and only those
    # frozen kernels are ever quantized (ops/quant.py)
    frozen_mask = frozen_param_mask(abstract) if rank else None

    # --- params + optimizer + grads -----------------------------------
    frozen_bytes = trainable_bytes = opt_bytes = grad_bytes = 0.0
    flat = jax.tree_util.tree_flatten_with_path(abstract)[0]
    flat_specs = {
        tuple(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]
    }
    flat_frozen = (
        {
            tuple(str(getattr(k, "key", k)) for k in path): f
            for path, f in jax.tree_util.tree_flatten_with_path(frozen_mask)[0]
        }
        if frozen_mask is not None
        else {}
    )
    for path, leaf in flat:
        key = tuple(str(getattr(k, "key", k)) for k in path)
        div = shard_div(flat_specs.get(key))
        n = leaf.size / div
        trainable = not flat_frozen.get(key, False) if rank else True
        # param storage dtype: params are stored f32 (master); the frozen
        # base's leaves are whatever the model actually declares (f32
        # kernels, or int8/nf4 codes + scales when quantize is set — the
        # abstract tree was built with the real quantize mode, so
        # size × itemsize is exact, replication of small scale leaves
        # included via their own sharding specs)
        if trainable:
            trainable_bytes += n * 4
            opt_bytes += n * 4 * 2  # adam mu+nu f32
            grad_bytes += n * 4
        else:
            frozen_bytes += n * leaf.dtype.itemsize
    # --- activations ---------------------------------------------------
    B, S, H, L = micro_batch, seq, cfg.hidden_size, cfg.num_hidden_layers
    batch_div = mesh_factors.get("data", 1) * mesh_factors.get("fsdp", 1)
    seq_div = mesh_factors.get("sequence", 1)
    bytes_el = 2 if dtype == "bf16" else 4
    tok = (B / batch_div) * (S / seq_div)
    heads = cfg.num_attention_heads / mesh_factors.get("tensor", 1)
    # Per-layer dot outputs saved by the 'dots' family of remat policies:
    # hidden-width — q, k, v, attn out-proj, mlp down-proj (its OUTPUT is
    # H-wide even though its input is inter-wide) plus the layer-boundary
    # residual = 6×H; inter-width — mlp gate and up projections = 2×inter.
    # 'dots_narrow' recomputes exactly those 2 inter-width dots
    # (params_util.remat_policy 'dots_narrow'), so both policies must share
    # one inter count for the predicted dots→dots_narrow saving
    # (2 × inter × tok × bytes_el per layer) to match the policy's true
    # delta.  (Earlier accounting charged dots 3×inter / dots_narrow 5×H,
    # which overstated the saving by inter−H per token per layer.)
    n_hidden_dots, n_inter_dots = 6, 2
    if remat == "full":
        act = L * tok * H * bytes_el  # layer-boundary residual per layer
    elif remat == "dots":
        inter = cfg.intermediate_size / mesh_factors.get("tensor", 1)
        per_layer = tok * (H * n_hidden_dots + inter * n_inter_dots) * bytes_el
        act = L * per_layer
    elif remat == "dots_narrow":
        per_layer = tok * (H * n_hidden_dots) * bytes_el
        act = L * per_layer
    elif remat == "dots_all":
        # dots_saveable additionally keeps the S^2-per-head attention
        # logits as residuals, in COMPUTE dtype (params_util.remat_policy)
        inter = cfg.intermediate_size / mesh_factors.get("tensor", 1)
        per_layer = tok * (H * n_hidden_dots + inter * n_inter_dots) * bytes_el + (
            (B / batch_div) * heads * (S / seq_div) * S * bytes_el
        )
        act = L * per_layer
    else:  # none: dense residuals incl. f32 S^2 attention probs (measured)
        inter = cfg.intermediate_size / mesh_factors.get("tensor", 1)
        per_layer = tok * (H * 8 + inter * 3) * bytes_el + (
            (B / batch_div) * heads * (S / seq_div) * S * 4
        )
        act = L * per_layer
    logits = 0 if loss == "chunked" else tok * cfg.vocab_size * 4
    total = frozen_bytes + trainable_bytes + opt_bytes + grad_bytes + act + logits
    hbm = CHIP_HBM[chip]
    return {
        "model": model,
        "devices": n_devices,
        # unrounded, for tools asserting live measurements against the plan
        # (the _gb fields are display-rounded to 1 MB and can carry >10%
        # relative rounding error on small components)
        "per_device_bytes": {
            "frozen_params": frozen_bytes,
            "trainable_params": trainable_bytes,
            "adam_moments": opt_bytes,
            "grads": grad_bytes,
            "activations": act,
            "logits": logits,
            "total": total,
        },
        "per_device_gb": {
            "frozen_params": round(frozen_bytes / 1e9, 3),
            "trainable_params": round(trainable_bytes / 1e9, 3),
            "adam_moments": round(opt_bytes / 1e9, 3),
            "grads": round(grad_bytes / 1e9, 3),
            "activations": round(act / 1e9, 3),
            "logits": round(logits / 1e9, 3),
            "total": round(total / 1e9, 3),
        },
        "chip": chip,
        "hbm_gb": hbm / 1e9,
        # budget = 0.9*HBM (10% reserved for XLA workspace); headroom is
        # against the same budget so fits=false never shows positive headroom
        "budget_gb": round(hbm * 0.9 / 1e9, 2),
        "fits": total < hbm * 0.9,
        "headroom_gb": round((hbm * 0.9 - total) / 1e9, 2),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama_1b")
    p.add_argument("--rank", type=int, default=128, help="0 = full-rank training")
    p.add_argument("--mesh", default="", help="e.g. fsdp=8,tensor=2 (default: single chip)")
    p.add_argument("--micro-batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--quantize", default=None, choices=[None, "int8", "nf4"])
    p.add_argument("--base-dtype", default=None, choices=[None, "bf16"],
                   help="unquantized frozen-base storage dtype (default f32 master)")
    p.add_argument(
        "--remat", default="full", choices=["full", "dots", "dots_narrow", "dots_all", "none"]
    )
    p.add_argument("--loss", default="dense", choices=["dense", "chunked"])
    p.add_argument("--chip", default="v5e", choices=sorted(CHIP_HBM))
    p.add_argument("--layers", type=int, default=0, help="override layer count")
    args = p.parse_args()

    # abstract-only tool: always run on CPU (eval_shape never touches a
    # device, and waiting on a TPU tunnel to plan memory would be absurd)
    os.environ["JAX_PLATFORMS"] = "cpu"
    from relora_tpu.utils.logging import honor_platform_request

    honor_platform_request()
    out = plan(
        args.model,
        rank=args.rank,
        mesh=args.mesh,
        micro_batch=args.micro_batch,
        seq=args.seq,
        dtype=args.dtype,
        quantize=args.quantize,
        base_dtype=args.base_dtype,
        remat=args.remat,
        loss=args.loss,
        chip=args.chip,
        layers=args.layers,
    )
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
