from relora_tpu.core.schedules import (
    linear_with_warmup,
    cyclical_cosine_with_min_lr,
    cosine_with_restarts,
    make_schedule,
)
from relora_tpu.core.optim import (
    build_optimizer,
    lora_label_tree,
    reset_optimizer_state,
    zeroed_fraction,
)
from relora_tpu.core.relora import (
    LoraSpec,
    is_lora_path,
    merge_and_reinit,
    lora_param_mask,
    split_param_counts,
)
