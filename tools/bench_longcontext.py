"""Long-context ring attention memory/throughput measurement.

Two modes:

- ``--mode memory`` (any host, no TPU needed): compile the sequence-sharded
  ring attention on a virtual device mesh and report XLA's peak temp-buffer
  allocation per device as a function of the flash key-tile size.  This is
  the O(S_loc·tile) vs O(S_loc²) claim, measured from the compiler's own
  buffer assignment rather than estimated.
- ``--mode throughput`` (real chip): time a jitted fwd+bwd of the flash
  ring fold body at long context on a single device (ring=1 degenerates to
  pure flash-tiled attention — the per-device compute path of the ring).

Prints one JSON line per configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_memory(seq: int, ring: int, tiles, heads: int, kv_heads: int, head_dim: int):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={ring}"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # the sandbox's sitecustomize registers the TPU backend at interpreter
    # start; env vars alone don't stick (see tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from relora_tpu.parallel.mesh import MeshSpec, make_mesh
    from relora_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh(MeshSpec(data=1, sequence=ring))
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    B = 1
    q = jnp.zeros((B, seq, heads, head_dim), jnp.bfloat16)
    k = jnp.zeros((B, seq, kv_heads, head_dim), jnp.bfloat16)
    v = jnp.zeros((B, seq, kv_heads, head_dim), jnp.bfloat16)
    args = tuple(jax.device_put(x, spec) for x in (q, k, v))

    for tile in tiles:
        # sweep: each iteration compiles a DIFFERENT tile config on purpose
        fn = jax.jit(  # noqa: RTL103
            lambda a, b, c, t=tile: ring_attention(a, b, c, mesh, causal=True, tile=t)
        )
        mem = fn.lower(*args).compile().memory_analysis()
        print(
            json.dumps(
                {
                    "metric": f"ring-attn peak temp MiB (seq={seq}, ring={ring}, tile={tile})",
                    "value": round(mem.temp_size_in_bytes / 2**20 / ring, 1),
                    "unit": "MiB/device",
                    "detail": {
                        "seq_local": seq // ring,
                        "heads": heads,
                        "kv_heads": kv_heads,
                        "argument_MiB": round(mem.argument_size_in_bytes / 2**20, 1),
                    },
                }
            ),
            flush=True,
        )


def measure_throughput(seq: int, tiles, heads: int, kv_heads: int, head_dim: int):
    import time

    import jax
    import jax.numpy as jnp

    from relora_tpu.parallel.mesh import MeshSpec, make_mesh
    from relora_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh(MeshSpec(data=1, sequence=1))
    B = 1
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, seq, heads, head_dim), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, seq, kv_heads, head_dim), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, seq, kv_heads, head_dim), jnp.bfloat16)

    for tile in tiles:
        def loss(a, b, c, t=tile):
            return jnp.sum(
                ring_attention(a, b, c, mesh, causal=True, tile=t).astype(jnp.float32) ** 2
            )

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))  # noqa: RTL103 - per-tile sweep
        out = step(q, k, v)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(5):
            out = step(q, k, v)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 5
        # causal attention fwd+bwd ~ 3.5 * (2 * S^2 * H) * N FLOPs (0.5 causal)
        flops = 3.5 * 2 * seq * seq * head_dim * heads * B * 0.5
        print(
            json.dumps(
                {
                    "metric": f"flash-ring fwd+bwd (seq={seq}, tile={tile})",
                    "value": round(seq * B / dt, 1),
                    "unit": "tokens/sec",
                    "detail": {"step_ms": round(dt * 1e3, 2), "tflops": round(flops / dt / 1e12, 2)},
                }
            ),
            flush=True,
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("memory", "throughput"), default="memory")
    ap.add_argument("--seq", type=int, default=32768)
    ap.add_argument("--ring", type=int, default=8)
    ap.add_argument("--tiles", type=int, nargs="+", default=[4096, 1024, 512])
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    args = ap.parse_args()
    if args.mode == "memory":
        measure_memory(args.seq, args.ring, args.tiles, args.heads, args.kv_heads, args.head_dim)
    else:
        measure_throughput(args.seq, args.tiles, args.heads, args.kv_heads, args.head_dim)


if __name__ == "__main__":
    main()
