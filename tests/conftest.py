"""Test configuration: run everything on CPU with 8 virtual devices.

Multi-device sharding logic is testable without TPU hardware via XLA's host
platform device-count override — set before jax is first imported.
"""

import os

# Force, don't setdefault: the sandbox exports JAX_PLATFORMS=axon (the real
# TPU) and a sitecustomize re-asserts it, which would silently run the whole
# suite on the TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: makes repeated test runs much faster on the
# slow sandbox CPU (compile once, reuse across pytest invocations).
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
