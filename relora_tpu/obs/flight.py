"""Flight recorder: a bounded ring buffer of recent spans and events.

The last N spans before a fault are the forensics that aggregate metrics
cannot provide: *what was the run doing* when SIGTERM landed, when the loss
spiked, or when the process crashed?  Tracers feed every finished span into
a process-wide :class:`FlightRecorder` (deque ring buffers — O(1) append,
bounded memory, no I/O); ``train/resilience.PreemptionGuard`` and the
trainer's crash/rollback paths call :func:`dump_on_fault` to write the
buffer to disk as JSON that ``tools/trace_report.py`` renders.

Dump location, first match wins: ``RELORA_TPU_FLIGHT_DIR`` env, the dir set
via :func:`configure` (the trainer points this at ``save_dir``), the
current directory.  Dumps are written atomically (tmp + rename) because the
SIGTERM path may be mid-write when the process is killed for real.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "default_recorder",
    "configure",
    "dump_on_fault",
]

#: ring capacities — ~2k spans covers minutes of serving traffic or hundreds
#: of train steps at <1 MB resident; sized for forensics, not archival
SPAN_CAPACITY = 2048
EVENT_CAPACITY = 512


class FlightRecorder:
    """Thread-safe ring buffer of span/event dicts with atomic JSON dumps."""

    def __init__(self, span_capacity: int = SPAN_CAPACITY, event_capacity: int = EVENT_CAPACITY):
        self._lock = threading.Lock()
        self._spans: "collections.deque[Dict[str, Any]]" = collections.deque(maxlen=span_capacity)
        self._events: "collections.deque[Dict[str, Any]]" = collections.deque(maxlen=event_capacity)
        self.dropped_spans = 0  # total appends beyond capacity

    def add_span(self, span: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped_spans += 1
            self._spans.append(span)

    def add_event(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(event)

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._events.clear()
            self.dropped_spans = 0

    def dump(self, path: str, reason: str = "manual") -> str:
        """Write the buffer as JSON (atomic rename).  Returns the path."""
        with self._lock:
            payload = {
                "reason": reason,
                "wall_time": time.time(),
                "pid": os.getpid(),
                "dropped_spans": self.dropped_spans,
                "spans": list(self._spans),
                "events": list(self._events),
            }
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
        return path


# -- process default ---------------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()
_DUMP_DIR: Optional[str] = None


def default_recorder() -> FlightRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def configure(dump_dir: Optional[str] = None) -> None:
    """Set the preferred dump directory (the trainer passes its save_dir)."""
    global _DUMP_DIR
    _DUMP_DIR = dump_dir


def _dump_dir() -> str:
    return os.environ.get("RELORA_TPU_FLIGHT_DIR") or _DUMP_DIR or "."


def dump_on_fault(reason: str) -> Optional[str]:
    """Dump the default recorder to ``<dir>/flight_<reason>_<pid>.json``.

    Fault-path safe: never raises (a failed dump must not mask the original
    fault or break the signal handler), returns None if the buffer is empty
    or the write fails.
    """
    rec = default_recorder()
    try:
        if not rec.spans() and not rec.events():
            return None
        path = os.path.join(_dump_dir(), f"flight_{reason}_{os.getpid()}.json")
        return rec.dump(path, reason=reason)
    except Exception:
        return None
