"""Block-granular KV-cache paging: free-list allocator + prefix cache.

The contiguous engine reserves one ``cache_size``-length row per decode slot,
so cache HBM scales as ``max_batch × cache_size`` whether or not a request
ever fills its row.  The paged engine instead keeps one shared pool of
fixed-size token *pages* per layer — shape ``(num_pages, page_size, kv_heads,
head_dim)`` — and gives each request a *block table* mapping its logical page
index (``position // page_size``) to a pool page.  Cache HBM then scales
with the pages actually in flight, and the pool size is an operator dial
independent of ``max_batch``.

This module is the host-side bookkeeping for that pool (no jax imports — the
device side lives in ops/attention.paged_cached_attention and the models'
``attend_with_paged_cache``):

- :class:`PageAllocator` — a free-list stack over page ids with per-page
  refcounts.  ``alloc`` is all-or-nothing: a request either gets every page
  its worst case needs (``ceil((prompt + max_new_tokens) / page_size)``) or
  stays queued — mid-decode pool exhaustion is impossible by construction,
  so there is no preemption/swap path to get wrong.  Page id 0 is reserved
  as the **null page**: never allocated, it is where padded block-table
  entries point, so garbage writes from idle decode rows and chunk padding
  land in a page nothing ever reads unmasked.
- :class:`PrefixCache` — refcounted sharing of page-aligned prompt
  prefixes.  When a finished request's prompt fully covers pages
  ``0..k-1``, those pages are registered under the hash of their token
  content; a later request with the same prompt prefix increfs them into
  its own block table and starts prefilling *after* the shared portion —
  zero prefill for a repeated system prompt.  Entries are evicted LRU under
  allocation pressure; eviction only drops the cache's own references, so a
  page shared with an active request survives until that request retires.

All operations are O(1) per page touched and run on the scheduler's model
thread (single-threaded by the scheduler's contract, so no locking).
"""
# relora-lint: hot-path

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["NULL_PAGE", "PageAllocator", "PrefixCache", "pages_needed"]

#: reserved pool page: block-table padding points here, trash writes land
#: here, and the allocator never hands it out
NULL_PAGE = 0


def pages_needed(n_tokens: int, page_size: int) -> int:
    """Pages required to hold ``n_tokens`` cache entries (ceil division)."""
    return -(-n_tokens // page_size)


class PageAllocator:
    """Free-list allocator over pool pages ``1..num_pages-1`` (0 is null).

    ``alloc(n)`` pops ``n`` pages (refcount 1 each) or returns ``None``
    without allocating anything — the caller keeps the request queued and
    retries after pages free up.  ``incref``/``decref`` implement sharing
    (prefix cache): a page returns to the free list only when its last
    reference drops.

    ``page_bytes`` is the resident HBM one page costs across every layer —
    codes plus, for an int8 pool, its per-page scales (the scheduler passes
    ``engine.pool_bytes() / num_pages``).  It only feeds the ``used_bytes``
    / ``free_bytes`` accounting views; allocation itself counts pages.
    """

    def __init__(self, num_pages: int, page_size: int, page_bytes: int = 0):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2 (page 0 is reserved), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.page_bytes = page_bytes
        # stack: pop() hands out low page ids first (cosmetic, but makes the
        # allocation order deterministic for tests and debugging)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: List[int] = [0] * num_pages
        self.peak_used = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    @property
    def used_bytes(self) -> int:
        """HBM held by allocated pages (0 when ``page_bytes`` unset)."""
        return self.used_pages * self.page_bytes

    @property
    def free_bytes(self) -> int:
        return self.free_pages * self.page_bytes

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` pages, all-or-nothing.  Returns None when fewer than
        ``n`` pages are free (nothing is allocated in that case)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for page in pages:
            self._refs[page] = 1
        self.peak_used = max(self.peak_used, self.used_pages)
        return pages

    def incref(self, pages: Sequence[int]) -> None:
        for page in pages:
            if self._refs[page] < 1:
                raise ValueError(f"incref of free page {page}")
            self._refs[page] += 1

    def decref(self, pages: Sequence[int]) -> int:
        """Drop one reference per page; pages reaching zero return to the
        free list.  Returns how many pages were actually freed."""
        freed = 0
        for page in pages:
            if page == NULL_PAGE or not 0 < page < self.num_pages:
                raise ValueError(f"decref of invalid page {page}")
            if self._refs[page] < 1:
                raise ValueError(f"double free of page {page}")
            self._refs[page] -= 1
            if self._refs[page] == 0:
                self._free.append(page)
                freed += 1
        return freed

    def refcount(self, page: int) -> int:
        return self._refs[page]


@dataclasses.dataclass
class _PrefixEntry:
    pages: Tuple[int, ...]  # pool pages holding this prefix, logical order
    n_tokens: int  # len(pages) * page_size


class PrefixCache:
    """Digest-keyed cache of page-aligned prompt prefixes over a
    :class:`PageAllocator`.

    ``lookup(prompt)`` returns the longest cached page-aligned prefix of the
    prompt (pages increfed for the caller) — capped at ``(len(prompt)-1) //
    page_size`` pages so at least one prompt token is always re-prefilled
    (the first sampled token needs its logits).  ``register(prompt, pages)``
    files every page-aligned prefix of a *fully prefilled* prompt; only
    pages completely covered by prompt tokens are ever registered, so a
    donor's decode writes (at positions >= len(prompt)) never touch a
    shared page.  ``evict(n)`` drops least-recently-used entries until the
    allocator has ``n`` pages free — it only releases the cache's own
    references, so pages shared with live requests survive.
    """

    def __init__(self, allocator: PageAllocator, *, max_entries: int = 256):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.allocator = allocator
        self.max_entries = max_entries
        # insertion/touch order is the LRU order: move_to_end on every hit
        self._entries: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        self.lookups = 0
        self.hits = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    @staticmethod
    def _digest(tokens: Sequence[int]) -> bytes:
        h = hashlib.sha1()
        for t in tokens:
            h.update(int(t).to_bytes(8, "little", signed=True))
        return h.digest()

    def lookup(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page-aligned proper prefix of ``prompt``.  Returns
        ``(pages, n_tokens)`` with every returned page increfed for the
        caller (who must decref them at retire), or ``([], 0)``."""
        ps = self.allocator.page_size
        self.lookups += 1
        for k in range((len(prompt) - 1) // ps, 0, -1):
            digest = self._digest(prompt[: k * ps])
            entry = self._entries.get(digest)
            if entry is None:
                continue
            self._entries.move_to_end(digest)
            self.allocator.incref(entry.pages)
            self.hits += 1
            return list(entry.pages), entry.n_tokens
        return [], 0

    def register(self, prompt: Sequence[int], pages: Sequence[int]) -> int:
        """File every page-aligned prefix of a fully prefilled prompt whose
        block pages are ``pages`` (logical order).  Returns how many new
        entries were created.  Capacity overflow evicts LRU entries."""
        ps = self.allocator.page_size
        created = 0
        for k in range(1, len(prompt) // ps + 1):
            digest = self._digest(prompt[: k * ps])
            if digest in self._entries:
                self._entries.move_to_end(digest)
                continue
            entry = _PrefixEntry(pages=tuple(pages[:k]), n_tokens=k * ps)
            self.allocator.incref(entry.pages)
            self._entries[digest] = entry
            created += 1
            while len(self._entries) > self.max_entries:
                self._drop_lru()
        return created

    def digests(self, limit: int = 64) -> List[str]:
        """Hex digests of the most-recently-used entries, MRU first — the
        replica advertises these on /healthz for the fleet prefix-page
        directory (bounded so the payload stays scrape-sized)."""
        out: List[str] = []
        for digest in reversed(self._entries):
            out.append(digest.hex())
            if len(out) >= limit:
                break
        return out

    def acquire(self, digest_hex: str) -> Optional[Tuple[List[int], int]]:
        """Pin an entry's pages for an in-flight export: increfs every page
        and returns ``(pages, n_tokens)``, or None when the digest is not
        cached.  The caller must ``allocator.decref(pages)`` once the
        transfer completes — the pin is what keeps LRU eviction (or a
        concurrent ``clear``) from freeing a run mid-transfer."""
        try:
            digest = bytes.fromhex(digest_hex)
        except ValueError:
            return None
        entry = self._entries.get(digest)
        if entry is None:
            return None
        self._entries.move_to_end(digest)
        self.allocator.incref(entry.pages)
        return list(entry.pages), entry.n_tokens

    def evict(self, pages_wanted: int) -> int:
        """Drop LRU entries until the allocator has ``pages_wanted`` free
        pages or the cache is empty.  Returns pages actually freed."""
        freed = 0
        while self._entries and self.allocator.free_pages < pages_wanted:
            freed += self._drop_lru()
        return freed

    def clear(self) -> int:
        freed = 0
        while self._entries:
            freed += self._drop_lru()
        return freed

    def _drop_lru(self) -> int:
        _, entry = self._entries.popitem(last=False)
        return self.allocator.decref(entry.pages)

    def stats(self) -> Dict[str, float]:
        return {
            "entries": len(self._entries),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": round(self.hit_rate, 4),
        }
