"""Quantized-base feasibility replan (round-5 verdict item #2, offline half):
which (remat, loss, micro_batch, quantize) configs fit the 16 GB v5e at
llama_1b r=128 seq1024 once the frozen base is int8/nf4 instead of an f32
master.  Feasibility comes from the planner's own unrounded ``fits`` /
``headroom_gb`` fields (total < 90% of HBM — tools/plan_memory.py:214-215);
the display-rounded ``per_device_gb.total`` is recorded for the table only.

In-process plan() calls (pure eval_shape arithmetic, no device memory), so
the full 216-config grid runs in seconds — this sweep is also queued for
tunnel-recovery windows where wall time is chip time.

Usage::

    JAX_PLATFORMS=cpu python scripts/quant_replan.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from relora_tpu.utils.logging import honor_platform_request

honor_platform_request()

from tools.plan_memory import plan  # noqa: E402

OUT = "bench_results/r5_quant_feasible.json"


def main() -> None:
    rows = []
    # "bf16" rides the quantize axis of the sweep but is LoraSpec.base_dtype
    # (unquantized bf16 storage) — round-5 addition after the on-chip OOM
    # dumps showed the f32 master costs ~5 GB of hoisted convert temps the
    # planner can't see; bf16 storage has no such temps
    for quantize in (None, "bf16", "int8", "nf4"):
        for loss in ("dense", "chunked"):
            for remat in ("full", "dots", "dots_all"):
                for mb in (2, 4, 8, 16, 24, 32, 48, 64, 96):
                    p = plan(
                        "llama_1b", rank=128, seq=1024, chip="v5e",
                        micro_batch=mb, remat=remat, loss=loss,
                        quantize=None if quantize == "bf16" else quantize,
                        base_dtype="bf16" if quantize == "bf16" else None,
                    )
                    rows.append({
                        "quantize": quantize or "f32", "loss": loss,
                        "remat": remat, "micro_batch": mb,
                        "planned_total_gb": p["per_device_gb"]["total"],
                        "fits_90pct": p["fits"],
                        "headroom_gb": p["headroom_gb"],
                    })
    feasible = [r for r in rows if r["fits_90pct"]]
    best = {}
    for r in feasible:
        k = (r["quantize"], r["loss"], r["remat"])
        if k not in best or r["micro_batch"] > best[k]["micro_batch"]:
            best[k] = r
    result = {
        "experiment": "llama_1b r=128 seq1024 single v5e (16 GB, 90% budget): "
                      "feasible (remat, loss, micro_batch) set by frozen-base storage",
        "baseline_note": "r4 ranking found dots/dots_all infeasible above mb4/mb2 "
                         "with an f32 master base (bench_results/r4_lever_rank.json)",
        "findings": [
            "quantized base does NOT admit dots at mb8+: dots-remat activations, "
            "not the frozen base, are the wall there (the r4 hypothesis that freed "
            "HBM would admit dots mb8-16 is refuted by the plan)",
            "what it does buy: ~3.6-4.1 GB headroom at dots/chunked mb4 "
            "(14.08 -> 10.46/10.01 GB) -- the config the f32 plan called 'tight' "
            "and r1's compile rejected; dots_all mb2 now fits even with dense loss",
            "full-remat chunked grows mb48 -> mb64 (11.7/11.2 GB int8/nf4)",
            "on-chip A/B still required: the r2 measurement showed logits-side "
            "levers are noise, so the quantized-base win must be measured, not "
            "assumed (queued in the recovery watcher)",
        ],
        "largest_feasible_mb": {f"{q}/{l}/{m}": r for (q, l, m), r in sorted(best.items())},
        "grid": rows,
    }
    with open(OUT, "w") as f:
        json.dump(result, f, indent=2)
    for k, r in sorted(best.items()):
        print(k, "-> mb", r["micro_batch"], f"({r['planned_total_gb']} GB)")
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
