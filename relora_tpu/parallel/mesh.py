"""Device mesh and sharding rules — the distributed runtime.

The reference's distributed layer is torch.distributed + NCCL: DDP gradient
all-reduce (torchrun_main.py:616-622), ZeRO-1 optimizer-state sharding
(:668-675), rank-sliced batches (megatron_dataset/samplers.py).  None of that
survives as explicit code here: we declare a ``jax.sharding.Mesh`` over up to
four axes and annotate arrays; XLA/GSPMD compiles in the collectives
(reduce-scatter/all-gather over ICI, psum for loss aggregation).

Axes:

- ``data``     — pure data parallelism (batch sharding).  DDP equivalent.
- ``fsdp``     — parameter/optimizer sharding (embed dim of every kernel +
  batch).  Subsumes both ZeRO-1 and the FSDP the reference had to disable
  (torchrun_main.py:611-613): merge-and-reinit is a sharded pytree update
  here, so the conflict never existed.
- ``tensor``   — Megatron-style tensor parallelism (qkv/mlp/vocab dims).
- ``sequence`` — context parallelism for long sequences (ring attention).

Logical-to-mesh translation follows the t5x/flax convention: modules annotate
params with *logical* axis names; ``LOGICAL_RULES`` maps those to mesh axes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQUENCE_AXIS = "sequence"

# logical axis name -> mesh axis (None = replicated)
LOGICAL_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", (DATA_AXIS, FSDP_AXIS)),
    ("embed", FSDP_AXIS),
    ("vocab", TENSOR_AXIS),
    ("qkv", TENSOR_AXIS),
    ("mlp", TENSOR_AXIS),
    ("heads", TENSOR_AXIS),
    # k/v projection output dim (kv_heads * head_dim) shards over tensor
    # like the q projection, so a tp group splits attention by head end to
    # end; the serving page pool shards its kv_heads axis to match
    # (serve/engine.py pool_shardings)
    ("kv", TENSOR_AXIS),
    ("seq", SEQUENCE_AXIS),
    ("lora", None),  # LoRA factors are small: replicate by default
    ("layers", None),  # scan axis stays unsharded
)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """How to factor the device grid.  ``data=-1`` fills remaining devices."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int, int, int]:
        fixed = self.fsdp * self.tensor * self.sequence
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fsdp*tensor*sequence={fixed}"
                )
            data = n_devices // fixed
        if data * fixed > n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.tensor}x{self.sequence} needs "
                f"{data * fixed} devices but only {n_devices} exist"
            )
        return (data, self.fsdp, self.tensor, self.sequence)


def make_mesh(spec: MeshSpec = MeshSpec(), devices: Optional[Sequence] = None) -> Mesh:
    """Build the mesh; an explicit spec smaller than the device pool uses the
    first N devices (useful for tests and debugging on shared hosts)."""
    devices = list(devices if devices is not None else jax.devices())
    shape = spec.resolve(len(devices))
    n_used = int(np.prod(shape))
    grid = np.asarray(devices[:n_used]).reshape(shape)
    return Mesh(grid, (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, SEQUENCE_AXIS))


def param_shardings(mesh: Mesh, logical_specs: PyTree) -> PyTree:
    """NamedSharding tree from the model's logical PartitionSpecs
    (models.params_util.logical_partition_specs)."""
    return nn.logical_to_mesh_sharding(logical_specs, mesh, list(LOGICAL_RULES))


def batch_sharding(mesh: Mesh, seq_sharded: bool = False) -> NamedSharding:
    """Sharding for a ``(grad_accum, batch, seq)`` token array: batch over
    data+fsdp, optionally sequence over the sequence axis (context
    parallelism)."""
    if seq_sharded:
        return NamedSharding(mesh, P(None, (DATA_AXIS, FSDP_AXIS), SEQUENCE_AXIS))
    return NamedSharding(mesh, P(None, (DATA_AXIS, FSDP_AXIS)))


def eval_batch_sharding(mesh: Mesh, seq_sharded: bool = False) -> NamedSharding:
    """Sharding for a 2-D ``(batch, seq)`` eval array."""
    if seq_sharded:
        return NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS), SEQUENCE_AXIS))
    return NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: PyTree, shardings: PyTree) -> PyTree:
    """Place a host-resident param tree onto the mesh."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, shardings
    )


def partition_rule_version() -> str:
    """Stable fingerprint of ``LOGICAL_RULES``.

    Stamped into checkpoint manifests so a restore site can tell whether the
    checkpoint's arrays were laid out under the same logical->mesh mapping.
    Chip count and mesh shape may change across an elastic resume (that is
    the point of train/elastic.py); the rule table may not — resharding
    re-applies the rules by name, so a renamed or remapped logical axis would
    silently place arrays wrong.
    """
    import hashlib

    return hashlib.sha1(repr(LOGICAL_RULES).encode()).hexdigest()[:12]


def mesh_metadata(mesh: Optional[Mesh]) -> dict:
    """JSON-safe description of the mesh a checkpoint was saved under:
    axis-name -> size shape, total chip count, and the partition-rule
    fingerprint.  ``mesh=None`` (single-device training) records chip count 1
    and an empty shape."""
    if mesh is None:
        shape: dict = {}
        chips = 1
    else:
        shape = {name: int(size) for name, size in mesh.shape.items()}
        chips = int(np.prod(list(mesh.shape.values())))
    return {
        "mesh_shape": shape,
        "chip_count": chips,
        "partition_rule_version": partition_rule_version(),
    }


# ---------------------------------------------------------------------------
# current-mesh registry: ops that need an explicit mesh (e.g. the ring
# attention shard_map) read it here; the Trainer/driver sets it once.
# ---------------------------------------------------------------------------

_CURRENT_MESH: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH
