"""Continuous-deployment tests: publish/watch, in-place reload, canary gate.

The zero-downtime acceptance criteria on CPU with a tiny model:

- the trainer's manifest-commit fence atomically publishes a ``latest``
  pointer, and the CheckpointWatcher NEVER hands an unverified or torn dir
  to its callback (corrupt dirs are rejected with the failing file named);
- ``engine.reload_params`` swaps the full merged tree in place with zero
  steady-state retraces, and an identical tree yields token-identical
  greedy output across the swap;
- the server's ``/admin/reload`` fences the swap between decode rounds:
  in-flight requests finish (on the old weights), the version only moves
  on full success, and an injected apply failure (``deploy_reload``) fails
  closed with the old weights still serving;
- the RollingUpdater's canary gate rolls the WHOLE fleet back on a
  divergent replica while concurrent in-flight requests all complete, and
  a crash mid-update (``deploy_crash_mid_update``) leaves a mixed fleet
  that a plain re-run converges to one consistent version.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import init_params
from relora_tpu.serve import deploy
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.sampling import SamplingParams
from relora_tpu.utils import faults

from tests.test_server import _Server, _generate, _http  # shared serving idioms

pytestmark = pytest.mark.serve

TINY = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=512,
)
CACHE = 512


@pytest.fixture
def disarm_faults():
    faults.reset()
    yield
    faults.reset()


def _build_engine():
    model = build_decode_model(TINY, cache_size=CACHE)
    base = type(model)(TINY, lora=None, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return InferenceEngine(TINY, params, cache_size=CACHE)


@pytest.fixture(scope="module")
def engine():
    return _build_engine()


@pytest.fixture(scope="module")
def engine_b():
    return _build_engine()


def _host_tree(engine):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(engine.params))


def _perturb_tree(tree, seed):
    """A deterministically different model: additive noise on every leaf.
    (Uniform scaling would be normalized away by RMSNorm and leave greedy
    argmax unchanged — noise actually moves the canary outputs.)"""
    rng = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) + rng.normal(scale=0.1, size=np.shape(x)).astype(
            np.asarray(x).dtype
        ),
        tree,
    )


def _greedy(engine, prompt, n=8):
    return engine.generate(
        [prompt],
        max_new_tokens=n,
        sampling=SamplingParams(temperature=0.0),
        eos_id=-1,
        key=jax.random.PRNGKey(0),
    )[0]


# -- publish + watch ----------------------------------------------------------


def test_checkpoint_step_parses_model_dirs():
    assert deploy.checkpoint_step("/a/b/model_32") == 32
    assert deploy.checkpoint_step("model_0") == 0
    assert deploy.checkpoint_step("/a/b/model_32/") == 32
    assert deploy.checkpoint_step("/a/b/notacheckpoint") is None
    assert deploy.checkpoint_step("/a/b/model_x") is None


def test_publish_and_read_latest_atomic(tmp_path):
    save_dir = str(tmp_path)
    ckpt = tmp_path / "model_16"
    ckpt.mkdir()
    deploy.publish_latest(save_dir, str(ckpt))
    assert deploy.read_latest(save_dir) == str(ckpt)
    # a torn pointer write must read as absent, not as an error
    with open(tmp_path / deploy.LATEST_FILE, "w") as f:
        f.write('{"path": "mod')
    assert deploy.read_latest(save_dir) is None
    # pointer escaping the save dir is refused
    with open(tmp_path / deploy.LATEST_FILE, "w") as f:
        json.dump({"path": "../evil"}, f)
    assert deploy.read_latest(save_dir) is None


def _save_real_checkpoint(tmp_path, step, devices):
    """A real manifest-committed checkpoint via the trainer's save path."""
    from relora_tpu.parallel.mesh import MeshSpec, make_mesh
    from relora_tpu.train import checkpoint as ckpt
    from tests.test_checkpoint import make_state

    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh, 8)
    path = ckpt.save_checkpoint(str(tmp_path), step, state, {"update_step": step})
    ckpt.wait_for_save()
    return path


def _corrupt_state_file(path):
    """Flip one byte in a state payload file; returns the file touched."""
    for root, _dirs, files in os.walk(os.path.join(path, "state")):
        for name in files:
            target = os.path.join(root, name)
            if os.path.getsize(target) > 0:
                with open(target, "r+b") as f:
                    byte = f.read(1)
                    f.seek(0)
                    f.write(bytes([byte[0] ^ 0xFF]))
                return target
    raise AssertionError(f"no non-empty state file under {path}")


def test_trainer_publishes_latest_at_manifest_commit(tmp_path, devices):
    path = _save_real_checkpoint(tmp_path, 16, devices)
    assert deploy.read_latest(str(tmp_path)) == os.path.abspath(path)


def test_watcher_never_acts_on_unverified_dirs(tmp_path, devices):
    accepted, rejected = [], []
    watcher = deploy.CheckpointWatcher(
        str(tmp_path),
        accepted.append,
        on_reject=lambda path, reason: rejected.append((path, reason)),
    )
    assert watcher.poll_once() is None  # no pointer yet: nothing to do

    path = _save_real_checkpoint(tmp_path, 16, devices)
    bad_file = _corrupt_state_file(path)
    assert watcher.poll_once() is None
    assert accepted == []  # the gate held
    assert len(rejected) == 1
    assert os.path.basename(bad_file) in rejected[0][1]  # names the file
    # unchanged bad dir: remembered, not re-verified and not re-reported
    assert watcher.poll_once() is None
    assert len(rejected) == 1

    # a new good checkpoint re-publishes the pointer; the watcher fires
    good = _save_real_checkpoint(tmp_path, 24, devices)
    assert watcher.poll_once() == os.path.abspath(good)
    assert accepted == [os.path.abspath(good)]
    # already current: no re-fire
    assert watcher.poll_once() is None
    assert len(accepted) == 1

    # a rollout that reports failure (on_new -> False) is NOT latched: the
    # next poll retries the same verified checkpoint until it succeeds
    newer = _save_real_checkpoint(tmp_path, 32, devices)
    attempts = []
    outcomes = [False, False, True]
    watcher.on_new = lambda p: (attempts.append(p), outcomes[len(attempts) - 1])[1]
    for _ in range(2):
        assert watcher.poll_once() is None  # failed rollout: retried
    assert watcher.poll_once() == os.path.abspath(newer)  # third try sticks
    assert attempts == [os.path.abspath(newer)] * 3
    assert watcher.poll_once() is None  # latched only after success


def test_restore_serving_params_refuses_corrupt_checkpoint(tmp_path, devices):
    from relora_tpu.train.checkpoint import restore_serving_params

    path = _save_real_checkpoint(tmp_path, 16, devices)
    bad_file = _corrupt_state_file(path)
    with pytest.raises(ValueError, match="refusing to serve"):
        restore_serving_params(path)
    try:
        restore_serving_params(path)
    except ValueError as e:
        assert os.path.basename(bad_file) in str(e)  # error names the file


# -- in-place engine reload ---------------------------------------------------


def test_reload_params_token_identical_and_zero_retrace(engine):
    prompt = [1, 2, 3, 4]
    before = _greedy(engine, prompt)
    host = _host_tree(engine)
    retraces0 = engine.compile_watcher.steady_state_retraces
    for _ in range(3):  # repeated reloads must pin ONE compiled signature
        engine.reload_params(host)
    after = _greedy(engine, prompt)
    assert after == before  # same weights in, token-identical greedy out
    assert engine.compile_watcher.steady_state_retraces == retraces0


def test_reload_params_changes_output_and_swaps_back(engine):
    prompt = [5, 6, 7]
    host = _host_tree(engine)
    before = _greedy(engine, prompt)
    engine.reload_params(_perturb_tree(host, seed=7))
    engine.reload_params(host)  # swap back
    assert _greedy(engine, prompt) == before


def _break_first_leaf(tree):
    """Replace the first array leaf with a wrong-shape array, in place."""
    for key, value in tree.items():
        if isinstance(value, dict):
            if _break_first_leaf(value):
                return True
        else:
            tree[key] = np.zeros((3, 3), np.float32)
            return True
    return False


def test_reload_params_rejects_bad_trees(engine):
    import copy

    host = _host_tree(engine)
    bad = copy.deepcopy(host)
    assert _break_first_leaf(bad)
    with pytest.raises(ValueError, match="shape"):
        engine.reload_params(bad)
    with pytest.raises(ValueError, match="does not exist in the live tree"):
        engine.reload_params({**host, "not_a_real_leaf": np.zeros(3, np.float32)})


# -- server reload boundary ---------------------------------------------------


def _serving_fleet_server(engine, trees, *, version=1, checkpoint="/ckpt/model_1", **kw):
    """A _Server whose /admin/reload maps fake checkpoint paths to prepared
    host trees — the transport/fencing layer under test, no disk IO."""

    def reload_prepare(path):
        tree = trees.get(os.path.abspath(path))
        if tree is None:
            raise ValueError(f"refusing to serve corrupt checkpoint {path}")
        return lambda: engine.reload_params(tree)

    return _Server(
        engine,
        reload_prepare=reload_prepare,
        weights_version=version,
        weights_checkpoint=checkpoint,
        **kw,
    )


def test_server_reload_between_decode_rounds(engine, disarm_faults):
    host = _host_tree(engine)
    trees = {"/ckpt/model_1": host, "/ckpt/model_2": host}
    with _serving_fleet_server(engine, trees, max_batch=2, max_queue=32) as server:
        port = server.port
        status, headers, _ = _http(port, "GET", "/healthz")
        payload = json.loads(_http(port, "GET", "/healthz")[2])
        assert payload["weights_version"] == 1
        assert payload["weights_checkpoint"] == "/ckpt/model_1"

        # concurrent load across the swap: nothing may drop
        results = []

        def pound():
            for _ in range(4):
                tokens, final = _generate(
                    port, {"prompt": [1, 2, 3], "max_new_tokens": 6}
                )
                results.append(final["finish_reason"])

        threads = [threading.Thread(target=pound) for _ in range(2)]
        for t in threads:
            t.start()
        status, _headers, body = _http(
            port, "POST", "/admin/reload", {"checkpoint": "/ckpt/model_2"}
        )
        for t in threads:
            t.join(120)
        assert status == 200, body
        reply = json.loads(body)
        assert reply["ok"] is True and reply["weights_version"] == 2
        assert len(results) == 8
        assert all(r in ("length", "eos") for r in results)  # zero dropped

        # the new version is on healthz AND stamped on every response
        assert json.loads(_http(port, "GET", "/healthz")[2])["weights_version"] == 2
        _status, headers, _body = _http(
            port, "POST", "/v1/generate", {"prompt": [1], "max_new_tokens": 2}
        )
        assert headers.get("x-relora-weights") == "2"

        # unknown checkpoint: prepare fails -> 422, version does not move
        status, _h, body = _http(
            port, "POST", "/admin/reload", {"checkpoint": "/ckpt/nope"}
        )
        assert status == 422
        assert json.loads(_http(port, "GET", "/healthz")[2])["weights_version"] == 2


@pytest.mark.faults
def test_injected_reload_failure_fails_closed(engine, disarm_faults):
    host = _host_tree(engine)
    trees = {"/ckpt/model_1": host, "/ckpt/model_2": host}
    faults.configure("deploy_reload", exc=RuntimeError)
    with _serving_fleet_server(engine, trees, max_queue=8) as server:
        port = server.port
        status, _h, body = _http(
            port, "POST", "/admin/reload", {"checkpoint": "/ckpt/model_2"}
        )
        assert status == 500
        reply = json.loads(body)
        assert reply["ok"] is False and "injected fault" in reply["error"]
        # failed closed: old version, old weights, still serving
        payload = json.loads(_http(port, "GET", "/healthz")[2])
        assert payload["status"] == "ok" and payload["weights_version"] == 1
        tokens, final = _generate(port, {"prompt": [1, 2], "max_new_tokens": 4})
        assert final["finish_reason"] in ("length", "eos")
        # the fault fired once; the retry goes through
        status, _h, body = _http(
            port, "POST", "/admin/reload", {"checkpoint": "/ckpt/model_2"}
        )
        assert status == 200 and json.loads(body)["weights_version"] == 2


# -- rolling update + canary + rollback ---------------------------------------


def _fleet(engine, engine_b, trees_a, trees_b):
    a = _serving_fleet_server(engine, trees_a, max_batch=2, max_queue=32)
    b = _serving_fleet_server(engine_b, trees_b, max_batch=2, max_queue=32)
    return a, b


def _updater(ports, events):
    return deploy.RollingUpdater(
        lambda: {i: ("127.0.0.1", p) for i, p in enumerate(ports)},
        canary_prompts=[[1, 2, 3], [7, 8]],
        canary_max_new_tokens=4,
        emit=lambda event, idx, detail: events.append((event, idx, detail)),
        probe_timeout_s=30.0,
        verify=lambda path: (True, "ok"),  # fake paths; transport under test
    )


def test_updater_refuses_partial_fleet():
    # a half-booted fleet (replica without a port yet) must not be walked:
    # updating only the visible replicas would latch a mixed-version fleet
    events = []
    updater = deploy.RollingUpdater(
        lambda: {0: ("127.0.0.1", 1), 1: ("127.0.0.1", None)},
        expect_replicas=2,
        emit=lambda event, idx, detail: events.append((event, idx, detail)),
        verify=lambda path: (True, "ok"),
    )
    assert updater.run("/ckpt/model_5") is False
    assert [e[0] for e in events] == ["deploy_reject"]
    assert "1/2" in str(events[0][2])


@pytest.mark.faults
def test_canary_failure_rolls_whole_fleet_back(engine, engine_b, disarm_faults):
    host_a, host_b = _host_tree(engine), _host_tree(engine_b)
    v2 = _perturb_tree(host_a, seed=1)
    trees_a = {"/ckpt/model_1": host_a, "/ckpt/model_2": v2}
    # replica b's "model_2" is a DIFFERENT tree: the canary must catch it
    trees_b = {"/ckpt/model_1": host_b, "/ckpt/model_2": _perturb_tree(host_b, seed=2)}
    sa, sb = _fleet(engine, engine_b, trees_a, trees_b)
    with sa as server_a, sb as server_b:
        ports = [server_a.port, server_b.port]
        events = []
        updater = _updater(ports, events)

        inflight = []

        def pound(port):
            for _ in range(3):
                _tokens, final = _generate(
                    port, {"prompt": [9, 9, 9], "max_new_tokens": 6}
                )
                inflight.append(final["finish_reason"])

        threads = [threading.Thread(target=pound, args=(p,)) for p in ports]
        for t in threads:
            t.start()
        assert updater.run("/ckpt/model_2") is False
        for t in threads:
            t.join(120)

        names = [e[0] for e in events]
        assert "deploy_canary_fail" in names
        assert "deploy_rollback" in names
        # the WHOLE fleet converged back onto version 1
        for port in ports:
            payload = json.loads(_http(port, "GET", "/healthz")[2])
            assert payload["status"] == "ok"
            assert payload["weights_version"] == 1
            assert payload["weights_checkpoint"] == "/ckpt/model_1"
        # zero dropped requests while the update failed and rolled back
        assert len(inflight) == 6
        assert all(r in ("length", "eos") for r in inflight)


@pytest.mark.faults
def test_crash_mid_update_converges_on_rerun(engine, engine_b, disarm_faults):
    host_a, host_b = _host_tree(engine), _host_tree(engine_b)
    # model_3 is the SAME weights on both replicas: a clean target
    trees_a = {"/ckpt/model_1": host_a, "/ckpt/model_3": _perturb_tree(host_a, seed=1)}
    trees_b = {"/ckpt/model_1": host_b, "/ckpt/model_3": _perturb_tree(host_b, seed=1)}
    sa, sb = _fleet(engine, engine_b, trees_a, trees_b)
    with sa as server_a, sb as server_b:
        ports = [server_a.port, server_b.port]
        events = []
        updater = _updater(ports, events)

        faults.configure("deploy_crash_mid_update", exc=RuntimeError)
        with pytest.raises(RuntimeError, match="deploy_crash_mid_update"):
            updater.run("/ckpt/model_3")
        # mid-update death: the fleet is split across versions
        versions = sorted(
            json.loads(_http(p, "GET", "/healthz")[2])["weights_version"]
            for p in ports
        )
        assert versions == [1, 3]

        # recovery is a plain re-run of the same target: no special casing
        faults.reset()
        assert updater.run("/ckpt/model_3") is True
        assert [e[0] for e in events].count("deploy_complete") == 1
        for port in ports:
            payload = json.loads(_http(port, "GET", "/healthz")[2])
            assert payload["status"] == "ok"
            assert payload["weights_version"] == 3
            assert payload["weights_checkpoint"] == "/ckpt/model_3"
        # engines really swapped: both replicas greedy-agree on the new tree
        outs = [
            _generate(p, {"prompt": [3, 1, 4], "max_new_tokens": 5})[0]
            for p in ports
        ]
        assert outs[0] == outs[1]
