"""Megatron data stack tests: mmap format roundtrip, C++-vs-NumPy
differential oracles (the reference's own strategy — SURVEY.md §2.2), packed
sample semantics, blending, split parsing, resume rewind."""

import os

import numpy as np
import pytest

from relora_tpu.data.blendable import BlendableDataset, build_blending_indices_py
from relora_tpu.data.megatron import (
    MegatronDataConfig,
    PackedBatchIterator,
    build_split_datasets,
    parse_split_string,
)
from relora_tpu.data.memmap import MemmapTokenDataset, MemmapTokenWriter, best_dtype
from relora_tpu.data.native import (
    build_blending_indices_native,
    build_sample_idx_native,
    load as load_native,
)
from relora_tpu.data.sample_index import (
    PackedCausalDataset,
    build_doc_idx,
    build_sample_idx_py,
    build_shuffle_idx,
    num_epochs_needed,
)


def write_corpus(tmp_path, n_docs=50, seed=0, vocab=1000):
    rs = np.random.RandomState(seed)
    prefix = str(tmp_path / "corpus")
    docs = []
    with MemmapTokenWriter(prefix, dtype=best_dtype(vocab)) as w:
        for _ in range(n_docs):
            doc = rs.randint(0, vocab, size=rs.randint(5, 200))
            docs.append(doc)
            w.add_document(doc)
    return prefix, docs


def test_memmap_roundtrip(tmp_path):
    prefix, docs = write_corpus(tmp_path)
    ds = MemmapTokenDataset(prefix)
    assert len(ds) == len(docs)
    assert ds.dtype == np.uint16
    for i in (0, 7, len(docs) - 1):
        np.testing.assert_array_equal(np.asarray(ds[i]), docs[i].astype(np.uint16))
    # partial reads
    np.testing.assert_array_equal(
        np.asarray(ds.get(3, offset=2, length=3)), docs[3][2:5].astype(np.uint16)
    )
    assert ds.n_tokens == sum(len(d) for d in docs)


def test_memmap_merge_file(tmp_path):
    """merge_file grafts shards bit-exactly (parity:
    MMapIndexedDatasetBuilder.merge_file_, indexed_dataset.py:596-603)."""
    pa, docs_a = write_corpus(tmp_path / "a", n_docs=7, seed=1)
    pb, docs_b = write_corpus(tmp_path / "b", n_docs=11, seed=2)
    out = str(tmp_path / "merged")
    with MemmapTokenWriter(out, dtype=np.uint16) as w:
        w.merge_file(pa)
        w.add_document(np.arange(13))  # interleaved direct writes still work
        w.merge_file(pb)
    ds = MemmapTokenDataset(out)
    expect = docs_a + [np.arange(13)] + docs_b
    assert len(ds) == len(expect)
    for i, doc in enumerate(expect):
        np.testing.assert_array_equal(np.asarray(ds[i]), doc.astype(np.uint16))
    # doc boundaries: one per document plus the leading sentinel,
    # monotonically increasing through the graft points
    np.testing.assert_array_equal(ds.doc_idx, np.arange(len(expect) + 1))
    # the merged .bin is the exact byte concatenation of its sources
    from relora_tpu.data.memmap import data_path

    with open(data_path(out), "rb") as f:
        merged_bytes = f.read()
    with open(data_path(pa), "rb") as f:
        assert merged_bytes.startswith(f.read())
    with open(data_path(pb), "rb") as f:
        assert merged_bytes.endswith(f.read())


def test_memmap_merge_file_dtype_mismatch(tmp_path):
    pa, _ = write_corpus(tmp_path / "a", n_docs=3, vocab=1000)  # uint16
    with MemmapTokenWriter(str(tmp_path / "m"), dtype=np.int32) as w:
        with pytest.raises(ValueError, match="cannot merge"):
            w.merge_file(pa)
        w.add_document(np.arange(4))  # writer still usable after the error


def test_memmap_merge_empty_shard(tmp_path):
    """A pretokenizer worker that received no documents produces an empty
    shard; merging it must be a no-op, not a crash."""
    empty = str(tmp_path / "empty")
    with MemmapTokenWriter(empty, dtype=np.uint16):
        pass
    pa, docs_a = write_corpus(tmp_path / "a", n_docs=3)
    out = str(tmp_path / "m")
    with MemmapTokenWriter(out, dtype=np.uint16) as w:
        w.merge_file(empty)
        w.merge_file(pa)
    ds = MemmapTokenDataset(out)
    assert len(ds) == len(docs_a)
    np.testing.assert_array_equal(np.asarray(ds[0]), docs_a[0].astype(np.uint16))


def test_memmap_merge_self_guard(tmp_path):
    pa, _ = write_corpus(tmp_path / "a", n_docs=3)
    w = MemmapTokenWriter(pa + "_new", dtype=np.uint16)
    with pytest.raises(ValueError, match="itself"):
        # spelled differently but resolving to the writer's own prefix
        w.merge_file(os.path.join(os.path.dirname(pa), ".", os.path.basename(pa) + "_new"))
    w._bin.close()


def test_memmap_writer_aborts_on_exception(tmp_path):
    """A with-block that raises must NOT leave a loadable .idx behind —
    a valid-looking index over a partial .bin is a silently truncated
    corpus (reviewer finding, round 5)."""
    out = str(tmp_path / "m")
    with pytest.raises(RuntimeError):
        with MemmapTokenWriter(out, dtype=np.uint16) as w:
            w.add_document(np.arange(5))
            raise RuntimeError("mid-stream failure")
    assert not os.path.exists(out + ".idx")
    with pytest.raises((ValueError, FileNotFoundError)):
        MemmapTokenDataset(out)


def test_merge_corpus_cli(tmp_path):
    pa, docs_a = write_corpus(tmp_path / "a", n_docs=4, seed=3)
    pb, docs_b = write_corpus(tmp_path / "b", n_docs=5, seed=4)
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "merge_corpus", os.path.join(os.path.dirname(__file__), "..", "tools", "merge_corpus.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "merged")
    mod.main([pa, pb, "--out", out])
    ds = MemmapTokenDataset(out)
    assert len(ds) == len(docs_a) + len(docs_b)
    np.testing.assert_array_equal(np.asarray(ds[5]), docs_b[1].astype(np.uint16))


def test_native_helpers_compile():
    assert load_native() is not None, "C++ helpers failed to build"


def test_sample_idx_cpp_matches_python_oracle():
    rs = np.random.RandomState(1)
    sizes = rs.randint(3, 50, size=200).astype(np.int32)
    documents = np.arange(200)
    tokens_per_epoch = int(sizes.sum())
    seq_length = 32
    num_samples = 150
    epochs = num_epochs_needed(tokens_per_epoch, seq_length, num_samples)
    doc_idx = build_doc_idx(documents, epochs, np.random.RandomState(7))

    py = build_sample_idx_py(sizes, doc_idx, seq_length, num_samples)
    cpp = build_sample_idx_native(sizes, doc_idx, seq_length, num_samples)
    assert cpp is not None
    np.testing.assert_array_equal(np.asarray(cpp, dtype=np.int64), py)


def test_sample_idx_int64_path():
    sizes = np.asarray([2**20] * 4, dtype=np.int32)
    # force i64 by a doc_idx longer than int32 range? too big — instead check
    # the i64 entry point directly
    doc_idx = np.arange(4, dtype=np.int64)
    from relora_tpu.data.native import load

    lib = load()
    out = np.zeros((3 + 1, 2), dtype=np.int64)
    rc = lib.relora_build_sample_idx_i64(
        sizes, doc_idx, len(doc_idx), 1024, 3, out.reshape(-1)
    )
    assert rc == 0
    py = build_sample_idx_py(sizes, doc_idx, 1024, 3)
    np.testing.assert_array_equal(out, py)


def test_blending_cpp_matches_python_oracle():
    weights = np.asarray([0.5, 0.3, 0.2])
    size = 1000
    py_idx, py_sample = build_blending_indices_py(weights, size)
    cpp = build_blending_indices_native(weights, size)
    assert cpp is not None
    np.testing.assert_array_equal(cpp[0], py_idx)
    np.testing.assert_array_equal(cpp[1], py_sample)
    # achieved ratios approximate the weights
    counts = np.bincount(py_idx, minlength=3) / size
    np.testing.assert_allclose(counts, weights, atol=0.01)


def test_packed_dataset_samples(tmp_path):
    prefix, docs = write_corpus(tmp_path)
    data = MemmapTokenDataset(prefix)
    seq = 32
    ds = PackedCausalDataset(
        name="train",
        data=data,
        documents=np.arange(len(data)),
        num_samples=60,
        seq_length=seq,
        seed=3,
    )
    assert len(ds) == 60
    for i in range(60):
        sample = ds[i]["input_ids"]
        assert sample.shape == (seq + 1,)
        assert sample.dtype == np.int64
    # modulo wrap
    np.testing.assert_array_equal(ds[60 + 3]["input_ids"], ds[3]["input_ids"])
    # sample boundaries advance by exactly seq_length tokens (windows overlap
    # by the one shared boundary token)
    si = np.asarray(ds.sample_idx, dtype=np.int64)
    sizes = np.asarray(ds.data.sizes)
    doc_idx = np.asarray(ds.doc_idx)
    token_pos = np.concatenate([[0], np.cumsum(sizes[doc_idx])])
    abs_pos = token_pos[si[:, 0]] + si[:, 1]
    np.testing.assert_array_equal(np.diff(abs_pos), np.full(len(si) - 1, seq))


def test_packed_dataset_cache_reused(tmp_path):
    prefix, _ = write_corpus(tmp_path)
    data = MemmapTokenDataset(prefix)
    kw = dict(
        data=data, documents=np.arange(len(data)), num_samples=30, seq_length=16, seed=5
    )
    a = PackedCausalDataset(name="t", **kw)
    b = PackedCausalDataset(name="t", **kw)  # second build loads the .npy cache
    np.testing.assert_array_equal(a[0]["input_ids"], b[0]["input_ids"])


def test_blendable_dataset(tmp_path):
    p1, _ = write_corpus(tmp_path / "a", n_docs=30, seed=1)
    p2, _ = write_corpus(tmp_path / "b", n_docs=30, seed=2)
    mk = lambda p, name: PackedCausalDataset(
        name=name,
        data=MemmapTokenDataset(p),
        documents=np.arange(30),
        num_samples=40,
        seq_length=16,
        seed=0,
    )
    blend = BlendableDataset([mk(p1, "a"), mk(p2, "b")], [0.7, 0.3])
    assert len(blend) == 80
    sample = blend[5]["input_ids"]
    assert sample.shape == (17,)


def test_parse_split_string():
    r = parse_split_string("969,30,1", 1000)
    assert [len(x) for x in r] == [969, 30, 1]
    r = parse_split_string("8,1,1", 100)
    assert [len(x) for x in r] == [80, 10, 10]
    r = parse_split_string("100,0,0", 50)
    assert len(r[0]) == 50 and len(r[1]) == 0
    with pytest.raises(ValueError):
        parse_split_string("0,0,0", 10)


def test_parse_split_string_reference_differential():
    """Bit-parity with get_train_valid_test_split_ (data_utils.py:163-187):
    cumulative int(round(frac*size)) bounds, then the terminal rounding
    excess subtracted from EVERY bound (not clamped on the tail) — so
    small-n splits never collapse a middle range the reference keeps.

    Expected bounds are precomputed by hand-executing the reference
    algorithm (golden values, not a re-derivation in code)."""
    cases = [
        # (split, n) -> reference splits_index [0, b1, b2, n]
        ("1,1,1", 10, [0, 4, 7, 10]),  # cum [0,3,6,9], diff -1 → +1 each
        ("1,1,1", 4, [0, 2, 3, 4]),  # cum [0,1,2,3], diff -1
        ("969,30,1", 997, [0, 966, 996, 997]),  # diff 0
        ("8,1,1", 7, [0, 5, 6, 7]),  # cum [0,6,7,8], diff +1 → -1 each
        ("949,50,1", 33, [0, 31, 33, 33]),  # zero-width test split survives
        ("90/5/5", 21, [0, 19, 20, 21]),  # '/' separator form
        ("100", 13, [0, 13, 13, 13]),  # single-value form
        ("2,1", 9, [0, 6, 9, 9]),  # two-value form pads a zero
    ]
    for split, n, expect in cases:
        got = parse_split_string(split, n)
        bounds = [got[0].start, got[0].stop, got[1].stop, got[2].stop]
        assert bounds == expect, (split, n, bounds, expect)


def test_split_datasets_and_iterator_rewind(tmp_path):
    prefix, _ = write_corpus(tmp_path, n_docs=100)
    mcfg = MegatronDataConfig(data_path=prefix, split="8,1,1", seq_length=16, seed=0)
    train, valid, test = build_split_datasets(mcfg, (64, 8, 8))
    assert train is not None and valid is not None
    assert len(train) == 64

    it = PackedBatchIterator(train, microbatch=2, grad_accum=2)
    batches = list(it)
    assert len(batches) == 16
    assert batches[0].shape == (2, 2, 17)
    # rewind: skipping 5 updates reproduces the tail exactly
    it2 = PackedBatchIterator(train, microbatch=2, grad_accum=2, skip_updates=5)
    tail = list(it2)
    assert len(tail) == 11
    np.testing.assert_array_equal(tail[0], batches[5])
    np.testing.assert_array_equal(tail[-1], batches[-1])
    # per-host slicing covers the global batch disjointly
    h0 = next(iter(PackedBatchIterator(train, microbatch=2, grad_accum=1, process_index=0, process_count=2)))
    h1 = next(iter(PackedBatchIterator(train, microbatch=2, grad_accum=1, process_index=1, process_count=2)))
    assert not np.array_equal(h0, h1)


def test_yaml_config_accepts_reference_format(tmp_path):
    """The reference's pile_megatron_dataset.yaml shape loads (extra NeoX keys
    ignored)."""
    import yaml

    prefix, _ = write_corpus(tmp_path)
    raw = {
        "pipe_parallel_size": 1,
        "model_parallel_size": 1,
        "train_data_paths": [prefix],
        "valid_data_paths": [prefix],
        "test_data_paths": [prefix],
        "tokenizer_type": "HFTokenizer",
        "train_micro_batch_size_per_gpu": "",
        "seq_length": 16,
        "train_iters": 100,
        "data_impl": "mmap",
        "num_layers": 12,  # ignored model keys
        "hidden_size": 768,
    }
    p = tmp_path / "m.yaml"
    p.write_text(yaml.safe_dump(raw))
    mcfg = MegatronDataConfig.from_yaml(str(p))
    assert mcfg.seq_length == 16 and mcfg.train_data_paths == [prefix]
    train, valid, test = build_split_datasets(mcfg, (32, 8, 8))
    assert train[0]["input_ids"].shape == (17,)


def test_yaml_inconsistent_neox_batch_keys_warn(tmp_path):
    """Dropped NeoX batch keys are cross-checked: an inconsistent
    train_batch_size/micro/grad_accum triple warns instead of loading
    silently (reference solves this arithmetic in arguments.py:754-812)."""
    import io
    import logging as _logging

    import yaml

    def load_capturing(p):
        buf = io.StringIO()
        h = _logging.StreamHandler(buf)
        lg = _logging.getLogger("relora_tpu.data.megatron")
        lg.addHandler(h)
        try:
            MegatronDataConfig.from_yaml(str(p))
        finally:
            lg.removeHandler(h)
        return buf.getvalue()

    prefix, _ = write_corpus(tmp_path)
    raw = {
        "train_data_paths": [prefix],
        "seq_length": 16,
        "train_batch_size": 100,  # not a multiple of 8*3
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 3,
    }
    p = tmp_path / "bad_batch.yaml"
    p.write_text(yaml.safe_dump(raw))
    out = load_capturing(p)
    assert "inconsistent NeoX batch arithmetic" in out

    # consistent triple: only the "not consumed" notice, no inconsistency warning
    raw["train_batch_size"] = 48
    p2 = tmp_path / "ok_batch.yaml"
    p2.write_text(yaml.safe_dump(raw))
    out = load_capturing(p2)
    assert "not consumed" in out and "inconsistent NeoX batch arithmetic" not in out
    # the present keys are retained (as ints) for the dp-aware cross-check
    mcfg = MegatronDataConfig.from_yaml(str(p2))
    assert mcfg.neox_batch_keys == {
        "train_batch_size": 48,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 3,
    }


def test_solve_batch_parameters_reference_cases():
    """Solver completes any sufficient subset of the NeoX batch triple with
    the reference's exact case analysis — floor-division quirks included
    (NeoXArgs.calculate_batch_parameters, arguments.py:753-791)."""
    from relora_tpu.data.megatron import check_batch_parameters, solve_batch_parameters

    # fully specified: returned untouched (even if inconsistent — check is
    # a separate step, as in the reference)
    assert solve_batch_parameters(2, 64, 8, 4) == (64, 8, 4)
    # train+micro -> grad_acc = (train // micro) // dp
    assert solve_batch_parameters(2, 64, 8, None) == (64, 8, 4)
    # train+grad_acc -> micro = (train // dp) // grad_acc
    assert solve_batch_parameters(2, 64, None, 4) == (64, 8, 4)
    # micro+grad_acc -> train = micro * grad_acc * dp
    assert solve_batch_parameters(2, None, 8, 4) == (64, 8, 4)
    # train only -> grad_acc 1, micro = train // dp
    assert solve_batch_parameters(4, 64, None, None) == (64, 16, 1)
    # micro only -> train = micro * dp, grad_acc 1
    assert solve_batch_parameters(4, None, 16, None) == (64, 16, 1)
    # reference floor-division quirk preserved: non-divisible inputs floor
    assert solve_batch_parameters(2, 100, 8, None) == (100, 8, 6)
    # insufficient: neither train nor micro
    with pytest.raises(ValueError):
        solve_batch_parameters(2, None, None, 4)

    check_batch_parameters(2, 64, 8, 4)  # consistent: no raise
    with pytest.raises(ValueError):
        check_batch_parameters(2, 100, 8, 6)  # 100 != 8*6*2
    with pytest.raises(ValueError):
        check_batch_parameters(2, 64, 0, 4)


def test_cross_check_neox_batch_against_mesh(tmp_path):
    """At startup the YAML's batch keys are solved at the REAL dp size and
    compared with the training config: agreement logs info, disagreement
    warns, unsolvable warns — never raises (reference YAMLs keep loading)."""
    import io
    import logging as _logging

    import yaml

    from relora_tpu.data.megatron import cross_check_neox_batch

    def capture(fn):
        buf = io.StringIO()
        h = _logging.StreamHandler(buf)
        lg = _logging.getLogger("relora_tpu.data.megatron")
        old_level = lg.level
        lg.setLevel(_logging.INFO)
        lg.addHandler(h)
        try:
            fn()
        finally:
            lg.removeHandler(h)
            lg.setLevel(old_level)
        return buf.getvalue()

    prefix, _ = write_corpus(tmp_path)
    raw = {
        "train_data_paths": [prefix],
        "seq_length": 16,
        "train_batch_size": 64,
        "train_micro_batch_size_per_gpu": 8,
    }
    p = tmp_path / "neox.yaml"
    p.write_text(yaml.safe_dump(raw))
    mcfg = MegatronDataConfig.from_yaml(str(p))

    # solved at dp=2: (64, 8, 4) == training config -> consistent
    out = capture(lambda: cross_check_neox_batch(
        mcfg, str(p), 2, micro_batch=8, grad_accum=4, total_batch_size=64))
    assert "consistent with the training config" in out

    # training config disagrees -> warning naming both triples
    out = capture(lambda: cross_check_neox_batch(
        mcfg, str(p), 2, micro_batch=4, grad_accum=4, total_batch_size=32))
    assert "the training config wins" in out

    # keys that cannot solve (grad_acc alone) warn instead of raising
    mcfg.neox_batch_keys = {"gradient_accumulation_steps": 4}
    out = capture(lambda: cross_check_neox_batch(
        mcfg, str(p), 2, micro_batch=4, grad_accum=4, total_batch_size=32))
    assert "do not solve" in out

    # a zero divisor key hits the solver's floor division: warn, never crash
    mcfg.neox_batch_keys = {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 0}
    out = capture(lambda: cross_check_neox_batch(
        mcfg, str(p), 2, micro_batch=4, grad_accum=4, total_batch_size=32))
    assert "do not solve" in out

    # no keys: silent no-op
    mcfg.neox_batch_keys = {}
    assert capture(lambda: cross_check_neox_batch(mcfg, str(p), 2, 4, 4, 32)) == ""


def test_bert_mapping_builders():
    """BERT-style span builders: spans lie within documents, cover multiple
    sentences, respect target lengths, deterministic by seed."""
    from relora_tpu.data.native import build_bert_mapping

    rs = np.random.RandomState(0)
    # 20 docs x ~6 sentences of 5..60 tokens
    sent_counts = rs.randint(2, 8, size=20)
    docs = np.concatenate([[0], np.cumsum(sent_counts)]).astype(np.int64)
    sizes = rs.randint(5, 60, size=int(docs[-1])).astype(np.int32)

    kw = dict(num_epochs=2, max_num_samples=1000, max_seq_length=128,
              short_seq_prob=0.1, seed=7)
    maps = build_bert_mapping(docs, sizes, **kw)
    assert maps is not None and maps.shape[1] == 3 and len(maps) > 0
    # spans are sentence ranges inside some document
    for start, end, target in maps[:50]:
        assert 0 <= start < end <= docs[-1]
        assert 2 <= target <= 128
        # start/end within one document
        d = np.searchsorted(docs, start, side="right") - 1
        assert docs[d] <= start and end <= docs[d + 1]
    # deterministic
    maps2 = build_bert_mapping(docs, sizes, **kw)
    np.testing.assert_array_equal(maps, maps2)
    # different seed shuffles differently
    maps3 = build_bert_mapping(docs, sizes, **{**kw, "seed": 8})
    assert not np.array_equal(maps, maps3)

    from relora_tpu.data.native import build_blocks_mapping

    titles = rs.randint(0, 10, size=20).astype(np.int32)
    blocks = build_blocks_mapping(
        docs, sizes, titles, num_epochs=2, max_num_samples=1000,
        max_seq_length=128, seed=7,
    )
    assert blocks.shape[1] == 4
    for start, end, d, block_id in blocks[:50]:
        assert docs[d] <= start < end <= docs[d + 1]


def test_blocks_mapping_bit_parity_goldens():
    """Byte-identical to the reference's compiled build_blocks_mapping
    (helpers.cpp:513-747) on stored goldens — regenerate with
    tools/gen_blocks_goldens.py (requires /root/reference)."""
    import glob
    import os

    from relora_tpu.data.native import build_blocks_mapping

    golden_dir = os.path.join(os.path.dirname(__file__), "golden")
    files = sorted(glob.glob(os.path.join(golden_dir, "blocks_mapping_*.npz")))
    assert files, "golden files missing — run tools/gen_blocks_goldens.py"
    for f in files:
        g = np.load(f)
        got = build_blocks_mapping(
            g["docs"], g["sizes"], g["titles"],
            num_epochs=int(g["num_epochs"]),
            max_num_samples=int(g["max_num_samples"]),
            max_seq_length=int(g["max_seq_length"]),
            seed=int(g["seed"]),
            use_one_sent_blocks=bool(g["use_one_sent_blocks"]),
        )
        assert got.dtype == g["expected"].dtype, f
        np.testing.assert_array_equal(got, g["expected"], err_msg=f)


def test_interleaved_host_slicing(tmp_path):
    prefix, _ = write_corpus(tmp_path, n_docs=100)
    mcfg = MegatronDataConfig(data_path=prefix, split="10,0,0", seq_length=16, seed=0)
    train, _, _ = build_split_datasets(mcfg, (16, 0, 0))
    # two interleaved hosts cover the same global batch as one host, striped
    both = next(iter(PackedBatchIterator(train, microbatch=4, grad_accum=1)))
    h0 = next(iter(PackedBatchIterator(train, microbatch=2, grad_accum=1,
                                       process_index=0, process_count=2, interleaved=True)))
    h1 = next(iter(PackedBatchIterator(train, microbatch=2, grad_accum=1,
                                       process_index=1, process_count=2, interleaved=True)))
    np.testing.assert_array_equal(h0[0][0], both[0][0])
    np.testing.assert_array_equal(h1[0][0], both[0][1])
    np.testing.assert_array_equal(h0[0][1], both[0][2])


def test_reference_production_yaml_loads():
    """Drop-in config compatibility: the 1B production recipe file (the
    repo's copy of the reference's training_configs/1B_v1.0.yaml, or the
    reference checkout itself when present) parses and finalizes."""
    from relora_tpu.config.training import TrainingConfig

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    recipe = "/root/reference/training_configs/1B_v1.0.yaml"
    data_yaml = "/root/reference/configs/pile_megatron_dataset.yaml"
    if not os.path.exists(recipe):
        recipe = os.path.join(repo, "training_configs", "1B_v1.0.yaml")
        data_yaml = os.path.join(repo, "configs", "pile_megatron_dataset.yaml")

    cwd = os.getcwd()
    os.chdir(repo)  # the recipe names its dataset yaml repo-relative
    try:
        cfg = TrainingConfig.from_yaml(recipe)
    finally:
        os.chdir(cwd)
    assert cfg.use_peft and cfg.relora == 1000
    assert cfg.optimizer_reset_mode == "magnitude" and cfg.optimizer_reset_ratio == 0.8
    assert cfg.lr == 4e-4 and cfg.total_batch_size == 1024
    assert cfg.scheduler == "cosine_restarts" and cfg.num_training_steps == 130_000
    # and the reference-format megatron yaml parses through our slim config
    mcfg = MegatronDataConfig.from_yaml(data_yaml)
    assert mcfg.seq_length == 2048 and mcfg.data_impl == "mmap"
    assert len(mcfg.train_data_paths) == 1
    assert mcfg.train_data_paths[0].endswith("pile_20B_tokenizer_text_document")


def test_label_dataset_alignment(tmp_path):
    """Parallel label corpus assembled with the same index maps
    (parity: label_dataset, dataset.py:96-126)."""
    prefix, docs = write_corpus(tmp_path / "d", n_docs=40, seed=3)
    # label corpus: same doc lengths, tokens shifted by +1 mod vocab
    lp = str(tmp_path / "l" / "labels")
    with MemmapTokenWriter(lp, dtype=np.uint16) as w:
        for d in docs:
            w.add_document((d + 1) % 1000)
    data = MemmapTokenDataset(prefix)
    labels = MemmapTokenDataset(lp)
    ds = PackedCausalDataset(
        name="t", data=data, documents=np.arange(40), num_samples=20,
        seq_length=16, seed=0, label_data=labels,
    )
    for i in range(20):
        s = ds[i]
        assert s["label"].shape == s["input_ids"].shape
        np.testing.assert_array_equal(s["label"], (s["input_ids"] + 1) % 1000)
    short_prefix, _ = write_corpus(tmp_path / "short", n_docs=3, seed=9)
    with pytest.raises(ValueError, match="align"):
        PackedCausalDataset(
            name="t2", data=data, documents=np.arange(40), num_samples=5,
            seq_length=16, seed=0, label_data=MemmapTokenDataset(short_prefix),
        )


def test_data_order_invariant_to_host_count(tmp_path):
    """SURVEY 'hard part': deterministic resumable data order across host
    counts — the global update batch is identical whether read by 1 host or
    sliced by 2 (contiguous slicing)."""
    prefix, _ = write_corpus(tmp_path, n_docs=80)
    mcfg = MegatronDataConfig(data_path=prefix, split="10,0,0", seq_length=16, seed=0)
    train, _, _ = build_split_datasets(mcfg, (32, 0, 0))

    single = list(PackedBatchIterator(train, microbatch=4, grad_accum=2))
    h0 = list(PackedBatchIterator(train, microbatch=2, grad_accum=2,
                                  process_index=0, process_count=2))
    h1 = list(PackedBatchIterator(train, microbatch=2, grad_accum=2,
                                  process_index=1, process_count=2))
    assert len(single) == len(h0) == len(h1)
    for s, a, b in zip(single, h0, h1):
        # global batch rows = concat of per-host rows, in order
        combined = np.concatenate([a.reshape(-1, 17), b.reshape(-1, 17)])
        np.testing.assert_array_equal(s.reshape(-1, 17), combined)


def test_legacy_indexed_dataset_roundtrip(tmp_path):
    """Legacy fairseq-style format (parity: IndexedDataset /
    IndexedCachedDataset, indexed_dataset.py:133-273): write, sniff, read
    lazily and cached, and feed the packed dataset."""
    from relora_tpu.data.memmap import (
        LegacyIndexedDataset,
        LegacyIndexedWriter,
        open_token_dataset,
    )

    rs = np.random.RandomState(0)
    prefix = str(tmp_path / "legacy")
    docs = [rs.randint(0, 1000, size=rs.randint(5, 60)) for _ in range(40)]
    with LegacyIndexedWriter(prefix, dtype=np.int32) as w:
        for d in docs:
            w.add_document(d)

    for impl in ("lazy", "cached", "infer"):
        ds = open_token_dataset(prefix, impl)
        assert len(ds) == 40
        np.testing.assert_array_equal(np.asarray(ds[7]), docs[7])
        np.testing.assert_array_equal(
            np.asarray(ds.get(3, offset=2, length=3)), docs[3][2:5]
        )
        assert ds.n_tokens == sum(len(d) for d in docs)

    # mmap files are inferred as mmap
    mp, _ = write_corpus(tmp_path / "mm", n_docs=5)
    assert type(open_token_dataset(mp, "infer")).__name__ == "MemmapTokenDataset"

    # legacy corpus through the packed sampler
    packed = PackedCausalDataset(
        name="legacy", data=LegacyIndexedDataset(prefix), documents=np.arange(40),
        num_samples=10, seq_length=16, seed=0,
    )
    assert packed[0]["input_ids"].shape == (17,)


def test_migrate_legacy_to_mmap(tmp_path):
    from relora_tpu.data.memmap import LegacyIndexedWriter, MemmapTokenDataset
    import subprocess, sys as _sys

    rs = np.random.RandomState(1)
    src = str(tmp_path / "old")
    docs = [rs.randint(0, 500, size=rs.randint(3, 30)) for _ in range(25)]
    with LegacyIndexedWriter(src, dtype=np.int32) as w:
        for d in docs:
            w.add_document(d)
    dst = str(tmp_path / "new")
    r = subprocess.run(
        [_sys.executable, "tools/migrate_dataset.py", "--src", src, "--dst", dst],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    out = MemmapTokenDataset(dst)
    assert len(out) == 25
    for i in (0, 12, 24):
        np.testing.assert_array_equal(np.asarray(out[i]), docs[i])


@pytest.mark.parametrize("trial", range(8))
def test_sample_idx_differential_fuzz(trial):
    """Randomized differential coverage of the C++ packer vs the NumPy
    oracle: varied doc-length regimes (incl. many 1-token docs and docs far
    longer than seq), seq lengths, and epoch counts."""
    rs = np.random.RandomState(100 + trial)
    n_docs = rs.randint(5, 400)
    regime = trial % 4
    if regime == 0:
        sizes = rs.randint(1, 8, size=n_docs)  # tiny docs: many crossings
    elif regime == 1:
        sizes = rs.randint(1000, 5000, size=n_docs)  # docs >> seq
    elif regime == 2:
        sizes = np.where(rs.rand(n_docs) < 0.5, 1, rs.randint(1, 300, size=n_docs))
    else:
        sizes = rs.randint(1, 300, size=n_docs)
    sizes = sizes.astype(np.int32)
    seq_length = int(rs.choice([8, 32, 129, 512]))
    documents = np.arange(n_docs)
    num_samples = int(rs.randint(1, 200))
    epochs = num_epochs_needed(int(sizes.sum()), seq_length, num_samples)
    doc_idx = build_doc_idx(documents, epochs, np.random.RandomState(trial))
    py = build_sample_idx_py(sizes, doc_idx, seq_length, num_samples)
    cpp = build_sample_idx_native(sizes, doc_idx, seq_length, num_samples)
    np.testing.assert_array_equal(np.asarray(cpp, np.int64), py)


@pytest.mark.parametrize("n_datasets", [2, 5, 16])
def test_blending_differential_fuzz(n_datasets):
    rs = np.random.RandomState(n_datasets)
    w = rs.dirichlet(np.ones(n_datasets))
    size = int(rs.randint(100, 5000))
    py = build_blending_indices_py(w, size)
    cpp = build_blending_indices_native(w, size)
    np.testing.assert_array_equal(cpp[0], py[0])
    np.testing.assert_array_equal(cpp[1], py[1])
