"""Multi-replica serving drills: supervisor, health-aware router, failover.

Three layers of coverage, cheapest first:

- **Unit**: the CircuitBreaker state machine on a fake clock and the
  supervisor's backoff schedule — pure functions of time, no processes.
- **Fake replicas**: the Router proxying to in-process asyncio stubs whose
  behavior is switchable at runtime (healthy / 500s / drop-before-byte /
  die-mid-stream), pinning least-loaded routing, the pre-stream retry
  boundary, the typed mid-stream error, and the circuit lifecycle without
  paying a jax import.
- **Real children**: the supervisor restarting genuinely crashing processes
  (quarantine after a crash loop, rolling-drain sequencing), and the
  acceptance drill — a 2-replica ``serve.py --random-init`` fleet behind the
  router where one replica ``os._exit``s mid-decode (``serve_crash`` fault)
  under concurrent load: every accepted request must terminate (finish
  record or typed error, none hung), the supervisor must restart the dead
  replica, and traffic must return to it once its circuit closes.

The subprocess fleet is module-scoped: ~10s per replica incarnation
(jax import + tiny-model compile on CPU) is paid once, and the crash /
recovery / rolling-drain tests share it in file order (tier-1 runs with
``-p no:randomly``).
"""

import asyncio
import json
import os
import signal
import socket
import sys
import threading
import time

import pytest

from relora_tpu.serve.router import CircuitBreaker, Router
from relora_tpu.serve.supervisor import ReplicaSupervisor, backoff_delay

pytestmark = [pytest.mark.serve, pytest.mark.faults]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- unit: fault-spec parsing for the serving sites ---------------------------


def test_faults_env_parsing_and_boot_summary():
    """The serving drills are armed through RELORA_TPU_FAULTS: int keys
    (at_token, code), float keys (sleep_s), exception names incl.
    connectionerror — and summary() renders one loud boot line."""
    from relora_tpu.utils import faults

    faults.reset()
    try:
        assert faults.summary() == "faults: none armed"
        faults.configure_from_env(
            "serve_crash:at_token=40,code=13;"
            "serve_stall:sleep_s=0.01,times=2;"
            "serve_decode:exc=connectionerror"
        )
        assert faults.active("serve_crash")
        line = faults.summary()
        assert line.startswith("FAULTS ARMED (drill, not production): ")
        assert "serve_crash:at_token=40,code=13" in line
        assert "serve_stall:sleep_s=0.01,times=2" in line
        assert "serve_decode:exc=ConnectionError" in line
        # the armed specs carry the parsed types, not strings
        with pytest.raises(ConnectionError):
            faults.serve_tick(0)
        faults.reset()
        assert faults.summary() == "faults: none armed"
    finally:
        faults.reset()


# -- unit: breaker + backoff --------------------------------------------------


def test_circuit_breaker_lifecycle():
    """closed -> open after N consecutive failures; open -> half_open after
    the cooldown with exactly one trial; failed trial doubles the cooldown;
    a success closes and resets."""
    clock = [0.0]
    br = CircuitBreaker(
        failure_threshold=3, cooldown_s=1.0, cooldown_max_s=4.0, clock=lambda: clock[0]
    )
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed" and br.allow()  # under threshold
    br.record_failure()
    assert br.state == "open" and br.opens_total == 1
    assert not br.allow()  # cooldown not elapsed
    clock[0] = 1.0
    assert br.allow()  # the half-open trial
    assert br.state == "half_open"
    assert not br.allow()  # only one trial at a time
    br.record_failure()  # trial failed: reopen, cooldown doubles
    assert br.state == "open" and br.opens_total == 2
    clock[0] = 2.5
    assert not br.allow()  # doubled cooldown (2s from t=1) not elapsed
    clock[0] = 3.0
    assert br.allow() and br.state == "half_open"
    br.record_success()
    assert br.state == "closed" and br.failures == 0
    # cooldown reset: a fresh open waits cooldown_s again, not the doubled one
    br.record_failure(), br.record_failure(), br.record_failure()
    assert br.state == "open"
    clock[0] = 4.0
    assert br.allow()


def test_backoff_delay_schedule():
    """min(base * 2^(n-1), cap), plus bounded relative jitter."""
    no_jitter = dict(base_s=0.5, cap_s=8.0, jitter=0.0)
    assert [backoff_delay(n, **no_jitter) for n in (1, 2, 3, 4, 5, 6)] == [
        0.5, 1.0, 2.0, 4.0, 8.0, 8.0  # capped
    ]
    # jitter is relative and one-sided: delay * (1 + jitter * U[0,1))
    hi = backoff_delay(2, base_s=0.5, cap_s=8.0, jitter=0.2, rand=lambda: 1.0)
    assert hi == pytest.approx(1.2)
    assert backoff_delay(2, base_s=0.5, cap_s=8.0, jitter=0.2, rand=lambda: 0.0) == 1.0


# -- supervisor with real (non-jax) children ---------------------------------


def test_supervisor_crash_loop_backoff_then_quarantine(tmp_path):
    """A replica that keeps crashing is respawned with backoff, then
    quarantined after ``quarantine_after`` crashes inside the window — and
    never respawned again."""
    events = []
    lock = threading.Lock()

    def on_event(event, idx, detail):
        with lock:
            events.append((event, idx, dict(detail)))

    sup = ReplicaSupervisor(
        lambda idx, port_file: [sys.executable, "-c", "import sys; sys.exit(3)"],
        1,
        str(tmp_path),
        backoff_base_s=0.02,
        backoff_cap_s=0.1,
        backoff_jitter=0.0,
        quarantine_after=3,
        crash_window_s=60.0,
        poll_interval_s=0.01,
        on_event=on_event,
    )
    sup.start()
    try:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if sup.status()["r0"]["quarantined"]:
                break
            time.sleep(0.02)
        st = sup.status()["r0"]
        assert st["quarantined"], f"never quarantined: {st}, events={events}"
        assert st["last_exit_code"] == 3
        with lock:
            names = [e for e, _, _ in events]
        # 1 spawn + 2 respawns = 3 crashes = quarantine_after
        assert names.count("spawn") == 1
        assert names.count("respawn") == 2
        assert names.count("crash") == 3
        assert names.count("quarantine") == 1
        # quarantine is terminal: no further respawn ever happens
        time.sleep(0.3)
        with lock:
            assert [e for e, _, _ in events].count("respawn") == 2
        assert sup.endpoints()["r0"] == ("127.0.0.1", None)
    finally:
        sup.stop()


_DRAINABLE_CHILD = """
import os, signal, sys, time
out, port_file = sys.argv[1], sys.argv[2]
def on_term(sig, frame):
    with open(out, "w") as fh:
        fh.write(repr(time.time()))
    time.sleep(0.3)  # a graceful drain takes time
    sys.exit(0)
signal.signal(signal.SIGTERM, on_term)
with open(port_file, "w") as fh:
    fh.write("1")  # pretend-bind so endpoints() sees us
while True:
    time.sleep(0.05)
"""


def test_supervisor_rolling_drain_is_sequential(tmp_path):
    """begin_rolling_drain SIGTERMs one replica at a time, waiting for each
    graceful exit before touching the next; clean drain exits are not
    counted as crashes."""
    events = []

    def on_event(event, idx, detail):
        events.append((event, idx, dict(detail)))

    ts_files = [str(tmp_path / f"term_{i}.ts") for i in range(2)]
    sup = ReplicaSupervisor(
        lambda idx, port_file: [
            sys.executable, "-c", _DRAINABLE_CHILD, ts_files[idx], port_file
        ],
        2,
        str(tmp_path),
        poll_interval_s=0.02,
        drain_timeout_s=10.0,
        on_event=on_event,
    )
    sup.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            eps = sup.endpoints()
            if all(port is not None for _, port in eps.values()):
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"children never bound: {sup.endpoints()}")
        sup.begin_rolling_drain()

        t_term = [float(open(f).read()) for f in ts_files]
        # each child sleeps 0.3s after SIGTERM: strictly sequential drains
        # put the second SIGTERM >= 0.3s after the first
        assert t_term[1] - t_term[0] >= 0.25, f"drain overlapped: {t_term}"
        names = [e for e, _, _ in events]
        assert names.count("drain_begin") == 2
        assert names.count("drain_complete") == 2
        assert "crash" not in names, f"clean drain counted as crash: {events}"
        drains = [d for e, _, d in events if e == "drain_complete"]
        assert all(d["exit_code"] == 0 for d in drains), drains
        st = sup.status()
        assert not st["r0"]["running"] and not st["r1"]["running"]
    finally:
        sup.stop()


# -- fake replicas: router behavior without jax -------------------------------


class _FakeReplica:
    """A switchable stand-in for one serve.py process: answers /healthz and
    /v1/generate on a real socket, with failure modes a test flips at
    runtime (``mode`` = ok | http500 | drop | die_midstream; ``alive``
    gates /healthz)."""

    def __init__(self, *, n_events=3, queue_depth=0):
        self.mode = "ok"
        self.alive = True  # healthz 200 vs 503
        self.n_events = n_events
        self.queue_depth = queue_depth
        self.gen_hits = 0
        self.port = None
        self._started = threading.Event()
        self._loop = None
        self._stop = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(10), "fake replica failed to start"

    def _run(self):
        asyncio.run(self._main())

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = server.sockets[0].getsockname()[1]
        self._started.set()
        async with server:
            await self._stop.wait()

    def close(self):
        if self._loop is not None and self._stop is not None:
            try:
                self._loop.call_soon_threadsafe(self._stop.set)
            except RuntimeError:
                pass
        self._thread.join(10)

    async def _handle(self, reader, writer):
        try:
            data = b""
            while b"\r\n\r\n" not in data:
                chunk = await reader.read(4096)
                if not chunk:
                    return
                data += chunk
            head, _, rest = data.partition(b"\r\n\r\n")
            request_line = head.split(b"\r\n")[0].decode()
            clen = 0
            for line in head.split(b"\r\n")[1:]:
                k, _, v = line.decode().partition(":")
                if k.strip().lower() == "content-length":
                    clen = int(v.strip())
            while len(rest) < clen:
                rest += await reader.read(4096)
            if "/healthz" in request_line:
                await self._respond_healthz(writer)
            else:
                await self._respond_generate(writer)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _respond_healthz(self, writer):
        if self.alive:
            code, payload = 200, {
                "status": "ok",
                "queue_depth": self.queue_depth,
                "active_slots": 0,
            }
        else:
            code, payload = 503, {"status": "stuck"}
        body = json.dumps(payload).encode()
        writer.write(
            f"HTTP/1.1 {code} X\r\nContent-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
            + body
        )
        await writer.drain()

    async def _respond_generate(self, writer):
        self.gen_hits += 1
        if self.mode == "drop":
            return  # close with zero response bytes (accept-drop shape)
        if self.mode == "http500":
            body = json.dumps({"error": "injected"}).encode()
            writer.write(
                f"HTTP/1.1 500 X\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
                + body
            )
            await writer.drain()
            return
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n\r\n"
        )
        await writer.drain()
        upto = 2 if self.mode == "die_midstream" else self.n_events
        for i in range(upto):
            writer.write(
                f"data: {json.dumps({'uid': 0, 'index': i, 'token': i + 1})}\n\n".encode()
            )
            await writer.drain()
        if self.mode == "die_midstream":
            return  # EOF without a finish record or [DONE]
        final = {"uid": 0, "finish_reason": "length", "tokens": list(range(1, upto + 1))}
        writer.write(f"data: {json.dumps(final)}\n\ndata: [DONE]\n\n".encode())
        await writer.drain()


class _RouterHarness:
    """Run a Router over the given endpoints in a background thread."""

    def __init__(self, endpoints, **kwargs):
        self.router = Router(endpoints, port=0, **kwargs)
        self.thread = threading.Thread(
            target=lambda: asyncio.run(self.router.serve_forever()), daemon=True
        )

    def __enter__(self) -> Router:
        self.thread.start()
        assert self.router.started.wait(10), "router failed to start"
        return self.router

    def __exit__(self, *exc):
        self.router.begin_shutdown()
        self.thread.join(10)
        assert not self.thread.is_alive(), "router did not shut down"

    def wait_healthy(self, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if sum(st.healthy for st in self.router.replicas.values()) >= n:
                return
            time.sleep(0.02)
        states = {r: st.status for r, st in self.router.replicas.items()}
        pytest.fail(f"router never saw {n} healthy replicas: {states}")


def _http(port, method, path, body=None, timeout=30.0):
    payload = b"" if body is None else json.dumps(body).encode()
    req = (
        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as sock:
        sock.sendall(req)
        data = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            data += chunk
    head, _, rest = data.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    status = int(lines[0].split(b" ", 2)[1])
    headers = {}
    for line in lines[1:]:
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip().lower()] = v.strip()
    return status, headers, rest


def _sse_events(body: bytes):
    events = []
    for block in body.decode().split("\n\n"):
        block = block.strip()
        if not block.startswith("data: "):
            continue
        payload = block[len("data: "):]
        events.append("[DONE]" if payload == "[DONE]" else json.loads(payload))
    return events


def test_router_proxies_and_prefers_less_loaded(tmp_path):
    """Streams proxy through whole (events + finish + [DONE], with the
    X-Relora-Replica header); a replica reporting queue depth is avoided
    while an idle sibling exists."""
    a, b = _FakeReplica(), _FakeReplica()
    harness = _RouterHarness(
        {"a": ("127.0.0.1", a.port), "b": ("127.0.0.1", b.port)},
        probe_interval_s=0.05,
    )
    try:
        with harness as router:
            harness.wait_healthy(2)
            status, headers, body = _http(
                router.port, "POST", "/v1/generate",
                {"prompt": [1], "max_new_tokens": 3},
            )
            assert status == 200
            assert headers["x-relora-replica"] in ("a", "b")
            events = _sse_events(body)
            assert events[-1] == "[DONE]"
            assert events[-2]["finish_reason"] == "length"
            assert [e["token"] for e in events[:-2]] == [1, 2, 3]

            # load-aware: b reports a deep queue -> everything goes to a
            b.queue_depth = 50
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if router.replicas["b"].load() >= 50:
                    break
                time.sleep(0.02)
            before_a, before_b = a.gen_hits, b.gen_hits
            for _ in range(4):
                status, headers, _ = _http(
                    router.port, "POST", "/v1/generate",
                    {"prompt": [1], "max_new_tokens": 2},
                )
                assert status == 200 and headers["x-relora-replica"] == "a"
            assert a.gen_hits == before_a + 4 and b.gen_hits == before_b

            # aggregated views
            status, _, body = _http(router.port, "GET", "/healthz")
            health = json.loads(body)
            assert status == 200 and health["status"] == "ok"
            assert health["healthy_replicas"] == 2
            assert set(health["replicas"]) == {"a", "b"}
            status, _, body = _http(router.port, "GET", "/metrics")
            text = body.decode()
            assert status == 200
            assert "relora_router_proxied_total" in text
            assert "relora_router_healthy_replicas 2" in text
    finally:
        a.close()
        b.close()


def test_router_retries_pre_stream_failure_on_sibling():
    """A replica that accepts and drops before any response byte is retried
    transparently on a sibling: the client sees one complete 200 stream."""
    a, b = _FakeReplica(), _FakeReplica()
    a.mode = "drop"
    b.queue_depth = 1  # bias the first pick to a, so the drop path runs
    harness = _RouterHarness(
        {"a": ("127.0.0.1", a.port), "b": ("127.0.0.1", b.port)},
        probe_interval_s=0.05,
        retry_backoff_s=0.01,
    )
    try:
        with harness as router:
            harness.wait_healthy(2)
            status, headers, body = _http(
                router.port, "POST", "/v1/generate",
                {"prompt": [1], "max_new_tokens": 3},
            )
            assert status == 200
            assert headers["x-relora-replica"] == "b"
            events = _sse_events(body)
            assert events[-1] == "[DONE]" and events[-2]["finish_reason"] == "length"
            assert a.gen_hits == 1  # the dropped first attempt
            snap = router.stats.snapshot()
            assert snap.get("retries_total", 0) >= 1
            assert snap.get("upstream_failures_total.a", 0) >= 1
            assert snap.get("failovers_total.b", 0) >= 1
    finally:
        a.close()
        b.close()


def test_router_midstream_death_is_typed_error_not_replay():
    """Once body bytes have been streamed, a dying replica must NOT trigger
    a retry (generation is not idempotent): the client gets the partial
    events, a typed ``stream_interrupted`` error event, and no [DONE]."""
    a, b = _FakeReplica(), _FakeReplica()
    a.mode = "die_midstream"
    b.queue_depth = 1  # bias the pick to a
    harness = _RouterHarness(
        {"a": ("127.0.0.1", a.port), "b": ("127.0.0.1", b.port)},
        probe_interval_s=0.05,
    )
    try:
        with harness as router:
            harness.wait_healthy(2)
            status, headers, body = _http(
                router.port, "POST", "/v1/generate",
                {"prompt": [1], "max_new_tokens": 5},
            )
            assert status == 200 and headers["x-relora-replica"] == "a"
            events = _sse_events(body)
            assert "[DONE]" not in events, "a broken stream must not claim success"
            assert [e["token"] for e in events[:-1]] == [1, 2]  # partial output
            err = events[-1]["error"]
            assert err["type"] == "stream_interrupted"
            assert err["replica"] == "a"
            assert err["retryable"] is False
            assert b.gen_hits == 0, "mid-stream failure must never replay"
            snap = router.stats.snapshot()
            assert snap.get("midstream_errors_total.a", 0) == 1
    finally:
        a.close()
        b.close()


def test_router_circuit_opens_on_5xx_and_closes_via_probe():
    """Consecutive 5xx opens the replica's circuit (requests stop flowing);
    when the replica recovers, a successful health probe is the half-open
    trial that closes it and traffic resumes."""
    a = _FakeReplica()
    harness = _RouterHarness(
        {"a": ("127.0.0.1", a.port)},
        probe_interval_s=2.0,  # long: the breaker, not the prober, drives this
        failure_threshold=2,
        cooldown_s=30.0,  # only a probe success can close it in test time
        retry_backoff_s=0.01,
        max_attempts=2,
    )
    try:
        with harness as router:
            harness.wait_healthy(1)
            a.mode = "http500"
            a.alive = False  # next probe round will also eject it
            # two quick requests inside the stale-health window: each gets
            # the passthrough 500, each charges the breaker
            for _ in range(2):
                status, _, body = _http(
                    router.port, "POST", "/v1/generate",
                    {"prompt": [1], "max_new_tokens": 2},
                )
                assert status == 500 and b"injected" in body
            br = router.replicas["a"].breaker
            assert br.state == "open" and br.opens_total >= 1
            # circuit open (and soon: probe marks unhealthy): no replica
            status, _, body = _http(
                router.port, "POST", "/v1/generate",
                {"prompt": [1], "max_new_tokens": 2},
            )
            assert status == 503
            assert b"no healthy replica" in body

            # recovery: healthz 200 again -> probe closes the circuit
            a.mode, a.alive = "ok", True
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                st = router.replicas["a"]
                if st.healthy and st.breaker.state == "closed":
                    break
                time.sleep(0.05)
            else:
                pytest.fail("circuit never closed after recovery")
            status, headers, body = _http(
                router.port, "POST", "/v1/generate",
                {"prompt": [1], "max_new_tokens": 2},
            )
            assert status == 200 and headers["x-relora-replica"] == "a"
            assert _sse_events(body)[-1] == "[DONE]"
    finally:
        a.close()


def test_router_503s_with_retry_after_when_fleet_is_down():
    """No routable replica and nothing streamed: a typed 503 with a
    Retry-After hint, not a hang."""
    with _RouterHarness({}, probe_interval_s=0.05) as router:
        status, headers, body = _http(
            router.port, "POST", "/v1/generate", {"prompt": [1], "max_new_tokens": 2}
        )
        assert status == 503
        assert headers.get("retry-after") == "1"
        assert json.loads(body)["error"] == "no healthy replica available"
        status, _, body = _http(router.port, "GET", "/healthz")
        assert status == 503 and json.loads(body)["status"] == "unavailable"


# -- the acceptance drill: a real fleet, a real crash -------------------------


class _Fleet:
    """2 serve.py --random-init replicas under a real ReplicaSupervisor,
    fronted by a real Router.  Replica 0 is armed (via env, first
    incarnation only) to ``os._exit(13)`` mid-decode once its cumulative
    token count passes ``crash_at``."""

    def __init__(self, workdir: str, crash_at: int = 40):
        self.events = []
        self._ev_lock = threading.Lock()
        self.sup = ReplicaSupervisor(
            [
                sys.executable,
                os.path.join(ROOT, "serve.py"),
                "--model_config", "llama_9m",
                "--random-init",
                "--max-batch", "4",
                "--max-queue", "16",
                "--no-warmup",
            ],
            2,
            workdir,
            backoff_base_s=0.1,
            backoff_cap_s=1.0,
            backoff_jitter=0.0,
            quarantine_after=5,
            poll_interval_s=0.05,
            env_overrides={
                0: {"RELORA_TPU_FAULTS": f"serve_crash:at_token={crash_at},code=13"}
            },
            env_overrides_respawn=False,  # restart comes back clean
            on_event=self._on_event,
        )
        self.harness = _RouterHarness(
            self.sup.endpoints,
            probe_interval_s=0.1,
            retry_backoff_s=0.02,
            failure_threshold=2,
            cooldown_s=0.2,
        )
        self.router = None

    def _on_event(self, event, idx, detail):
        with self._ev_lock:
            self.events.append((event, idx, dict(detail)))

    def event_count(self, name, idx=None):
        with self._ev_lock:
            return sum(
                1 for e, i, _ in self.events if e == name and (idx is None or i == idx)
            )

    def start(self):
        self.sup.start()
        self.router = self.harness.__enter__()
        self.harness.wait_healthy(2, timeout=120.0)
        return self

    def stop(self):
        try:
            self.harness.__exit__(None, None, None)
        finally:
            self.sup.stop()


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    fl = _Fleet(str(tmp_path_factory.mktemp("fleet")))
    fl.start()
    yield fl
    fl.stop()


def _drive_stream(port, payload, out, idx):
    """One client: record how its request terminated (never raises)."""
    try:
        status, headers, body = _http(port, "POST", "/v1/generate", payload, timeout=60.0)
        if status != 200:
            out[idx] = ("http_error", status)
            return
        events = _sse_events(body)
        if events and events[-1] == "[DONE]":
            out[idx] = ("finished", events[-2].get("finish_reason"))
        elif events and isinstance(events[-1], dict) and "error" in events[-1]:
            out[idx] = ("typed_error", events[-1]["error"]["type"])
        else:
            out[idx] = ("truncated", len(events))
    except Exception as e:  # a hung/errored client is a failed drill
        out[idx] = ("exception", repr(e))


def test_replica_crash_under_load_no_request_hangs(fleet):
    """Acceptance: SIGKILL-shaped crash (os._exit mid-decode) on replica 0
    under 8 concurrent streams.  Every accepted request terminates — as a
    finish record, a typed error, or an HTTP error — none hang; the
    supervisor restarts the dead replica; traffic reaches it again once its
    circuit closes."""
    port = fleet.router.port
    results = [None] * 8
    threads = [
        threading.Thread(
            target=_drive_stream,
            args=(
                port,
                {"prompt": [i + 1, 2, 3], "max_new_tokens": 20},
                results,
                i,
            ),
        )
        for i in range(len(results))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)
    assert not any(t.is_alive() for t in threads), f"hung clients: {results}"
    assert all(r is not None for r in results), results

    # the crash actually happened (8 x 20 tokens across 2 replicas crosses
    # replica 0's at_token=40 trigger)...
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if fleet.event_count("crash", idx=0) >= 1:
            break
        time.sleep(0.1)
    assert fleet.event_count("crash", idx=0) >= 1, fleet.events
    # ...and every request still terminated in a defined way
    kinds = [kind for kind, _ in results]
    assert "truncated" not in kinds and "exception" not in kinds, results
    finished = kinds.count("finished")
    assert finished >= 1, results

    # the supervisor restarts replica 0 (clean incarnation: the fault env
    # applies to the first spawn only)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if fleet.event_count("respawn", idx=0) >= 1:
            break
        time.sleep(0.1)
    assert fleet.event_count("respawn", idx=0) >= 1, fleet.events
    assert fleet.sup.status()["r0"]["restarts"] >= 1
    fleet.harness.wait_healthy(2, timeout=120.0)

    # traffic returns to the restarted replica once its circuit closes
    seen = set()
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline and "r0" not in seen:
        status, headers, _ = _http(
            port, "POST", "/v1/generate", {"prompt": [5, 6], "max_new_tokens": 2},
            timeout=60.0,
        )
        if status == 200:
            seen.add(headers.get("x-relora-replica"))
    assert "r0" in seen, f"restarted replica never served again: {seen}"

    status, _, body = _http(port, "GET", "/metrics")
    assert status == 200
    text = body.decode()
    assert "relora_router_proxied_total" in text
    assert "relora_router_healthy_replicas 2" in text


def test_rolling_drain_loses_zero_requests(fleet):
    """SIGTERM semantics: with streams in flight, a rolling drain finishes
    every one of them (replicas drain one at a time while the rest of the
    fleet keeps serving)."""
    port = fleet.router.port
    results = [None] * 4
    threads = [
        threading.Thread(
            target=_drive_stream,
            args=(port, {"prompt": [i + 1, 9], "max_new_tokens": 30}, results, i),
        )
        for i in range(len(results))
    ]
    for t in threads:
        t.start()
    time.sleep(0.5)  # let the streams start before the drain begins
    drainer = threading.Thread(target=fleet.sup.begin_rolling_drain)
    drainer.start()
    for t in threads:
        t.join(120.0)
    drainer.join(120.0)
    assert not drainer.is_alive(), "rolling drain never completed"
    assert not any(t.is_alive() for t in threads), f"hung clients: {results}"
    # zero loss: every in-flight stream ran to a normal finish
    assert all(r == ("finished", "length") for r in results), results
    assert fleet.event_count("drain_complete") == 2
    st = fleet.sup.status()
    assert not st["r0"]["running"] and not st["r1"]["running"]
