# relora-lint: hot-path
"""Refcounted LRU registry of HBM adapter slots for multi-tenant serving.

One base model, many tenants: every LoRA factor in the decode model is
stacked ``(num_slots, …)`` (models/lora.py's ``num_slots`` layout) and the
grouped kernel (ops/pallas_lora_matmul.grouped_lora_matmul) routes each
batch row to its slot through a per-row ``adapter_idx``.  This module owns
the *contents* of those slots:

- **Slot 0 is the identity (base-model) adapter** — zeros, never loaded,
  never evicted.  Requests with no ``"adapter"`` field decode pure base.
- **Load/evict is refcounted LRU**, the ``PageAllocator``/``PrefixCache``
  design from serve/paging.py transplanted: a free-list of slots, a
  refcount per resident adapter (one per in-flight request using it), and
  an ``OrderedDict`` in LRU order.  ``acquire`` on a miss pops a free slot
  or evicts the least-recently-used adapter *with zero active requests*;
  when every slot is pinned by live traffic it returns ``None`` and the
  scheduler keeps the request queued (evict-then-retry, exactly the prefix
  cache's admission contract).
- **Loading is unmerged**: an adapter checkpoint dir (with its
  ``relora_config.json`` sidecar) is restored host-side and only its
  ``lora_a``/``lora_b`` leaves are kept — the base W never moves.  The
  engine-provided ``writer(slot, factors, scale)`` callback copies them
  into the stacked device buffers (a traced dynamic_update_slice — pure
  data movement, no retrace; see serve/engine.py).

The registry itself is jax-free apart from what the injected loader/writer
pull in, so the LRU/refcount properties unit-test without a device.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: the reserved identity adapter: slot 0, always resident, zero factors
BASE_ADAPTER = "base"

#: sidecar an adapter checkpoint dir must carry (train/checkpoint.py)
RELORA_CONFIG_FILE = "relora_config.json"


def is_lora_leaf_name(name: str) -> bool:
    return str(name).startswith("lora_")


def extract_lora_factors(params: Any) -> Dict[str, Any]:
    """Keep only the ``lora_a``/``lora_b`` leaves of a restored param tree,
    preserving the module structure (so the engine can align them against
    the stacked decode tree path-by-path).  Returns a nested dict; empty
    modules are dropped."""
    if not isinstance(params, dict):
        return {}
    out: Dict[str, Any] = {}
    for key, value in params.items():
        if isinstance(value, dict):
            sub = extract_lora_factors(value)
            if sub:
                out[key] = sub
        elif key in ("lora_a", "lora_b"):
            out[key] = value
    return out


def default_loader(path: str, expected_r: Optional[int] = None) -> Tuple[Dict[str, Any], float]:
    """Restore an adapter checkpoint dir host-side and return
    ``(factors, scale)``: the unmerged lora_a/lora_b subtree plus the
    sidecar's ``alpha / r`` scale.  Raises ``ValueError`` when the dir has
    no sidecar or its rank disagrees with the serving stack's."""
    from relora_tpu.train.checkpoint import load_lora_spec, restore_params_host

    spec = load_lora_spec(path)
    if spec is None:
        raise ValueError(
            f"adapter dir {path} has no {RELORA_CONFIG_FILE} sidecar "
            "(adapters must be unmerged ReLoRA checkpoints)"
        )
    if expected_r is not None and spec.r != expected_r:
        raise ValueError(
            f"adapter {path} has r={spec.r} but the serving stack was built "
            f"with r={expected_r}; all tenant adapters must share the base rank"
        )
    factors = extract_lora_factors(restore_params_host(path))
    if not factors:
        raise ValueError(f"adapter dir {path} restored no lora_a/lora_b leaves")
    return factors, spec.scale


class AdapterRegistry:
    """Fixed pool of HBM adapter slots with refcounted LRU load/evict."""

    def __init__(
        self,
        adapter_dir: Optional[str],
        num_slots: int,
        *,
        expected_r: Optional[int] = None,
        writer: Optional[Callable[[int, Dict[str, Any], float], None]] = None,
        loader: Optional[Callable[[str, Optional[int]], Tuple[Dict[str, Any], float]]] = None,
        metrics: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if num_slots < 2:
            raise ValueError(
                f"num_slots must be >= 2 (slot 0 is the identity adapter), got {num_slots}"
            )
        self.adapter_dir = adapter_dir
        self.num_slots = num_slots
        self.expected_r = expected_r
        self._writer = writer
        self._loader = loader or default_loader
        self.metrics = metrics
        self._clock = clock
        # slot 0 is the identity adapter: out of the free list forever
        self._free: List[int] = list(range(num_slots - 1, 0, -1))
        self._resident: "OrderedDict[str, int]" = OrderedDict()  # name -> slot, LRU order
        self._refs: Dict[str, int] = {}  # name -> active requests (loaded names only)
        self.loads_total = 0
        self.evictions_total = 0
        self.hits_total = 0
        self.misses_total = 0

    # -- discovery -----------------------------------------------------------

    def adapter_path(self, name: str) -> Optional[str]:
        if self.adapter_dir is None:
            return None
        path = os.path.join(self.adapter_dir, name)
        if os.path.isfile(os.path.join(path, RELORA_CONFIG_FILE)):
            return path
        return None

    def known(self, name: str) -> bool:
        """Can this adapter be served at all?  ``base`` always; others iff a
        sidecar'd checkpoint dir exists (or it is already resident — the
        test path that preloads factors without a directory)."""
        if name == BASE_ADAPTER:
            return True
        return name in self._resident or self.adapter_path(name) is not None

    def list_adapters(self) -> List[str]:
        if self.adapter_dir is None or not os.path.isdir(self.adapter_dir):
            return []
        return sorted(
            d for d in os.listdir(self.adapter_dir)
            if os.path.isfile(os.path.join(self.adapter_dir, d, RELORA_CONFIG_FILE))
        )

    # -- the admission surface ----------------------------------------------

    def slot_of(self, name: Optional[str]) -> Optional[int]:
        if name is None or name == BASE_ADAPTER:
            return 0
        return self._resident.get(name)

    def acquire(self, name: Optional[str]) -> Optional[int]:
        """Pin ``name``'s slot for one request and return its index, loading
        the adapter into a slot first if it is not resident.  Returns
        ``None`` when no slot can be made free (every resident adapter has
        live requests) — the caller keeps the request queued and retries.
        The identity adapter always succeeds (slot 0 is never contended).
        """
        if name is None or name == BASE_ADAPTER:
            return 0
        slot = self._resident.get(name)
        if slot is not None:
            self.hits_total += 1
            self._refs[name] = self._refs.get(name, 0) + 1
            self._resident.move_to_end(name)
            return slot
        self.misses_total += 1
        if self.adapter_path(name) is None:
            # Unknown names must fail loudly even when every slot is pinned;
            # otherwise the caller queues a request that can never run.
            raise ValueError(
                f"unknown adapter {name!r} (no dir under {self.adapter_dir})"
            )
        slot = self._take_slot()
        if slot is None:
            return None  # every slot pinned: stay queued, evict-then-retry later
        try:
            self._load_into(name, slot)
        except Exception:
            self._free.append(slot)  # the slot stays clean: nothing was registered
            raise
        self._refs[name] = 1
        return slot

    def release(self, name: Optional[str]) -> None:
        """Drop one request's pin.  The adapter stays resident (warm) until
        eviction needs its slot — the prefix-cache retire contract."""
        if name is None or name == BASE_ADAPTER:
            return
        refs = self._refs.get(name)
        if refs is None or refs <= 0:
            raise ValueError(f"release of adapter {name!r} with no active requests")
        self._refs[name] = refs - 1

    # -- internals -----------------------------------------------------------

    def _take_slot(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        # evict the least-recently-used resident adapter with no live pins
        for victim, slot in self._resident.items():
            if self._refs.get(victim, 0) == 0:
                del self._resident[victim]
                del self._refs[victim]
                self.evictions_total += 1
                if self.metrics is not None:
                    self.metrics.inc("adapter_evictions_total")
                logger.info(f"evicting adapter {victim!r} from slot {slot}")
                return slot
        return None

    def _load_into(self, name: str, slot: int) -> None:
        path = self.adapter_path(name)
        if path is None:
            raise ValueError(f"unknown adapter {name!r} (no dir under {self.adapter_dir})")
        t0 = self._clock()
        factors, scale = self._loader(path, self.expected_r)
        if self._writer is not None:
            self._writer(slot, factors, scale)
        dt = self._clock() - t0
        self.loads_total += 1
        if self.metrics is not None:
            self.metrics.observe("adapter_load_seconds", dt)
        self._resident[name] = slot
        self._resident.move_to_end(name)
        logger.info(f"loaded adapter {name!r} into slot {slot} in {dt * 1e3:.1f} ms")

    def preload(self, name: str, factors: Dict[str, Any], scale: float) -> int:
        """Install already-materialized factors (tests, warm starts) without
        touching disk.  Same slot discipline as :meth:`acquire` but leaves
        the refcount at zero — nothing is pinned."""
        if name == BASE_ADAPTER:
            raise ValueError("slot 0 is reserved; the identity adapter is not loadable")
        if name in self._resident:
            return self._resident[name]
        slot = self._take_slot()
        if slot is None:
            raise RuntimeError("no adapter slot free for preload (all pinned)")
        if self._writer is not None:
            self._writer(slot, factors, scale)
        self.loads_total += 1
        self._resident[name] = slot
        self._refs[name] = 0
        return slot

    # -- observability --------------------------------------------------------

    def slots_used(self) -> int:
        return 1 + len(self._resident)  # identity slot counts as used

    def stats(self) -> Dict[str, Any]:
        return {
            "num_slots": self.num_slots,
            "slots_used": self.slots_used(),
            "slots_free": len(self._free),
            "resident": {
                name: {"slot": slot, "refs": self._refs.get(name, 0)}
                for name, slot in self._resident.items()
            },
            "loads_total": self.loads_total,
            "evictions_total": self.evictions_total,
            "hits_total": self.hits_total,
            "misses_total": self.misses_total,
            "hit_rate": (
                round(self.hits_total / (self.hits_total + self.misses_total), 4)
                if (self.hits_total + self.misses_total)
                else 0.0
            ),
        }
