"""Real multi-process distributed training test.

Launches two separate Python processes that form one JAX distributed system
(jax.distributed.initialize over a local coordinator, CPU devices), build the
same Trainer on a 2-way data-parallel mesh, read disjoint per-host batch
slices, and train — exercising the actual multi-host code paths
(process_count > 1 branch of device_batch via
make_array_from_process_local_data, per-host TokenBatchIterator slicing,
process-0-only checkpoint JSON) that single-process tests cannot reach.

The reference has no equivalent test (single-node only, SURVEY.md §4.4).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
coordinator, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(coordinator_address=coordinator, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 2

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(out_path))))
sys.path.insert(0, "/root/repo")
from tests.test_end_to_end import TINY, FakeTokens, make_cfg
from relora_tpu.data.hf_pipeline import TokenBatchIterator
from relora_tpu.train.trainer import Trainer

cfg = make_cfg(
    __import__("pathlib").Path(os.path.dirname(out_path)),
    num_training_steps=6, relora=None, use_peft=False, scheduler="cosine",
    cycle_length=6, save_every=6, dp_size=2, batch_size=4, total_batch_size=8,
)
trainer = Trainer(cfg, model_cfg=TINY)
data = FakeTokens(n=256)
it = TokenBatchIterator(
    data,
    microbatch=cfg.batch_size * trainer.n_batch_shards // jax.process_count(),
    grad_accum=trainer.grad_accum,
    process_index=jax.process_index(),
    process_count=jax.process_count(),
)
result = trainer.fit(iter(it), None)
import numpy as np
probe = float(np.asarray(trainer.state.params["lm_head"]["kernel"]).sum())
with open(out_path, "w") as f:
    json.dump({"process": pid, "result": result, "probe": probe}, f)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_data_parallel_training(tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(WORKER)
    procs = []
    outs = []
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    for pid in range(2):
        out = tmp_path / f"out_{pid}.json"
        outs.append(out)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker_file), coordinator, str(pid), str(out)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process run timed out")
        assert p.returncode == 0, f"worker failed:\n{stderr[-3000:]}"

    results = [json.load(open(o)) for o in outs]
    # both processes completed the same run and hold identical replicated-state
    assert all(r["result"]["update_step"] == 6 for r in results)
    assert results[0]["probe"] == pytest.approx(results[1]["probe"], rel=1e-6)
    assert np.isfinite(results[0]["probe"])
