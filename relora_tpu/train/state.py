"""Training state: one pytree carrying everything a step mutates.

Unlike the reference (mutable model + optimizer objects + loose Python
counters, torchrun_main.py:749-753), all device state lives in one immutable
struct so steps are pure, donation-friendly, and checkpointable as a unit.
Host-side counters (tokens_seen, wall-clock) stay in the Trainer.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

PyTree = Any


class TrainState(struct.PyTreeNode):
    step: jax.Array  # update_step (optimizer applications + NaN-skipped steps)
    params: PyTree  # full tree: frozen kernels + trainable leaves
    opt_state: PyTree  # optax state over the *trainable subtree* only
    n_skipped: jax.Array  # NaN-gated skipped updates (torchrun_main.py:817-822)

    @classmethod
    def create(cls, params: PyTree, opt_state: PyTree) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            n_skipped=jnp.zeros((), jnp.int32),
        )
