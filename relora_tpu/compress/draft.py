"""Export a pruned+merged checkpoint as a servable draft model.

The prune-retrain pipeline (PERP) leaves a training checkpoint whose frozen
base is already sparse; this module turns it into a standalone checkpoint
the serve path can load next to the full model for model-drafted
speculative decoding (``serve.py --spec model --draft-checkpoint ...``).

The export is just the serving restore (merge-verified) with the prune
mask applied and re-saved through the normal checkpoint writer, so the
output dir has everything ``restore_serving_params`` expects: an Orbax
``state/`` tree, a size+crc32 manifest covering the ``prune_mask.npz`` /
``prune_meta.json`` sidecars, and the mesh/partition-rule metadata — plus a
``pruned`` block in the manifest metadata recording sparsity and the mask
checksum.

Because the draft shares the base model's architecture (same config, just
sparser kernels), the serving engine can run it through the base's already
compiled prefill/decode programs — loading a draft never recompiles.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Tuple, Union

from relora_tpu.compress.prune import (
    PruneMaskMismatchError,
    _walk_prunable,
    apply_mask,
    load_mask,
    magnitude_mask,
    mask_checksum,
    save_mask,
    sparsity_stats,
)

PyTree = Any

logger = logging.getLogger(__name__)


def build_draft_params(
    checkpoint_dir: str,
    *,
    sparsity: Optional[float] = None,
    scope: str = "global",
    nm: Union[str, Tuple[int, int], None] = None,
) -> Tuple[PyTree, PyTree, dict]:
    """Restore ``checkpoint_dir`` merged for serving and prune it.

    Returns ``(pruned_params, mask, meta)``.  The mask comes from the
    checkpoint's own ``prune_mask.npz`` sidecar when present (a
    prune-retrain run — the LoRA factors were trained against exactly this
    mask, so reusing it is the right call); otherwise it is computed here
    at ``sparsity``/``nm`` over the merged kernels, using the unmerged
    tree's LoRA paths to decide which modules are prunable.
    """
    from relora_tpu.train import checkpoint as ckpt

    mask, meta = load_mask(checkpoint_dir)
    params = ckpt.restore_serving_params(checkpoint_dir)
    if mask is None:
        if sparsity is None and nm is None:
            raise ValueError(
                f"{checkpoint_dir} has no prune_mask.npz sidecar and no "
                "sparsity/nm was given — nothing to prune with"
            )
        host = ckpt.restore_params_host(checkpoint_dir)
        paths = [path for path, _ in _walk_prunable(host)]
        if not paths:
            raise PruneMaskMismatchError(
                f"{checkpoint_dir} has no LoRA factors to locate prunable "
                "modules by — export from an unmerged training checkpoint, "
                "or from one carrying a prune_mask.npz sidecar"
            )
        mask = magnitude_mask(
            params, 0.0 if sparsity is None else sparsity,
            scope=scope, nm=nm, paths=paths,
        )
        meta = {
            "target_sparsity": sparsity,
            "scope": scope,
            "nm": nm,
            "computed_at": "draft_export",
        }
    pruned = apply_mask(params, mask)
    return pruned, mask, dict(meta or {})


def export_draft_checkpoint(
    checkpoint_dir: str,
    out_dir: str,
    *,
    sparsity: Optional[float] = None,
    scope: str = "global",
    nm: Union[str, Tuple[int, int], None] = None,
) -> str:
    """Write a pruned+merged draft checkpoint under ``out_dir``; returns the
    ``model_N`` path (N = the source checkpoint's update step).

    The output passes ``verify_checkpoint`` and loads through
    ``restore_serving_params`` — exactly what ``serve.py --draft-checkpoint``
    and ``engine.load_draft_params`` consume.
    """
    from relora_tpu.parallel.mesh import current_mesh, mesh_metadata
    from relora_tpu.train import checkpoint as ckpt

    pruned, mask, mask_meta = build_draft_params(
        checkpoint_dir, sparsity=sparsity, scope=scope, nm=nm
    )
    stats = sparsity_stats(mask)
    try:
        training_state = ckpt.load_training_state(checkpoint_dir)
    except (OSError, ValueError):
        training_state = {}
    step = int(training_state.get("update_step", 0))
    metadata = mesh_metadata(current_mesh())
    metadata["pruned"] = {
        "sparsity": round(stats["sparsity"], 6),
        "mask_crc32": mask_checksum(mask),
        "source_checkpoint": os.path.abspath(checkpoint_dir),
    }
    path = ckpt.save_checkpoint(
        out_dir,
        step,
        {"params": pruned},
        {**training_state, "draft_export": True},
        manifest_metadata=metadata,
    )
    # the sidecar pair lands before the manifest fence below, so the
    # manifest's size+crc32 walk covers it
    save_mask(path, mask, mask_meta)
    ckpt.wait_for_save()
    logger.info(
        f"draft export: {path} at {stats['sparsity']:.1%} sparsity "
        f"(mask crc32 {metadata['pruned']['mask_crc32']})"
    )
    return path


def main(argv=None) -> None:
    """``python -m relora_tpu.compress.draft CKPT OUT [--sparsity S]``"""
    import argparse

    p = argparse.ArgumentParser(description=export_draft_checkpoint.__doc__)
    p.add_argument("checkpoint", help="source checkpoint dir (model_N)")
    p.add_argument("out_dir", help="output dir; the export lands in out_dir/model_N")
    p.add_argument(
        "--sparsity",
        type=float,
        default=None,
        help="target sparsity when the source has no prune_mask.npz sidecar",
    )
    p.add_argument("--scope", choices=("global", "per_matrix"), default="global")
    p.add_argument("--nm", default=None, help="structured N:M sparsity, e.g. 2:4")
    args = p.parse_args(argv)
    path = export_draft_checkpoint(
        args.checkpoint,
        args.out_dir,
        sparsity=args.sparsity,
        scope=args.scope,
        nm=args.nm,
    )
    print(path)


if __name__ == "__main__":
    main()
