"""Training-run visualization from metrics.jsonl (the wandb-dashboard view,
offline).  Three modes, covering the reference's plotting notebooks:

``curves`` (default — notebook 07_plotting): loss/LR/throughput curves for
one or more runs with merge/reset markers and optional smoothing::

    python tools/plot_metrics.py ckpts/relora [more_run_dirs...] --out curves.png
    python tools/plot_metrics.py curves ckpts/relora ckpts/full --ema 0.98

``scaling`` (notebook 03_scaling_laws_plotting): final loss vs trainable
params — or vs training compute C=6·N·D with ``--x compute`` — log-log per
run group, with a least-squares power-law fit ``loss = a * x^b`` per group
(full-rank vs ReLoRA, split on use_peft from each run's run_config.json).
Inputs are run dirs, or ``metrics.jsonl:model_config:group`` triplets for
committed sweep artifacts that carry no run_config.json; ``--fit-out``
writes the fits as JSON::

    python tools/plot_metrics.py scaling ckpts/run_* --out scaling.png
    python tools/plot_metrics.py scaling \
        bench_results/r3_loss_parity_cpu_metrics/full_rank.jsonl:llama_9m:full_rank \
        ... --x compute --fit-out scaling_fit.json

``lr`` (notebook 04_plot_lr): preview any supported schedule's LR curve
without running anything — the schedules are the real ones from
core/schedules.py, not a re-derivation::

    python tools/plot_metrics.py lr --scheduler cosine_restarts --lr 2e-3 \
        --num-training-steps 8000 --warmup-steps 250 --cycle-length 1000 \
        --restart-warmup-steps 100 --out lr.png
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODES = ("curves", "scaling", "lr")


def load_metrics(run_dir: str):
    path = os.path.join(run_dir, "metrics.jsonl")
    rows = [json.loads(l) for l in open(path)]
    return [r for r in rows if "loss" in r and "update_step" in r]


def load_run_config(run_dir: str) -> dict:
    path = os.path.join(run_dir, "run_config.json")
    if os.path.exists(path):
        return json.load(open(path))
    return {}


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def cmd_curves(argv) -> None:
    p = argparse.ArgumentParser(prog="plot_metrics.py curves")
    p.add_argument("run_dirs", nargs="+")
    p.add_argument("--out", default="curves.png")
    p.add_argument("--ema", type=float, default=0.0, help="EMA smoothing factor (0 = off)")
    args = p.parse_args(argv)
    plt = _mpl()

    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    for run_dir in args.run_dirs:
        rows = load_metrics(run_dir)
        if not rows:
            print(f"no metrics in {run_dir}")
            continue
        name = os.path.basename(os.path.normpath(run_dir))
        steps = [r["update_step"] for r in rows]
        loss = [r["loss"] for r in rows]
        if args.ema > 0:
            sm, out = None, []
            for v in loss:
                sm = v if sm is None else args.ema * sm + (1 - args.ema) * v
                out.append(sm)
            loss = out
        axes[0].plot(steps, loss, label=name)
        axes[1].plot(steps, [r.get("lr", 0) for r in rows], label=name)
        axes[2].plot(steps, [r.get("throughput_tokens", 0) for r in rows], label=name)
        # merge markers: steps where n_lora_restarts increments
        prev = 0
        for r in rows:
            n = r.get("n_lora_restarts", 0)
            if n > prev:
                axes[0].axvline(r["update_step"], color="gray", alpha=0.4, linestyle="--")
                prev = n

    for ax, title, ylab in zip(
        axes,
        ("loss (merges dashed)", "learning rate", "throughput"),
        ("loss", "lr", "tokens/s"),
    ):
        ax.set_title(title)
        ax.set_xlabel("update step")
        ax.set_ylabel(ylab)
        ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")


def fit_power_law(xs, ys):
    """Least-squares fit of loss = a * x^b in log-log space (no scipy in the
    image; for positive data this is the standard linearization)."""
    lx = [math.log(x) for x in xs]
    ly = [math.log(y) for y in ys]
    n = len(lx)
    mx, my = sum(lx) / n, sum(ly) / n
    sxx = sum((v - mx) ** 2 for v in lx)
    if sxx == 0:
        return math.exp(my), 0.0
    b = sum((u - mx) * (v - my) for u, v in zip(lx, ly)) / sxx
    a = math.exp(my - b * mx)
    return a, b


def final_eval_loss(rows) -> float:
    """The run's final_eval_loss if recorded, else the last eval_loss, else
    the mean of the last 20 train losses."""
    finals = [r for r in rows if r.get("final_eval_loss") is not None]
    if finals:
        return float(finals[-1]["final_eval_loss"])
    evals = [r for r in rows if r.get("eval_loss") is not None]
    if evals:
        return float(evals[-1]["eval_loss"])
    tail = [r["loss"] for r in rows if "loss" in r][-20:]
    return float(sum(tail) / len(tail))


def _zoo_param_count_m(model_name: str) -> float:
    """Exact full-rank parameter count for a MODEL_ZOO entry, in millions.

    Shape-only (jax.eval_shape) — no weights are materialized, so this is
    cheap even for the 1B/7B entries.  Used for metrics files recorded
    without a run_config.json sidecar (e.g. the committed loss-parity
    sweeps): compute-axis scaling needs N, and the 6·N·D FLOP estimate uses
    the same total-N for full-rank and ReLoRA runs (frozen weights still
    do forward+backward work)."""
    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import MODEL_ZOO
    from relora_tpu.models import LlamaForCausalLM
    from relora_tpu.models.pythia import GPTNeoXForCausalLM

    mc = MODEL_ZOO[model_name]
    cls = GPTNeoXForCausalLM if mc.family == "neox" else LlamaForCausalLM
    model = cls(config=mc, scan_layers=False)
    shapes = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )
    return sum(
        math.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes)
    ) / 1e6


def _parse_scaling_entry(entry: str):
    """A scaling input is a run dir, or ``metrics.jsonl:model_config:group``
    for bare metrics files (committed sweep artifacts carry no
    run_config.json).  Returns (rows, trainable_M, total_M, group, label)
    or None when the entry lacks what the fit needs."""
    if ":" in entry and entry.split(":", 1)[0].endswith(".jsonl"):
        parts = entry.split(":")
        if len(parts) != 3:
            print(f"skipping {entry}: expected metrics.jsonl:model_config:group")
            return None
        path, model_name, group = parts
        rows = [json.loads(l) for l in open(path)]
        rows = [
            r for r in rows
            if ("loss" in r and "update_step" in r)
            or r.get("final_eval_loss") is not None
        ]
        if not rows:
            print(f"skipping {entry}: no usable loss rows")
            return None
        try:
            n = _zoo_param_count_m(model_name)
        except KeyError:
            print(f"skipping {entry}: unknown model config {model_name!r}")
            return None
        # bare files carry no LoRA breakdown: N is the base model count
        # (exact for full-rank; for ReLoRA entries use --x compute, where
        # base-N is the right N anyway)
        return rows, n, n, group, path
    rows = load_metrics(entry)
    cfg = load_run_config(entry)
    if not rows or "trainable_params" not in cfg:
        print(f"skipping {entry}: missing metrics or run_config.json trainable_params")
        return None
    group = "relora" if cfg.get("use_peft") else "full_rank"
    # run_config.json stores param counts already in millions
    # (trainer.py writes counts / 1e6), matching the axis label and the
    # printed params_M fit — no further scaling.  Compute-axis N is
    # equivalent_params (base model, LoRA folded out) so run dirs and bare
    # triplets put identical compute at identical x.
    total_m = float(cfg.get("equivalent_params") or cfg["total_params"])
    return rows, float(cfg["trainable_params"]), total_m, group, entry


def _final_tokens(rows) -> float:
    toks = [r["tokens_seen"] for r in rows if r.get("tokens_seen")]
    return float(toks[-1]) if toks else 0.0


def cmd_scaling(argv) -> None:
    p = argparse.ArgumentParser(prog="plot_metrics.py scaling")
    p.add_argument("run_dirs", nargs="+",
                   help="run dirs, or metrics.jsonl:model_config:group triplets")
    p.add_argument("--out", default="scaling.png")
    p.add_argument("--x", choices=("params", "compute"), default="params",
                   help="x axis: trainable params (M) or training compute "
                        "C = 6*N*D FLOPs (notebook 03's loss-vs-compute view)")
    p.add_argument("--fit-out", default=None,
                   help="write the per-group power-law fits as JSON")
    args = p.parse_args(argv)
    plt = _mpl()

    groups: dict = {}
    for entry in args.run_dirs:
        parsed = _parse_scaling_entry(entry)
        if parsed is None:  # reason already printed by the parser
            continue
        rows, trainable_m, total_m, group, label = parsed
        if args.x == "compute":
            d = _final_tokens(rows)
            if d == 0:
                print(f"skipping {label}: no tokens_seen recorded")
                continue
            x = 6.0 * total_m * 1e6 * d  # FLOPs
        else:
            x = trainable_m
        groups.setdefault(group, []).append((x, final_eval_loss(rows), label))

    xname = "compute C=6·N·D (FLOPs)" if args.x == "compute" else "params_M"
    fits = {}
    fig, ax = plt.subplots(figsize=(5.5, 5.5))
    for group, pts in sorted(groups.items()):
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        ax.scatter(xs, ys, label=group)
        if len(pts) >= 2:
            a, b = fit_power_law(xs, ys)
            grid = [min(xs) * (max(xs) / min(xs)) ** (i / 99) for i in range(100)]
            ax.plot(grid, [a * x**b for x in grid], linestyle="--", alpha=0.7,
                    label=f"{group}: {a:.2f}·x^{b:.3f}")
            print(f"{group}: loss = {a:.4g} * x^{b:.4f}  (x = {xname}, {len(pts)} runs)")
            fits[group] = {
                "a": a,
                "b": b,
                "x_axis": args.x,
                "points": [
                    {"x": x, "loss": y, "run": lbl} for x, y, lbl in pts
                ],
            }
    ax.set_xscale("log")
    ax.set_yscale("log")
    ax.set_xlabel("Training compute (FLOPs)" if args.x == "compute"
                  else "Trainable parameters (M)")
    ax.set_ylabel("Loss")
    ax.set_title(f"Scaling: loss vs {'compute' if args.x == 'compute' else 'trainable params'}")
    ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(args.out, dpi=150)
    print(f"wrote {args.out}")
    if args.fit_out:
        with open(args.fit_out, "w") as f:
            json.dump({"model": "loss = a * x^b", "fits": fits}, f, indent=2)
        print(f"wrote {args.fit_out}")


def cmd_lr(argv) -> None:
    p = argparse.ArgumentParser(prog="plot_metrics.py lr")
    p.add_argument("--scheduler", default="cosine_restarts")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--num-training-steps", type=int, default=8000)
    p.add_argument("--warmup-steps", type=int, default=250)
    p.add_argument("--min-lr-ratio", type=float, default=0.1)
    p.add_argument("--cycle-length", type=int, default=1000)
    p.add_argument("--restart-warmup-steps", type=int, default=100)
    p.add_argument("--adjust-step", type=int, default=0)
    p.add_argument("--out", default="lr.png")
    args = p.parse_args(argv)

    # analysis-only tool: always CPU (the sandbox env force-selects the TPU
    # backend; evaluating a schedule needs no chip)
    os.environ["JAX_PLATFORMS"] = "cpu"
    from relora_tpu.utils.logging import honor_platform_request

    honor_platform_request()
    from relora_tpu.core.schedules import make_schedule

    sched = make_schedule(
        args.scheduler,
        lr=args.lr,
        num_training_steps=args.num_training_steps,
        warmup_steps=args.warmup_steps,
        min_lr_ratio=args.min_lr_ratio,
        cycle_length=args.cycle_length,
        restart_warmup_steps=args.restart_warmup_steps,
        adjust_step=args.adjust_step,
    )
    steps = list(range(args.num_training_steps))
    values = [float(sched(s)) for s in steps]
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(8, 4))
    ax.plot(steps, values)
    ax.set_xlabel("update step")
    ax.set_ylabel("learning rate")
    ax.set_title(f"{args.scheduler} lr={args.lr}")
    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    mode = "curves"
    if argv and argv[0] in MODES:
        mode = argv.pop(0)
    {"curves": cmd_curves, "scaling": cmd_scaling, "lr": cmd_lr}[mode](argv)


if __name__ == "__main__":
    main()
