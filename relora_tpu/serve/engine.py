"""Jitted prefill/decode step functions over the cache-aware model forwards.

The models gained a ``decode=True`` mode (models/llama.py, models/pythia.py):
attention keeps per-layer K/V buffers of fixed capacity in the flax ``cache``
variable collection, writes the current chunk at its absolute positions, and
attends with the ``j <= position`` visibility mask (ops/attention.py:
cached_attention).  This module wraps that into an inference engine:

- ``prefill(ids, lengths)`` — run the whole (right-padded) prompt batch in one
  forward, returning full logits and a populated cache.  Pad tokens write
  garbage K/V beyond each row's length, but an entry at index ``j`` only
  becomes visible to queries at positions ``>= j`` — and the decode loop
  overwrites index ``j`` at the step that reaches position ``j``, before it
  ever attends.  So right-padding needs no separate pad mask.
- ``decode(cache, token, pos)`` — one token per row against the cache, cache
  buffers donated so XLA updates them in place (no per-step reallocation).
- ``insert(dcache, pcache, slot)`` — copy a freshly prefilled single-row cache
  into slot ``slot`` of the persistent decode cache (continuous batching
  admission).  ``slot`` is traced, so admissions never retrace.

Prompt lengths are bucketed to powers of two (``bucket_length``) to bound the
number of prefill compilations.

Shardings: with a mesh, params shard per the model's logical annotations
(parallel/mesh.py LOGICAL_RULES) and cache buffers shard their batch axis over
``data``×``fsdp`` — K/V heads stay replicated like the ``kv`` logical axis.
Without a mesh the same code runs single-host (CPU tests, dev boxes).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.obs import memory as obs_memory
from relora_tpu.obs.compile import CompileWatcher
from relora_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, param_shardings
from relora_tpu.serve.sampling import SamplingParams, sample

PyTree = Any

# leaves are (B, capacity, kv_heads, head_dim), plus a leading scan-layers
# axis when the model scans; the batch axis is always ndim-4
_CACHE_RANK = 4


def _cache_batch_axis(leaf) -> int:
    return leaf.ndim - _CACHE_RANK


def bucket_length(n: int, minimum: int = 16) -> int:
    """Round a prompt length up to the next power of two (>= minimum) so
    prefill compiles once per bucket, not once per prompt length."""
    if n < 1:
        raise ValueError(f"prompt length must be >= 1, got {n}")
    return max(minimum, 1 << (n - 1).bit_length())


def build_decode_model(
    model_cfg: ModelConfig,
    *,
    cache_size: int,
    dtype=jnp.float32,
    scan_layers: bool = True,
    attention_impl: str = "auto",
    lora: Optional[LoraSpec] = None,
):
    """The serving twin of train.trainer.build_model: same family dispatch,
    decode cache enabled, no remat.  ``lora=None`` (the default) serves a
    merged, LoRA-free param tree; passing the checkpoint's ``LoraSpec``
    serves the factors unmerged (quantized bases that can't absorb the
    delta, or adapter hot-swap).  An unmerged spec is rewritten for decode:
    ``weights_static`` tells ops/lora_dispatch's cost model that W/A/B are
    constant across steps, and ``fused=False`` is promoted to ``"auto"`` so
    the decode forward actually routes through the dispatcher — which picks
    the merged ``x @ (W + s·A@B)`` arm at decode-sized M."""
    if lora is not None:
        lora = dataclasses.replace(
            lora,
            weights_static=True,
            fused="auto" if lora.fused is False else lora.fused,
        )
    kwargs = dict(
        config=model_cfg,
        lora=lora,
        dtype=dtype,
        scan_layers=scan_layers,
        remat=False,
        attention_impl=attention_impl,
        logits_dtype=jnp.float32,
        decode=True,
        cache_size=cache_size,
    )
    if model_cfg.family == "llama":
        from relora_tpu.models.llama import LlamaForCausalLM

        return LlamaForCausalLM(**kwargs)
    if model_cfg.family == "neox":
        from relora_tpu.models.pythia import GPTNeoXForCausalLM

        return GPTNeoXForCausalLM(**kwargs)
    raise ValueError(f"Unknown model family {model_cfg.family!r}")


class InferenceEngine:
    """Owns the decode-mode model, the jitted step functions, and placement.

    ``params`` must match the training layout (scan-stacked layers when
    ``scan_layers``): a merged LoRA-free tree by default (see
    train.checkpoint.restore_serving_params), or — with ``lora=`` set to the
    checkpoint's spec — the raw tree with its LoRA factors still separate.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        params: PyTree,
        *,
        cache_size: int,
        dtype=jnp.float32,
        scan_layers: bool = True,
        attention_impl: str = "auto",
        mesh: Optional[Mesh] = None,
        lora: Optional[LoraSpec] = None,
        compile_watcher: Optional[CompileWatcher] = None,
    ):
        if cache_size < 1:
            raise ValueError(f"cache_size must be >= 1, got {cache_size}")
        self.config = model_cfg
        self.cache_size = cache_size
        self.mesh = mesh
        self.model = build_decode_model(
            model_cfg,
            cache_size=cache_size,
            dtype=dtype,
            scan_layers=scan_layers,
            attention_impl=attention_impl,
            lora=lora,
        )
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if mesh is not None:
            from relora_tpu.models.params_util import logical_partition_specs

            sample_ids = jnp.zeros((1, 1), jnp.int32)
            specs = logical_partition_specs(self.model, sample_ids)
            shardings = param_shardings(mesh, specs)
            params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        self.params = params

        def prefill_fn(p, ids, positions, cache):
            logits, variables = self.model.apply(
                {"params": p, "cache": cache}, ids, positions=positions, mutable=["cache"]
            )
            return logits, variables["cache"]

        def decode_fn(p, cache, token, pos):
            logits, variables = self.model.apply(
                {"params": p, "cache": cache}, token, positions=pos, mutable=["cache"]
            )
            return logits[:, -1, :], variables["cache"]

        def insert_fn(dcache, pcache, slot):
            def ins(d, src):
                starts = [0] * d.ndim
                starts[_cache_batch_axis(d)] = slot
                return jax.lax.dynamic_update_slice(d, src.astype(d.dtype), tuple(starts))

            return jax.tree_util.tree_map(ins, dcache, pcache)

        # the fresh prefill cache and the persistent decode cache are both
        # donated: the step's output cache reuses the input buffers in place.
        # The compile watcher tracks each entry point's abstract signatures:
        # warmup() compiles are tagged expected, anything after counts toward
        # compile_steady_state_retraces (docs/observability.md)
        self.compile_watcher = compile_watcher or CompileWatcher(service="engine")
        cw = self.compile_watcher
        self._prefill = cw.wrap("prefill", jax.jit(prefill_fn, donate_argnums=(3,)))
        self._decode = cw.wrap("decode", jax.jit(decode_fn, donate_argnums=(1,)))
        self._insert = cw.wrap("insert", jax.jit(insert_fn, donate_argnums=(0,)))
        self._sample = jax.jit(sample, static_argnames=("top_k",))

    # -- cache construction --------------------------------------------------

    def cache_shapes(self, batch: int) -> PyTree:
        """Abstract (shape, dtype) tree of the cache for a given batch size —
        eval_shape over model.init, so no FLOPs or memory."""
        ids = jnp.zeros((batch, 1), jnp.int32)
        variables = jax.eval_shape(
            lambda: self.model.init(jax.random.PRNGKey(0), ids)
        )
        return variables["cache"]

    def cache_shardings(self, batch: int) -> Optional[PyTree]:
        """Batch axis over data×fsdp, everything else replicated — K/V heads
        stay unsharded like the ``kv`` logical axis in LOGICAL_RULES."""
        if self.mesh is None:
            return None

        def spec(leaf):
            axes = [None] * leaf.ndim
            n_shards = (
                self.mesh.shape[DATA_AXIS] * self.mesh.shape[FSDP_AXIS]
            )
            if batch % n_shards == 0:
                axes[_cache_batch_axis(leaf)] = (DATA_AXIS, FSDP_AXIS)
            return NamedSharding(self.mesh, P(*axes))

        return jax.tree_util.tree_map(spec, self.cache_shapes(batch))

    def init_cache(self, batch: int) -> PyTree:
        """Concrete zero cache for ``batch`` rows, placed per the mesh."""
        shardings = self.cache_shardings(batch)
        shapes = self.cache_shapes(batch)
        if shardings is None:
            return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        return jax.tree_util.tree_map(
            lambda s, sh: jax.device_put(jnp.zeros(s.shape, s.dtype), sh),
            shapes,
            shardings,
        )

    # -- step functions ------------------------------------------------------

    def prefill(self, ids: jax.Array, lengths=None) -> Tuple[jax.Array, PyTree]:
        """Run a right-padded prompt batch ``(B, T)``; returns full logits
        ``(B, T, V)`` and the populated cache.  ``T`` must be <= cache_size
        (bucket prompts with ``bucket_length`` before calling)."""
        B, T = ids.shape
        if T > self.cache_size:
            raise ValueError(f"prompt length {T} exceeds cache capacity {self.cache_size}")
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        cache = self.init_cache(B)
        return self._prefill(self.params, jnp.asarray(ids), positions, cache)

    def decode(self, cache: PyTree, token: jax.Array, pos: jax.Array) -> Tuple[jax.Array, PyTree]:
        """One decode step: ``token``/``pos`` are ``(B, 1)``; returns logits
        ``(B, V)`` and the updated cache.  The input cache is donated —
        the caller must not reuse it after this call."""
        return self._decode(
            self.params, cache, jnp.asarray(token), jnp.asarray(pos, jnp.int32)
        )

    def insert(self, dcache: PyTree, pcache: PyTree, slot) -> PyTree:
        """Copy a single-row prefilled cache into decode slot ``slot``.
        ``dcache`` is donated; ``slot`` is traced (no retrace per slot)."""
        return self._insert(dcache, pcache, jnp.asarray(slot, jnp.int32))

    def warmup(self, batch: int, *, prompt_buckets: Sequence[int] = (16,)) -> dict:
        """Compile the serving step functions before traffic arrives: one
        prefill per prompt bucket, one insert, one decode at ``batch`` rows.
        An online server calls this at startup so the first real request
        pays queueing latency, not XLA compilation.

        Returns a report of what was compiled — the buckets and batch shapes
        plus per-compile durations — so operators can log it and compile
        telemetry can tell these expected compiles apart from steady-state
        retraces (a prompt landing in an un-warmed bucket after this)."""
        cw = self.compile_watcher
        n_before = len(cw.compile_events())
        buckets: List[int] = []
        with cw.expected_compiles("warmup"):
            pcache = None
            for bucket in prompt_buckets:
                T = min(bucket_length(bucket), self.cache_size)
                if T not in buckets:
                    buckets.append(T)
                _, pcache = self.prefill(jnp.zeros((1, T), jnp.int32))
            cache = self.init_cache(batch)
            if pcache is not None:
                cache = self.insert(cache, pcache, 0)
            logits, cache = self.decode(
                cache, jnp.zeros((batch, 1), jnp.int32), jnp.zeros((batch, 1), jnp.int32)
            )
            jax.block_until_ready(logits)
        events = cw.compile_events()[n_before:]
        return {
            "batch": batch,
            "prompt_buckets": buckets,
            "shapes": {
                "prefill": [[1, T] for T in buckets],
                "insert": [[batch], [1]],
                "decode": [batch, 1],
            },
            "n_compiles": len(events),
            "compiles": [
                {"fn": ev.fn, "duration_s": round(ev.duration_s, 4), "reason": ev.reason}
                for ev in events
            ],
        }

    def memory_plans(self, batch: int, *, prompt_buckets: Sequence[int] = (16,)) -> dict:
        """Static HBM plans for every jitted serving entry point (per-bucket
        prefill, insert, decode at ``batch`` rows) plus the per-pytree
        breakdown of what stays resident (params, KV cache).

        Uses AOT lower+compile, which does NOT warm the traced-call cache —
        each plan pays a real compile (tagged expected), so call this at
        startup or in reports, not per request.  Off-accelerator the XLA
        numbers describe host buffers, but the relative breakdown holds."""
        plans: dict = {
            "pytree": obs_memory.pytree_breakdown(
                {"params": self.params, "kv_cache": self.cache_shapes(batch)}
            )
        }
        dcache = self.cache_shapes(batch)
        pcache1 = self.cache_shapes(1)
        i32 = jnp.int32
        # AOT plans bypass __call__, so the watcher never sees them — no
        # expected_compiles block needed
        for bucket in prompt_buckets:
            T = min(bucket_length(bucket), self.cache_size)
            plans[f"prefill_b{T}"] = obs_memory.plan_for(
                self._prefill,
                self.params,
                jax.ShapeDtypeStruct((1, T), i32),
                jax.ShapeDtypeStruct((1, T), i32),
                pcache1,
            )
        plans["insert"] = obs_memory.plan_for(
            self._insert, dcache, pcache1, jax.ShapeDtypeStruct((), i32)
        )
        plans["decode"] = obs_memory.plan_for(
            self._decode,
            self.params,
            dcache,
            jax.ShapeDtypeStruct((batch, 1), i32),
            jax.ShapeDtypeStruct((batch, 1), i32),
        )
        return plans

    # -- convenience: one-shot batch generation ------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        max_new_tokens: int,
        sampling: SamplingParams = SamplingParams(),
        eos_id: Optional[int] = None,
        key: Optional[jax.Array] = None,
    ) -> List[List[int]]:
        """Batch generation without continuous batching: pad all prompts to one
        bucket, prefill, then decode until every row hits EOS/max_new_tokens.
        The scheduler (serve/scheduler.py) is the production path; this is the
        one-shot ``--prompt`` path and the parity-test oracle."""
        if not prompts:
            return []
        if key is None:
            key = jax.random.PRNGKey(0)
        lengths = np.array([len(p) for p in prompts], np.int32)
        if lengths.min() < 1:
            raise ValueError("empty prompt")
        T = min(bucket_length(int(lengths.max())), self.cache_size)
        if int(lengths.max()) + max_new_tokens > self.cache_size:
            raise ValueError(
                f"prompt ({lengths.max()}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds cache capacity {self.cache_size}"
            )
        B = len(prompts)
        ids = np.zeros((B, T), np.int32)
        for i, p in enumerate(prompts):
            ids[i, : lengths[i]] = np.asarray(p, np.int32)

        logits, cache = self.prefill(jnp.asarray(ids), lengths)
        last = jnp.take_along_axis(
            logits, jnp.asarray(lengths - 1)[:, None, None], axis=1
        )[:, 0, :]
        token = self._sample(
            last,
            jax.random.fold_in(key, 0),
            temperature=sampling.temperature,
            top_k=sampling.top_k,
            top_p=sampling.top_p,
        )
        pos = jnp.asarray(lengths, jnp.int32)
        out: List[List[int]] = [[] for _ in range(B)]
        done = np.zeros(B, bool)
        for step in range(max_new_tokens):
            host_tok = np.asarray(token)
            for i in range(B):
                if not done[i]:
                    out[i].append(int(host_tok[i]))
                    if eos_id is not None and host_tok[i] == eos_id:
                        done[i] = True
            if done.all() or step == max_new_tokens - 1:
                break
            logits, cache = self.decode(cache, token[:, None], pos[:, None])
            pos = pos + 1
            token = self._sample(
                logits,
                jax.random.fold_in(key, step + 1),
                temperature=sampling.temperature,
                top_k=sampling.top_k,
                top_p=sampling.top_p,
            )
        return out
