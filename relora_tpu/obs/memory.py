"""HBM accounting: static memory plans, per-pytree byte breakdowns, and a
cadence-gated live ``memory_stats`` poller.

Three views of device memory, from cheapest to most detailed:

- :func:`pytree_bytes` / :func:`pytree_breakdown` — pure metadata sums over a
  pytree's leaf shapes (concrete arrays or ``ShapeDtypeStruct``): what the
  *resident state* (params / opt_state / KV cache) occupies.  No device work.
- :func:`xla_memory_plan` / :func:`plan_for` — XLA's own static plan for one
  compiled program (``compiled.memory_analysis()``): argument / output / temp
  / donated-alias bytes.  ``plan_for`` lowers **and compiles** — an AOT
  compile does NOT warm the traced-call jit cache on this jax, so callers
  gate it (the trainer honors ``RELORA_TPU_MEM_PLAN=0``).
- :func:`live_memory_stats` / :class:`MemoryPoller` — the allocator's live
  and peak gauges.  ``device.memory_stats()`` returns None on the CPU
  backend; the normalized schema keeps ``available: False`` there so CPU and
  TPU runs share one code path (used by ``utils/benchlib`` for the
  ``hbm_peak_gb`` BENCH field).

Everything imports jax lazily, keeping ``relora_tpu.obs`` import-light.  The
module is registered hot (analysis/hotpaths.py): nothing here may sync the
host on device *values* — ``memory_stats()`` is an allocator-metadata read,
not a computation fence, and even so the poller is only ever called at the
metrics cadence (the trainer's flush), never per step.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Mapping, Optional

__all__ = [
    "pytree_bytes",
    "pytree_breakdown",
    "xla_memory_plan",
    "plan_for",
    "live_memory_stats",
    "hbm_peak_gb",
    "reconcile",
    "MemoryPoller",
]


def _leaf_nbytes(leaf: Any) -> int:
    """Bytes of one leaf: concrete arrays via ``.nbytes``, abstract leaves
    (ShapeDtypeStruct) via shape x itemsize, non-array leaves count zero."""
    nbytes = getattr(leaf, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize is None:
        import numpy as np

        itemsize = np.dtype(dtype).itemsize
    # shape/itemsize are python metadata, not device values — no sync here
    return int(math.prod(shape)) * int(itemsize)  # noqa: RTL202


def pytree_bytes(tree: Any) -> int:
    """Total bytes of a pytree's array leaves (concrete or abstract)."""
    import jax

    return sum(_leaf_nbytes(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def pytree_breakdown(named: Mapping[str, Any]) -> Dict[str, int]:
    """``{"params": tree, "opt_state": tree, ...}`` -> flat byte counts per
    group plus ``total_bytes`` — the per-pytree HBM plan the trainer emits
    as a ``memory_plan`` event into metrics.jsonl."""
    out: Dict[str, int] = {}
    total = 0
    for name, tree in named.items():
        b = pytree_bytes(tree)
        out[f"{name}_bytes"] = b
        total += b
    out["total_bytes"] = total
    return out


#: CompiledMemoryStats fields worth surfacing; the serialized HLO proto blob
#: and pjrt-internal extras are deliberately excluded
_PLAN_FIELDS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "temp_size_in_bytes",
    "host_generated_code_size_in_bytes",
    "host_argument_size_in_bytes",
    "host_output_size_in_bytes",
    "host_alias_size_in_bytes",
    "host_temp_size_in_bytes",
)


def xla_memory_plan(compiled: Any) -> Optional[Dict[str, int]]:
    """Normalize ``compiled.memory_analysis()`` into a plain dict.

    Keys drop the ``_size_in_bytes`` suffix (``argument_bytes``,
    ``temp_bytes``, ...).  ``plan_total_bytes`` is the static residency
    estimate: arguments + outputs + temporaries + generated code, minus the
    alias bytes that donation lets outputs share with inputs.  Returns None
    when the backend offers no analysis.
    """
    try:
        stats = compiled.memory_analysis()
    except Exception:
        return None
    if stats is None:
        return None
    out: Dict[str, int] = {}
    for field in _PLAN_FIELDS:
        value = getattr(stats, field, None)
        if isinstance(value, int) and (value != 0 or not field.startswith("host_")):
            out[field[: -len("_size_in_bytes")] + "_bytes"] = value
    if not out:
        return None
    out["plan_total_bytes"] = max(
        0,
        out.get("argument_bytes", 0)
        + out.get("output_bytes", 0)
        + out.get("temp_bytes", 0)
        + out.get("generated_code_bytes", 0)
        - out.get("alias_bytes", 0),
    )
    return out


def plan_for(jitted_fn: Any, *args: Any, **kwargs: Any) -> Dict[str, Any]:
    """Static memory plan of one jitted entry point: ``lower(...).compile()``
    then :func:`xla_memory_plan`.  Arguments may be concrete arrays or
    ``jax.ShapeDtypeStruct`` — mixing is fine.

    NOTE: the AOT compile this performs does not populate the traced-call
    cache, so the first real call still pays its own compile.  Call it where
    a duplicate compile is acceptable (startup, tests, reports) and gate it
    for large models.  Never raises: failures come back as ``{"error": ...}``.
    """
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
    except Exception as e:  # backend-specific; a plan must never fail the run
        return {"error": f"{type(e).__name__}: {e}"}
    return xla_memory_plan(compiled) or {"error": "memory_analysis unavailable"}


def live_memory_stats(device: Any = None) -> Dict[str, Any]:
    """Allocator live/peak gauges in one schema for every backend.

    TPU/GPU backends report ``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit``; the CPU backend's ``memory_stats()`` is None, which
    comes back as ``available: False`` with None values — callers never
    branch on the backend, only on the fields.
    """
    stats = None
    try:
        import jax

        if device is None:
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        stats = None
    out: Dict[str, Any] = {
        "available": stats is not None,
        "bytes_in_use": None,
        "peak_bytes_in_use": None,
        "bytes_limit": None,
    }
    if stats:
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            value = stats.get(key)
            if value is not None:
                out[key] = int(value)
    return out


def hbm_peak_gb(device: Any = None) -> Optional[float]:
    """Peak allocator bytes in GB, or None where the backend keeps no stats
    (CPU) — the single code path behind the ``hbm_peak_gb`` BENCH field."""
    peak = live_memory_stats(device).get("peak_bytes_in_use")
    return round(peak / 1e9, 2) if peak is not None else None


def reconcile(plan_total_bytes: Optional[int], live: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Plan-vs-actual: how much of the static plan the allocator's peak
    confirms.  ``live_vs_plan`` > 1 means the plan undercounts (fragmentation,
    other programs resident); None when either side is unknown."""
    if live is None:
        live = live_memory_stats()
    peak = live.get("peak_bytes_in_use")
    out: Dict[str, Any] = {
        "plan_total_bytes": plan_total_bytes,
        "live_peak_bytes": peak,
        "live_vs_plan": None,
    }
    if plan_total_bytes and peak:
        out["live_vs_plan"] = round(peak / plan_total_bytes, 4)
    return out


class MemoryPoller:
    """Cadence-gated live-memory gauges.

    ``poll()`` reads the allocator stats once and mirrors them into a
    :class:`~relora_tpu.obs.metrics.MetricsRegistry` as ``hbm_*`` gauges.
    It must only be called at the metrics cadence (the trainer calls it from
    the ``log_every`` flush) — never inside the per-step hot loop, where even
    an allocator-metadata read per step is wasted host time.
    """

    def __init__(self, registry: Any = None, device: Any = None):
        self.registry = registry
        self.device = device
        self.last: Optional[Dict[str, Any]] = None

    def poll(self) -> Dict[str, Any]:
        stats = live_memory_stats(self.device)
        self.last = stats
        if self.registry is not None and stats["available"]:
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                value = stats.get(key)
                if value is not None:
                    self.registry.set_gauge(f"hbm_{key}", float(value))
        return stats
