"""Train-step tests: loss descent, NaN gating, and the sharded multi-device
path on an 8-virtual-device CPU mesh (the capability the reference never had
an equivalent of — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.optim import build_optimizer
from relora_tpu.core.relora import LoraSpec, trainable_param_mask
from relora_tpu.models.llama import LlamaForCausalLM
from relora_tpu.models.params_util import init_params, logical_partition_specs
from relora_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    make_mesh,
    param_shardings,
    shard_params,
)
from relora_tpu.train.state import TrainState
from relora_tpu.train.step import make_eval_step, make_train_step

TINY = ModelConfig(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=2,
    max_sequence_length=32,
)


def build(lora=None, lr=1e-2):
    model = LlamaForCausalLM(TINY, lora=lora, dtype=jnp.float32)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: lr)
    from relora_tpu.core.partition import partition

    trainable, _ = partition(params, mask)
    opt_state = tx.init(trainable)
    state = TrainState.create(params, opt_state)
    step = make_train_step(model, tx, mask, clip_grad_norm=1.0, schedule=lambda s: lr)
    return model, state, step


def test_loss_decreases_full_rank():
    model, state, step = build()
    step = jax.jit(step, donate_argnums=0)
    batch = jax.random.randint(jax.random.PRNGKey(1), (2, 4, 16), 0, 128)  # (ga, micro, seq)
    first = None
    for i in range(30):
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    assert int(state.step) == 30
    assert float(metrics["loss"]) < first * 0.7
    assert float(metrics["lr"]) == pytest.approx(1e-2)
    assert int(state.n_skipped) == 0


def test_loss_decreases_lora_only_trainables_move():
    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    model, state, step = build(lora=spec)
    step = jax.jit(step, donate_argnums=0)
    batch = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 16), 0, 128)
    frozen_kernel_before = np.asarray(
        state.params["layers"]["self_attn"]["q_proj"]["kernel"]
    ).copy()
    lora_b_before = np.asarray(
        state.params["layers"]["self_attn"]["q_proj"]["lora_b"]
    ).copy()
    first = None
    for i in range(20):
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
    # frozen base kernel unchanged; lora_b moved off zero
    np.testing.assert_array_equal(
        np.asarray(state.params["layers"]["self_attn"]["q_proj"]["kernel"]),
        frozen_kernel_before,
    )
    assert np.abs(np.asarray(state.params["layers"]["self_attn"]["q_proj"]["lora_b"])).max() > 0
    assert np.abs(lora_b_before).max() == 0


def test_bf16_base_storage_trains_and_stays_bf16():
    """base_dtype='bf16' stores ONLY the frozen LoRA-base kernels in bf16
    (trainables — LoRA factors, embeddings, norms, lm_head — keep the f32
    master) and the step still descends."""
    spec = LoraSpec(r=4, alpha=32, dropout=0.0, base_dtype="bf16")
    model, state, step = build(lora=spec)
    attn = state.params["layers"]["self_attn"]
    assert attn["q_proj"]["kernel"].dtype == jnp.bfloat16
    assert attn["q_proj"]["lora_a"].dtype == jnp.float32
    assert state.params["embed_tokens"]["embedding"].dtype == jnp.float32
    assert state.params["lm_head"]["kernel"].dtype == jnp.float32

    step = jax.jit(step, donate_argnums=0)
    batch = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 16), 0, 128)
    first = None
    for i in range(20):
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
    assert state.params["layers"]["self_attn"]["q_proj"]["kernel"].dtype == jnp.bfloat16


def test_nan_gate_skips_update_but_advances_step():
    model, state, step = build()
    step = jax.jit(step)
    # poison one param with NaN -> loss is NaN -> update must be skipped
    poisoned = state.replace(
        params={
            **state.params,
            "lm_head": {
                "kernel": state.params["lm_head"]["kernel"].at[0, 0].set(jnp.nan)
            },
        }
    )
    batch = jax.random.randint(jax.random.PRNGKey(1), (1, 2, 16), 0, 128)
    new_state, metrics = step(poisoned, batch, jax.random.PRNGKey(0))
    assert float(metrics["skipped"]) == 1.0
    assert int(new_state.step) == 1
    assert int(new_state.n_skipped) == 1
    # untouched (non-poisoned) params identical — no partial update
    np.testing.assert_array_equal(
        np.asarray(new_state.params["embed_tokens"]["embedding"]),
        np.asarray(poisoned.params["embed_tokens"]["embedding"]),
    )
    # optimizer state unchanged (schedule count rolled back too)
    for a, b in zip(
        jax.tree_util.tree_leaves(new_state.opt_state),
        jax.tree_util.tree_leaves(poisoned.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_logged_lr_tracks_applied_schedule_after_skip():
    """After a NaN skip the reported lr must match the rolled-back schedule
    count (number of applied updates), not state.step."""
    schedule = lambda s: 1e-2 * (s + 1)
    model = LlamaForCausalLM(TINY, lora=None, dtype=jnp.float32)
    from relora_tpu.models.params_util import init_params as ip

    params = ip(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=schedule)
    from relora_tpu.core.partition import partition

    opt_state = tx.init(partition(params, mask)[0])
    state = TrainState.create(params, opt_state)
    step = jax.jit(make_train_step(model, tx, mask, schedule=schedule))
    batch = jax.random.randint(jax.random.PRNGKey(1), (1, 2, 16), 0, 128)

    # poison params -> skipped step; then a clean step
    poisoned = state.replace(
        params={
            **state.params,
            "lm_head": {"kernel": state.params["lm_head"]["kernel"].at[0, 0].set(jnp.nan)},
        }
    )
    s1, m1 = step(poisoned, batch, jax.random.PRNGKey(0))
    assert float(m1["skipped"]) == 1.0
    # repair params, keep counters: next applied update uses schedule count 0
    repaired = s1.replace(
        params={
            **s1.params,
            "lm_head": {"kernel": jnp.nan_to_num(s1.params["lm_head"]["kernel"])},
        }
    )
    s2, m2 = step(repaired, batch, jax.random.PRNGKey(2))
    assert float(m2["skipped"]) == 0.0
    # step index was 1 but 0 updates applied before it -> lr = schedule(0)
    np.testing.assert_allclose(float(m2["lr"]), schedule(0), rtol=1e-6)


def test_eval_step_returns_weighted_sums():
    model, state, _ = build()
    eval_step = jax.jit(make_eval_step(model))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 128)
    out = eval_step(state.params, tokens)
    assert float(out["n_tokens"]) == 4 * 15
    assert np.isfinite(float(out["loss_sum"]))


@pytest.mark.usefixtures("devices")
def test_sharded_train_step_on_mesh():
    """FSDP×TP×DP sharded step on 8 virtual devices: params sharded by the
    logical rules, batch sharded on (data, fsdp), one step runs and the loss
    matches the unsharded step."""
    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32)
    sample = jnp.zeros((1, 8), jnp.int32)
    params = init_params(model, jax.random.PRNGKey(0), sample)
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: 1e-2)
    from relora_tpu.core.partition import partition

    trainable, _ = partition(params, mask)
    opt_state = tx.init(trainable)
    state = TrainState.create(params, opt_state)
    step_fn = make_train_step(model, tx, mask, schedule=lambda s: 1e-2)

    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    specs = logical_partition_specs(model, sample)
    shardings = param_shardings(mesh, specs)
    sharded_params = shard_params(params, shardings)
    sharded_state = TrainState.create(sharded_params, jax.jit(tx.init)(partition(sharded_params, mask)[0]))

    batch = jax.random.randint(jax.random.PRNGKey(1), (2, 8, 16), 0, 128)
    sharded_batch = jax.device_put(batch, batch_sharding(mesh))

    jitted = jax.jit(step_fn)
    new_sharded, m_sharded = jitted(sharded_state, sharded_batch, jax.random.PRNGKey(0))
    new_plain, m_plain = jax.jit(step_fn)(state, batch, jax.random.PRNGKey(0))

    assert np.isfinite(float(m_sharded["loss"]))
    assert float(m_sharded["loss"]) == pytest.approx(float(m_plain["loss"]), rel=1e-4)
    # param kernels really are distributed: embed dim sharded over fsdp
    k = new_sharded.params["layers"]["self_attn"]["q_proj"]["kernel"]
    assert not k.sharding.is_fully_replicated
    # and the updated sharded params match the unsharded update
    np.testing.assert_allclose(
        np.asarray(new_sharded.params["layers"]["mlp"]["gate_proj"]["lora_b"]),
        np.asarray(new_plain.params["layers"]["mlp"]["gate_proj"]["lora_b"]),
        atol=1e-5,
    )


def test_mesh_spec_validation():
    with pytest.raises(ValueError):
        MeshSpec(data=3, fsdp=3).resolve(8)
    assert MeshSpec(data=-1, fsdp=4).resolve(8) == (2, 4, 1, 1)


@pytest.mark.usefixtures("devices")
def test_sequence_parallel_train_step_ring_attention():
    """Full train step with context parallelism: sequence sharded over a
    4-way ring, loss matches the single-device step."""
    from relora_tpu.parallel.mesh import set_current_mesh

    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    set_current_mesh(mesh)
    try:
        model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32, attention_impl="ring")
        ref_model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32)
        sample = jnp.zeros((1, 8), jnp.int32)
        params = init_params(ref_model, jax.random.PRNGKey(0), sample)
        mask = trainable_param_mask(params)
        tx = build_optimizer(schedule=lambda s: 1e-2)
        from relora_tpu.core.partition import partition

        opt_state = tx.init(partition(params, mask)[0])

        sharded_params = shard_params(params, param_shardings(mesh, logical_partition_specs(ref_model, sample)))
        with mesh:
            sharded_state = TrainState.create(
                sharded_params, jax.jit(tx.init)(partition(sharded_params, mask)[0])
            )
        plain_state = TrainState.create(params, opt_state)

        batch = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 32), 0, 128)
        sharded_batch = jax.device_put(batch, batch_sharding(mesh, seq_sharded=True))

        step_ring = jax.jit(make_train_step(model, tx, mask, schedule=lambda s: 1e-2))
        step_ref = jax.jit(make_train_step(ref_model, tx, mask, schedule=lambda s: 1e-2))
        _, m_ring = step_ring(sharded_state, sharded_batch, jax.random.PRNGKey(2))
        _, m_ref = step_ref(plain_state, batch, jax.random.PRNGKey(2))
        assert float(m_ring["loss"]) == pytest.approx(float(m_ref["loss"]), rel=1e-4)
    finally:
        set_current_mesh(None)


@pytest.mark.usefixtures("devices")
def test_zigzag_layout_train_step_matches_plain():
    """End-to-end zigzag context parallelism: the permuted-layout train step
    (zigzag attention + permuted positions + pre-shifted labels) computes
    the same loss as the plain single-device step."""
    from relora_tpu.parallel.mesh import set_current_mesh

    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    set_current_mesh(mesh)
    try:
        zz_model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32, attention_impl="ring_zigzag")
        ref_model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32)
        sample = jnp.zeros((2, 8), jnp.int32)
        params = init_params(ref_model, jax.random.PRNGKey(0), sample)
        mask = trainable_param_mask(params)
        tx = build_optimizer(schedule=lambda s: 1e-2)
        from relora_tpu.core.partition import partition

        sharded_params = shard_params(
            params, param_shardings(mesh, logical_partition_specs(ref_model, sample))
        )
        with mesh:
            zz_state = TrainState.create(
                sharded_params, jax.jit(tx.init)(partition(sharded_params, mask)[0])
            )
        plain_state = TrainState.create(params, tx.init(partition(params, mask)[0]))

        batch = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 32), 0, 128)
        zz_batch = jax.device_put(batch, batch_sharding(mesh, seq_sharded=True))

        step_zz = jax.jit(make_train_step(zz_model, tx, mask, schedule=lambda s: 1e-2, zigzag_ring=4))
        step_ref = jax.jit(make_train_step(ref_model, tx, mask, schedule=lambda s: 1e-2))
        new_zz, m_zz = step_zz(zz_state, zz_batch, jax.random.PRNGKey(2))
        new_ref, m_ref = step_ref(plain_state, batch, jax.random.PRNGKey(2))
        # zigzag loss averages over S valid labels vs S-1 in the shifted
        # path (the permuted layout keeps a -100 sentinel for the final
        # token), so compare losses directly: same mean over the same
        # (token, target) pairs
        assert float(m_zz["loss"]) == pytest.approx(float(m_ref["loss"]), rel=1e-4)
        # and gradients moved the same trainables the same way
        np.testing.assert_allclose(
            np.asarray(new_zz.params["layers"]["mlp"]["gate_proj"]["lora_b"]),
            np.asarray(new_ref.params["layers"]["mlp"]["gate_proj"]["lora_b"]),
            atol=1e-5,
        )
    finally:
        set_current_mesh(None)


def test_chunked_loss_train_step_matches_dense():
    """loss_impl=chunked (streamed vocab CE from hidden states) gives the
    same loss and updates as the dense path."""
    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    model = LlamaForCausalLM(TINY, lora=spec, dtype=jnp.float32)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: 1e-2)
    from relora_tpu.core.partition import partition

    mk_state = lambda: TrainState.create(params, tx.init(partition(params, mask)[0]))
    batch = jax.random.randint(jax.random.PRNGKey(1), (1, 4, 16), 0, 128)

    dense = jax.jit(make_train_step(model, tx, mask, schedule=lambda s: 1e-2))
    chunked = jax.jit(
        make_train_step(model, tx, mask, schedule=lambda s: 1e-2,
                        loss_impl="chunked", vocab_chunk=48)  # 128 vocab, padded chunks
    )
    s_d, m_d = dense(mk_state(), batch, jax.random.PRNGKey(2))
    s_c, m_c = chunked(mk_state(), batch, jax.random.PRNGKey(2))
    assert float(m_c["loss"]) == pytest.approx(float(m_d["loss"]), rel=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_c.params["layers"]["mlp"]["gate_proj"]["lora_b"]),
        np.asarray(s_d.params["layers"]["mlp"]["gate_proj"]["lora_b"]),
        atol=1e-6,
    )
    # lm_head is trainable; the chunked path's gradient through the streamed
    # projection matches the dense path's
    np.testing.assert_allclose(
        np.asarray(s_c.params["lm_head"]["kernel"]),
        np.asarray(s_d.params["lm_head"]["kernel"]),
        atol=1e-6,
    )
