"""Headline benchmark: ReLoRA training throughput on one TPU chip.

Config mirrors BASELINE.md benchmark 3 scaled to a single chip: llama_1b,
LoRA r=128 (the production 1B recipe's rank), seq 1024, bf16 compute,
remat-over-scanned-layers, scan grad-accum train step.  Prints ONE JSON
line::

    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

``vs_baseline`` is measured MFU / 0.5 — the reference repo publishes no
throughput numbers (BASELINE.md), so the committed target is the north-star
"≥50% MFU" from BASELINE.json; 1.0 means that target is met on this chip.
(Note: the sandbox's remote-compile tunnel rejects programs above a size
threshold, which caps microbatch at 8 here; MFU counts only the 6N model
FLOPs, so remat recompute deflates it.)
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp

# Watchdog: if the TPU tunnel wedges (observed in this sandbox), emit a
# diagnostic line instead of hanging forever.  A daemon thread (not SIGALRM):
# the hang sits inside native device-init code where signal handlers never
# get a chance to run, but GIL-releasing native waits let threads proceed.
WATCHDOG_SECS = int(os.environ.get("BENCH_WATCHDOG_SECS", "900"))


def _watchdog():
    print(
        json.dumps(
            {
                "metric": "bench watchdog",
                "value": 0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0,
                "detail": {"error": f"no result within {WATCHDOG_SECS}s (TPU tunnel stalled?)"},
            }
        )
    )
    sys.stdout.flush()
    os._exit(2)

MODEL = "llama_1b"
MICRO_BATCH = 8
GRAD_ACCUM = 1
SEQ = 1024
REMAT = True
WARMUP_STEPS = 3
MEASURE_STEPS = 10

# bf16 peak of one TPU v5e (v5 lite) chip
PEAK_FLOPS = 197e12


def main() -> None:
    from relora_tpu.config.model import MODEL_ZOO
    from relora_tpu.core.optim import build_optimizer
    from relora_tpu.core.partition import partition
    from relora_tpu.core.relora import LoraSpec, trainable_param_mask
    from relora_tpu.models.llama import LlamaForCausalLM
    from relora_tpu.models.params_util import init_params
    from relora_tpu.train.state import TrainState
    from relora_tpu.train.step import make_train_step

    cfg = MODEL_ZOO[MODEL]
    spec = LoraSpec(r=128, alpha=32, dropout=0.1)
    model = LlamaForCausalLM(
        cfg, lora=spec, dtype=jnp.bfloat16, scan_layers=True, remat=REMAT
    )
    sample = jnp.zeros((1, 8), jnp.int32)
    params = init_params(model, jax.random.PRNGKey(0), sample)
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: 1e-3)
    opt_state = jax.jit(tx.init)(partition(params, mask)[0])
    state = TrainState.create(params, opt_state)
    step = jax.jit(make_train_step(model, tx, mask), donate_argnums=0)

    batch = jax.random.randint(
        jax.random.PRNGKey(1), (GRAD_ACCUM, MICRO_BATCH, SEQ), 0, cfg.vocab_size
    )
    rng = jax.random.PRNGKey(2)

    for i in range(WARMUP_STEPS):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))
    float(metrics["loss"])  # full sync (block_until_ready can return early
    # through the axon relay; a scalar pull cannot)

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, metrics = step(state, batch, jax.random.fold_in(rng, 100 + i))
    # the final loss depends on every preceding step's params, so this one
    # sync forces the whole chain to have executed
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_update = GRAD_ACCUM * MICRO_BATCH * SEQ
    tokens_per_sec = tokens_per_update * MEASURE_STEPS / dt

    # 6*N per token fwd+bwd on the dense (equivalent) params
    n_params = cfg.num_params(include_embeddings=False) + cfg.vocab_size * cfg.hidden_size
    flops_per_token = 6 * n_params
    mfu = tokens_per_sec * flops_per_token / PEAK_FLOPS

    print(
        json.dumps(
            {
                "metric": f"{MODEL} ReLoRA r=128 seq{SEQ} bf16 training throughput",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(mfu / 0.5, 4),
                "detail": {
                    "mfu": round(mfu, 4),
                    "step_time_s": round(dt / MEASURE_STEPS, 4),
                    "tokens_per_update": tokens_per_update,
                    "loss": final_loss,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    timer = threading.Timer(WATCHDOG_SECS, _watchdog)
    timer.daemon = True
    timer.start()
    main()
    timer.cancel()
