"""Index-construction benchmark: C++ builders vs the NumPy oracles.

Shows why the hot loops are native (the reference made the same call with
its runtime-compiled pybind11 helpers): sample-index packing walks every
document of every epoch, which is minutes of pure Python on billion-token
corpora and milliseconds in C++.

Usage::

    python tools/bench_data.py [--docs 200000] [--samples 200000]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--docs", type=int, default=200_000)
    p.add_argument("--samples", type=int, default=200_000)
    p.add_argument("--seq_length", type=int, default=2048)
    args = p.parse_args(argv)

    sys.path.insert(0, ".")
    from relora_tpu.data import native
    from relora_tpu.data.blendable import build_blending_indices_py
    from relora_tpu.data.native import (
        build_blending_indices_native,
        build_sample_idx_native,
    )
    from relora_tpu.data.sample_index import (
        build_doc_idx,
        build_sample_idx_py,
        num_epochs_needed,
    )

    # build/load the shared object outside the timed window (first use
    # compiles with g++)
    if native.load() is None:
        sys.exit("native helpers unavailable (no compiler?) — nothing to benchmark")

    rs = np.random.RandomState(0)
    sizes = rs.randint(64, 4096, size=args.docs).astype(np.int32)
    documents = np.arange(args.docs)
    epochs = num_epochs_needed(int(sizes.sum()), args.seq_length, args.samples)
    doc_idx = build_doc_idx(documents, epochs, np.random.RandomState(1))
    print(
        f"corpus: {args.docs:,} docs, {sizes.sum()/1e6:.1f}M tokens, "
        f"{epochs} epochs for {args.samples:,} samples of {args.seq_length}"
    )

    t0 = time.perf_counter()
    cpp = build_sample_idx_native(sizes, doc_idx, args.seq_length, args.samples)
    t_cpp = time.perf_counter() - t0
    t0 = time.perf_counter()
    py = build_sample_idx_py(sizes, doc_idx, args.seq_length, args.samples)
    t_py = time.perf_counter() - t0
    assert np.array_equal(np.asarray(cpp, np.int64), py)
    print(f"sample_idx: C++ {t_cpp*1000:.1f} ms vs NumPy {t_py*1000:.1f} ms "
          f"({t_py/max(t_cpp,1e-9):.0f}x) — identical outputs")

    weights = np.asarray([0.5, 0.3, 0.2])
    n = args.samples
    t0 = time.perf_counter()
    cpp_b = build_blending_indices_native(weights, n)
    t_cpp = time.perf_counter() - t0
    t0 = time.perf_counter()
    py_b = build_blending_indices_py(weights, n)
    t_py = time.perf_counter() - t0
    assert np.array_equal(cpp_b[0], py_b[0]) and np.array_equal(cpp_b[1], py_b[1])
    print(f"blending:   C++ {t_cpp*1000:.1f} ms vs NumPy {t_py*1000:.1f} ms "
          f"({t_py/max(t_cpp,1e-9):.0f}x) — identical outputs")


if __name__ == "__main__":
    main()
