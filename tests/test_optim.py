"""Tests for optimizer construction, resets, and pruning semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from relora_tpu.core.optim import (
    build_optimizer,
    clip_by_global_norm,
    global_norm,
    reset_optimizer_state,
    zeroed_fraction,
)
from relora_tpu.core.schedules import linear_with_warmup


def make_trainable_tree(rng=0):
    k = jax.random.PRNGKey(rng)
    ks = jax.random.split(k, 4)
    return {
        "layer": {
            "q_proj": {
                "lora_a": jax.random.normal(ks[0], (16, 4)),
                "lora_b": jax.random.normal(ks[1], (4, 24)),
            },
            "norm": {"scale": jnp.ones((16,))},
        },
        "embed": {"embedding": jax.random.normal(ks[2], (32, 16))},
    }


def run_steps(tx, params, n=3):
    state = tx.init(params)
    for i in range(n):
        grads = jax.tree_util.tree_map(
            lambda p: jax.random.normal(jax.random.PRNGKey(i), p.shape), params
        )
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params, state


def find_adam_state(state):
    if isinstance(state, optax.ScaleByAdamState):
        return state
    if isinstance(state, tuple):
        for s in state:
            found = find_adam_state(s)
            if found is not None:
                return found
    return None


def test_optimizer_updates_and_state_layout():
    params = make_trainable_tree()
    tx = build_optimizer(
        schedule=linear_with_warmup(1e-3, 10, 100), weight_decay=0.01
    )
    new_params, state = run_steps(tx, params)
    adam = find_adam_state(state)
    assert adam is not None
    # state mirrors the param tree: moments exist for every trainable leaf
    assert jax.tree_util.tree_structure(adam.mu) == jax.tree_util.tree_structure(params)
    # params actually moved
    assert float(jnp.abs(new_params["embed"]["embedding"] - params["embed"]["embedding"]).max()) > 0


@pytest.mark.parametrize("mode", ["zero", "random", "magnitude"])
def test_reset_prunes_only_lora_moments(mode):
    params = make_trainable_tree()
    tx = build_optimizer(schedule=lambda s: 1e-3)
    _, state = run_steps(tx, params)
    before = find_adam_state(state)

    ratio = {"zero": 1.0, "random": 0.9, "magnitude": 0.8}[mode]
    new_state = reset_optimizer_state(
        state, mode=mode, ratio=ratio, rng=jax.random.PRNGKey(0)
    )
    after = find_adam_state(new_state)

    # non-LoRA moments untouched
    np.testing.assert_array_equal(
        np.asarray(after.mu["embed"]["embedding"]), np.asarray(before.mu["embed"]["embedding"])
    )
    np.testing.assert_array_equal(
        np.asarray(after.nu["layer"]["norm"]["scale"]), np.asarray(before.nu["layer"]["norm"]["scale"])
    )

    # LoRA moments pruned
    mu_a = np.asarray(after.mu["layer"]["q_proj"]["lora_a"])
    z = (mu_a == 0).mean()
    if mode == "zero":
        assert z == 1.0
    elif mode == "random":
        assert 0.75 <= z <= 1.0  # ~90% zeroed
    else:  # magnitude: quantile(0.8) keeps ~20% largest
        assert 0.7 <= z <= 0.9

    # Adam step count preserved (reference never resets it)
    assert int(after.count) == int(before.count)


def test_magnitude_pruning_keeps_largest():
    t = jnp.asarray([[0.1, -5.0, 0.2, 4.0, -0.05, 3.0, 0.01, -2.0, 0.3, 1.0]])
    state = optax.ScaleByAdamState(
        count=jnp.asarray(1),
        mu={"m": {"lora_a": t}},
        nu={"m": {"lora_a": jnp.abs(t)}},
    )
    new = reset_optimizer_state((state,), mode="magnitude", ratio=0.7)
    pruned = np.asarray(new[0].mu["m"]["lora_a"])[0]
    # 70th percentile of |t| ~ 2.3 → keeps 5.0, 4.0, 3.0 (strictly greater)
    kept = set(np.nonzero(pruned)[0].tolist())
    assert kept == {1, 3, 5}


def test_zeroed_fraction():
    params = make_trainable_tree()
    tx = build_optimizer(schedule=lambda s: 1e-3)
    _, state = run_steps(tx, params)
    assert float(zeroed_fraction(state)) < 0.1
    state2 = reset_optimizer_state(state, mode="zero", ratio=1.0)
    frac = float(zeroed_fraction(state2))
    # lora moments are a large share of this tiny tree
    n_lora = 16 * 4 + 4 * 24
    n_total = n_lora + 16 + 32 * 16
    assert frac == pytest.approx(n_lora / n_total, abs=0.05)


def test_reset_is_jittable_structure_preserving():
    params = make_trainable_tree()
    tx = build_optimizer(schedule=lambda s: 1e-3)
    _, state = run_steps(tx, params)
    jitted = jax.jit(
        lambda s, k: reset_optimizer_state(s, mode="random", ratio=0.9, rng=k)
    )
    out = jitted(state, jax.random.PRNGKey(1))
    assert jax.tree_util.tree_structure(out) == jax.tree_util.tree_structure(state)


def test_clip_by_global_norm():
    tree = {"a": jnp.ones((10,)) * 3.0, "b": jnp.ones((5,)) * 4.0}
    norm = float(global_norm(tree))
    assert norm == pytest.approx(np.sqrt(10 * 9 + 5 * 16))
    clipped, pre = clip_by_global_norm(tree, 1.0)
    assert float(pre) == pytest.approx(norm)
    assert float(global_norm(clipped)) == pytest.approx(1.0, rel=1e-4)
    # no-op when under the limit
    small = {"a": jnp.asarray([0.1])}
    out, _ = clip_by_global_norm(small, 1.0)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.1], rtol=1e-6)


def test_reset_recurses_into_wrapper_states():
    """Regression: MultiSteps/multi_transform wrappers must not hide the Adam
    state from the ReLoRA reset."""
    params = make_trainable_tree()
    inner = build_optimizer(schedule=lambda s: 1e-3)
    tx = optax.MultiSteps(inner, every_k_schedule=2)
    state = tx.init(params)
    for i in range(4):
        grads = jax.tree_util.tree_map(
            lambda p: jax.random.normal(jax.random.PRNGKey(i), p.shape), params
        )
        _, state = tx.update(grads, state, params)
    new_state = reset_optimizer_state(state, mode="zero", ratio=1.0)
    adam = find_adam_state(jax.tree_util.tree_leaves(new_state, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState))[0] if False else new_state.inner_opt_state)
    assert adam is not None
    assert float(jnp.abs(adam.mu["layer"]["q_proj"]["lora_a"]).max()) == 0.0
    assert float(jnp.abs(adam.mu["embed"]["embedding"]).max()) > 0.0


def test_path_hash_deterministic():
    from relora_tpu.core.optim import _path_hash

    assert _path_hash(("layer", "lora_a")) == 2415058558 % (2**32) or isinstance(
        _path_hash(("layer", "lora_a")), int
    )
    # stable across calls and independent of PYTHONHASHSEED (crc32-based)
    import zlib

    assert _path_hash(("a", "b")) == zlib.crc32(b"a/b")


@pytest.mark.usefixtures("devices")
def test_magnitude_reset_on_sharded_state_matches_unsharded():
    """SURVEY 'hard part': torch.quantile on a full tensor must become a
    correct global quantile when the optimizer state is sharded.  jnp.quantile
    under GSPMD computes globally — verify sharded == unsharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from relora_tpu.parallel.mesh import MeshSpec, make_mesh

    params = make_trainable_tree()
    tx = build_optimizer(schedule=lambda s: 1e-3)
    _, state = run_steps(tx, params)

    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    shard = NamedSharding(mesh, P("fsdp"))

    def shard_leaf(x):
        if x.ndim >= 1 and x.shape[0] % 8 == 0:
            return jax.device_put(x, shard)
        return jax.device_put(x, NamedSharding(mesh, P()))

    sharded_state = jax.tree_util.tree_map(shard_leaf, state)
    with mesh:
        out_sharded = jax.jit(
            lambda s: reset_optimizer_state(s, mode="magnitude", ratio=0.8)
        )(sharded_state)
    out_plain = reset_optimizer_state(state, mode="magnitude", ratio=0.8)
    a = find_adam_state(out_sharded)
    b = find_adam_state(out_plain)
    np.testing.assert_array_equal(
        np.asarray(a.mu["layer"]["q_proj"]["lora_a"]),
        np.asarray(b.mu["layer"]["q_proj"]["lora_a"]),
    )


@pytest.mark.usefixtures("devices")
def test_init_opt_state_sharded_pins_moment_shardings():
    """Adam moments must be born with the trainables' shardings, not
    replicated-then-resharded (a transient mesh-size× HBM spike at init —
    the thing init_opt_state_sharded exists to prevent)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from relora_tpu.core.optim import init_opt_state_sharded
    from relora_tpu.parallel.mesh import MeshSpec, make_mesh

    params = make_trainable_tree()
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    shard = NamedSharding(mesh, P("fsdp"))
    replicated = NamedSharding(mesh, P())

    def shard_leaf(x):
        if x.ndim >= 1 and x.shape[0] % 8 == 0:
            return jax.device_put(x, shard)
        return jax.device_put(x, replicated)

    sharded_params = jax.tree_util.tree_map(shard_leaf, params)
    tx = build_optimizer(schedule=lambda s: 1e-3)
    with mesh:
        state = init_opt_state_sharded(tx, sharded_params, mesh)

    adam = find_adam_state(state)
    for moments in (adam.mu, adam.nu):
        flat_p = jax.tree_util.tree_leaves_with_path(sharded_params)
        flat_m = jax.tree_util.tree_leaves_with_path(moments)
        assert [k for k, _ in flat_p] == [k for k, _ in flat_m]
        for (_, p), (path, m) in zip(flat_p, flat_m):
            assert m.sharding == p.sharding, path
    # scalar counters stay replicated
    assert adam.count.sharding == replicated
    # and the values are what tx.init would produce (zeros)
    assert float(jnp.sum(jnp.abs(adam.mu["embed"]["embedding"]))) == 0.0


@pytest.mark.usefixtures("devices")
def test_init_opt_state_sharded_mixed_tree_uses_plan():
    """Warm starts graft uncommitted default-device leaves into a
    mesh-sharded tree; with a placement plan the moments must still be born
    on their planned shardings (not fall back to XLA-placed init)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from relora_tpu.core.optim import init_opt_state_sharded
    from relora_tpu.parallel.mesh import MeshSpec, make_mesh

    params = make_trainable_tree()
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    shard = NamedSharding(mesh, P("fsdp"))
    replicated = NamedSharding(mesh, P())

    def plan_for(x):
        return shard if (x.ndim >= 1 and x.shape[0] % 8 == 0) else replicated

    plan = jax.tree_util.tree_map(plan_for, params)
    sharded = jax.tree_util.tree_map(jax.device_put, params, plan)
    # graft: replace the embedding with a fresh uncommitted default-device
    # array (what hf_compat.graft_base_weights produces on warm start)
    sharded["embed"]["embedding"] = jnp.asarray(
        np.asarray(params["embed"]["embedding"])
    )
    tx = build_optimizer(schedule=lambda s: 1e-3)
    with mesh:
        state = init_opt_state_sharded(tx, sharded, mesh, shardings=plan)

    adam = find_adam_state(state)
    for moments in (adam.mu, adam.nu):
        for (path, m), (_, s) in zip(
            jax.tree_util.tree_leaves_with_path(moments),
            jax.tree_util.tree_leaves_with_path(plan),
        ):
            assert m.sharding == s, path
