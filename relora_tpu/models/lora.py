"""LoRA-factored Dense layer: the TPU-native ReLoRaLinear.

The reference swaps ``nn.Linear`` modules for ``ReLoRaLinear`` objects after
model construction (relora.py:94-134) and tracks trainability with
``requires_grad`` flags (relora.py:259-261).  Here LoRA is a property of the
layer itself: when a ``LoraSpec`` is provided, the module owns extra pytree
leaves ``lora_a`` / ``lora_b`` (and optionally ``lora_s``) next to its frozen
``kernel``, and trainability is a *mask over the param tree*
(relora_tpu.core.relora) — no module surgery, no flags.

Forward (parity: relora.py:309-323)::

    y = x @ W  (+ bias)  +  ((dropout(x) @ A) @ B) * scale

Init: A ~ kaiming-uniform, B = 0 — so the wrapped model equals the base model
at init (B=0 ⇒ the LoRA branch contributes nothing), which is the reference's
own init-equivalence invariant (relora.py:120-124).  Deliberate deviation:
the reference *additionally* zeroes A when keep_original_weights=True, which
puts A/B at an exact saddle (both gradients identically zero) until the first
merge re-draws A; we keep A at kaiming so learning starts immediately, while
preserving the same init-equivalence guarantee.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from relora_tpu.core.relora import LoraSpec, kaiming_uniform

import logging

# (module name, width) pairs already warned about the nf4->int8 fallback —
# the warning should fire once per projection, not on every trace
_NF4_FALLBACK_WARNED: set = set()


def _env_pallas_quant() -> bool:
    """RELORA_TPU_PALLAS_QUANT=1 opt-in, read at module *construction* —
    never inside the traced ``__call__`` (the retrace footgun RTL1xx
    polices: an env flip between traces would silently split the cache)."""
    return os.environ.get("RELORA_TPU_PALLAS_QUANT") == "1"


class LoRALinear(nn.Module):
    """Dense layer with optional LoRA factors as first-class pytree leaves.

    ``kernel_axes`` are *logical* partitioning names resolved to mesh axes by
    relora_tpu.parallel's rules; the rank axis is named "lora" (replicated by
    default, shardable for very large models).
    """

    features: int
    use_bias: bool = False
    lora: Optional[LoraSpec] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: nn.initializers.Initializer = nn.initializers.normal(stddev=0.02)
    kernel_axes: Tuple[Optional[str], Optional[str]] = (None, None)
    quantize: Optional[str] = None  # None | "int8" (frozen base only)
    # Pallas dequant-matmul opt-in for the int8 base.  None = consult the
    # RELORA_TPU_PALLAS_QUANT env var once, here at construction.
    pallas_quant: Optional[bool] = None

    def __post_init__(self):
        if self.pallas_quant is None:
            object.__setattr__(self, "pallas_quant", _env_pallas_quant())
        super().__post_init__()

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        deterministic: bool = True,
        adapter_idx: Optional[jax.Array] = None,
    ) -> jax.Array:
        in_features = x.shape[-1]
        if self.lora is not None and self.lora.num_slots > 0:
            # multi-tenant serving layout: factors stacked (num_slots, ...),
            # each activation row routed to its slot by adapter_idx
            return self._grouped(x, in_features, adapter_idx)
        if self.lora is not None and self.lora.lora_only:
            # pure-LoRA layer: no base weight, no bias (relora.py:209-211)
            return self._lora_branch(x, in_features, deterministic)
        # quantization follows the LoRA spec (parity: quantize lives in
        # ReLoRaConfig, relora.py:18-28) unless set explicitly
        quantize = self.quantize or (self.lora.quantize if self.lora else None)
        if quantize == "nf4" and in_features % 2:
            # nf4 packs two codes per byte along in_features; an odd width
            # (e.g. llama_1b's 5461-wide down_proj) can't pack, so this
            # projection falls back to int8 — the rest of the model stays
            # nf4, and the per-module merge dispatches on leaf names so a
            # mixed base merges correctly (bnb instead pads the flattened
            # tensor, reference relora.py:222-238)
            quantize = "int8"
            key = (self.name, in_features)
            if key not in _NF4_FALLBACK_WARNED:
                # once per module/width at trace time: the user asked for
                # nf4 but this projection stores int8 (2x the bytes) —
                # memory/accuracy comparisons against pure-nf4 expectations
                # would otherwise misattribute the difference
                _NF4_FALLBACK_WARNED.add(key)
                logging.getLogger(__name__).warning(
                    "nf4 requested but in_features=%d is odd for module %r; "
                    "storing this base as int8 (plan_memory accounts for it)",
                    in_features, self.name,
                )
        # Fused/dispatched composite: spec.fused routes the whole
        # y = x@W + ((x@A)@B)*scale through ops/lora_dispatch instead of the
        # three-matmul path below.  Dropout makes the branch input differ
        # from the base input and nf4 has no fused kernel — both keep the
        # historical path (the fallback matrix in docs/kernels.md).
        dropout_active = (
            self.lora is not None and self.lora.dropout > 0.0 and not deterministic
        )
        if (
            self.lora is not None
            and self.lora.fused in (True, "auto")
            and quantize in (None, "int8")
            and not dropout_active
        ):
            return self._dispatched(x, in_features, quantize)
        if quantize == "int8":
            kernel_q, kernel_scale = self._int8_params(in_features)
            y = self._int8_matmul(x, kernel_q, kernel_scale)
        elif quantize == "nf4":
            y = self._nf4_matmul(x, in_features)
        elif quantize is not None:
            raise ValueError(f"Unknown quantize mode {quantize!r}")
        else:
            kernel = self._dense_kernel(in_features)
            y = jnp.matmul(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            y = y + self._bias_param().astype(self.dtype)

        if self.lora is not None:
            y = y + self._lora_branch(x, in_features, deterministic)
        return y

    # -- param definitions (shared by the historical and dispatched paths;
    # flax params are name-keyed, so both paths see identical init values) --

    def _dense_kernel(self, in_features: int) -> jax.Array:
        # frozen-base storage dtype: spec.base_dtype == "bf16" drops the
        # f32 master for the base kernel (it takes no per-step optimizer
        # updates; merges cast back to storage dtype in core/relora.py).
        # Only applies when the kernel IS a frozen LoRA base — a plain
        # Dense (no LoRA spec) keeps the f32 master.
        base_dtype = (
            jnp.bfloat16
            if (self.lora is not None and self.lora.base_dtype == "bf16")
            else self.param_dtype
        )
        return self.param(
            "kernel",
            nn.with_logical_partitioning(self.kernel_init, self.kernel_axes),
            (in_features, self.features),
            base_dtype,
        )

    def _int8_params(self, in_features: int) -> Tuple[jax.Array, jax.Array]:
        # Fresh init is W=0 (codes zero, scales one): a quantized base is
        # only meaningful warm-started from real weights — exactly how the
        # reference uses bitsandbytes (it quantizes the wrapped module's
        # existing weight_data, relora.py:222-238).  Use
        # hf_compat.graft_base_weights, which quantizes f32 sources on
        # the fly.
        def q_init(key, shape, dtype):
            return jnp.zeros(shape, dtype)

        def s_init(key, shape, dtype):
            return jnp.ones(shape, dtype)

        kernel_q = self.param(
            "kernel_q",
            nn.with_logical_partitioning(q_init, self.kernel_axes),
            (in_features, self.features),
            jnp.int8,
        )
        kernel_scale = self.param(
            "kernel_scale",
            nn.with_logical_partitioning(s_init, (None, self.kernel_axes[1])),
            (1, self.features),
            jnp.float32,
        )
        return kernel_q, kernel_scale

    def _bias_param(self) -> jax.Array:
        return self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), (self.kernel_axes[1],)),
            (self.features,),
            self.param_dtype,
        )

    def _dispatched(self, x: jax.Array, in_features: int, quantize: Optional[str]) -> jax.Array:
        """The y = x@W + ((x@A)@B)*scale composite via ops/lora_dispatch.

        ``fused=True`` pins the fused Pallas kernel (untileable shapes fall
        back to the ordered reference inside the dispatcher); ``"auto"``
        lets the roofline cost model pick per shape.  The frozen base gets
        ``stop_gradient`` so every arm agrees its cotangent is zero — the
        optimizer mask already never applies base updates, this just keeps
        grads arm-independent.
        """
        from relora_tpu.ops.lora_dispatch import lora_matmul

        if quantize == "int8":
            kernel_q, kernel_scale = self._int8_params(in_features)
            base = (kernel_q, kernel_scale)
        else:
            base = jax.lax.stop_gradient(
                self._dense_kernel(in_features).astype(self.dtype)
            )
        lora_a, lora_b, scale = self._lora_factors(in_features)
        y = lora_matmul(
            x.astype(self.dtype),
            base,
            lora_a.astype(self.dtype),
            lora_b.astype(self.dtype),
            scale,
            arm="fused" if self.lora.fused is True else "auto",
            dtype=self.dtype,
            weights_static=self.lora.weights_static,
        )
        if self.use_bias:
            y = y + self._bias_param().astype(self.dtype)
        return y

    def _grouped(
        self, x: jax.Array, in_features: int, adapter_idx: Optional[jax.Array]
    ) -> jax.Array:
        """Multi-tenant composite: stacked (num_slots, ...) factor leaves and
        the per-row slot map through ops/lora_dispatch.lora_matmul_grouped.

        Every slot zero-inits (lora_b = 0 ⇒ identity branch), so slot 0 is
        the base-model adapter by construction and unloaded slots are inert;
        serve/adapters.py overwrites slots in place as tenants load/evict —
        shapes are static, swaps are pure data movement.  ``adapter_idx`` may
        be per-row (M,) or per-batch (B,) (repeated across the row dim);
        ``None`` routes everything to slot 0.
        """
        from relora_tpu.ops.lora_dispatch import lora_matmul_grouped

        spec = self.lora
        base = jax.lax.stop_gradient(
            self._dense_kernel(in_features).astype(self.dtype)
        )
        a_stack = self.param(
            "lora_a",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, self.kernel_axes[0], "lora")
            ),
            (spec.num_slots, in_features, spec.r),
            self.param_dtype,
        )
        b_stack = self.param(
            "lora_b",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, "lora", self.kernel_axes[1])
            ),
            (spec.num_slots, spec.r, self.features),
            self.param_dtype,
        )
        # per-slot scale (each adapter's sidecar may carry its own alpha)
        s_stack = self.param(
            "lora_s",
            lambda key, shape, dtype: jnp.full(shape, spec.scale, dtype),
            (spec.num_slots,),
            jnp.float32,
        )
        rows = 1
        for d in x.shape[:-1]:
            rows *= d
        if adapter_idx is None:
            idx = jnp.zeros((rows,), jnp.int32)
        else:
            idx = adapter_idx.reshape(-1).astype(jnp.int32)
            if idx.shape[0] != rows:
                idx = jnp.repeat(idx, rows // idx.shape[0])
        y = lora_matmul_grouped(
            x.astype(self.dtype),
            base,
            a_stack.astype(self.dtype),
            b_stack.astype(self.dtype),
            s_stack,
            idx,
            arm="auto",
            dtype=self.dtype,
        )
        if self.use_bias:
            y = y + self._bias_param().astype(self.dtype)
        return y

    def _int8_matmul(self, x, kernel_q, kernel_scale) -> jax.Array:
        """x @ int8 base.  Default: dequantize then matmul (XLA fuses).
        ``pallas_quant`` (RELORA_TPU_PALLAS_QUANT=1, read at construction)
        opts into the custom pallas kernel that keeps the weight int8 into
        VMEM (ops/pallas_quant_matmul) when the shapes tile; falls back
        otherwise."""
        if self.pallas_quant:
            from relora_tpu.ops.lora_dispatch import plan_blocks
            from relora_tpu.ops.pallas_quant_matmul import dequant_matmul

            M = 1
            for d in x.shape[:-1]:
                M *= d
            planned = plan_blocks(M, self.features)
            if planned:
                bm, bn = planned
                lead = x.shape[:-1]
                out = dequant_matmul(
                    x.reshape(M, x.shape[-1]).astype(self.dtype),
                    kernel_q,
                    kernel_scale,
                    block_m=bm,
                    block_n=bn,
                    interpret=jax.default_backend() == "cpu",
                    out_dtype=self.dtype,
                )
                return out.reshape(*lead, self.features)
        from relora_tpu.ops.quant import dequantize_int8

        kernel = dequantize_int8(kernel_q, kernel_scale, self.dtype)
        return jnp.matmul(x.astype(self.dtype), kernel)

    def _nf4_matmul(self, x: jax.Array, in_features: int) -> jax.Array:
        """x @ nf4 base (~0.53 bytes/element in HBM; see ops/quant.py).

        Like int8, a fresh init is W=0 (all codes point at codebook entry 7
        == 0.0) — only meaningful warm-started via graft_base_weights, which
        nf4-quantizes f32 sources on the fly.  Double-quant is the LoraSpec's
        ``use_double_quant`` (it sets the bscale_q dtype at init)."""
        from relora_tpu.ops.quant import dequantize_nf4, nf4_block_for

        block = nf4_block_for(in_features)
        dq = self.lora.use_double_quant if self.lora else True
        leaves = {
            "codes": self.param(
                "kernel_codes",
                nn.with_logical_partitioning(
                    # codebook entry 7 is exactly 0.0 -> W=0 at fresh init
                    lambda key, shape, dtype: jnp.full(shape, 0x77, dtype),
                    self.kernel_axes,
                ),
                (in_features // 2, self.features),
                jnp.uint8,
            ),
            "bscale_q": self.param(
                "kernel_bscale_q",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init() if dq else nn.initializers.ones_init(),
                    (None, self.kernel_axes[1]),
                ),
                (in_features // block, self.features),
                jnp.int8 if dq else jnp.float32,
            ),
            "bscale_scale": self.param(
                "kernel_bscale_scale",
                nn.with_logical_partitioning(
                    nn.initializers.ones_init(), (None, self.kernel_axes[1])
                ),
                (1, self.features),
                jnp.float32,
            ),
            "bscale_offset": self.param(
                "kernel_bscale_offset",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), (None, self.kernel_axes[1])
                ),
                (1, self.features),
                jnp.float32,
            ),
        }
        kernel = dequantize_nf4(leaves, self.dtype)
        return jnp.matmul(x.astype(self.dtype), kernel)

    def _lora_factors(self, in_features: int):
        """Define the LoRA leaves; returns (lora_a, lora_b, scale) where
        scale is either the static spec.scale float or the traced
        trainable-scaling ``tanh(lora_s)`` (parity: relora.py:263-267)."""
        spec = self.lora
        lora_a = self.param(
            "lora_a",
            nn.with_logical_partitioning(
                lambda key, shape, dtype: kaiming_uniform(key, shape, dtype),
                (self.kernel_axes[0], "lora"),
            ),
            (in_features, spec.r),
            self.param_dtype,
        )
        lora_b = self.param(
            "lora_b",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("lora", self.kernel_axes[1])
            ),
            (spec.r, self.features),
            self.param_dtype,
        )
        if spec.trainable_scaling:
            lora_s = self.param(
                "lora_s", nn.initializers.ones_init(), (1,), self.param_dtype
            )
            # parity: trainable scaling passes through tanh (relora.py:263-267)
            scale = jnp.tanh(lora_s.astype(self.dtype))
        else:
            scale = spec.scale
        return lora_a, lora_b, scale

    def _lora_branch(self, x: jax.Array, in_features: int, deterministic: bool) -> jax.Array:
        """((dropout(x) @ A) @ B) * scale (parity: relora.py:309-323)."""
        spec = self.lora
        lora_a, lora_b, scale = self._lora_factors(in_features)
        h = x
        if spec.dropout > 0.0 and not deterministic:
            h = nn.Dropout(rate=spec.dropout, deterministic=False)(h)
        z = jnp.matmul(h.astype(self.dtype), lora_a.astype(self.dtype))
        z = jnp.matmul(z, lora_b.astype(self.dtype))
        return z * scale
