"""RTL6xx — concurrency discipline across the serving tier's thread roots.

The serving plane mixes three execution domains: asyncio handlers on the
event loop, dedicated worker threads (`threading.Thread(target=...)` — the
model loop, watchdog, supervisor monitor, autoscaler, checkpoint watcher),
and executor jobs (`loop.run_in_executor`).  The rules here are driven by
the module call graph (:class:`~relora_tpu.analysis.core.ModuleIndex`):
thread entry points are inferred from `Thread(target=...)` /
`run_in_executor` / `signal` registrations plus `async def` handlers, and
every method is attributed to the *root group* that reaches it — spawned
roots each form their own group, while async handlers, signal handlers and
otherwise-unclaimed public methods form the ambient "main" group (external
callers run them on the main/event-loop thread).

- RTL601: instance attribute rebound from two different root groups with no
  lock held in common across the write sites (lock-set inference over
  ``with self._lock:`` scopes).  Rebinding only — ``.append``/subscript
  mutation is out of scope, and ``__init__`` writes are exempt (happen
  before any thread is spawned).
- RTL602: blocking call inside an ``async def`` body — ``time.sleep``,
  sync-primitive ``.wait()``/``.get()``/``.put()`` without a timeout,
  socket/urllib/subprocess, or a jitted engine/scheduler step.  Blessed:
  ``await asyncio.sleep`` and ``run_in_executor(None, fn)`` (the callable is
  passed, not called).
- RTL603: asyncio object (``asyncio.Event``/``asyncio.Queue`` attribute)
  mutated from code reachable from a thread/executor/signal root.  Blessed:
  ``loop.call_soon_threadsafe(evt.set)`` — again passed, not called.
- RTL604: lock-acquisition-order cycle in a class's static acquire graph
  (nested ``with`` plus one call level).  The `_scale_lock`-vs-drain shape:
  two methods taking the same two locks in opposite orders deadlock under
  concurrency even though each is individually correct.
- RTL605: ``Thread(target=...)``/``run_in_executor`` pointed at an
  ``async def`` — the call returns an un-awaited coroutine and the "thread"
  silently does nothing.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from relora_tpu.analysis.core import (
    LOCK_FACTORIES,
    THREAD_FACTORIES,
    FileContext,
    Finding,
    ModuleIndex,
    catalog,
    checker,
    dotted_name,
    get_kwarg,
    get_module_index,
    target_path,
)

catalog(
    RTL601="attribute written from two thread roots with no common lock (data race)",
    RTL602="blocking call inside an async def body (stalls the event loop)",
    RTL603="cross-thread asyncio mutation not routed through call_soon_threadsafe",
    RTL604="lock acquisition order cycle (static deadlock shape)",
    RTL605="Thread/executor target is an async def (coroutine is never awaited)",
)

#: dotted calls that block the calling thread outright
BLOCKING_CALLS = frozenset(
    {
        "time.sleep",
        "socket.create_connection",
        "socket.getaddrinfo",
        "urllib.request.urlopen",
        "subprocess.run",
        "subprocess.call",
        "subprocess.check_call",
        "subprocess.check_output",
        "requests.get",
        "requests.post",
        "requests.request",
    }
)

#: factories whose instances have blocking .get/.put/.wait/.join/.acquire
SYNC_PRIMITIVE_FACTORIES = frozenset(
    {
        "queue.Queue",
        "queue.SimpleQueue",
        "queue.LifoQueue",
        "queue.PriorityQueue",
        "threading.Event",
        "threading.Thread",
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "threading.Semaphore",
    }
)
BLOCKING_METHODS = frozenset({"get", "put", "wait", "join", "acquire"})

ASYNCIO_FACTORIES = frozenset({"asyncio.Event", "asyncio.Queue", "asyncio.Condition"})
ASYNCIO_MUTATORS = frozenset({"set", "clear", "put", "put_nowait"})

#: method names that dispatch into jitted device code on the serving engine
ENGINE_BLOCKING_METHODS = frozenset(
    {"step", "prefill", "decode", "insert", "decode_paged", "prefill_chunk"}
)
ENGINE_RECEIVER_HINTS = ("engine", "sched")


def _lock_attrs(mi: ModuleIndex, cls: str) -> FrozenSet[str]:
    return frozenset(
        attr
        for attr, fac in mi.attr_types.get(cls, {}).items()
        if fac in LOCK_FACTORIES
    )


def _class_methods(mi: ModuleIndex, cls: str) -> Set[str]:
    return {qn for qn, fi in mi.functions.items() if fi.owner_class == cls}


def _root_groups(mi: ModuleIndex, cls: str) -> Dict[str, Set[str]]:
    """Root-group id -> methods of *cls* that group's thread can execute.
    Spawned roots (thread/executor) each get their own group; async
    handlers, signal handlers, and public methods not claimed by a spawned
    root form the ambient "main" group."""
    methods = _class_methods(mi, cls)
    groups: Dict[str, Set[str]] = {}
    spawned_reach: Set[str] = set()
    for qn, kind in sorted(mi.thread_roots.items()):
        if qn in methods and kind in ("thread", "executor"):
            reach = mi.reachable([qn]) & methods
            groups[f"{kind}:{qn}"] = reach
            spawned_reach |= reach
    main_entries = {
        qn
        for qn in methods
        if (
            not qn.rsplit(".", 1)[-1].startswith("_")
            or mi.thread_roots.get(qn) in ("async", "signal")
        )
        and qn not in spawned_reach
    }
    main = mi.reachable(main_entries) & methods
    if main:
        groups["main"] = main
    return groups


class _MethodFacts(ast.NodeVisitor):
    """Per-method facts: self-attribute writes with held lock sets, lock
    acquire nesting edges, and locks acquired at any depth.  Does not
    descend into nested function/class definitions (those are separate
    entries in the module index)."""

    def __init__(self, lock_attrs: FrozenSet[str]) -> None:
        self.lock_attrs = lock_attrs
        self.held: List[str] = []
        # attr -> list of (frozenset(held locks), anchor node)
        self.writes: Dict[str, List[Tuple[FrozenSet[str], ast.AST]]] = {}
        # (outer lock, inner lock, anchor node) for nested acquires
        self.acquire_edges: List[Tuple[str, str, ast.AST]] = []
        self.acquired: Set[str] = set()
        # (resolved dotted callee, frozenset(held locks)) for call edges
        self.calls_holding: List[Tuple[str, FrozenSet[str]]] = []
        self._root: Optional[ast.AST] = None

    def run(self, func_node: ast.AST) -> "_MethodFacts":
        self._root = func_node
        for stmt in getattr(func_node, "body", []):
            self.visit(stmt)
        return self

    def _skip(self, node: ast.AST) -> None:  # nested defs are separate scopes
        return

    visit_FunctionDef = _skip
    visit_AsyncFunctionDef = _skip
    visit_ClassDef = _skip

    def _with_locks(self, node) -> List[str]:
        locks = []
        for item in node.items:
            path = target_path(item.context_expr)
            if path.startswith("self.") and path.split(".", 1)[1] in self.lock_attrs:
                locks.append(path.split(".", 1)[1])
        return locks

    def _visit_with(self, node) -> None:
        locks = self._with_locks(node)
        for lock in locks:
            for outer in self.held:
                if outer != lock:
                    self.acquire_edges.append((outer, lock, node))
            self.acquired.add(lock)
            self.held.append(lock)
        self.generic_visit(node)
        for lock in locks:
            self.held.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def _record_write(self, target: ast.AST, anchor: ast.AST) -> None:
        path = target_path(target)
        if path.startswith("self.") and path.count(".") == 1:
            attr = path.split(".", 1)[1]
            self.writes.setdefault(attr, []).append((frozenset(self.held), anchor))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record_write(tgt, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func)
        if dotted:
            self.calls_holding.append((dotted, frozenset(self.held)))
        self.generic_visit(node)


def _method_facts(
    mi: ModuleIndex, cls: str
) -> Dict[str, _MethodFacts]:
    locks = _lock_attrs(mi, cls)
    facts = {}
    for qn in _class_methods(mi, cls):
        facts[qn] = _MethodFacts(locks).run(mi.functions[qn].node)
    return facts


def _check_shared_writes(
    ctx: FileContext, mi: ModuleIndex, cls: str, facts: Dict[str, _MethodFacts]
) -> List[Finding]:
    groups = _root_groups(mi, cls)
    if len(groups) < 2:
        return []
    findings: List[Finding] = []
    # attr -> group -> write sites (init-time writes exempt: they happen
    # before any thread exists)
    per_attr: Dict[str, Dict[str, List[Tuple[FrozenSet[str], ast.AST]]]] = {}
    for group, methods in groups.items():
        for qn in methods:
            if qn.rsplit(".", 1)[-1] in ("__init__", "__post_init__"):
                continue
            for attr, sites in facts[qn].writes.items():
                per_attr.setdefault(attr, {}).setdefault(group, []).extend(sites)
    for attr in sorted(per_attr):
        by_group = per_attr[attr]
        if len(by_group) < 2:
            continue
        all_sites = [s for sites in by_group.values() for s in sites]
        common = frozenset.intersection(*(locks for locks, _ in all_sites))
        if common:
            continue
        # anchor at a spawned-thread write site when there is one
        anchor = None
        for group in sorted(by_group):
            if group != "main":
                anchor = by_group[group][0][1]
                break
        if anchor is None:
            anchor = all_sites[0][1]
        names = " and ".join(sorted(by_group))
        findings.append(
            ctx.finding(
                anchor,
                "RTL601",
                f"self.{attr} is written from {names} with no common lock — "
                "guard every write with one lock or confine writes to a "
                "single thread",
            )
        )
    return findings


def _check_lock_order(
    ctx: FileContext, mi: ModuleIndex, cls: str, facts: Dict[str, _MethodFacts]
) -> List[Finding]:
    # static acquire graph: nested `with` edges plus one call level (a
    # method called while holding L acquires its own locks under L)
    edges: Dict[str, Set[str]] = {}
    anchors: Dict[Tuple[str, str], ast.AST] = {}
    for qn, f in facts.items():
        for outer, inner, node in f.acquire_edges:
            edges.setdefault(outer, set()).add(inner)
            anchors.setdefault((outer, inner), node)
        for dotted, held in f.calls_holding:
            if not held:
                continue
            callee = mi.resolve_local(dotted, qn)
            if callee is None or callee not in facts:
                continue
            for inner in facts[callee].acquired:
                for outer in held:
                    if outer != inner:
                        edges.setdefault(outer, set()).add(inner)
                        anchors.setdefault(
                            (outer, inner), mi.functions[callee].node
                        )
    findings: List[Finding] = []
    seen_cycles: Set[FrozenSet[str]] = set()

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(edges.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycle = path + [start]
                    anchor = anchors.get((path[-1], start)) or anchors.get(
                        (path[0], path[1])
                    )
                    findings.append(
                        ctx.finding(
                            anchor,
                            "RTL604",
                            f"lock order cycle in {cls}: "
                            + " -> ".join(f"self.{l}" for l in cycle)
                            + " — pick one global order and acquire both "
                            "locks in it everywhere",
                        )
                    )
            elif nxt not in path:
                dfs(start, nxt, path + [nxt])

    for lock in sorted(edges):
        dfs(lock, lock, [lock])
    return findings


class _AsyncBodyVisitor(ast.NodeVisitor):
    """RTL602 over one async function body."""

    def __init__(self, ctx: FileContext, mi: ModuleIndex, cls: str) -> None:
        self.ctx = ctx
        self.mi = mi
        self.cls = cls
        self.findings: List[Finding] = []
        self._root: Optional[ast.AST] = None

    def run(self, func_node: ast.AST) -> List[Finding]:
        self._root = func_node
        for stmt in func_node.body:
            self.visit(stmt)
        return self.findings

    def _skip(self, node: ast.AST) -> None:
        return

    visit_FunctionDef = _skip
    visit_AsyncFunctionDef = _skip
    visit_ClassDef = _skip

    def _attr_factory(self, recv: ast.AST) -> str:
        path = target_path(recv)
        if path.startswith("self.") and path.count(".") == 1:
            return self.mi.attr_types.get(self.cls, {}).get(path.split(".", 1)[1], "")
        if path and "." not in path:
            return self.mi.module_types.get(path, "")
        return ""

    def visit_Call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func)
        if dotted in BLOCKING_CALLS:
            hint = (
                "use await asyncio.sleep(...)"
                if dotted == "time.sleep"
                else "move it to run_in_executor"
            )
            self.findings.append(
                self.ctx.finding(
                    node,
                    "RTL602",
                    f"{dotted}() inside an async def blocks the event loop "
                    f"(every other stream stalls) — {hint}",
                )
            )
        elif isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth in BLOCKING_METHODS:
                factory = self._attr_factory(node.func.value)
                if factory in SYNC_PRIMITIVE_FACTORIES and (
                    get_kwarg(node, "timeout") is None
                ):
                    self.findings.append(
                        self.ctx.finding(
                            node,
                            "RTL602",
                            f".{meth}() on a {factory} inside an async def "
                            "with no timeout — blocks the event loop; use "
                            "run_in_executor or an asyncio primitive",
                        )
                    )
            if meth in ENGINE_BLOCKING_METHODS:
                recv = dotted_name(node.func.value)
                parts = recv.split(".") if recv else []
                if any(h in p for p in parts for h in ENGINE_RECEIVER_HINTS):
                    self.findings.append(
                        self.ctx.finding(
                            node,
                            "RTL602",
                            f"jitted engine call {recv}.{meth}() inside an "
                            "async def — device dispatch blocks the event "
                            "loop; route it through the model thread queue",
                        )
                    )
        self.generic_visit(node)


def _check_async_blocking(ctx: FileContext, mi: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for qn, fi in sorted(mi.functions.items()):
        if fi.is_async:
            findings.extend(_AsyncBodyVisitor(ctx, mi, fi.owner_class).run(fi.node))
    return findings


class _AsyncioMutationVisitor(ast.NodeVisitor):
    """RTL603 over one thread-side function body."""

    def __init__(self, ctx: FileContext, mi: ModuleIndex, cls: str, root: str) -> None:
        self.ctx = ctx
        self.mi = mi
        self.cls = cls
        self.root = root
        self.findings: List[Finding] = []

    def run(self, func_node: ast.AST) -> List[Finding]:
        for stmt in getattr(func_node, "body", []):
            self.visit(stmt)
        return self.findings

    def _skip(self, node: ast.AST) -> None:
        return

    visit_FunctionDef = _skip
    visit_AsyncFunctionDef = _skip
    visit_ClassDef = _skip

    def visit_Call(self, node: ast.Call) -> None:
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ASYNCIO_MUTATORS
        ):
            path = target_path(node.func.value)
            factory = ""
            if path.startswith("self.") and path.count(".") == 1:
                factory = self.mi.attr_types.get(self.cls, {}).get(
                    path.split(".", 1)[1], ""
                )
            elif path and "." not in path:
                factory = self.mi.module_types.get(path, "")
            if factory in ASYNCIO_FACTORIES:
                self.findings.append(
                    self.ctx.finding(
                        node,
                        "RTL603",
                        f"{path}.{node.func.attr}() from {self.root} mutates "
                        "an asyncio object off the event loop — route it "
                        "through loop.call_soon_threadsafe(...)",
                    )
                )
        self.generic_visit(node)


def _check_cross_thread_asyncio(ctx: FileContext, mi: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    spawned = {
        qn: kind
        for qn, kind in mi.thread_roots.items()
        if kind in ("thread", "executor", "signal") and qn in mi.functions
    }
    if not spawned:
        return findings
    seen: Set[str] = set()
    for root, kind in sorted(spawned.items()):
        label = f"the {root} {('signal handler' if kind == 'signal' else kind)}"
        for qn in sorted(mi.reachable([root])):
            if qn in seen:
                continue
            seen.add(qn)
            fi = mi.functions[qn]
            if fi.is_async:
                continue
            findings.extend(
                _AsyncioMutationVisitor(ctx, mi, fi.owner_class, label).run(fi.node)
            )
    return findings


class _RootTargetVisitor(ast.NodeVisitor):
    """RTL605: Thread/executor registrations pointed at async defs."""

    def __init__(self, ctx: FileContext, mi: ModuleIndex) -> None:
        super().__init__()
        self.ctx = ctx
        self.mi = mi
        self.stack: List[str] = []
        self.findings: List[Finding] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func)
        basename = dotted.rsplit(".", 1)[-1] if dotted else (
            node.func.attr if isinstance(node.func, ast.Attribute) else ""
        )
        target: Optional[ast.AST] = None
        what = ""
        if dotted in THREAD_FACTORIES:
            target, what = get_kwarg(node, "target"), dotted
        elif basename == "run_in_executor" and len(node.args) >= 2:
            target, what = node.args[1], "run_in_executor"
        if target is not None:
            tgt = dotted_name(target)
            resolved = self.mi.resolve_local(tgt, ".".join(self.stack)) if tgt else None
            if resolved is not None and self.mi.functions[resolved].is_async:
                self.findings.append(
                    self.ctx.finding(
                        node,
                        "RTL605",
                        f"{what} target {tgt} is an async def — calling it "
                        "returns an un-awaited coroutine and the worker does "
                        "nothing; make it sync or schedule it on the loop",
                    )
                )
        self.generic_visit(node)


@checker
def check_concurrency(ctx: FileContext) -> List[Finding]:
    mi = get_module_index(ctx)
    findings: List[Finding] = []
    for cls in sorted(mi.classes):
        facts = _method_facts(mi, cls)
        findings.extend(_check_shared_writes(ctx, mi, cls, facts))
        findings.extend(_check_lock_order(ctx, mi, cls, facts))
    findings.extend(_check_async_blocking(ctx, mi))
    findings.extend(_check_cross_thread_asyncio(ctx, mi))
    rt = _RootTargetVisitor(ctx, mi)
    rt.visit(ctx.tree)
    findings.extend(rt.findings)
    return findings
