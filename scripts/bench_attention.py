"""On-chip attention A/B: XLA fused SDPA vs the pallas flash kernel.

Measures forward+backward wall time of just the attention op (the thing the
two impls actually change) across sequence lengths, isolating it from the
rest of the model so remote compiles stay small.  VERDICT r1 #3 artifact.

    python scripts/bench_attention.py --seqs 1024 4096 16384 --impls xla pallas
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_one(
    impl: str, B: int, S: int, N: int, H: int, steps: int, n_kv: int = 0
) -> dict:
    import jax
    import jax.numpy as jnp

    from relora_tpu.ops.attention import dot_product_attention

    n_kv = n_kv or N  # GQA: fewer K/V heads, exercised un-expanded
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, N, H), jnp.bfloat16)
    k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (B, S, n_kv, H), jnp.bfloat16)
        for i in range(1, 3)
    )

    def fwd_bwd(q, k, v):
        def f(q, k, v):
            o = dot_product_attention(q, k, v, causal=True, impl=impl)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        loss, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return loss, grads

    step = jax.jit(fwd_bwd)
    loss, grads = step(q, k, v)  # compile + warmup
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = step(q, k, v)
    float(loss)  # full sync through the relay
    dt = (time.perf_counter() - t0) / steps
    # causal attention FLOPs: fwd 2*(QK^T)+2*(PV) over the lower triangle
    # (~S^2/2 each), bwd ~2x fwd
    flops = 3 * 4 * B * N * (S * S / 2) * H
    return {
        "impl": impl,
        "seq": S,
        "kv_heads": n_kv,
        "ms": round(dt * 1e3, 2),
        "tflops": round(flops / dt / 1e12, 3),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", type=int, nargs="+", default=[1024, 4096, 16384])
    p.add_argument("--impls", nargs="+", default=["xla", "pallas"])
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--kv-heads", type=int, default=0, help="0 = MHA (= --heads)")
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()

    for S in args.seqs:
        for impl in args.impls:
            try:
                res = bench_one(
                    impl, args.batch, S, args.heads, args.head_dim, args.steps,
                    n_kv=args.kv_heads,
                )
            except Exception as e:  # OOM at long seq is itself a result
                res = {
                    "impl": impl,
                    "seq": S,
                    "kv_heads": args.kv_heads or args.heads,
                    "error": str(e).split("\n")[0][:200],
                }
            print(json.dumps(res))
            sys.stdout.flush()


if __name__ == "__main__":
    main()
