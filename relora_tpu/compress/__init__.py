"""Compression subsystem: prune-retrain, magnitude-aware resets, draft export.

Three pieces, each one seam deep into the existing stack:

- :mod:`relora_tpu.compress.prune` — layer-wise magnitude pruning of the
  *frozen base* (PERP, arXiv:2312.15230): mask construction (global /
  per-matrix thresholds, structured N:M), mask application through the
  merge/requant flow, and the checkpoint sidecar format.
- :mod:`relora_tpu.compress.resets` — magnitude-informed A/B re-init at
  ReLoRA resets ("The Primacy of Magnitude in Low-Rank Adaptation",
  arXiv:2507.06558) behind the ``reset_init={random,magnitude}`` dial.
- :mod:`relora_tpu.compress.draft` — export a pruned+merged checkpoint as
  a servable *draft model* for ``--spec model`` speculative decoding.
"""

from relora_tpu.compress.prune import (  # noqa: F401
    PruneMaskMismatchError,
    apply_mask,
    load_mask,
    magnitude_mask,
    mask_checksum,
    save_mask,
    sparsity_stats,
)
from relora_tpu.compress.draft import (  # noqa: F401
    build_draft_params,
    export_draft_checkpoint,
)
from relora_tpu.compress.resets import magnitude_a_init, make_reinit_fn  # noqa: F401
