from relora_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    LOGICAL_RULES,
    param_shardings,
    batch_sharding,
)
