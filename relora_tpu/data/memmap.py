"""Memory-mapped indexed token storage, binary-compatible with the Megatron
``.idx``/``.bin`` format.

Capability parity with MMapIndexedDataset
(peft_pretraining/megatron_dataset/indexed_dataset.py:348-565): zero-copy
np.memmap reads, partial ``get(doc, offset, length)`` access, and a builder
that autoselects uint16 for vocab < 65500 (:28-32).  Binary compatibility
means existing corpora (e.g. the tokenized Pile the reference's production
recipe points at) load unchanged.

Format (one header + three arrays in ``.idx``, raw tokens in ``.bin``)::

    magic   b"MMIDIDX\\x00\\x00"
    version u64 = 1
    dtype   u8 code (1 u8, 2 i8, 3 i16, 4 i32, 5 i64, 6 f32, 7 f64, 8 u16)
    n_seqs  u64
    n_docs  u64
    sizes   i32[n_seqs]      tokens per sequence
    ptrs    i64[n_seqs]      byte offset of each sequence in .bin
    docs    i64[n_docs]      sequence index at each document boundary
"""

from __future__ import annotations

import os
import struct
from typing import Optional

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

_CODE_TO_DTYPE = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float32,
    7: np.float64,
    8: np.uint16,
}
_DTYPE_TO_CODE = {np.dtype(v): k for k, v in _CODE_TO_DTYPE.items()}


def best_dtype(vocab_size: int) -> np.dtype:
    """uint16 when the vocab fits (parity: indexed_dataset.py:28-32)."""
    return np.dtype(np.uint16) if vocab_size is not None and vocab_size < 65500 else np.dtype(np.int32)


def data_path(prefix: str) -> str:
    return prefix + ".bin"


def index_path(prefix: str) -> str:
    return prefix + ".idx"


def _read_index_arrays(prefix: str):
    """Parse just the ``.idx`` header + arrays: (dtype, sizes, doc_idx).

    Unlike MemmapTokenDataset this never touches the ``.bin`` file, so it
    works on empty shards and holds no mappings open."""
    with open(index_path(prefix), "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{index_path(prefix)}: bad magic {magic!r}")
        (version,) = struct.unpack("<Q", f.read(8))
        if version != _VERSION:
            raise ValueError(f"unsupported index version {version}")
        (code,) = struct.unpack("<B", f.read(1))
        dtype = np.dtype(_CODE_TO_DTYPE[code])
        (n_seqs,) = struct.unpack("<Q", f.read(8))
        (n_docs,) = struct.unpack("<Q", f.read(8))
        sizes = np.frombuffer(f.read(n_seqs * 4), dtype=np.int32)
        f.seek(n_seqs * 8, os.SEEK_CUR)  # skip the byte-offset pointers
        doc_idx = np.frombuffer(f.read(n_docs * 8), dtype=np.int64)
    return dtype, sizes, doc_idx


class MemmapTokenDataset:
    """Read-only mmap view of a tokenized corpus.

    ``self.sizes`` is the per-sequence token count; ``get(i, offset, length)``
    returns a zero-copy slice of sequence ``i``'s tokens.
    """

    def __init__(self, prefix: str):
        self.prefix = prefix
        with open(index_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_path(prefix)}: bad magic {magic!r}")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_CODE_TO_DTYPE[code])
            (n_seqs,) = struct.unpack("<Q", f.read(8))
            (n_docs,) = struct.unpack("<Q", f.read(8))
            header_end = f.tell()

        self._idx_map = np.memmap(index_path(prefix), mode="r", order="C")
        off = header_end
        self.sizes = np.frombuffer(self._idx_map, dtype=np.int32, count=n_seqs, offset=off)
        off += n_seqs * 4
        self.pointers = np.frombuffer(self._idx_map, dtype=np.int64, count=n_seqs, offset=off)
        off += n_seqs * 8
        self.doc_idx = np.frombuffer(self._idx_map, dtype=np.int64, count=n_docs, offset=off)
        self._data = np.memmap(data_path(prefix), dtype=self.dtype, mode="r", order="C")

    def __len__(self) -> int:
        return len(self.sizes)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        """Partial read of one sequence (parity: indexed_dataset.py:528-541)."""
        size = int(self.sizes[idx])
        if length is None:
            length = size - offset
        start = self.pointers[idx] // self.dtype.itemsize + offset
        return self._data[start : start + length]

    def __getitem__(self, idx: int) -> np.ndarray:
        return self.get(idx)

    @property
    def n_tokens(self) -> int:
        return int(self.sizes.sum())


_LEGACY_MAGIC = b"TNTIDX\x00\x00"


class LegacyIndexedDataset:
    """Reader for the legacy (pre-mmap, fairseq-derived) ``.idx``/``.bin``
    format (parity: IndexedDataset / IndexedCachedDataset,
    indexed_dataset.py:133-273).

    Header: magic ``TNTIDX``, <Q version 1, <QQ dtype code + element size,
    <QQ n_items + n_sizes, <Q doc_idx length; int64 arrays dim_offsets,
    data_offsets, sizes, doc_idx.  ``cached=True`` reads the whole token
    buffer into RAM once (the IndexedCachedDataset behavior); otherwise
    reads seek the file lazily.
    """

    def __init__(self, prefix: str, cached: bool = False):
        self.prefix = prefix
        with open(index_path(prefix), "rb") as f:
            magic = f.read(len(_LEGACY_MAGIC))
            if magic != _LEGACY_MAGIC:
                raise ValueError(f"{index_path(prefix)}: bad legacy magic {magic!r}")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported legacy index version {version}")
            dcode, self.element_size = struct.unpack("<QQ", f.read(16))
            self.dtype = np.dtype(_CODE_TO_DTYPE[dcode])
            n_items, n_sizes = struct.unpack("<QQ", f.read(16))
            (n_docs,) = struct.unpack("<Q", f.read(8))
            self.dim_offsets = np.fromfile(f, np.int64, n_items + 1)
            self.data_offsets = np.fromfile(f, np.int64, n_items + 1)
            self.sizes = np.fromfile(f, np.int64, n_sizes).astype(np.int32)
            self.doc_idx = np.fromfile(f, np.int64, n_docs)
        self._file = None
        self._cache = None
        if cached:
            self._cache = np.fromfile(data_path(prefix), dtype=self.dtype)

    def __len__(self) -> int:
        return len(self.data_offsets) - 1

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        size = int(self.data_offsets[idx + 1] - self.data_offsets[idx])
        if length is None:
            length = size - offset
        start = int(self.data_offsets[idx]) + offset
        if self._cache is not None:
            return self._cache[start : start + length]
        if self._file is None:
            self._file = open(data_path(self.prefix), "rb", buffering=0)
        self._file.seek(start * self.element_size)
        return np.frombuffer(self._file.read(length * self.element_size), dtype=self.dtype)

    def __getitem__(self, idx: int) -> np.ndarray:
        return self.get(idx)

    @property
    def n_tokens(self) -> int:
        return int(self.data_offsets[-1])


class LegacyIndexedWriter:
    """Writer for the legacy format (parity: IndexedDatasetBuilder,
    indexed_dataset.py:276-339) — mainly for tests and migration tooling."""

    def __init__(self, prefix: str, dtype: np.dtype = np.dtype(np.int32)):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        os.makedirs(os.path.dirname(os.path.abspath(prefix)), exist_ok=True)
        self._bin = open(data_path(prefix), "wb")
        self.data_offsets = [0]
        self.dim_offsets = [0]
        self.sizes: list[int] = []
        self.doc_idx = [0]

    def add_document(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes())
        self.data_offsets.append(self.data_offsets[-1] + arr.size)
        self.sizes.append(arr.size)
        self.dim_offsets.append(self.dim_offsets[-1] + 1)
        self.doc_idx.append(len(self.sizes))

    def finalize(self) -> None:
        self._bin.close()
        with open(index_path(self.prefix), "wb") as f:
            f.write(_LEGACY_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<QQ", _DTYPE_TO_CODE[self.dtype], self.dtype.itemsize))
            f.write(struct.pack("<QQ", len(self.data_offsets) - 1, len(self.sizes)))
            f.write(struct.pack("<Q", len(self.doc_idx)))
            for arr in (self.dim_offsets, self.data_offsets, self.sizes, self.doc_idx):
                f.write(np.asarray(arr, dtype=np.int64).tobytes())

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # finalize only on clean exit: writing a valid-looking .idx over a
        # partially-streamed .bin would leave a silently truncated corpus
        # that downstream pipelines could load and train on
        if exc_type is None:
            self.finalize()
        else:
            self._bin.close()


def open_token_dataset(prefix: str, impl: str = "infer"):
    """Open a tokenized corpus by format: 'mmap', 'lazy', 'cached', or
    'infer' (sniff the index magic — parity: make_dataset/infer_dataset_impl,
    indexed_dataset.py:36-78)."""
    if impl == "infer":
        with open(index_path(prefix), "rb") as f:
            magic = f.read(9)
        impl = "mmap" if magic.startswith(_MAGIC[:8]) else "lazy"
    if impl == "mmap":
        return MemmapTokenDataset(prefix)
    if impl == "lazy":
        return LegacyIndexedDataset(prefix, cached=False)
    if impl == "cached":
        return LegacyIndexedDataset(prefix, cached=True)
    raise ValueError(f"unknown data impl {impl!r}")


class MemmapTokenWriter:
    """Streaming writer producing the same ``.idx``/``.bin`` pair
    (parity: MMapIndexedDatasetBuilder, indexed_dataset.py:568-603)."""

    def __init__(self, prefix: str, dtype: np.dtype = np.dtype(np.uint16)):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_TO_CODE:
            raise ValueError(f"unsupported dtype {dtype}")
        os.makedirs(os.path.dirname(os.path.abspath(prefix)), exist_ok=True)
        self._bin = open(data_path(prefix), "wb")
        self._sizes: list[int] = []
        self._doc_ends: list[int] = [0]

    def add_document(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))
        self._doc_ends.append(len(self._sizes))

    def merge_file(self, prefix: str) -> None:
        """Append an already-written corpus shard wholesale (parity:
        MMapIndexedDatasetBuilder.merge_file_, indexed_dataset.py:596-603).

        The shard's raw ``.bin`` bytes are streamed onto this writer's data
        file and its sizes/doc boundaries grafted onto the index, so merging
        pre-tokenized shards never re-encodes tokens.  Only the shard's
        ``.idx`` arrays are parsed (no memmap of the data file), so an
        empty shard — a per-worker pretokenizer output that received no
        documents — merges as a no-op instead of crashing."""
        import shutil

        if os.path.realpath(os.path.abspath(prefix)) == os.path.realpath(
            os.path.abspath(self.prefix)
        ):
            raise ValueError(
                f"cannot merge a corpus into itself ({prefix!r}): the "
                "writer already truncated this prefix's .bin"
            )
        dtype, sizes, doc_idx = _read_index_arrays(prefix)
        if dtype != self.dtype:
            raise ValueError(
                f"cannot merge {prefix!r} ({dtype}) into a "
                f"{self.dtype} corpus — re-tokenize or migrate the shard"
            )
        base = len(self._sizes)
        self._sizes.extend(int(s) for s in sizes)
        # doc_idx[0] is the leading 0 sentinel — already represented by
        # this writer's current end marker
        self._doc_ends.extend(base + int(d) for d in doc_idx[1:])
        with open(data_path(prefix), "rb") as f:
            shutil.copyfileobj(f, self._bin)

    def finalize(self) -> None:
        self._bin.close()
        sizes = np.asarray(self._sizes, dtype=np.int32)
        pointers = np.zeros(len(sizes), dtype=np.int64)
        np.cumsum(sizes[:-1] * self.dtype.itemsize, out=pointers[1:])
        docs = np.asarray(self._doc_ends, dtype=np.int64)
        with open(index_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_TO_CODE[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(docs)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(docs.tobytes(order="C"))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # finalize only on clean exit: writing a valid-looking .idx over a
        # partially-streamed .bin would leave a silently truncated corpus
        # that downstream pipelines could load and train on
        if exc_type is None:
            self.finalize()
        else:
            self._bin.close()
