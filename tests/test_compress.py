"""Compression subsystem (relora_tpu/compress): magnitude pruning of the
frozen base, magnitude-aware ReLoRA resets, and the pruned draft model for
``--spec model`` speculative decoding.

The two contracts under test:

- **Mask invariance**: once the prune mask exists, pruned positions are
  exactly ``0.0`` — through ``apply_mask`` in every storage format (dense /
  int8 / nf4: requant is idempotent on exact zeros), through repeated
  ``merge_and_reinit`` cycles with live LoRA factors (the merge re-applies
  the mask before requant), through LoRA-only retraining steps, and through
  the serving engine's ``reload_params`` hot swap.
- **The parity oracle**: a greedy drain through ``spec="model"`` (the
  pruned draft proposing, the base verifying) must be token-identical to
  the non-speculative paged drain — acceptance is argmax match against the
  base's own logits, so the draft can only change *how fast* tokens commit,
  never *which* tokens.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from relora_tpu.compress.prune import (
    PruneMaskMismatchError,
    apply_mask,
    load_mask,
    magnitude_mask,
    mask_checksum,
    parse_nm,
    save_mask,
    sparsity_stats,
)
from relora_tpu.compress.resets import magnitude_a_init, make_reinit_fn
from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import (
    LoraSpec,
    kaiming_uniform,
    merge_and_reinit,
    merged_params,
    trainable_param_mask,
)
from relora_tpu.models.params_util import init_params
from relora_tpu.ops.quant import (
    dequantize_int8,
    dequantize_nf4,
    nf4_leaves_from_module,
    nf4_leaves_to_module,
    quantize_int8,
    quantize_nf4,
)
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.scheduler import PagedContinuousBatchingScheduler, Request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.compress]

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)

SPEC = LoraSpec(r=4, alpha=32)


def make_params(rng=0, in_dim=16, out_dim=24, r=4):
    """A hand-built LoRA tree with all three base storage formats side by
    side (dense f32, int8, nf4) plus non-prunable bystanders."""
    ks = jax.random.split(jax.random.PRNGKey(rng), 8)

    def lora(i):
        return {
            "lora_a": jax.random.normal(ks[i], (in_dim, r)) * 0.1,
            "lora_b": jax.random.normal(ks[i + 1], (r, out_dim)) * 0.1,
        }

    dense = jax.random.normal(ks[0], (in_dim, out_dim)) * 0.1
    q, scale = quantize_int8(jax.random.normal(ks[1], (in_dim, out_dim)))
    codes = nf4_leaves_to_module(
        quantize_nf4(jax.random.normal(ks[2], (in_dim, out_dim)))
    )
    return {
        "embed": {"embedding": jax.random.normal(ks[3], (32, in_dim))},
        "layer": {
            "q_proj": {"kernel": dense, **lora(2)},
            "k_proj": {"kernel_q": q, "kernel_scale": scale, **lora(4)},
            "v_proj": {**codes, **lora(6)},
            "norm": {"scale": jnp.ones((in_dim,))},
        },
    }


def dequant_base(mod):
    if "kernel" in mod:
        return np.asarray(mod["kernel"], np.float32)
    if "kernel_q" in mod:
        return np.asarray(dequantize_int8(mod["kernel_q"], mod["kernel_scale"]))
    return np.asarray(dequantize_nf4(nf4_leaves_from_module(mod)))


MODULES = ("q_proj", "k_proj", "v_proj")


# -- mask construction --------------------------------------------------------


def test_magnitude_mask_scopes():
    params = make_params()
    per = magnitude_mask(params, 0.5, scope="per_matrix")
    # per-matrix: every module lands the target sparsity independently
    for name in MODULES:
        frac = 1.0 - np.asarray(per["layer"][name]["kernel"]).mean()
        assert frac == pytest.approx(0.5, abs=0.05), name
    glob = magnitude_mask(params, 0.5, scope="global")
    assert sparsity_stats(glob)["sparsity"] == pytest.approx(0.5, abs=0.05)
    # global: one threshold ranks the dense 0.1-scale module against the
    # unit-scale quantized ones, so ITS sparsity is far above the target
    dense_frac = 1.0 - np.asarray(glob["layer"]["q_proj"]["kernel"]).mean()
    assert dense_frac > 0.9
    # sparsity 0.0 is the identity mask
    ones = magnitude_mask(params, 0.0)
    assert sparsity_stats(ones)["sparsity"] == 0.0


def test_nm_structured_mask():
    params = make_params()
    mask = magnitude_mask(params, 0.0, nm="2:4")
    for name in MODULES:
        keep = np.asarray(mask["layer"][name]["kernel"])
        groups = keep.reshape(-1, 4, keep.shape[-1])
        # exactly N kept in every group of M along the input axis
        np.testing.assert_array_equal(groups.sum(axis=1), 2)
        # and they are the N largest magnitudes of the group
        mags = np.abs(dequant_base(params["layer"][name])).reshape(
            -1, 4, keep.shape[-1]
        )
        kept = np.where(groups, mags, np.inf).min(axis=1)
        dropped = np.where(~groups, mags, -np.inf).max(axis=1)
        assert (kept >= dropped).all(), name
    with pytest.raises(ValueError, match="N:M"):
        parse_nm("4:2")
    with pytest.raises(ValueError, match="in_features % M"):
        magnitude_mask(make_params(in_dim=10), 0.0, nm="2:4")


def test_mask_construction_guards():
    params = make_params()
    with pytest.raises(ValueError, match="scope"):
        magnitude_mask(params, 0.5, scope="per_tensor")
    with pytest.raises(ValueError, match="sparsity"):
        magnitude_mask(params, 1.0)
    with pytest.raises(ValueError, match="no prunable"):
        magnitude_mask({"layer": {"norm": {"scale": jnp.ones(4)}}}, 0.5)
    # explicit paths: a path with no base kernel fails loudly
    with pytest.raises(PruneMaskMismatchError, match="embed"):
        magnitude_mask(params, 0.5, paths=[("embed",)])


# -- exact-zero application ---------------------------------------------------


def test_apply_mask_exact_zero_all_storages():
    params = make_params()
    mask = magnitude_mask(params, 0.5, scope="per_matrix")
    pruned = apply_mask(params, mask)
    for name in MODULES:
        keep = np.asarray(mask["layer"][name]["kernel"])
        vals = dequant_base(pruned["layer"][name])
        assert (vals[~keep] == 0.0).all(), f"{name}: pruned positions not exact zero"
        assert (vals[keep] != 0.0).any(), name
    # dense kept positions are untouched (no requant round trip)
    keep_q = np.asarray(mask["layer"]["q_proj"]["kernel"])
    np.testing.assert_array_equal(
        np.asarray(pruned["layer"]["q_proj"]["kernel"])[keep_q],
        np.asarray(params["layer"]["q_proj"]["kernel"])[keep_q],
    )
    # LoRA factors and bystanders pass through untouched
    np.testing.assert_array_equal(
        np.asarray(pruned["layer"]["q_proj"]["lora_a"]),
        np.asarray(params["layer"]["q_proj"]["lora_a"]),
    )
    # requant is idempotent on already-masked values: second application is
    # byte-identical (the hot-swap and merge-cycle invariance rely on this)
    again = apply_mask(pruned, mask)
    for a, b in zip(jax.tree_util.tree_leaves(again), jax.tree_util.tree_leaves(pruned)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_apply_mask_named_errors():
    params = make_params()
    ghost = {"layer": {"o_proj": {"kernel": jnp.ones((4, 4), bool)}}}
    with pytest.raises(PruneMaskMismatchError, match="o_proj"):
        apply_mask(params, ghost)
    bad_shape = {"layer": {"q_proj": {"kernel": jnp.ones((4, 4), bool)}}}
    with pytest.raises(PruneMaskMismatchError, match="q_proj"):
        apply_mask(params, bad_shape)


# -- the full prune-retrain cycle ---------------------------------------------


def test_pruned_zeros_survive_merge_retrain_cycles():
    """The PERP loop: merge -> prune -> re-init A/B -> retrain, three times
    over.  Pruned base positions must be exactly zero after every merge in
    every storage format, even though the LoRA factors between merges are
    dense (their delta lands on pruned positions and must be re-zeroed)."""
    params = make_params()
    mask = magnitude_mask(params, 0.5, scope="per_matrix")
    params = apply_mask(params, mask)

    # LoRA-only retraining: optax.masked freezes the base, so steps between
    # merges cannot touch the zeros (the optimizer half of the invariant)
    tx = optax.masked(optax.adam(1e-2), trainable_param_mask(params, lora_only=True))
    opt_state = tx.init(params)

    @jax.jit
    def retrain_step(p, s):
        # differentiate w.r.t. the float LoRA factors only (the int8/nf4
        # base leaves are not valid grad inputs); everything else gets a
        # zero cotangent, which the masked optimizer ignores anyway
        def loss(ab):
            base = p["layer"]["q_proj"]["kernel"] + (ab[0] @ ab[1]) * SPEC.scale
            return jnp.sum(jnp.square(base @ jnp.ones((base.shape[-1], 1))))

        mod = p["layer"]["q_proj"]
        ga, gb = jax.grad(loss)((mod["lora_a"], mod["lora_b"]))
        grads = jax.tree_util.tree_map(jnp.zeros_like, p)
        grads["layer"]["q_proj"]["lora_a"] = ga
        grads["layer"]["q_proj"]["lora_b"] = gb
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s

    for cycle in range(3):
        for _ in range(2):
            params, opt_state = retrain_step(params, opt_state)
        # the LoRA delta is dense here — the merge must re-zero the holes
        params = merge_and_reinit(
            params, jax.random.PRNGKey(cycle), SPEC, mask=mask
        )
        opt_state = tx.init(params)  # ReLoRA optimizer reset
        for name in MODULES:
            keep = np.asarray(mask["layer"][name]["kernel"])
            vals = dequant_base(params["layer"][name])
            assert (vals[~keep] == 0.0).all(), f"cycle {cycle} {name}"
        # the cycle continues: fresh A, zero B
        assert float(jnp.abs(params["layer"]["q_proj"]["lora_b"]).max()) == 0.0
        assert float(jnp.abs(params["layer"]["q_proj"]["lora_a"]).max()) > 0.0


# -- reset_init dial ----------------------------------------------------------


def test_make_reinit_fn_dial():
    assert make_reinit_fn("random") is None  # the byte-for-byte kaiming path
    assert make_reinit_fn("magnitude") is magnitude_a_init
    with pytest.raises(ValueError, match="reset_init"):
        make_reinit_fn("xavier")


def test_random_reset_is_byte_identical():
    """reset_init='random' must not perturb today's behavior: same key, same
    draw, every leaf byte-for-byte."""
    params = make_params()
    key = jax.random.PRNGKey(3)
    legacy = merge_and_reinit(params, key, SPEC)
    dialed = merge_and_reinit(
        params, key, SPEC, a_init=make_reinit_fn("random"), mask=None
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(legacy), jax.tree_util.tree_leaves(dialed)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_magnitude_a_init_shape_determinism_and_profile():
    key = jax.random.PRNGKey(11)
    shape = (16, 4)
    merged = jnp.concatenate(
        [jnp.zeros((8, 24)), jax.random.normal(key, (8, 24))], axis=0
    )
    a1 = magnitude_a_init(key, shape, merged)
    a2 = magnitude_a_init(key, shape, merged)
    assert a1.shape == shape
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))  # deterministic
    # zero-magnitude (pruned-away) input rows get exactly zero init signal
    assert float(jnp.abs(a1[:8]).max()) == 0.0
    assert float(jnp.abs(a1[8:]).max()) > 0.0
    # no profile -> plain kaiming
    np.testing.assert_array_equal(
        np.asarray(magnitude_a_init(key, shape, None)),
        np.asarray(kaiming_uniform(key, shape)),
    )
    # RMS normalization keeps the overall init energy at kaiming's scale
    uniform = jnp.ones((16, 24))
    np.testing.assert_allclose(
        np.asarray(magnitude_a_init(key, shape, uniform)),
        np.asarray(kaiming_uniform(key, shape)),
        rtol=1e-6,
    )


def test_merge_with_magnitude_init_keeps_delta_zero():
    """The dial changes only A: merged kernels identical to the random path
    and B zero, so the model function is continuous across the reset
    whatever the dial."""
    params = make_params()
    key = jax.random.PRNGKey(5)
    mask = magnitude_mask(params, 0.5, scope="per_matrix")
    rand = merge_and_reinit(params, key, SPEC, mask=mask)
    mag = merge_and_reinit(
        params, key, SPEC, a_init=make_reinit_fn("magnitude"), mask=mask
    )
    for name in MODULES:
        np.testing.assert_array_equal(
            dequant_base(rand["layer"][name]), dequant_base(mag["layer"][name])
        )
        assert float(jnp.abs(mag["layer"][name]["lora_b"]).max()) == 0.0
    # pruned input rows of the merged base got zero A signal
    keep = np.asarray(mask["layer"]["q_proj"]["kernel"])
    dead_rows = ~keep.any(axis=-1)
    if dead_rows.any():
        a = np.asarray(mag["layer"]["q_proj"]["lora_a"])
        assert (a[dead_rows] == 0.0).all()


# -- sidecar round trip -------------------------------------------------------


def test_mask_sidecar_roundtrip(tmp_path):
    params = make_params()
    mask = magnitude_mask(params, 0.5, scope="per_matrix")
    meta = save_mask(str(tmp_path), mask, {"target_sparsity": 0.5})
    assert meta["mask_crc32"] == mask_checksum(mask)
    assert meta["sparsity"] == pytest.approx(0.5, abs=0.05)
    back, back_meta = load_mask(str(tmp_path))
    assert back_meta["target_sparsity"] == 0.5
    assert mask_checksum(back) == mask_checksum(mask)
    for name in MODULES:
        np.testing.assert_array_equal(
            np.asarray(back["layer"][name]["kernel"]),
            np.asarray(mask["layer"][name]["kernel"]),
        )
    # an unpruned checkpoint is (None, None), not an error
    assert load_mask(str(tmp_path / "nowhere")) == (None, None)
    # a tampered mask fails its recorded crc32
    import json

    meta_path = tmp_path / "prune_meta.json"
    doc = json.loads(meta_path.read_text())
    doc["mask_crc32"] ^= 1
    meta_path.write_text(json.dumps(doc))
    with pytest.raises(PruneMaskMismatchError, match="crc32"):
        load_mask(str(tmp_path))


# -- draft checkpoint export --------------------------------------------------


def test_export_draft_checkpoint_roundtrip(tmp_path):
    """Export = serving restore + prune + re-save through the normal writer:
    the output passes manifest verification, restores through
    restore_serving_params with the holes intact, and records sparsity +
    mask checksum in both the manifest metadata and the sidecar."""
    from relora_tpu.compress.draft import export_draft_checkpoint
    from relora_tpu.train import checkpoint as ckpt

    params = make_params()
    src = ckpt.save_checkpoint(
        str(tmp_path / "src"), 7, {"params": params}, {"update_step": 7}, SPEC
    )
    ckpt.wait_for_save()

    out = export_draft_checkpoint(src, str(tmp_path / "draft"), sparsity=0.5)
    served = ckpt.restore_serving_params(out)  # manifest-verified restore
    mask, meta = load_mask(out)
    assert meta["target_sparsity"] == 0.5
    for name in MODULES:
        keep = np.asarray(mask["layer"][name]["kernel"])
        vals = dequant_base(served["layer"][name])
        assert (vals[~keep] == 0.0).all(), name
        assert "lora_a" not in served["layer"][name]  # merged tree
    block = ckpt.load_manifest_metadata(out)["pruned"]
    assert block["mask_crc32"] == mask_checksum(mask)
    assert block["sparsity"] == pytest.approx(0.5, abs=0.05)
    assert block["source_checkpoint"] == os.path.abspath(src)

    # a prune-retrain source carries its own sidecar: the export must reuse
    # that exact mask (the factors were trained against it), not recompute
    save_mask(src, mask, {"target_sparsity": 0.5})
    out2 = export_draft_checkpoint(src, str(tmp_path / "draft2"))
    assert ckpt.load_manifest_metadata(out2)["pruned"]["mask_crc32"] == mask_checksum(mask)


def test_export_draft_requires_mask_or_sparsity(tmp_path):
    from relora_tpu.compress.draft import export_draft_checkpoint
    from relora_tpu.train import checkpoint as ckpt

    src = ckpt.save_checkpoint(
        str(tmp_path / "src"), 1, {"params": make_params()}, {"update_step": 1}, SPEC
    )
    ckpt.wait_for_save()
    with pytest.raises(ValueError, match="no prune_mask.npz"):
        export_draft_checkpoint(src, str(tmp_path / "out"))


# -- model-drafted speculative decoding ---------------------------------------


def make_model_spec_engines(cfg, *, sparsity=0.3, cache_size=32, page_size=8, spec_k=4):
    """(plain engine, spec engine with pruned draft, mask): base = merged
    LoRA model, draft = the same merge with a magnitude mask applied."""
    model = build_decode_model(cfg, cache_size=cache_size)
    lora_model = type(model)(cfg, lora=SPEC, dtype=jnp.float32, scan_layers=True)
    params = init_params(
        lora_model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    mask = magnitude_mask(params, sparsity, scope="per_matrix")
    base_tree = jax.tree_util.tree_map(np.asarray, merged_params(params, SPEC))
    draft_tree = jax.tree_util.tree_map(
        np.asarray, apply_mask(merged_params(params, SPEC), mask)
    )
    kw = dict(
        cache_size=cache_size,
        page_size=page_size,
        # model mode doubles the worst-case pages per slot (base + draft)
        num_pages=4 * (cache_size // page_size) + 1,
        chunk_size=8,
    )
    plain = InferenceEngine(cfg, base_tree, **kw)
    spec_eng = InferenceEngine(cfg, base_tree, spec_k=spec_k, **kw)
    spec_eng.load_draft_params(draft_tree)
    return plain, spec_eng, mask


def model_spec_requests(vocab):
    rng = np.random.default_rng(7)
    return [
        Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=8),
        Request(uid=2, prompt=rng.integers(1, vocab, 13).tolist(), max_new_tokens=6),
        Request(uid=3, prompt=[2, 4] * 6, max_new_tokens=7, temperature=0.8, top_p=0.9),
        Request(uid=4, prompt=rng.integers(1, vocab, 5).tolist(), max_new_tokens=5),
    ]


def drain(engine, reqs, **kwargs):
    sched = PagedContinuousBatchingScheduler(
        engine, max_batch=2, eos_id=9, key=jax.random.PRNGKey(42), **kwargs
    )
    completions = sched.run(reqs)
    return sched, {uid: c.tokens for uid, c in completions.items()}


@pytest.mark.serve
@pytest.mark.spec
@pytest.mark.parametrize("cfg", [TINY_LLAMA, TINY_NEOX], ids=["llama", "neox"])
def test_greedy_model_spec_drain_token_identical(cfg):
    """Acceptance pin: greedy requests through ``spec='model'`` with a
    *pruned* draft emit exactly the tokens the non-speculative drain emits —
    the draft proposes, the base verifies, and ``spec_verify_draws`` math is
    untouched, so divergent proposals cost acceptance, never parity."""
    plain, spec_eng, mask = make_model_spec_engines(cfg)
    reqs = model_spec_requests(cfg.vocab_size)
    _, want = drain(plain, reqs)
    sched, got = drain(spec_eng, reqs, spec="model")
    for uid in (1, 2, 4):  # the greedy rows are token-pinned
        assert got[uid] == want[uid], f"uid {uid}"
    assert got[3] and all(0 <= t < cfg.vocab_size for t in got[3])
    stats = sched.spec_stats()
    assert stats["mode"] == "model" and stats["k"] == 4
    assert stats["drafted"] > 0  # the model drafter always proposes
    assert 0 <= stats["accepted"] <= stats["drafted"]
    # base AND draft page runs both released at retirement
    assert sched.allocator.used_pages == 0
    assert sched.prefix_cache is None or not sched.prefix_cache  # lockstep guard

    # hot-swap invariance: reload the plain engine with the pruned draft
    # tree — the masked zeros must survive the jitted device swap exactly
    plain.reload_params(
        jax.tree_util.tree_map(np.asarray, spec_eng.draft_params)
    )
    from relora_tpu.compress.prune import _mask_items, _module_at

    checked = 0
    for path, keep in _mask_items(mask):
        mod = _module_at(plain.params, path)
        if mod is None:
            continue
        vals = dequant_base(jax.tree_util.tree_map(np.asarray, mod))
        assert (vals[~np.asarray(keep)] == 0.0).all(), "/".join(path)
        checked += 1
    assert checked > 0


@pytest.mark.serve
@pytest.mark.spec
def test_identical_draft_accepts_everything():
    """Degenerate oracle: when the draft IS the base, every greedy proposal
    matches the base argmax, so acceptance is total and the drain finishes
    in far fewer decode dispatches than one-per-token."""
    plain, spec_eng, _ = make_model_spec_engines(TINY_LLAMA, sparsity=0.0)
    reqs = [
        Request(uid=1, prompt=[3, 5, 7] * 4, max_new_tokens=8),
        Request(uid=2, prompt=[2, 4] * 6, max_new_tokens=8),
    ]
    _, want = drain(plain, reqs)
    sched, got = drain(spec_eng, reqs, spec="model")
    assert got == want
    stats = sched.spec_stats()
    assert stats["drafted"] == stats["accepted"] > 0
    assert stats["accept_rate"] == 1.0


@pytest.mark.serve
@pytest.mark.spec
def test_model_spec_configuration_guards():
    plain, spec_eng, _ = make_model_spec_engines(TINY_LLAMA)
    # no draft installed -> the scheduler refuses up front
    bare = InferenceEngine(
        TINY_LLAMA, plain.params, cache_size=32, page_size=8, num_pages=13,
        chunk_size=8, spec_k=4,
    )
    with pytest.raises(ValueError, match="load_draft_params"):
        PagedContinuousBatchingScheduler(bare, max_batch=2, spec="model")
    # the draft loop runs on the per-row decode path: packed is out
    with pytest.raises(ValueError, match="packed"):
        PagedContinuousBatchingScheduler(
            spec_eng, max_batch=2, spec="model", packed=True
        )
    # disaggregated roles cannot migrate draft KV pages
    with pytest.raises(ValueError, match="role"):
        PagedContinuousBatchingScheduler(
            spec_eng, max_batch=2, spec="model", role="decode"
        )
    # prefix cache is force-disabled (base/draft prefill lockstep)
    sched = PagedContinuousBatchingScheduler(
        spec_eng, max_batch=2, spec="model", prefix_cache=True
    )
    assert sched.prefix_cache is None or not sched.prefix_cache


# -- training-config dials ----------------------------------------------------


def test_training_config_prune_validation():
    from relora_tpu.config.training import TrainingConfig

    def cfg(**kw):
        return TrainingConfig(dataset_path="/tmp/ds", batch_size=4, **kw)

    with pytest.raises(ValueError, match="use_peft"):
        cfg(prune_sparsity=0.5).finalize()
    with pytest.raises(ValueError, match="prune_sparsity"):
        cfg(use_peft=True, prune_sparsity=1.5).finalize()
    with pytest.raises(ValueError, match="prune_scope"):
        cfg(use_peft=True, prune_sparsity=0.5, prune_scope="everywhere").finalize()
    with pytest.raises(ValueError, match="N:M"):
        cfg(use_peft=True, prune_nm="4:2").finalize()
    with pytest.raises(ValueError, match="reset_init"):
        cfg(reset_init="xavier").finalize()
    ok = cfg(use_peft=True, prune_sparsity=0.5, reset_init="magnitude").finalize()
    assert ok.prune_enabled
    assert not cfg(use_peft=True).finalize().prune_enabled
