"""Custom pallas kernel tests (interpret mode on CPU; the TPU path shares
the exact same kernel body)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.ops.pallas_quant_matmul import dequant_matmul
from relora_tpu.ops.quant import dequantize_int8, quantize_int8


def test_dequant_matmul_matches_reference():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (256, 192))
    w = jax.random.normal(jax.random.fold_in(key, 1), (192, 256)) * 0.1
    q, s = quantize_int8(w)
    want = x @ dequantize_int8(q, s)
    got = dequant_matmul(x, q, s, block_m=128, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_dequant_matmul_batched_and_blocks():
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (2, 4, 128, 64))  # leading batch dims
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 128)) * 0.05
    q, s = quantize_int8(w)
    want = jnp.einsum("...mk,kn->...mn", x, dequantize_int8(q, s))
    got = dequant_matmul(x, q, s, block_m=256, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_dequant_matmul_validation():
    x = jnp.zeros((100, 64))
    q = jnp.zeros((64, 128), jnp.int8)
    s = jnp.ones((1, 128))
    with pytest.raises(ValueError, match="tile"):
        dequant_matmul(x, q, s, block_m=64, block_n=128, interpret=True)
    with pytest.raises(ValueError, match="mismatch"):
        dequant_matmul(jnp.zeros((128, 32)), q, s, interpret=True)
