"""Real multi-process distributed training test.

Launches two separate Python processes that form one JAX distributed system
(jax.distributed.initialize over a local coordinator, CPU devices), build the
same Trainer on a 2-way data-parallel mesh, read disjoint per-host batch
slices, and train — exercising the actual multi-host code paths
(process_count > 1 branch of device_batch via
make_array_from_process_local_data, per-host TokenBatchIterator slicing,
process-0-only checkpoint JSON) that single-process tests cannot reach.

The reference has no equivalent test (single-node only, SURVEY.md §4.4).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
coordinator, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(coordinator_address=coordinator, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 2

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(out_path))))
sys.path.insert(0, "/root/repo")
from tests.test_end_to_end import TINY, FakeTokens, make_cfg
from relora_tpu.data.hf_pipeline import TokenBatchIterator
from relora_tpu.train.trainer import Trainer

cfg = make_cfg(
    __import__("pathlib").Path(os.path.dirname(out_path)),
    num_training_steps=6, relora=None, use_peft=False, scheduler="cosine",
    cycle_length=6, save_every=6, dp_size=2, batch_size=4, total_batch_size=8,
)
trainer = Trainer(cfg, model_cfg=TINY)
data = FakeTokens(n=256)
it = TokenBatchIterator(
    data,
    microbatch=cfg.batch_size * trainer.n_batch_shards // jax.process_count(),
    grad_accum=trainer.grad_accum,
    process_index=jax.process_index(),
    process_count=jax.process_count(),
)
result = trainer.fit(iter(it), None)
import numpy as np
probe = float(np.asarray(trainer.state.params["lm_head"]["kernel"]).sum())
with open(out_path, "w") as f:
    json.dump({"process": pid, "result": result, "probe": probe}, f)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_data_parallel_training(tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(WORKER)
    procs = []
    outs = []
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    for pid in range(2):
        out = tmp_path / f"out_{pid}.json"
        outs.append(out)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker_file), coordinator, str(pid), str(out)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process run timed out")
        assert p.returncode == 0, f"worker failed:\n{stderr[-3000:]}"

    results = [json.load(open(o)) for o in outs]
    # both processes completed the same run and hold identical replicated-state
    assert all(r["result"]["update_step"] == 6 for r in results)
    assert results[0]["probe"] == pytest.approx(results[1]["probe"], rel=1e-6)
    assert np.isfinite(results[0]["probe"])


# ---------------------------------------------------------------------------
# 2-process x 2-local-device (fsdp=2 x data=2 mesh) ReLoRA over the megatron
# per-host data path, killed mid-run and autoresumed — the places multi-host
# bugs actually live: sharded params + merge under fsdp, coordinator-built
# index mappings with a cross-process barrier, per-host batch slicing,
# deterministic data rewind, and the commit-aware autoresume probe after a
# SIGKILL that may interrupt an async checkpoint write.  Multiple local
# devices per process mirrors real TPU-pod topology (4 chips/host); it also
# keeps cross-process compile skew inside gloo's 30s context-init deadline,
# which a 4-singleton-process layout exceeds on a contended CPU host.
# The continuity oracle: the resumed run's per-step losses must reproduce
# the killed run's exactly (same data order, restored optimizer/schedule
# state, same compiled program).
# ---------------------------------------------------------------------------

WORKER_2X2 = r"""
import faulthandler, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
# Fail fast WITH diagnostics: a wedged worker (e.g. a cross-process
# collective deadlock — see the jitted zeroed_fraction note in
# core/optim.py, found by exactly this dump) prints all thread stacks to
# stderr and exits instead of hanging the suite to the phase deadline.
faulthandler.dump_traceback_later(360, exit=True)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
coordinator, pid, workdir, steps = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
jax.distributed.initialize(coordinator_address=coordinator, num_processes=2, process_id=pid)
assert jax.process_count() == 2 and len(jax.devices()) == 4

sys.path.insert(0, "/root/repo")
import main as cli

cli.main([
    "--megatron_dataset_config", f"{workdir}/mega.yaml",
    "--model_config", f"{workdir}/model.json",
    "--batch_size", "2", "--total_batch_size", "8", "--max_length", "16",
    "--dp_size", "2", "--fsdp_size", "2",
    "--lr", "5e-3", "--use_peft", "true", "--lora_r", "4",
    "--relora", "5", "--cycle_length", "5",
    "--scheduler", "cosine_restarts", "--warmup_steps", "2",
    "--restart_warmup_steps", "2",
    "--num_training_steps", steps, "--save_every", "5",
    "--eval_every", "1000", "--seed", "0",
    "--save_dir", f"{workdir}/run", "--autoresume", "true",
])
"""


def _read_losses(metrics_path):
    losses = {}
    if not os.path.exists(metrics_path):
        return losses
    with open(metrics_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # half-written line at kill time
            if "loss" in rec and "update_step" in rec:
                losses[rec["update_step"]] = rec["loss"]
    return losses


def _drain(p):
    """communicate() that tolerates an already-drained process (a second
    call on a text=True piped Popen raises ValueError)."""
    try:
        return p.communicate()
    except ValueError:
        return ("", "")


def _spawn_2x2(tmp_path, worker_file, coordinator, steps):
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    return [
        subprocess.Popen(
            [sys.executable, str(worker_file), coordinator, str(pid), str(tmp_path), steps],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]


@pytest.mark.slow
def test_two_by_two_fsdp_megatron_kill_autoresume(tmp_path):
    import time

    from relora_tpu.data.memmap import MemmapTokenWriter, best_dtype

    # shared mmap corpus (structured so loss is comparable across runs)
    rs = np.random.RandomState(0)
    with MemmapTokenWriter(str(tmp_path / "corpus"), dtype=best_dtype(128)) as w:
        for _ in range(2000):
            start = rs.randint(128)
            w.add_document([(start + j) % 128 for j in range(rs.randint(10, 60))])
    (tmp_path / "mega.yaml").write_text(
        f"data_path: {tmp_path}/corpus\nsplit: '10,0,0'\nseq_length: 16\nseed: 0\ndata_impl: mmap\n"
    )
    from tests.test_end_to_end import TINY

    (tmp_path / "model.json").write_text(json.dumps(TINY.to_dict()))
    worker_file = tmp_path / "worker_2x2.py"
    worker_file.write_text(WORKER_2X2)
    metrics = tmp_path / "run" / "metrics.jsonl"

    # phase A: long run; kill both processes once a checkpoint committed and
    # step >= 7.  gloo's context init has a hard 30s deadline with no config
    # knob (make_gloo_tcp_collectives exposes none); on a contended host,
    # compile skew between the two processes can blow it on the cold first
    # attempt, so a load-induced transient gets ONE retry (the persistent
    # compile cache makes the second attempt skew-free) and anything else
    # fails immediately with the workers' stderr.  The budget is bounded:
    # workers self-kill with stack dumps at 360s (see WORKER_2X2), so a hang
    # surfaces as a fast failure with diagnostics, never a suite stall.
    for attempt in (1, 2):
        procs = _spawn_2x2(tmp_path, worker_file, f"127.0.0.1:{_free_port()}", "20")
        deadline = time.time() + 480
        gloo_skew = False
        try:
            while time.time() < deadline:
                committed = os.path.isdir(tmp_path / "run" / "model_5" / "state")
                if committed and max(_read_losses(metrics), default=0) >= 7:
                    break
                if any(p.poll() is not None for p in procs):
                    errs = "\n".join(
                        (_drain(p)[1] or "")[-3000:] for p in procs if p.poll() is not None
                    )
                    gloo_skew = (
                        "Gloo context initialization failed" in errs
                        # XLA:CPU's 40s cross-device rendezvous abort is the
                        # same class of load-induced transient as gloo skew
                        or "Termination timeout for" in errs
                    )
                    if gloo_skew and attempt < 2:
                        break
                    pytest.fail(f"phase A worker exited early:\n{errs}")
                time.sleep(1.0)
            else:
                pytest.fail("phase A never reached step 7 with a committed checkpoint")
        finally:
            for p in procs:
                p.kill()
        for p in procs:
            _drain(p)
        if not gloo_skew:
            break

    losses_a = _read_losses(metrics)
    assert losses_a and max(losses_a) >= 7

    # phase B: autoresume with the SAME step budget (the schedule envelope is
    # a function of num_training_steps; changing it would change lr and break
    # the continuity oracle) — must pick up model_5 and rewind data
    for attempt in (1, 2):
        procs = _spawn_2x2(tmp_path, worker_file, f"127.0.0.1:{_free_port()}", "20")
        stderrs = []
        for p in procs:
            try:
                # workers self-kill with stack dumps at 360s, so this outer
                # bound only fires if even that failed
                _, stderr = p.communicate(timeout=480)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("phase B timed out (and the worker self-kill did not fire)")
            stderrs.append(stderr or "")
        if all(p.returncode == 0 for p in procs):
            break
        if attempt < 2 and any(
            "Gloo context initialization failed" in s
            or "Termination timeout for" in s
            for s in stderrs
        ):
            continue  # same skew retry as phase A; autoresume makes it safe
        bad = next(i for i, p in enumerate(procs) if p.returncode != 0)
        pytest.fail(f"phase B worker failed:\n{stderrs[bad][-3000:]}")

    losses_b = _read_losses(metrics)
    # resumed losses reproduce the killed run bit-for-bit on overlapping steps
    overlap = [s for s in range(6, 21) if s in losses_a and s in losses_b and losses_b[s] is not None]
    assert overlap, f"no overlapping steps: A={sorted(losses_a)}, B={sorted(losses_b)}"
    for s in overlap:
        assert losses_b[s] == pytest.approx(losses_a[s], rel=1e-6), (
            f"loss diverged at resumed step {s}: {losses_a[s]} vs {losses_b[s]}"
        )
    # the run completed and a final checkpoint exists
    assert os.path.isdir(tmp_path / "run" / "model_20" / "state")
