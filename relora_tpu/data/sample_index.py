"""Sample-index mappings: random-access ``idx -> seq_length+1 tokens`` over a
document corpus.

Capability parity with GPT2Dataset
(peft_pretraining/megatron_dataset/dataset.py): three cached numpy arrays —

- ``doc_idx``     epoch-repeated shuffled document order (:275-287 analogue)
- ``sample_idx``  (num_samples+1, 2) [position-in-doc_idx, token-offset]
  marking each sample boundary; consecutive samples share one boundary token
  (input/target shift) (:289-320)
- ``shuffle_idx`` sample-order permutation

built once by process 0, cached as .npy and mmap-loaded everywhere
(:129-241); the packing loop runs in C++ (native/helpers.cpp) with the NumPy
implementation kept as the differential-testing oracle, exactly the
reference's own strategy.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Optional, Tuple

import numpy as np

from relora_tpu.data.memmap import MemmapTokenDataset
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# pure NumPy reference implementations (oracles)
# ---------------------------------------------------------------------------


def num_epochs_needed(tokens_per_epoch: int, seq_length: int, num_samples: int) -> int:
    """Smallest epoch count whose token supply covers num_samples windows
    (the -1: adjacent samples overlap by one boundary token)."""
    epochs = 0
    total = 0
    while True:
        epochs += 1
        total += tokens_per_epoch
        if (total - 1) // seq_length >= num_samples:
            return epochs


def build_doc_idx(documents: np.ndarray, num_epochs: int, rng: np.random.RandomState) -> np.ndarray:
    """Epoch-repeated document order, shuffled globally."""
    doc_idx = np.tile(np.asarray(documents, dtype=np.int32), num_epochs)
    rng.shuffle(doc_idx)
    return doc_idx


def build_sample_idx_py(
    sizes: np.ndarray, doc_idx: np.ndarray, seq_length: int, num_samples: int
) -> np.ndarray:
    """NumPy oracle for the C++ packer (same contract as
    native.build_sample_idx_native)."""
    sample_idx = np.zeros((num_samples + 1, 2), dtype=np.int64)
    doc_pos = 0
    doc_offset = 0
    sample_idx[0] = (doc_pos, doc_offset)
    for out in range(1, num_samples + 1):
        remaining = seq_length + 1
        while remaining > 0:
            doc_len = int(sizes[doc_idx[doc_pos]]) - doc_offset
            if doc_len >= remaining:
                doc_offset += remaining - 1
                remaining = 0
            else:
                remaining -= doc_len
                doc_pos += 1
                doc_offset = 0
        sample_idx[out] = (doc_pos, doc_offset)
    return sample_idx


def build_shuffle_idx(size: int, rng: np.random.RandomState) -> np.ndarray:
    dtype = np.uint32 if size < np.iinfo(np.uint32).max - 1 else np.int64
    idx = np.arange(size, dtype=dtype)
    rng.shuffle(idx)
    return idx


# ---------------------------------------------------------------------------
# cached builder
# ---------------------------------------------------------------------------


def build_index_mappings(
    name: str,
    prefix: str,
    documents: np.ndarray,
    sizes: np.ndarray,
    num_samples: int,
    seq_length: int,
    seed: int,
    cache_dir: Optional[str] = None,
    is_coordinator: bool = True,
    barrier=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build-or-load the three mapping arrays.

    Process 0 builds and writes ``.npy`` caches; other processes wait at
    ``barrier`` then mmap-load (parity: dataset.py:161-241 rank-0 pattern).
    """
    cache_dir = cache_dir or os.path.dirname(os.path.abspath(prefix))
    tokens_per_epoch = int(np.sum(sizes[documents]))
    epochs = num_epochs_needed(tokens_per_epoch, seq_length, num_samples)
    # the digest covers the document ids themselves, not just their count and
    # token total — two different subsets with coincident totals (e.g. a moved
    # split boundary) must not reuse each other's cached mappings
    doc_digest = hashlib.md5(np.ascontiguousarray(documents).tobytes()).hexdigest()[:16]
    key = hashlib.md5(
        f"{name}:{doc_digest}:{tokens_per_epoch}:{epochs}:{num_samples}:{seq_length}:{seed}".encode()
    ).hexdigest()[:16]
    base = os.path.join(cache_dir, f"{os.path.basename(prefix)}_{name}_{key}")
    paths = {k: f"{base}_{k}.npy" for k in ("doc_idx", "sample_idx", "shuffle_idx")}

    if is_coordinator and not all(os.path.exists(p) for p in paths.values()):
        t0 = time.time()
        rng = np.random.RandomState(seed)
        doc_idx = build_doc_idx(documents, epochs, rng)
        total_samples = (epochs * tokens_per_epoch - 1) // seq_length
        n = min(num_samples, total_samples)

        from relora_tpu.data.native import build_sample_idx_native

        sample_idx = build_sample_idx_native(sizes, doc_idx, seq_length, n)
        if sample_idx is None:
            logger.warning("native packer unavailable; NumPy fallback (slow for large corpora)")
            sample_idx = build_sample_idx_py(sizes, doc_idx, seq_length, n)
        shuffle_idx = build_shuffle_idx(sample_idx.shape[0] - 1, rng)

        np.save(paths["doc_idx"], doc_idx, allow_pickle=False)
        np.save(paths["sample_idx"], sample_idx, allow_pickle=False)
        np.save(paths["shuffle_idx"], shuffle_idx, allow_pickle=False)
        logger.info(
            f"built index mappings for {name} ({n} samples, {epochs} epochs) "
            f"in {time.time()-t0:.1f}s"
        )
    if barrier is not None:
        barrier()

    doc_idx = np.load(paths["doc_idx"], mmap_mode="r")
    sample_idx = np.load(paths["sample_idx"], mmap_mode="r")
    shuffle_idx = np.load(paths["shuffle_idx"], mmap_mode="r")
    return doc_idx, sample_idx, shuffle_idx


class PackedCausalDataset:
    """Random-access packed-sample view: ``ds[i]`` is ``seq_length+1`` int
    tokens assembled across document boundaries (parity: GPT2Dataset
    __getitem__ :78-126 including the modulo wrap on out-of-range)."""

    def __init__(
        self,
        name: str,
        data: MemmapTokenDataset,
        documents: np.ndarray,
        num_samples: int,
        seq_length: int,
        seed: int,
        cache_dir: Optional[str] = None,
        is_coordinator: bool = True,
        barrier=None,
        label_data: Optional[MemmapTokenDataset] = None,
    ):
        self.name = name
        self.data = data
        # optional parallel label corpus, token-aligned with ``data``
        # (parity: label_dataset, dataset.py:96-126 / label_data_paths)
        self.label_data = label_data
        if label_data is not None and len(label_data) != len(data):
            raise ValueError("label corpus must align document-for-document with data")
        self.seq_length = seq_length
        self.doc_idx, self.sample_idx, self.shuffle_idx = build_index_mappings(
            name,
            data.prefix,
            documents,
            data.sizes,
            num_samples,
            seq_length,
            seed,
            cache_dir=cache_dir,
            is_coordinator=is_coordinator,
            barrier=barrier,
        )

    def __len__(self) -> int:
        return min(len(self.shuffle_idx), self.sample_idx.shape[0] - 1)

    def _assemble(self, source: MemmapTokenDataset, s: int) -> np.ndarray:
        pos_f, off_f = int(self.sample_idx[s][0]), int(self.sample_idx[s][1])
        pos_l, off_l = int(self.sample_idx[s + 1][0]), int(self.sample_idx[s + 1][1])
        if pos_f == pos_l:
            tokens = source.get(int(self.doc_idx[pos_f]), offset=off_f, length=off_l - off_f + 1)
        else:
            parts = [source.get(int(self.doc_idx[pos_f]), offset=off_f)]
            for p in range(pos_f + 1, pos_l):
                parts.append(source.get(int(self.doc_idx[p])))
            parts.append(source.get(int(self.doc_idx[pos_l]), length=off_l + 1))
            tokens = np.concatenate(parts)
        return np.asarray(tokens, dtype=np.int64)

    def __getitem__(self, idx) -> dict:
        if isinstance(idx, slice):
            return {"input_ids": np.stack([self[i]["input_ids"] for i in range(*idx.indices(len(self)))])}
        if idx >= len(self):
            idx = idx % len(self)  # parity: modulo wrap (dataset.py:78-86)
        s = int(self.shuffle_idx[idx])
        out = {"input_ids": self._assemble(self.data, s)}
        if self.label_data is not None:
            # labels assembled with the same index maps — fully in sync
            # (parity: dataset.py:96-126)
            out["label"] = self._assemble(self.label_data, s)
        return out
