#!/bin/bash
# TPU-tunnel recovery watcher (bench insurance), round-5 priorities.
#
# The sandbox's one-chip TPU tunnel has died mid-round in every round so far
# (round 3: down the whole round); this watcher probes it and, the moment it
# answers, runs the queued on-chip work in strict priority order — committing
# each stage's artifacts to git immediately so a second outage can't erase a
# completed measurement:
#   1. bench.py (the driver's headline number)        -> bench_results/
#   2. remat/microbatch lever sweep (bench_sweep.py)  -> bench_results/r5_sweep.jsonl
#      + re-run the headline with the dots policy if it wins
#   3. attention op-level A/B (bench_attention.py)    -> bench_results/r5_attn.jsonl
#   4. quantized-base benches (int8 / nf4)            -> bench_results/r5_sweep.jsonl
#   5. extra bench configs (250m, magnitude)          -> bench_results/
#   6. loss-parity at llama_35m, 1000-step cycles (longest), then the
#      magnitude-pruning variant at the same cycle length (shares warmup +
#      full-rank branches)
#
# Usage: nohup bash scripts/tpu_recovery_watch.sh > /tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
RES=bench_results
mkdir -p "$RES"

commit() { # commit <message> -- <paths...>
  local msg="$1"; shift; shift
  git add "$@" 2>/dev/null
  git diff --cached --quiet || git commit -q -m "$msg

No-Verification-Needed: bench/measurement artifacts only" -- "$@"
}

probe() {
  timeout -k 10 180 python -c \
    "import jax,jax.numpy as jnp;print(float(jax.jit(lambda a:(a@a).sum())(jnp.ones((128,128)))))" \
    >/dev/null 2>&1
}

sweep() { # sweep <args...>
  # each config is a FRESH program on-chip (policy/microbatch changes the
  # HLO): remote compiles ran 5-15 min in past rounds, so give the compile
  # room — the watchdog only bounds a wedged tunnel, not a slow compile
  BENCH_WATCHDOG_SECS=1500 timeout 1800 python scripts/bench_sweep.py \
      --out "$RES/r5_sweep.jsonl" "$@" \
    || echo "{\"error\": \"failed: $*\"}" >> "$RES/r5_sweep.jsonl"
  commit "On-chip sweep: $*" -- "$RES/r5_sweep.jsonl"
}

echo "watcher start $(date -u +%FT%TZ)"
while ! probe; do
  echo "tunnel down $(date -u +%FT%TZ)"
  sleep 240
done
echo "tunnel UP $(date -u +%FT%TZ)"

# 1. headline bench
BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_local.json" 2>/tmp/bench_r5.err \
  && commit "On-chip headline bench (r5 local)" -- "$RES/BENCH_r5_local.json" "$RES/last_onchip.json"

# 2. lever sweep: the unmeasured big levers first
# Queue = the configs tools/plan_memory says FIT a 16 GB v5e at 1B/seq1024
# (the naive dots-family mb8/mb16 plans need 19-32 GB — r1's "compile
# rejected" dots attempts were never going to run), ordered by expected
# value: the dots policy cuts executed matmul FLOPs 24% (r4_lever_rank),
# so its small-mb configs lead; large-mb full-remat trades no FLOPs but
# better MXU utilization; dots_all mb2 misses the 90% HBM budget by 0.3 GB
# and gets exactly one attempt (a failure line is recorded and we move on).
sweep --remat --remat-policy dots --loss-impl chunked --micro-batch 4 --label "remat dots chunked mb4"
sweep --remat --remat-policy dots --loss-impl chunked --micro-batch 2 --label "remat dots chunked mb2"
sweep --remat --loss-impl chunked --micro-batch 32 --label "remat full chunked mb32"
sweep --remat --remat-policy dots_all --loss-impl chunked --micro-batch 2 --label "remat dots_all chunked mb2"
# 2a'. round-5 quantized-base configs (bench_results/r5_quant_feasible.json):
# int8/nf4 base gives dots/chunked mb4 ~4 GB of headroom (the f32 plan was
# 14.08 GB "tight" and r1's compile rejected it) and raises full/chunked to
# mb64 — measure whether the dequant cost eats the headroom win
sweep --quantize int8 --remat --remat-policy dots --loss-impl chunked --micro-batch 4 --label "int8 base dots chunked mb4"
sweep --quantize nf4 --remat --remat-policy dots --loss-impl chunked --micro-batch 4 --label "nf4 base dots chunked mb4"
sweep --quantize int8 --remat --loss-impl chunked --micro-batch 64 --label "int8 base full chunked mb64"
sweep --quantize int8 --remat --remat-policy dots_all --micro-batch 2 --label "int8 base dots_all dense mb2"
sweep --remat --dropout 0 --label "remat full dropout0"
sweep --remat --prng rbg --label "remat full rbg-prng"
sweep --remat --loss-impl chunked --micro-batch 16 --label "remat full chunked mb16"
sweep --remat --loss-impl chunked --micro-batch 24 --label "remat full chunked mb24"

# 2b. if a dots-family policy beat the stage-1 headline, land a headline
# number with the WINNING policy at the micro-batch it actually won at
# (dots_all may only fit at mb4; bench.py honors BENCH_MICRO_BATCH)
BEST=$(python - <<'EOF'
import json, re
best_mfu, best = 0.0, ""
try:
    for line in open("bench_results/r5_sweep.jsonl"):
        r = json.loads(line)
        label = r.get("label", "")
        mfu = r.get("mfu") or 0.0
        if "dots" in label and mfu > best_mfu:
            m = re.search(r"mb(\d+)", label)
            best_mfu = mfu
            best = ":".join((
                "dots_all" if "dots_all" in label else "dots",
                m.group(1) if m else "8",
                "chunked" if "chunked" in label else "dense",
                "0" if "dropout0" in label else "0.1",
                # quantized winners must be replayed QUANTIZED: bench.py
                # honors BENCH_QUANTIZE, and an f32 replay of the int8
                # dots/mb4 winner is the 14-GB plan r1's compile rejected
                "int8" if "int8" in label else ("nf4" if "nf4" in label else ""),
            ))
    head = json.load(open("bench_results/BENCH_r5_local.json"))
    print(best if best_mfu > head["detail"]["mfu"] else "")
except Exception:
    print("")
EOF
)
if [ -n "$BEST" ]; then
  IFS=: read -r BEST_POLICY BEST_MB BEST_LOSS BEST_DROPOUT BEST_QUANT <<< "$BEST"
  BENCH_REMAT_POLICY="$BEST_POLICY" BENCH_MICRO_BATCH="$BEST_MB" \
    BENCH_LOSS_IMPL="$BEST_LOSS" BENCH_DROPOUT="$BEST_DROPOUT" \
    BENCH_QUANTIZE="$BEST_QUANT" \
    BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py \
    > "$RES/BENCH_r5_local_${BEST_POLICY}.json" 2>/dev/null \
    && commit "On-chip headline bench with $BEST_POLICY remat (mb $BEST_MB, $BEST_LOSS loss, dropout $BEST_DROPOUT, quant ${BEST_QUANT:-f32})" -- "$RES/BENCH_r5_local_${BEST_POLICY}.json" "$RES/last_onchip.json"
fi

# 3. attention op-level A/B — MHA then GQA (16q/4kv, the un-expanded path)
timeout 2400 python scripts/bench_attention.py --seqs 1024 4096 16384 --impls xla pallas \
  > "$RES/r5_attn.jsonl" 2>/tmp/attn_r5.err \
  && commit "Attention op-level A/B (xla vs pallas, 1k/4k/16k)" -- "$RES/r5_attn.jsonl"
timeout 2400 python scripts/bench_attention.py --seqs 4096 16384 --impls xla pallas \
  --kv-heads 4 >> "$RES/r5_attn.jsonl" 2>>/tmp/attn_r5.err \
  && commit "Attention op-level A/B: GQA 16q/4kv" -- "$RES/r5_attn.jsonl"

# 4. quantized-base benches
sweep --remat --quantize int8 --label "remat int8-base"
sweep --remat --quantize nf4 --label "remat nf4-base"
RELORA_TPU_PALLAS_QUANT=1 sweep --remat --quantize int8 --label "remat int8-base pallas-dequant"

# 5. extra configs
BENCH_CONFIG=llama_250m BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_250m.json" 2>/dev/null \
  && commit "On-chip bench: llama_250m config" -- "$RES/BENCH_r5_250m.json"
BENCH_CONFIG=llama_1b_magnitude BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_magnitude.json" 2>/dev/null \
  && commit "On-chip bench: magnitude-reset config" -- "$RES/BENCH_r5_magnitude.json"

# 6. loss parity (longest): llama_35m, 4000 steps, 1000-step cycles — the
# scale rung the round-3 verdict asked for (~1.6h/branch on the v5e).
# loss_parity.sh keys run dirs by model/seed/variant, so the zero-reset and
# magnitude variants share the warmup + full-rank branches.
CORPUS=/tmp/corpus/local400 WORK=/tmp/loss_parity \
  STEPS_WARMUP=500 STEPS_TOTAL=4000 bash scripts/loss_parity.sh \
  > /tmp/loss_parity.log 2>&1
echo "loss_parity exit=$? $(date -u +%FT%TZ)"
if [ -f /tmp/loss_parity/compare_llama_35m.json ]; then
  cp /tmp/loss_parity/compare_llama_35m.json "$RES/r5_loss_parity_chip.json"
  commit "On-chip loss-parity result (llama_35m, 1000-step cycles)" -- "$RES/r5_loss_parity_chip.json"
fi

# 6b. magnitude-pruning reset at the same (reference-like) cycle length,
# reusing the shared warmup/full-rank branches — only the ReLoRA branch runs
CORPUS=/tmp/corpus/local400 WORK=/tmp/loss_parity OPT_PRUNE=0.9 \
  STEPS_WARMUP=500 STEPS_TOTAL=4000 bash scripts/loss_parity.sh \
  > /tmp/loss_parity_mag.log 2>&1
echo "loss_parity magnitude exit=$? $(date -u +%FT%TZ)"
if [ -f /tmp/loss_parity/compare_llama_35m_mag0.9.json ]; then
  cp /tmp/loss_parity/compare_llama_35m_mag0.9.json "$RES/r5_loss_parity_chip_mag.json"
  commit "On-chip loss-parity: magnitude-pruning reset at 1000-step cycles" -- "$RES/r5_loss_parity_chip_mag.json"
fi
echo "watcher done $(date -u +%FT%TZ)"
