"""Jittable token sampling for the decode loop.

One function, ``sample``, covers the standard policies — greedy, temperature,
top-k, top-p (nucleus) — composed in the usual order: top-k filter, then
nucleus filter, then temperature-scaled categorical.  Everything traces under
``jax.jit``:

- ``temperature`` and ``top_p`` may be traced scalars or per-row ``(B,)``
  arrays (the continuous-batching scheduler mixes requests with different
  sampling settings in one decode step).  ``temperature <= 0`` selects greedy
  for that row — computed as a ``where`` over both branches, so the compiled
  step never retraces when a greedy request shares a batch with sampled ones.
- ``top_k`` is a static int (it changes the ``lax.top_k`` shape); 0 disables.
- ``key`` is either one PRNG key shared across the batch, or a stacked
  ``(B, key_size)`` batch of per-row keys.  Per-row keys make a request's
  sample stream independent of which other requests happen to share its
  batch — fold in the request id, not the slot index.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

_NEG_INF = jnp.finfo(jnp.float32).min


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy.  ``temperature=0`` is greedy."""

    temperature: float = 0.0
    top_k: int = 0  # 0 disables; static (changes compiled shapes)
    top_p: float = 1.0

    def __post_init__(self):
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


def top_k_mask(logits: jax.Array, k: int) -> jax.Array:
    """Keep the k largest logits per row, -inf the rest.  ``k`` static."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def top_p_mask(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filter: keep the smallest set of tokens whose probability mass
    reaches ``top_p``, -inf the rest.  A token stays iff the mass *strictly
    before* it (descending order) is < top_p — so the argmax always survives
    and the kept set's mass is the smallest one >= top_p."""
    order = jnp.argsort(logits, axis=-1)[..., ::-1]  # descending
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = mass_before < jnp.asarray(top_p, jnp.float32)[..., None]
    inverse = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inverse, axis=-1)
    return jnp.where(keep, logits, _NEG_INF)


def sample(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature=0.0,
    top_k: int = 0,
    top_p=1.0,
) -> jax.Array:
    """Sample next-token ids ``(B,)`` from logits ``(B, V)``.

    ``temperature``/``top_p`` broadcast per-row; rows with ``temperature <= 0``
    take the argmax.  ``key`` is one key or a ``(B, ...)`` stack of keys.
    """
    logits = logits.astype(jnp.float32)
    B = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1)

    filtered = top_k_mask(logits, top_k)
    filtered = top_p_mask(filtered, jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,)))
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    scaled = filtered / jnp.maximum(temp, 1e-6)[:, None]
    if key.ndim > 1:  # per-row keys
        drawn = jax.vmap(jax.random.categorical)(key, scaled)
    else:
        drawn = jax.random.categorical(key, scaled)
    return jnp.where(temp <= 0.0, greedy, drawn)
