#!/usr/bin/env bash
# GLUE-harness end-to-end from a real pretrained checkpoint (VERDICT r1 #9):
# two classification tasks built from local text, fine-tuned with run_glue.py
# on a ReLoRA/full-rank checkpoint + its corpus tokenizer, metrics to
# $WORK/<task>/all_results.json and predictions to predict_results_*.txt.
#
#   CHECKPOINT=/tmp/loss_parity/warmup/model_1000 \
#   TOKENIZER=/tmp/corpus/local400.tokenizer.json \
#   bash scripts/glue_e2e.sh
set -euo pipefail
cd "$(dirname "$0")/.."

CHECKPOINT="${CHECKPOINT:?set CHECKPOINT=<model_N dir>}"
TOKENIZER="${TOKENIZER:?set TOKENIZER=<tokenizer.json>}"
MODEL_CONFIG="${MODEL_CONFIG:-llama_35m}"
WORK="${WORK:-/tmp/glue_e2e}"
SP="/opt/venv/lib/python3.12/site-packages"

mkdir -p "$WORK"

# task 1: code vs prose (binary)
python tools/build_cls_dataset.py --out "$WORK/data_srctype" --per-label 400 \
    --root "code=$SP/numpy,$SP/scipy@py" \
    --root "prose=$SP@md,rst,txt"

# task 2: which library does this code come from (3-way)
python tools/build_cls_dataset.py --out "$WORK/data_pkgid" --per-label 300 \
    --root "numpy=$SP/numpy@py" \
    --root "jax=$SP/jax@py" \
    --root "torch=$SP/torch@py"

for task in srctype pkgid; do
  rm -rf "$WORK/$task"
  python run_glue.py --task_name "$task" \
      --train_file "$WORK/data_$task/train.csv" \
      --validation_file "$WORK/data_$task/dev.csv" \
      --test_file "$WORK/data_$task/test.csv" --do_predict true \
      --model_config "$MODEL_CONFIG" --checkpoint "$CHECKPOINT" \
      --tokenizer "$TOKENIZER" \
      --batch_size 16 --num_epochs "${EPOCHS:-2}" --max_seq_length 128 \
      --lr 5e-5 --output_dir "$WORK/$task"
done

echo "=== results ==="
cat "$WORK"/srctype/all_results.json "$WORK"/pkgid/all_results.json
