"""Megatron-style data path: config parsing, dataset building, iterators.

Capability parity with megatron_dataset/data_utils.py +
the NeoXArgs data surface the training script actually uses
(torchrun_main.py:276-319): mmap ``.bin``/``.idx`` corpora, weighted
multi-corpus blending, train/valid/test from either explicit path lists or a
single ``data_path`` with a ``split`` string, deterministic resume rewind,
and per-host batch sharding.

The 2,800-LoC NeoXArgs dataclass aggregation collapses to the one small
typed config below: everything the reference's loader reads from it
(data paths/weights, split, seq_length, data_impl, seed) — the rest of the
reference YAML (model settings consumed by NeoX proper) is accepted and
ignored, so existing config files (configs/pile_megatron_dataset.yaml) load
unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np
import yaml

from relora_tpu.data.blendable import BlendableDataset
from relora_tpu.data.memmap import open_token_dataset
from relora_tpu.data.sample_index import PackedCausalDataset
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class MegatronDataConfig:
    train_data_paths: Optional[List[str]] = None
    valid_data_paths: Optional[List[str]] = None
    test_data_paths: Optional[List[str]] = None
    label_data_paths: Optional[List[str]] = None  # aligned with train_data_paths
    train_data_weights: Optional[List[float]] = None
    valid_data_weights: Optional[List[float]] = None
    test_data_weights: Optional[List[float]] = None
    data_path: Optional[str] = None
    split: str = "969,30,1"
    seq_length: int = 2048
    seed: int = 1234
    data_impl: str = "mmap"

    @classmethod
    def from_yaml(cls, path: str) -> "MegatronDataConfig":
        with open(path) as f:
            raw = yaml.safe_load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in raw.items() if k in known and v not in ("", None)}
        cfg = cls(**kwargs)
        _check_neox_batch_keys(raw, path)
        if cfg.data_impl not in ("mmap", "lazy", "cached", "infer"):
            raise NotImplementedError(
                f"data_impl={cfg.data_impl!r}: supported are mmap/lazy/cached/infer"
            )
        if cfg.data_path is None and not cfg.train_data_paths:
            raise ValueError("config needs train_data_paths or data_path")
        return cfg


def _check_neox_batch_keys(raw: dict, path: str) -> None:
    """Cross-check NeoX batch-arithmetic keys we deliberately don't consume.

    The reference solves/validates train_batch_size = micro_batch_per_gpu *
    gradient_accumulation_steps * world_size when loading a NeoX YAML
    (megatron_dataset/arguments.py:754-812). We collapse NeoXArgs to the data
    surface the training path reads, so those keys are ignored here — but a
    YAML whose batch fields are internally inconsistent should warn instead
    of being silently accepted.
    """
    tbs = raw.get("train_batch_size")
    micro = raw.get("train_micro_batch_size_per_gpu")
    ga = raw.get("gradient_accumulation_steps")
    present = {
        k: v
        for k, v in (
            ("train_batch_size", tbs),
            ("train_micro_batch_size_per_gpu", micro),
            ("gradient_accumulation_steps", ga),
        )
        if v is not None
    }
    if present:
        logger.warning(
            "%s: NeoX batch keys %s are not consumed by relora_tpu "
            "(batch arithmetic is set by the training config, not the data YAML)",
            path,
            sorted(present),
        )
    if tbs is not None and micro is not None and ga is not None:
        try:
            tbs_i, micro_i, ga_i = int(tbs), int(micro), int(ga)
        except (TypeError, ValueError):
            return
        # world_size isn't knowable from the YAML; consistency requires
        # train_batch_size to be a positive multiple of micro * grad_accum
        per_rank = micro_i * ga_i
        if per_rank <= 0 or tbs_i <= 0 or tbs_i % per_rank != 0:
            logger.warning(
                "%s: inconsistent NeoX batch arithmetic: train_batch_size=%s "
                "is not a positive multiple of train_micro_batch_size_per_gpu=%s "
                "* gradient_accumulation_steps=%s (reference validates this in "
                "arguments.py:754-812)",
                path,
                tbs,
                micro,
                ga,
            )


def parse_split_string(split: str, n: int) -> List[range]:
    """'969,30,1' (or '969/30/1') -> three contiguous document ranges
    covering [0, n) (bit-parity: data_utils.get_train_valid_test_split_
    :163-187).

    The rounding correction matters: the reference subtracts the cumulative
    rounding excess from *every* bound, not just the last — clamping only
    the tail can produce a zero-width middle split at small n (e.g.
    '1,1,1' over 10 docs is [0,4,7,10] here, not [0,3,6,10]).
    """
    s = str(split)
    sep = "," if "," in s else ("/" if "/" in s else None)
    parts = [float(x) for x in s.split(sep)] if sep else [float(s)]
    while len(parts) < 3:
        parts.append(0.0)
    parts = parts[:3]
    total = sum(parts)
    if total == 0:
        raise ValueError("split must have a nonzero component")
    fracs = [p / total for p in parts]
    bounds = [0]
    for f in fracs:
        bounds.append(bounds[-1] + int(round(f * float(n))))
    diff = bounds[-1] - n
    bounds = [bounds[0]] + [b - diff for b in bounds[1:]]
    if any(b < 0 for b in bounds) or any(
        bounds[i] > bounds[i + 1] for i in range(3)
    ):
        # degenerate splits (e.g. '0,1,1' over 3 docs) make the uniform
        # correction go negative; the reference silently emits the same
        # bounds and then wraps to wrong documents — fail loudly instead
        raise ValueError(
            f"split {split!r} over {n} documents produces invalid bounds {bounds}"
        )
    return [range(bounds[i], bounds[i + 1]) for i in range(3)]


def _build_packed(
    prefix: str,
    documents: np.ndarray,
    num_samples: int,
    seq_length: int,
    seed: int,
    name: str,
    is_coordinator: bool,
    barrier,
    data_impl: str = "infer",
):
    data = open_token_dataset(prefix, data_impl)
    return PackedCausalDataset(
        name=name,
        data=data,
        documents=documents,
        num_samples=num_samples,
        seq_length=seq_length,
        seed=seed,
        is_coordinator=is_coordinator,
        barrier=barrier,
    )


def build_split_datasets(
    mcfg: MegatronDataConfig,
    num_samples: Sequence[int],
    is_coordinator: bool = True,
    barrier=None,
):
    """(train, valid, test) datasets — weighted blends of explicit path lists,
    or a split of a single corpus (parity: data_utils.py:325-441)."""
    names = ("train", "valid", "test")
    out = []
    if mcfg.train_data_paths:
        path_lists = (mcfg.train_data_paths, mcfg.valid_data_paths, mcfg.test_data_paths)
        weight_lists = (mcfg.train_data_weights, mcfg.valid_data_weights, mcfg.test_data_weights)
        for name, paths, weights, n in zip(names, path_lists, weight_lists, num_samples):
            if not paths:
                out.append(None)
                continue
            weights = weights or [1.0] * len(paths)
            w = np.asarray(weights, dtype=np.float64)
            w = w / w.sum()
            label_paths = mcfg.label_data_paths if name == "train" else None
            parts = []
            for i, p in enumerate(paths):
                data = open_token_dataset(p, mcfg.data_impl)
                docs = np.arange(len(data), dtype=np.int32)
                # each corpus supplies its weighted share of samples (+5%
                # headroom, as the blend is not exactly proportional)
                share = int(np.ceil(n * w[i] * 1.05)) + 1
                parts.append(
                    PackedCausalDataset(
                        name=f"{name}_{i}",
                        data=data,
                        documents=docs,
                        num_samples=share,
                        seq_length=mcfg.seq_length,
                        seed=mcfg.seed,
                        is_coordinator=is_coordinator,
                        barrier=barrier,
                        label_data=(
                            open_token_dataset(label_paths[i], mcfg.data_impl) if label_paths else None
                        ),
                    )
                )
            out.append(parts[0] if len(parts) == 1 else BlendableDataset(parts, w))
    else:
        data = open_token_dataset(mcfg.data_path, mcfg.data_impl)
        ranges = parse_split_string(mcfg.split, len(data))
        for name, rng_, n in zip(names, ranges, num_samples):
            if len(rng_) == 0 or n == 0:
                out.append(None)
                continue
            docs = np.arange(rng_.start, rng_.stop, dtype=np.int32)
            out.append(
                _build_packed(
                    mcfg.data_path, docs, n, mcfg.seq_length, mcfg.seed,
                    name, is_coordinator, barrier, data_impl=mcfg.data_impl,
                )
            )
    return tuple(out)


class PackedBatchIterator:
    """Batches a random-access packed dataset into device-ready arrays with
    deterministic per-host slicing and update-step rewind (parity:
    DistributedBatchSampler + start_iter, samplers.py:88-165,
    data_utils.py:443-466).

    ``interleaved=False`` gives each host a contiguous run of the global
    batch; ``True`` stripes hosts across it (the reference supports both
    slicings, samplers.py:159-165).
    """

    def __init__(
        self,
        dataset,
        *,
        microbatch: int,
        grad_accum: Optional[int] = None,
        skip_updates: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        interleaved: bool = False,
    ):
        self.dataset = dataset
        self.microbatch = microbatch
        self.grad_accum = grad_accum
        self.process_index = process_index
        self.process_count = process_count
        self.interleaved = interleaved
        self._per_update = microbatch * (grad_accum or 1) * process_count
        self._start = skip_updates * self._per_update
        self._n_updates = len(dataset) // self._per_update

    def __len__(self) -> int:
        return max(0, self._n_updates - self._start // self._per_update)

    def _host_rows(self, start: int, per_host: int) -> list:
        if self.interleaved:
            idxs = range(start + self.process_index, start + self._per_update, self.process_count)
        else:
            lo = start + self.process_index * per_host
            idxs = range(lo, lo + per_host)
        return [self.dataset[i]["input_ids"] for i in idxs]

    def __iter__(self) -> Iterator[np.ndarray]:
        per_host = self.microbatch * (self.grad_accum or 1)
        for start in range(self._start, self._n_updates * self._per_update, self._per_update):
            arr = np.asarray(self._host_rows(start, per_host), dtype=np.int32)
            if self.grad_accum is None:
                yield arr
            else:
                yield arr.reshape(self.grad_accum, self.microbatch, -1)


def build_train_valid_test_iterators(cfg, trainer):
    """Wire the megatron path into the Trainer (parity:
    build_train_valid_test_dataloaders, data_utils.py:308-467)."""
    import jax

    mcfg = MegatronDataConfig.from_yaml(cfg.megatron_dataset_config)
    if mcfg.seq_length + 1 < cfg.max_length:
        logger.warning(
            f"megatron seq_length={mcfg.seq_length} < max_length={cfg.max_length}"
        )

    n_train = cfg.num_training_steps * cfg.total_batch_size
    # eval sees each token at most once (one pass of the split), capped at
    # what the 100M-token final eval needs (torchrun_main.py:984-987)
    n_eval = (120_000_000 // mcfg.seq_length) + 1
    barrier = None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        barrier = lambda: multihost_utils.sync_global_devices("megatron_index_build")

    # cap each eval split at one pass of its own tokens: the packed dataset
    # otherwise up-samples across epochs to satisfy any requested count, and
    # a 100M-token final eval would loop a small split thousands of times
    def one_pass_cap(split_tokens: int) -> int:
        return max(1, min(n_eval, split_tokens // (mcfg.seq_length + 1)))

    if mcfg.train_data_paths:
        def paths_tokens(paths):
            return sum(open_token_dataset(p, mcfg.data_impl).n_tokens for p in paths) if paths else 0

        valid_tokens = paths_tokens(mcfg.valid_data_paths)
        test_tokens = paths_tokens(mcfg.test_data_paths)
    else:
        data = open_token_dataset(mcfg.data_path, mcfg.data_impl)
        sizes = np.asarray(data.sizes)
        ranges = parse_split_string(mcfg.split, len(data))
        valid_tokens = int(sizes[list(ranges[1])].sum()) if len(ranges[1]) else 0
        test_tokens = int(sizes[list(ranges[2])].sum()) if len(ranges[2]) else 0

    train_ds, valid_ds, test_ds = build_split_datasets(
        mcfg,
        (n_train, one_pass_cap(valid_tokens), one_pass_cap(test_tokens)),
        is_coordinator=jax.process_index() == 0,
        barrier=barrier,
    )

    micro = cfg.batch_size * trainer.n_batch_shards // jax.process_count()

    def train_factory():
        return iter(
            PackedBatchIterator(
                train_ds,
                microbatch=micro,
                grad_accum=trainer.grad_accum,
                skip_updates=trainer.update_step,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        )

    def eval_factory():
        source = valid_ds if valid_ds is not None else test_ds
        return iter(
            PackedBatchIterator(
                source,
                microbatch=micro,
                grad_accum=None,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        )

    return train_factory, (eval_factory if (valid_ds or test_ds) else None)
