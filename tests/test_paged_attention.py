"""Fused paged-decode attention kernel + attention dispatch.

The kernel (ops/attention.paged_decode_attention) reads the page pool
directly through the block table — no gathered cache copy, no
``(B, heads, 1, S_kv)`` score matrix in HBM — so its only oracle is the
naive gather arm (ops/attention.paged_cached_attention), which these tests
hold it to in Pallas interpret mode on CPU, for bf16-stored and
int8-quantized pools.  The dispatcher tests mirror tests/test_lora_kernels:
dispatch changes the compute graph, never the result, and never picks the
interpreter on a non-TPU backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.ops.attention import (
    dot_product_attention,
    paged_cached_attention,
    paged_decode_attention,
)
from relora_tpu.ops.attention_dispatch import (
    ARMS,
    TRAIN_ARMS,
    choose_arm,
    choose_training_arm,
    estimate_arm_times,
    estimate_training_arm_times,
    paged_attention,
)
from relora_tpu.ops.quant import quantize_kv_page


def _max_err(a, b):
    return float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())


def _pool_case(seed, *, B=2, heads=4, kv_heads=2, head_dim=8, page_size=4, W=3):
    """A decode step against a shared pool: every row owns W pages, rows sit
    at staggered positions (ragged visibility), and unallocated pool pages
    hold garbage that only the mask keeps out of the result."""
    key = jax.random.PRNGKey(seed)
    num_pages = B * W + 3  # + null page + 2 never-referenced garbage pages
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, 1, heads, head_dim), jnp.float32)
    pool_k = jax.random.normal(ks[1], (num_pages, page_size, kv_heads, head_dim))
    pool_v = jax.random.normal(ks[2], (num_pages, page_size, kv_heads, head_dim))
    # rows own disjoint pages, deliberately not in pool order
    perm = np.random.default_rng(seed).permutation(B * W) + 1
    bt = jnp.asarray(perm.reshape(B, W), jnp.int32)
    # staggered positions: row 0 has a single visible token, last row is full
    pos = jnp.linspace(0, W * page_size - 1, B).astype(jnp.int32).reshape(B, 1)
    return q, pool_k, pool_v, bt, pos


@pytest.mark.parametrize("seed", [0, 1])
def test_fused_decode_matches_naive_bf16_pool(seed):
    q, pk, pv, bt, pos = _pool_case(seed)
    want = paged_cached_attention(q, pk, pv, bt, pos)
    got = paged_decode_attention(q, pk, pv, bt, pos, interpret=True)
    assert got.shape == want.shape and got.dtype == want.dtype
    assert _max_err(got, want) < 1e-5


@pytest.mark.parametrize("seed", [0, 1])
def test_fused_decode_matches_naive_int8_pool(seed):
    q, pk, pv, bt, pos = _pool_case(seed)
    qk, k_scale = quantize_kv_page(pk)
    qv, v_scale = quantize_kv_page(pv)
    want = paged_cached_attention(q, qk, qv, bt, pos, k_scale=k_scale, v_scale=v_scale)
    got = paged_decode_attention(
        q, qk, qv, bt, pos, k_scale=k_scale, v_scale=v_scale, interpret=True
    )
    assert _max_err(got, want) < 1e-5
    # and the int8 arm sits near the float result (quantization error only)
    ref = paged_cached_attention(q, pk, pv, bt, pos)
    assert _max_err(got, ref) < 0.05


def test_fused_decode_gqa_and_custom_scale():
    """Grouped heads (heads > kv_heads) with an explicit softmax scale."""
    q, pk, pv, bt, pos = _pool_case(3, heads=8, kv_heads=2, head_dim=16)
    want = paged_cached_attention(q, pk, pv, bt, pos, scale=0.5)
    got = paged_decode_attention(q, pk, pv, bt, pos, scale=0.5, interpret=True)
    assert _max_err(got, want) < 1e-5


def test_fused_decode_position_zero_row():
    """A row at position 0 (one visible token) must not NaN — the online
    softmax sees exactly one unmasked entry at w=0."""
    q, pk, pv, bt, pos = _pool_case(4)
    pos = jnp.zeros_like(pos)
    got = paged_decode_attention(q, pk, pv, bt, pos, interpret=True)
    want = paged_cached_attention(q, pk, pv, bt, pos)
    assert np.isfinite(np.asarray(got)).all()
    assert _max_err(got, want) < 1e-5


def _verify_case(seed, S, *, B=2, heads=4, kv_heads=2, head_dim=8, page_size=4, W=3):
    """A speculative verify window: S query tokens per row at consecutive
    positions, each row staggered so the visibility frontier lands at
    different page offsets (mid-page, page boundary, last page)."""
    q1, pk, pv, bt, base = _pool_case(seed, B=B, heads=heads, kv_heads=kv_heads,
                                      head_dim=head_dim, page_size=page_size, W=W)
    q = jax.random.normal(
        jax.random.PRNGKey(seed + 100), (B, S, heads, head_dim), jnp.float32
    )
    # per-token positions p..p+S-1, capped inside the table's capacity
    pos = jnp.minimum(base + jnp.arange(S)[None, :], W * page_size - 1)
    return q, pk, pv, bt, pos.astype(jnp.int32)


@pytest.mark.parametrize("S", [2, 4])
@pytest.mark.parametrize("seed", [0, 1])
def test_fused_verify_small_s_matches_naive(seed, S):
    """The speculative verify window: (B, S) queries at per-token positions
    must match the naive gather arm — each query row's visibility mask is
    independent, garbage beyond its own position stays masked."""
    q, pk, pv, bt, pos = _verify_case(seed, S)
    want = paged_cached_attention(q, pk, pv, bt, pos)
    got = paged_decode_attention(q, pk, pv, bt, pos, interpret=True)
    assert got.shape == want.shape and got.dtype == want.dtype
    assert _max_err(got, want) < 1e-5


def test_fused_verify_small_s_int8_pool():
    q, pk, pv, bt, pos = _verify_case(2, 4)
    qk, k_scale = quantize_kv_page(pk)
    qv, v_scale = quantize_kv_page(pv)
    want = paged_cached_attention(q, qk, qv, bt, pos, k_scale=k_scale, v_scale=v_scale)
    got = paged_decode_attention(
        q, qk, qv, bt, pos, k_scale=k_scale, v_scale=v_scale, interpret=True
    )
    assert _max_err(got, want) < 1e-5


def test_fused_verify_broadcast_positions():
    """(B,) / (B, 1) positions broadcast over the S query tokens — every
    token sees the same frontier, matching the naive arm fed (B, S)."""
    q, pk, pv, bt, pos1 = _pool_case(7)
    q = jnp.concatenate([q, q * 0.5, q * 2.0], axis=1)  # S=3
    want = paged_cached_attention(q, pk, pv, bt, jnp.broadcast_to(pos1, (q.shape[0], 3)))
    got_flat = paged_decode_attention(q, pk, pv, bt, pos1.reshape(-1), interpret=True)
    got_col = paged_decode_attention(q, pk, pv, bt, pos1, interpret=True)
    assert _max_err(got_flat, want) < 1e-5
    assert _max_err(got_col, want) < 1e-5


def test_fused_decode_requires_both_scales():
    q, pk, pv, bt, pos = _pool_case(6)
    qk, k_scale = quantize_kv_page(pk)
    with pytest.raises(ValueError, match="k_scale"):
        paged_decode_attention(q, qk, pv, bt, pos, k_scale=k_scale, interpret=True)


# ---------------------------------------------------------------------------
# dispatch (ops/attention_dispatch) — lora_dispatch mold
# ---------------------------------------------------------------------------


def test_estimate_arm_times_sane():
    t = estimate_arm_times(4, 1, 2048, 32, 8, 128, 16)
    assert set(t) == set(ARMS)
    assert all(v > 0 for v in t.values())
    # the fused arm moves strictly fewer bytes with fewer launches
    assert t["paged_decode"] < t["naive"]
    # int8 halves the cache traffic, so the fused estimate drops further
    t8 = estimate_arm_times(4, 1, 2048, 32, 8, 128, 16, kv_bytes=1)
    assert t8["paged_decode"] < t["paged_decode"]


def test_choose_arm_regimes():
    # single-token decode on TPU -> fused kernel
    assert choose_arm(4, 1, 2048, 32, 8, 128, 16) == "paged_decode"
    # same shape, fused unavailable (CPU) -> naive
    assert choose_arm(4, 1, 2048, 32, 8, 128, 16, fused_available=False) == "naive"
    # pure causal prefill, 128-aligned -> flash
    assert choose_arm(1, 512, 512, 32, 8, 128, 16) == "flash"
    # speculative verify window (small S) on TPU -> fused kernel
    assert choose_arm(4, 5, 2048, 32, 8, 128, 16) == "paged_decode"
    # chunked prefill (S beyond the verify cap): neither pallas arm applies
    assert choose_arm(1, 64, 512, 32, 8, 128, 16) == "naive"
    # allow= restricts the candidate set (the paged entry point never
    # considers flash — it is not servable from a pool)
    assert choose_arm(1, 512, 512, 32, 8, 128, 16, allow=("naive", "paged_decode")) == "naive"


def test_auto_never_interprets_on_cpu():
    """On a non-TPU backend, arm="auto" must not pick the fused interpreter."""
    assert jax.default_backend() != "tpu"
    arm = choose_arm(
        4, 1, 2048, 32, 8, 128, 16, fused_available=jax.default_backend() == "tpu"
    )
    assert arm != "paged_decode"


@pytest.mark.parametrize("quantized", [False, True], ids=["bf16", "int8"])
def test_dispatch_never_changes_numerics(quantized):
    """Every servable arm (and auto) produces the same value within
    tolerance — dispatch changes the compute graph, never the result."""
    q, pk, pv, bt, pos = _pool_case(7)
    kw = {}
    if quantized:
        pk, k_scale = quantize_kv_page(pk)
        pv, v_scale = quantize_kv_page(pv)
        kw = {"k_scale": k_scale, "v_scale": v_scale}
    want = paged_cached_attention(q, pk, pv, bt, pos, **kw)
    for arm in ("naive", "paged_decode", "auto"):
        got = paged_attention(q, pk, pv, bt, pos, arm=arm, interpret=True, **kw)
        assert _max_err(got, want) < 1e-5, f"arm={arm}"
    # auto on CPU resolves to the naive arm: bitwise-identical, no interpreter
    auto = paged_attention(q, pk, pv, bt, pos, arm="auto", **kw)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(want))


def test_dispatch_rejects_unknown_arm():
    q, pk, pv, bt, pos = _pool_case(8)
    with pytest.raises(ValueError, match="unknown/unservable"):
        paged_attention(q, pk, pv, bt, pos, arm="flash")


# ---------------------------------------------------------------------------
# training dispatch (choose_training_arm) — replaces the old
# RELORA_TPU_PALLAS_MIN_SEQ threshold with a fwd+bwd roofline ranking
# ---------------------------------------------------------------------------


def test_estimate_training_arm_times_ranking():
    """At the flagship training shape (B=4, S=1024, 16 heads, d=64) the
    fwd+bwd model must rank flash < xla < naive: flash skips masked causal
    blocks and never materializes the S² score matrix; naive pays f32 score
    traffic four ways."""
    t = estimate_training_arm_times(4, 1024, 16, 16, 64, act_bytes=2)
    assert set(t) == set(TRAIN_ARMS)
    assert all(v > 0 for v in t.values())
    assert t["flash"] < t["xla"] < t["naive"]
    # the backward roughly triples every arm's cost, preserving the order
    fwd = estimate_training_arm_times(4, 1024, 16, 16, 64, act_bytes=2, with_backward=False)
    assert all(t[a] > fwd[a] for a in TRAIN_ARMS)
    assert fwd["flash"] < fwd["xla"] < fwd["naive"]


def test_choose_training_arm_regimes():
    # flagship shape on TPU -> flash kernel
    assert choose_training_arm(4, 1024, 16, 16, 64) == "flash"
    # same shape off-TPU: flash struck, xla wins (never naive)
    assert choose_training_arm(4, 1024, 16, 16, 64, fused_available=False) == "xla"
    # non-128-tileable S strikes flash even with the kernel available
    assert choose_training_arm(4, 96, 16, 16, 64) != "flash"
    # allow= restricts the candidate set
    assert choose_training_arm(4, 1024, 16, 16, 64, allow=("naive",)) == "naive"
    # empty candidate set degrades to the safe default
    assert (
        choose_training_arm(4, 1024, 16, 16, 64, fused_available=False, allow=("flash",))
        == "xla"
    )


def _train_qkv(seed, *, B=2, S=64, heads=4, kv_heads=2, head_dim=8):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, heads, head_dim), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, kv_heads, head_dim), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, kv_heads, head_dim), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("impl", ["xla", "pallas", "auto"])
def test_training_forced_arm_parity(impl):
    """Every CPU-runnable training arm matches the naive f32 oracle in value
    AND gradient — dispatch changes the compute graph, never the result.
    (``pallas`` at this sub-tile S exercises the kernel's fused-XLA fallback;
    the on-TPU kernel itself is held to the same oracle by the bench's
    attention mode.)"""
    q, k, v = _train_qkv(0)
    want = dot_product_attention(q, k, v, impl="naive")
    got = dot_product_attention(q, k, v, impl=impl)
    assert got.shape == want.shape
    assert _max_err(got, want) < 1e-5, f"impl={impl}"

    def loss(fn_impl):
        return lambda qq: jnp.sum(dot_product_attention(qq, k, v, impl=fn_impl) ** 2)

    g_want = jax.grad(loss("naive"))(q)
    g_got = jax.grad(loss(impl))(q)
    assert _max_err(g_got, g_want) < 1e-4, f"impl={impl} (backward)"


def test_training_auto_on_cpu_is_xla_bitwise():
    """Off-TPU the dispatcher must resolve auto to the xla arm (flash is
    struck, and the model ranks xla under naive) — bitwise, no interpreter."""
    assert jax.default_backend() != "tpu"
    q, k, v = _train_qkv(1)
    auto = dot_product_attention(q, k, v, impl="auto")
    forced = dot_product_attention(q, k, v, impl="xla")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(forced))
