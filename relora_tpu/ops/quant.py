"""Int8 / NF4 quantization for frozen base weights.

TPU-native replacement for the reference's bitsandbytes 4/8-bit path
(relora.py:10-11, 222-238):

- **int8**: per-output-channel symmetric absmax — 1 byte/element, the fast
  simple mode.
- **nf4**: 4-bit NormalFloat codes (the bitsandbytes ``nf4`` data type:
  a 16-entry codebook of normal-distribution quantiles) with blockwise
  absmax scales, two codes packed per uint8 byte — ~0.53 bytes/element.
  With **double quantization** (``use_double_quant``, relora.py:57-63 →
  bnb ``bnb_4bit_use_double_quant``) the per-block f32 scales are
  themselves int8-quantized against a per-output-channel offset+scale,
  cutting scale overhead 4×.

Forward dequantizes into the compute dtype — XLA fuses the dequant into the
matmul epilogue — and merge-and-reinit does dequant → add ΔW → requant, the
same flow as the reference's 4-bit merge (relora.py:277-287).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def quantize_int8(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(..., in, out) float -> (int8 codes, f32 per-out-channel scales)."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# int8 KV-cache pages (serving): per-page, per-kv-head symmetric absmax
# ---------------------------------------------------------------------------


def quantize_kv_page(kv: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``(..., page_size, n_kv, head_dim)`` float -> (int8 codes, f32 scales).

    One symmetric absmax scale per ``(page, kv_head)``: the scale reduces
    over the token (page_size) and head_dim axes, so codes shape matches the
    input and scales drop those two axes — ``(..., n_kv)``.  K and V
    statistics differ per head but are stable within a page (16 consecutive
    tokens of one request), which is why this granularity holds greedy token
    parity while costing ``n_kv`` floats per page against
    ``page_size x n_kv x head_dim`` bytes of codes.

    The serving write path (models/llama.attend_with_paged_cache) maintains
    the same scales *incrementally* — pages fill one chunk or decode token at
    a time — as a running max with in-place requantization of the already
    written codes whenever a page's absmax grows; this function is the
    whole-page reference those writes must agree with, and the round-trip
    error-bound oracle for tests.
    """
    kv32 = kv.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(kv32), axis=(-3, -1))  # (..., n_kv)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(kv32 / scale[..., None, :, None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_kv_page(q: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_kv_page`: codes ``(..., page_size, n_kv,
    head_dim)`` x scales ``(..., n_kv)`` -> float pages in ``dtype``."""
    return (q.astype(jnp.float32) * scale[..., None, :, None]).astype(dtype)


# ---------------------------------------------------------------------------
# NF4: 4-bit NormalFloat (QLoRA) with blockwise scales + double quantization
# ---------------------------------------------------------------------------

# the bitsandbytes nf4 codebook: 16 quantiles of N(0,1) normalized to [-1, 1].
# numpy (not jnp): this module may be first imported inside a jit trace, and a
# module-level jnp constant created there would be a tracer that outlives it.
import numpy as _np  # noqa: E402

NF4_CODEBOOK = _np.asarray(
    [
        -1.0,
        -0.6961928009986877,
        -0.5250730514526367,
        -0.39491748809814453,
        -0.28444138169288635,
        -0.18477343022823334,
        -0.09105003625154495,
        0.0,
        0.07958029955625534,
        0.16093020141124725,
        0.24611230194568634,
        0.33791524171829224,
        0.44070982933044434,
        0.5626170039176941,
        0.7229568362236023,
        1.0,
    ],
    dtype=_np.float32,
)

NF4_BLOCK = 64  # bnb default blocksize for 4-bit


def nf4_block_for(in_features: int, block: int = NF4_BLOCK) -> int:
    """Largest power-of-two <= ``block`` dividing ``in_features`` (bnb pads
    the flattened tensor instead; per-column blocks make padding awkward, so
    odd widths get proportionally more scales — slightly more accurate,
    slightly more scale overhead)."""
    b = block
    while b > 1 and in_features % b:
        b //= 2
    if in_features % b or in_features % 2:
        raise ValueError(f"nf4 needs even in_features, got {in_features}")
    return b


def _nf4_encode(x: jax.Array) -> jax.Array:
    """Nearest-codebook-entry index for values in [-1, 1] via the midpoint
    boundaries (15 comparisons, vectorized)."""
    mids = (NF4_CODEBOOK[1:] + NF4_CODEBOOK[:-1]) / 2.0
    return jnp.sum(x[..., None] > mids, axis=-1).astype(jnp.uint8)


def quantize_nf4(
    w: jax.Array, *, block: int = NF4_BLOCK, double_quant: bool = True
) -> Dict[str, jax.Array]:
    """(in, out) float -> packed nf4 leaves.

    Returns a dict of arrays (the LoRALinear param leaves):

    - ``codes``  (in//2, out) uint8 — two 4-bit codes per byte along the
      *in* axis (low nibble = even row, high nibble = odd row)
    - ``bscale_q`` (in//block, out) int8 (double_quant) or f32 (not)
    - ``bscale_scale`` / ``bscale_offset`` (1, out) f32 — only meaningful
      under double_quant (identity values otherwise, kept for a stable
      pytree structure)

    Leading axes (scan-stacked layer kernels) are vmapped over.
    """
    if w.ndim > 2:
        return jax.vmap(
            lambda ww: quantize_nf4(ww, block=block, double_quant=double_quant)
        )(w)
    in_f, out_f = w.shape
    block = nf4_block_for(in_f, block)
    w32 = w.astype(jnp.float32)
    blocks = w32.reshape(in_f // block, block, out_f)
    absmax = jnp.max(jnp.abs(blocks), axis=1)  # (nb, out)
    bscale = jnp.maximum(absmax, 1e-12)
    normalized = blocks / bscale[:, None, :]
    idx = _nf4_encode(normalized).reshape(in_f, out_f)
    low = idx[0::2]
    high = idx[1::2]
    codes = (low | (high << 4)).astype(jnp.uint8)

    if double_quant:
        offset = jnp.mean(bscale, axis=0, keepdims=True)  # (1, out)
        resid = bscale - offset
        s2 = jnp.maximum(jnp.max(jnp.abs(resid), axis=0, keepdims=True) / 127.0, 1e-12)
        bscale_q = jnp.clip(jnp.round(resid / s2), -127, 127).astype(jnp.int8)
        return {
            "codes": codes,
            "bscale_q": bscale_q,
            "bscale_scale": s2.astype(jnp.float32),
            "bscale_offset": offset.astype(jnp.float32),
        }
    return {
        "codes": codes,
        "bscale_q": bscale.astype(jnp.float32),
        "bscale_scale": jnp.ones((1, out_f), jnp.float32),
        "bscale_offset": jnp.zeros((1, out_f), jnp.float32),
    }


def dequantize_nf4(leaves: Dict[str, jax.Array], dtype=jnp.float32) -> jax.Array:
    """Inverse of ``quantize_nf4`` -> (in, out) array in ``dtype``."""
    codes = leaves["codes"]
    if codes.ndim > 2:
        return jax.vmap(lambda lv: dequantize_nf4(lv, dtype))(leaves)
    half, out_f = codes.shape
    low = (codes & 0xF).astype(jnp.int32)
    high = (codes >> 4).astype(jnp.int32)
    idx = jnp.stack([low, high], axis=1).reshape(half * 2, out_f)
    vals = jnp.asarray(NF4_CODEBOOK)[idx]  # (in, out) in [-1, 1]
    bscale_q = leaves["bscale_q"]
    if bscale_q.dtype == jnp.int8:
        bscale = (
            bscale_q.astype(jnp.float32) * leaves["bscale_scale"] + leaves["bscale_offset"]
        )
    else:
        bscale = bscale_q
    nb = bscale.shape[0]
    block = (half * 2) // nb
    w = vals.reshape(nb, block, out_f) * bscale[:, None, :]
    return w.reshape(half * 2, out_f).astype(dtype)


# the module-param-name <-> quantize_nf4-leaf-name correspondence, shared by
# merge/graft/export so a new nf4 leaf only needs to be added here
NF4_MODULE_LEAVES = {
    "kernel_codes": "codes",
    "kernel_bscale_q": "bscale_q",
    "kernel_bscale_scale": "bscale_scale",
    "kernel_bscale_offset": "bscale_offset",
}


def nf4_leaves_from_module(module: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Pull the nf4 leaves out of a LoRALinear param dict."""
    return {leaf: module[param] for param, leaf in NF4_MODULE_LEAVES.items()}


def nf4_leaves_to_module(leaves: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Inverse of ``nf4_leaves_from_module`` (param-dict key names)."""
    return {param: leaves[leaf] for param, leaf in NF4_MODULE_LEAVES.items()}


def quant_bytes_per_param(mode: str, in_f: int, out_f: int, block: int = NF4_BLOCK) -> float:
    """Stored bytes per weight element for an (in, out) kernel under
    ``mode`` — the HBM-footprint arithmetic used by tests and tools."""
    n = in_f * out_f
    if mode == "int8":
        return (n + 4 * out_f) / n
    if mode == "nf4":  # double-quant layout
        return (n / 2 + (in_f // block) * out_f + 8 * out_f) / n
    if mode == "nf4-f32scale":
        return (n / 2 + 4 * (in_f // block) * out_f + 8 * out_f) / n
    raise ValueError(f"unknown mode {mode!r}")
