"""Which code counts as "hot path" for the RTL2xx host-sync rules.

Hot means: executed once per training update or once per decode step, where
a single stray ``.item()`` / ``np.asarray`` blocks the host on the device
(through a TPU tunnel, for milliseconds per hit) every single step.  Code
at save/eval/merge cadence is *not* hot — syncs there are intentional and
either live in non-hot helper functions or carry a baseline justification.

Three ways a region becomes hot, checked in order:

1. the file's repo-relative path ends with a key of :data:`HOT_FUNCTIONS`
   and the enclosing function's qualname matches one of the listed
   prefixes (an empty-string prefix marks the whole file, module level
   included);
2. the file contains the literal marker comment ``relora-lint: hot-path``
   (whole file; used by fixtures and by new modules that want the strict
   rules without editing this table);
3. the ``FileContext`` was built with ``force_hot=True`` (tests).

The sanctioned fix for a genuine sync need is to move it into a helper
*outside* the hot functions, called at a logging/metrics cadence —
``train/trainer._pull_metric_records`` is the model citizen.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from relora_tpu.analysis.core import FileContext

#: repo-relative path suffix -> hot function qualname prefixes ("" = whole file)
HOT_FUNCTIONS: Dict[str, List[str]] = {
    "relora_tpu/train/step.py": [""],  # every step builder is jitted hot code
    # kernel + dispatch modules: traced inside every LoRA linear, training
    # and decode both — a host sync here hits once per layer per step
    "relora_tpu/ops/pallas_lora_matmul.py": [""],
    "relora_tpu/ops/lora_dispatch.py": [""],
    "relora_tpu/ops/pallas_quant_matmul.py": [""],
    "relora_tpu/train/trainer.py": [
        "Trainer.fit",  # the update loop, including nested closures
        "Trainer._prefetched",
        "Trainer.evaluate",  # per-batch eval loop (syncs every sync_every)
    ],
    # decode attention (contiguous + paged gather) traces inside every
    # decode step; a host sync here stalls every active stream
    "relora_tpu/ops/attention.py": [
        "cached_attention",
        "gather_kv_pages",
        "paged_cached_attention",
        "dequantize_gathered_pages",
        "paged_decode_attention",
    ],
    "relora_tpu/ops/attention_dispatch.py": [""],
    "relora_tpu/serve/engine.py": [
        "InferenceEngine.prefill",
        "InferenceEngine.decode",
        "InferenceEngine.insert",
        "InferenceEngine.init_cache",
        "InferenceEngine.prefill_chunk",  # paged: once per round
        "InferenceEngine.decode_paged",  # paged: every decode step
        "InferenceEngine.init_pool",
        "InferenceEngine._row_idx",  # adapter routing, once per prefill/decode
        # model-drafted speculation: the draft forward runs per prefill
        # chunk / per draft-proposal step, right inside the round
        "InferenceEngine.draft_prefill_chunk",
        "InferenceEngine.draft_decode_paged",
    ],
    # multi-tenant registry: acquire/release run inside the schedulers' admit
    # and retire passes, once per request per round.  Loads and evictions do
    # intentional device writes at swap cadence in _load_into — a separate
    # non-hot helper, following the sanctioned pattern above.
    "relora_tpu/serve/adapters.py": [
        "AdapterRegistry.acquire",
        "AdapterRegistry.release",
    ],
    "relora_tpu/serve/sampling.py": [""],  # jitted per decode step
    # serve/paging.py carries the HOT_MARKER comment instead of an entry
    # here: the allocator + prefix cache run on every paged admit/retire
    "relora_tpu/serve/scheduler.py": [
        "ContinuousBatchingScheduler.run",  # the drain loop
        "ContinuousBatchingScheduler.step",  # one admit-plus-decode round
        "ContinuousBatchingScheduler._sample_rows",  # per decode step
        "PagedContinuousBatchingScheduler.step",  # one budgeted round
        "PagedContinuousBatchingScheduler._admit_pass",  # per round
        "PagedContinuousBatchingScheduler._prefill_pass",  # per round
        # --spec model: K autoregressive draft forwards per decode round
        "PagedContinuousBatchingScheduler._model_draft_pass",
        "ContinuousBatchingScheduler._acquire_adapter",  # per admitted request
        "ContinuousBatchingScheduler._release_adapter",  # per retired request
        # disaggregation seams that run on the model thread, inside the
        # round: export-and-park after a prefill finishes, adopt-and-resume
        # on the receiver, peer prefix fetch during admission.  The async
        # transfer itself (server._migrate_task and the /internal handlers)
        # is event-loop code that never touches device values — deliberately
        # NOT hot, same scoping as the rest of the HTTP front-end.
        "PagedContinuousBatchingScheduler._maybe_migrate",
        "PagedContinuousBatchingScheduler.submit_migrated",
        "PagedContinuousBatchingScheduler._fetch_prefix",
        "PagedContinuousBatchingScheduler.migration_commit",
        "PagedContinuousBatchingScheduler.migration_failed",
        "PagedContinuousBatchingScheduler.migration_abort",
    ],
    # role classification and the fleet prefix-page directory run per
    # routed request / per collector scrape on threads adjacent to the
    # serving plane; wire.py (framing) is transfer-cadence and stays cold
    "relora_tpu/serve/disagg.py": [
        "classify_request",
        "PrefixPageDirectory.update",
        "PrefixPageDirectory.lookup",
        "pick_peers",
    ],
    # the HTTP front-end's model thread calls scheduler.step() in a loop; a
    # stray sync there stalls every in-flight stream.  The asyncio handlers
    # and admission.py are host-side code that never touches device values —
    # deliberately NOT hot, so RTL2xx stays scoped to the decode loop.
    "relora_tpu/serve/server.py": [
        "GenerateServer._model_loop",
        "GenerateServer._drain_disagg_inbox",  # runs inside _model_loop's round
    ],
    # the tracer/metrics/flight-recorder run INSIDE the hot loops above (a
    # few spans per decode step / train update) — stdlib-only by design;
    # marking them hot keeps device syncs and hot-loop footguns out
    "relora_tpu/obs/tracer.py": [""],
    "relora_tpu/obs/metrics.py": [""],
    "relora_tpu/obs/flight.py": [""],
    # compile watcher wraps every jitted entry point (its __call__ runs per
    # train update and per decode step); the memory poller is cadence-gated
    # by contract — hot registration keeps device syncs out of both
    "relora_tpu/obs/compile.py": [""],
    "relora_tpu/obs/memory.py": [""],
    # fleet-tier entry points (PR-18 drift fix): these run once per scrape /
    # scale decision / monitor tick, not per decode step, but they execute on
    # dedicated threads next to the model loop — a device sync or hot-loop
    # footgun here stalls the serving plane just the same.  Registration also
    # puts them under the RTL6xx thread-root analysis via the call graph.
    "relora_tpu/serve/autoscale.py": [
        "Autoscaler._loop",
        "Autoscaler.step",
        "AutoscalerPolicy.decide",
    ],
    "relora_tpu/serve/deploy.py": [
        "CheckpointWatcher._run",
        "CheckpointWatcher.poll_once",
        "RollingUpdater.run",
    ],
    "relora_tpu/serve/supervisor.py": [
        "ReplicaSupervisor.scale_up",
        "ReplicaSupervisor.scale_down",
        "ReplicaSupervisor._monitor_loop",
        "ReplicaSupervisor._check",
    ],
    "relora_tpu/train/elastic.py": [
        "reshard_tree",
        "restore_resharded",
    ],
    "relora_tpu/obs/fleet.py": [
        "FleetCollector._loop",
        "FleetCollector.scrape_once",
        "FleetCollector._scrape_target",
        "FleetCollector._ingest_metrics",
        "SeriesStore.add_samples",
        "SeriesStore.add_event",
    ],
}

HOT_MARKER = "relora-lint: hot-path"


def hot_prefixes(ctx: FileContext) -> Sequence[str]:
    """Hot qualname prefixes for this file; empty sequence = nothing hot.
    A [""] result marks the whole file (module level included)."""
    if ctx.force_hot or HOT_MARKER in ctx.text:
        return [""]
    for suffix, prefixes in HOT_FUNCTIONS.items():
        if ctx.relpath.endswith(suffix):
            return prefixes
    return ()


def qualname_is_hot(qualname: str, prefixes: Sequence[str]) -> bool:
    for prefix in prefixes:
        if prefix == "":
            return True
        if qualname == prefix or qualname.startswith(prefix + "."):
            return True
    return False
