"""Rule engine for the RTL footgun linter (stdlib-``ast``, no deps).

The analysis is organized as *checkers* — functions ``(FileContext) ->
Iterable[Finding]`` registered with :func:`checker` — each of which may emit
findings for one or more rule codes declared in :data:`RULE_CATALOG`.  A
finding is identified for suppression purposes by ``(relpath, code,
stripped source line)``: line *text*, not line *number*, so baselines
survive unrelated edits above the finding.

Two suppression layers:

- inline ``# noqa: RTL###`` (or a bare ``# noqa``) on the offending line,
  for one-off intentional violations that a reader of the code should see;
- the checked-in baseline file (``tools/lint_baseline.txt``) for
  grandfathered findings, one per line with a mandatory justification::

      relora_tpu/train/trainer.py | RTL203 | jax.block_until_ready(...) | merge cadence, timed for logging

  New findings (not baselined, not noqa'd) fail the lint.  Baseline entries
  that no longer match anything are reported as stale so the file must
  shrink as violations are fixed, never silently rot.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Sequence, Union

# code -> one-line summary; every Finding.code must be declared here
RULE_CATALOG: Dict[str, str] = {}
CHECKERS: List[Callable[["FileContext"], Iterable["Finding"]]] = []

#: sentinel for a bare ``# noqa`` (suppresses every rule on that line)
ALL_CODES: FrozenSet[str] = frozenset({"*"})

_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>RTL\d+(?:\s*,\s*RTL\d+)*))?", re.IGNORECASE
)


def catalog(**rules: str) -> None:
    """Declare rule codes (``RTL101="summary"``); called at module import."""
    for code, summary in rules.items():
        RULE_CATALOG[code] = summary


def checker(fn: Callable[["FileContext"], Iterable["Finding"]]):
    CHECKERS.append(fn)
    return fn


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, posix separators
    line: int
    code: str
    message: str
    line_text: str  # stripped source of the offending line (baseline identity)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class FileContext:
    """One parsed file plus the per-line suppression map."""

    def __init__(self, path: str, relpath: str, text: str, force_hot: bool = False):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        self.force_hot = force_hot
        self._noqa: Dict[int, FrozenSet[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _NOQA_RE.search(line)
            if m:
                codes = m.group("codes")
                self._noqa[i] = (
                    frozenset(c.strip().upper() for c in codes.split(","))
                    if codes
                    else ALL_CODES
                )

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno: int, code: str) -> bool:
        codes = self._noqa.get(lineno)
        return codes is not None and (codes is ALL_CODES or code in codes)

    def finding(self, node: ast.AST, code: str, message: str) -> Finding:
        assert code in RULE_CATALOG, f"undeclared rule code {code}"
        lineno = getattr(node, "lineno", 1)
        return Finding(self.relpath, lineno, code, message, self.line_text(lineno))


# ---------------------------------------------------------------------------
# baseline


@dataclasses.dataclass
class BaselineEntry:
    path: str
    code: str
    snippet: str
    justification: str
    lineno: int  # line in the baseline file (for stale reports)

    def matches(self, f: Finding) -> bool:
        return (
            f.path == self.path and f.code == self.code and f.line_text == self.snippet
        )


def load_baseline(path: str) -> List[BaselineEntry]:
    entries: List[BaselineEntry] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split("|", 3)]
            if len(parts) != 4 or not parts[3]:
                raise ValueError(
                    f"{path}:{lineno}: baseline entries are "
                    f"'path | RTL### | source line | justification' "
                    f"(justification is mandatory)"
                )
            entries.append(BaselineEntry(parts[0], parts[1], parts[2], parts[3], lineno))
    return entries


def format_baseline_entry(f: Finding, justification: str = "TODO: justify") -> str:
    return f"{f.path} | {f.code} | {f.line_text} | {justification}"


# ---------------------------------------------------------------------------
# runner


@dataclasses.dataclass
class Report:
    findings: List[Finding]  # everything the rules produced (pre-suppression)
    new: List[Finding]  # not noqa'd, not baselined -> these fail the lint
    noqa_suppressed: int
    baselined: int
    stale_baseline: List[BaselineEntry]
    files_scanned: int
    parse_errors: List[str]

    @property
    def rule_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        return dict(sorted(counts.items()))


def lint_context(ctx: FileContext) -> List[Finding]:
    found: List[Finding] = []
    for check in CHECKERS:
        found.extend(check(ctx))
    return sorted(found, key=lambda f: (f.path, f.line, f.code))


def lint_text(
    text: str, relpath: str = "<text>", *, force_hot: bool = False
) -> List[Finding]:
    """Lint a source string (fixture/test entry point).  Returns raw
    findings; ``# noqa`` suppression is applied, the baseline is not."""
    ctx = FileContext(relpath, relpath, text, force_hot=force_hot)
    return [f for f in lint_context(ctx) if not ctx.suppressed(f.line, f.code)]


def _iter_py_files(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        yield path
        return
    skip = {".git", "__pycache__", ".venv", "node_modules", "build", "dist"}
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(d for d in dirnames if d not in skip)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    baseline: Union[str, Sequence[BaselineEntry], None] = None,
) -> Report:
    """Lint files/trees; relpaths (finding + baseline identity) are taken
    relative to ``root`` (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    entries: List[BaselineEntry] = []
    if isinstance(baseline, str):
        entries = load_baseline(baseline)
    elif baseline:
        entries = list(baseline)

    all_findings: List[Finding] = []
    new: List[Finding] = []
    noqa_count = 0
    baselined_count = 0
    used = [False] * len(entries)
    files = 0
    parse_errors: List[str] = []

    for path in paths:
        for fpath in _iter_py_files(path):
            abspath = os.path.abspath(fpath)
            relpath = os.path.relpath(abspath, root)
            try:
                with open(abspath, encoding="utf-8") as fh:
                    text = fh.read()
                ctx = FileContext(abspath, relpath, text)
            except (SyntaxError, UnicodeDecodeError) as e:
                parse_errors.append(f"{relpath}: {e}")
                continue
            files += 1
            for f in lint_context(ctx):
                all_findings.append(f)
                if ctx.suppressed(f.line, f.code):
                    noqa_count += 1
                    continue
                matched = False
                for i, entry in enumerate(entries):
                    if entry.matches(f):
                        used[i] = True
                        matched = True
                        break
                if matched:
                    baselined_count += 1
                else:
                    new.append(f)

    stale = [e for e, u in zip(entries, used) if not u]
    return Report(
        findings=all_findings,
        new=sorted(new, key=lambda f: (f.path, f.line, f.code)),
        noqa_suppressed=noqa_count,
        baselined=baselined_count,
        stale_baseline=stale,
        files_scanned=files,
        parse_errors=parse_errors,
    )


# ---------------------------------------------------------------------------
# shared AST helpers used by the rule modules


def dotted_name(node: ast.AST) -> str:
    """'jax.random.PRNGKey' for nested Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def target_path(node: ast.AST) -> str:
    """Dotted path for assignable/loadable chains rooted at a Name
    ('self.state.params'); '' for anything else (calls, subscripts...)."""
    return dotted_name(node)


def const_int_set(node: ast.AST) -> Optional[FrozenSet[int]]:
    """The set of ints in a literal int / tuple-or-list-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                vals.add(elt.value)
            else:
                return None
        return frozenset(vals)
    return None


def const_str_set(node: ast.AST) -> Optional[FrozenSet[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.add(elt.value)
            else:
                return None
        return frozenset(vals)
    return None


def get_kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


JIT_NAMES = frozenset({"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"})


def is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in JIT_NAMES


def unwrap_partial(node: ast.AST) -> Optional[ast.Call]:
    """``functools.partial(jax.jit, ...)`` / ``partial(jit, ...)`` as a
    pseudo jit-Call (kwargs of the partial are the jit kwargs)."""
    if (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in ("partial", "functools.partial")
        and node.args
        and dotted_name(node.args[0]) in JIT_NAMES
    ):
        return node
    return None


class QualnameVisitor(ast.NodeVisitor):
    """Base visitor tracking the dotted qualname of the enclosing
    function/class scope ('Trainer.fit.flush_pending')."""

    def __init__(self) -> None:
        self.stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self.stack)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func
