"""Runtime build + ctypes bindings for the native index builders.

Parity with the reference's runtime ``make`` hook
(megatron_dataset/data_utils.py:470-482, Makefile): the shared object is
compiled on first use with g++ and cached next to the source; if compilation
fails (no compiler on some hosts) callers fall back to the NumPy
implementations automatically.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "helpers.cpp")
_SO = os.path.join(_DIR, "_helpers.so")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _compile() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:
        logger.warning(f"native helpers build failed ({e}); using NumPy fallbacks")
        return False


def load() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the helpers library; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        src_mtime = os.path.getmtime(_SRC)
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime:
            if not _compile():
                return None
        lib = ctypes.CDLL(_SO)

        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")

        lib.relora_build_sample_idx_i32.argtypes = [
            i32p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64, i32p
        ]
        lib.relora_build_sample_idx_i32.restype = ctypes.c_int
        lib.relora_build_sample_idx_i64.argtypes = [
            i32p, i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64, i64p
        ]
        lib.relora_build_sample_idx_i64.restype = ctypes.c_int
        lib.relora_build_blending_indices.argtypes = [
            u8p, i64p, f64p, ctypes.c_int32, ctypes.c_int64
        ]
        lib.relora_build_blending_indices.restype = None
        lib.relora_shuffle_i64.argtypes = [i64p, ctypes.c_int64, ctypes.c_uint64]
        lib.relora_shuffle_i64.restype = None
        bert_args = [
            i64p, ctypes.c_int64, i32p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_double, ctypes.c_uint32,
        ]
        lib.relora_count_bert_mapping.argtypes = list(bert_args)
        lib.relora_count_bert_mapping.restype = ctypes.c_int64
        lib.relora_fill_bert_mapping.argtypes = list(bert_args) + [i64p]
        lib.relora_fill_bert_mapping.restype = None
        blocks_args = [
            i64p, ctypes.c_int64, i32p, i32p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
        ]
        lib.relora_count_blocks_mapping.argtypes = list(blocks_args)
        lib.relora_count_blocks_mapping.restype = ctypes.c_int64
        lib.relora_fill_blocks_mapping.argtypes = list(blocks_args) + [ctypes.c_uint32, i64p]
        lib.relora_fill_blocks_mapping.restype = None
        _LIB = lib
        return _LIB


def build_sample_idx_native(
    sizes: np.ndarray, doc_idx: np.ndarray, seq_length: int, num_samples: int
) -> Optional[np.ndarray]:
    """C++ sample-index packing; None if the native lib is unavailable.
    Uses int32 output when it fits (parity: dataset.py:189-203 dtype switch)."""
    lib = load()
    if lib is None:
        return None
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    use_i32 = (
        len(doc_idx) <= np.iinfo(np.int32).max
        and int(sizes.max(initial=0)) <= np.iinfo(np.int32).max
    )
    if use_i32:
        doc = np.ascontiguousarray(doc_idx, dtype=np.int32)
        out = np.zeros((num_samples + 1, 2), dtype=np.int32)
        rc = lib.relora_build_sample_idx_i32(
            sizes, doc, len(doc), seq_length, num_samples, out.reshape(-1)
        )
    else:
        doc = np.ascontiguousarray(doc_idx, dtype=np.int64)
        out = np.zeros((num_samples + 1, 2), dtype=np.int64)
        rc = lib.relora_build_sample_idx_i64(
            sizes, doc, len(doc), seq_length, num_samples, out.reshape(-1)
        )
    if rc != 0:
        raise ValueError(
            "document list exhausted while packing samples — sizes/doc_idx "
            "inconsistent with num_samples"
        )
    return out


def build_bert_mapping(
    docs: np.ndarray,
    sizes: np.ndarray,
    *,
    num_epochs: int,
    max_num_samples: int,
    max_seq_length: int,
    short_seq_prob: float,
    seed: int,
) -> Optional[np.ndarray]:
    """BERT-style span mapping (parity: helpers.cpp build_mapping :261-511).
    Rows are (first_sentence, end_sentence, target_len), shuffled
    deterministically by seed."""
    lib = load()
    if lib is None:
        return None
    docs = np.ascontiguousarray(docs, dtype=np.int64)
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    n_docs = len(docs) - 1
    args = (docs, n_docs, sizes, num_epochs, max_num_samples, max_seq_length, short_seq_prob, seed)
    n = lib.relora_count_bert_mapping(*args)
    maps = np.zeros((n, 3), dtype=np.int64)
    if n:
        lib.relora_fill_bert_mapping(*args, maps.reshape(-1))
    return maps


def build_blocks_mapping(
    docs: np.ndarray,
    sizes: np.ndarray,
    titles_sizes: np.ndarray,
    *,
    num_epochs: int,
    max_num_samples: int,
    max_seq_length: int,
    seed: int,
    use_one_sent_blocks: bool = False,
) -> Optional[np.ndarray]:
    """Block-span mapping, bit-identical to the reference's
    build_blocks_mapping (helpers.cpp:513-747) — golden-tested against its
    compiled module (tests/test_data_megatron.py).

    Rows are (span_start_sentence, span_end_sentence, doc, block_id), where
    the per-document target length is ``max_seq_length - titles_sizes[doc]``
    and block_id is a per-epoch running id; rows come Fisher-Yates shuffled
    with mt19937_64(seed + 1), exactly like the reference.  The output dtype
    follows the reference's rule: uint32 when the sentence count fits, else
    uint64."""
    lib = load()
    if lib is None:
        return None
    docs = np.ascontiguousarray(docs, dtype=np.int64)
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    titles_sizes = np.ascontiguousarray(titles_sizes, dtype=np.int32)
    n_docs = len(docs) - 1
    args = (
        docs, n_docs, sizes, titles_sizes, num_epochs, max_num_samples,
        max_seq_length, int(use_one_sent_blocks),
    )
    n = lib.relora_count_blocks_mapping(*args)
    maps = np.zeros((n, 4), dtype=np.int64)
    if n:
        lib.relora_fill_blocks_mapping(*args, seed, maps.reshape(-1))
    out_dtype = np.uint32 if len(sizes) <= np.iinfo(np.uint32).max else np.uint64
    return maps.astype(out_dtype)


def build_blending_indices_native(
    weights: np.ndarray, size: int
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    lib = load()
    if lib is None:
        return None
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    dataset_index = np.zeros(size, dtype=np.uint8)
    dataset_sample_index = np.zeros(size, dtype=np.int64)
    lib.relora_build_blending_indices(
        dataset_index, dataset_sample_index, weights, len(weights), size
    )
    return dataset_index, dataset_sample_index
