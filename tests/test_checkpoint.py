"""Checkpoint layer unit tests: async save/restore roundtrip, resharding
restore under a different device layout, and commit-awareness of the
autoresume probe."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from relora_tpu.parallel.mesh import MeshSpec, make_mesh
from relora_tpu.train import checkpoint as ckpt
from relora_tpu.train.state import TrainState


def make_state(mesh, fsdp_axis_parts):
    sharding = NamedSharding(mesh, P("fsdp", None))
    params = {
        "layer": {
            "kernel": jax.device_put(
                jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8), sharding
            ),
            "bias": jnp.ones((8,), jnp.float32),
        }
    }
    opt_state = {"mu": jax.tree_util.tree_map(jnp.zeros_like, params)}
    return TrainState.create(params, opt_state)


def test_async_save_restore_roundtrip(tmp_path, devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh, 8)
    path = ckpt.save_checkpoint(
        str(tmp_path), 10, state, {"update_step": 10, "global_step": 10}
    )
    # async write: the JSON lands immediately, the state dir commits in the
    # background; wait_for_save fences it
    ckpt.wait_for_save()
    assert os.path.isdir(os.path.join(path, ckpt.STATE_SUBDIR))

    restored = ckpt.restore_checkpoint(path, jax.eval_shape(lambda: state))
    np.testing.assert_array_equal(
        np.asarray(restored.params["layer"]["kernel"]),
        np.asarray(state.params["layer"]["kernel"]),
    )


def test_restore_under_different_device_layout(tmp_path, devices):
    """Save sharded fsdp=8, restore onto an fsdp=2 mesh (the device-count
    change scenario: pod resize between save and resume)."""
    mesh8 = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh8, 8)
    path = ckpt.save_checkpoint(str(tmp_path), 5, state, {"update_step": 5})
    ckpt.wait_for_save()

    mesh2 = make_mesh(MeshSpec(data=1, fsdp=2))
    target_sharding = NamedSharding(mesh2, P("fsdp", None))

    def abstract():
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=target_sharding)
            if x.ndim == 2
            else jax.ShapeDtypeStruct(x.shape, x.dtype),
            state,
        )

    restored = ckpt.restore_checkpoint(path, abstract())
    kernel = restored.params["layer"]["kernel"]
    assert kernel.sharding.mesh.shape["fsdp"] == 2
    np.testing.assert_array_equal(
        np.asarray(kernel), np.arange(64.0, dtype=np.float32).reshape(8, 8)
    )

    # topology-free host restore also works (warm starts / offline tools)
    host = ckpt.restore_state_host(path)
    np.testing.assert_array_equal(
        np.asarray(host["params"]["layer"]["kernel"]),
        np.arange(64.0, dtype=np.float32).reshape(8, 8),
    )


def test_get_last_checkpoint_skips_uncommitted(tmp_path, devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh, 8)
    ckpt.save_checkpoint(str(tmp_path), 3, state, {"update_step": 3})
    ckpt.wait_for_save()

    # a newer dir with JSON but no committed state/ (died mid-async-write)
    dead = os.path.join(str(tmp_path), "model_7")
    os.makedirs(dead)
    with open(os.path.join(dead, ckpt.TRAINING_STATE_FILE), "w") as f:
        json.dump({"update_step": 7}, f)

    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3
    assert path.endswith("model_3")

    # retention must neither count nor delete the uncommitted dir — with
    # keep=1 the committed model_3 survives (deleting it against an
    # in-flight model_7 would leave nothing restorable)
    ckpt.delete_old_checkpoints(str(tmp_path), keep=1)
    assert os.path.isdir(os.path.join(str(tmp_path), "model_3", ckpt.STATE_SUBDIR))


# ---------------------------------------------------------------------------
# manifest integrity + fallback


def _save_two(tmp_path, devices):
    """Two committed, manifest-verified checkpoints at steps 3 and 7."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state = make_state(mesh, 8)
    ckpt.save_checkpoint(str(tmp_path), 3, state, {"update_step": 3})
    p7 = ckpt.save_checkpoint(str(tmp_path), 7, state, {"update_step": 7})
    ckpt.wait_for_save()  # commits both writes and finalizes both manifests
    return p7


def _some_state_file(path):
    for root, _, names in os.walk(os.path.join(path, ckpt.STATE_SUBDIR)):
        for name in sorted(names):
            full = os.path.join(root, name)
            if os.path.getsize(full) > 8:
                return full
    raise AssertionError(f"no data files under {path}")


def test_manifest_written_and_verifies(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    assert os.path.exists(os.path.join(p7, ckpt.MANIFEST_FILE))
    ok, reason = ckpt.verify_checkpoint(p7, check_arrays=True)
    assert ok, reason
    with open(os.path.join(p7, ckpt.MANIFEST_FILE)) as f:
        manifest = json.load(f)
    # per-array shapes recorded from the in-memory tree
    kernel_recs = [v for k, v in manifest["arrays"].items() if "kernel" in k]
    assert any(rec["shape"] == [8, 8] for rec in kernel_recs)
    assert manifest["files"]  # per-file size+crc32 present


def test_bitflip_detected_and_older_checkpoint_selected(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    victim = _some_state_file(p7)
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))
    ok, reason = ckpt.verify_checkpoint(p7)
    assert not ok and "checksum" in reason
    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3 and path.endswith("model_3")


def test_truncation_detected_and_older_checkpoint_selected(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    victim = _some_state_file(p7)
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    ok, reason = ckpt.verify_checkpoint(p7)
    assert not ok and "size" in reason
    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3


def test_garbage_manifest_falls_back(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    with open(os.path.join(p7, ckpt.MANIFEST_FILE), "w") as f:
        f.write("{not json")
    ok, reason = ckpt.verify_checkpoint(p7)
    assert not ok and "manifest" in reason
    ts, _ = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3


def test_legacy_checkpoint_without_manifest_accepted(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    os.remove(os.path.join(p7, ckpt.MANIFEST_FILE))
    ok, reason = ckpt.verify_checkpoint(p7)
    assert ok and "legacy" in reason
    ts, _ = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 7


def test_missing_training_state_falls_back(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    # manifest pins training_state.json; drop the manifest too so this
    # exercises the independent unreadable-JSON skip in get_last_checkpoint
    os.remove(os.path.join(p7, ckpt.MANIFEST_FILE))
    os.remove(os.path.join(p7, ckpt.TRAINING_STATE_FILE))
    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3 and path.endswith("model_3")


def test_before_step_restricts_candidates(tmp_path, devices):
    _save_two(tmp_path, devices)
    ts, path = ckpt.get_last_checkpoint(str(tmp_path), before_step=7)
    assert ts["update_step"] == 3
    ts, path = ckpt.get_last_checkpoint(str(tmp_path), before_step=3)
    assert ts is None and path is None


def test_all_corrupt_returns_none(tmp_path, devices):
    p7 = _save_two(tmp_path, devices)
    for d in ("model_3", "model_7"):
        victim = _some_state_file(os.path.join(str(tmp_path), d))
        with open(victim, "r+b") as f:
            f.truncate(1)
    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts is None and path is None
