"""Packed mixed-batch serving tests: the single-dispatch round oracle.

A drain through ``PagedContinuousBatchingScheduler(packed=True)`` must be
**token-identical** to the sequential paged drain for the same request
stream — greedy and sampled, llama and neox, base and multi-tenant LoRA,
spec drafting on and off — because every packed token attends only its own
slot's pages (``row_map`` routing) and sampling keys stay
``(uid, token_index)``.  On top of parity: a loaded round issues exactly
ONE model dispatch, packing never changes allocator accounting, a row's
tokens don't depend on who else rides the dispatch, and a packed warmup
covers every steady-state shape (zero retraces under churn).
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.models.params_util import init_params
from relora_tpu.serve.adapters import AdapterRegistry, extract_lora_factors
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.scheduler import PagedContinuousBatchingScheduler, Request

pytestmark = pytest.mark.serve

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)

MAX_BATCH = 2
CHUNK = 8


_ENGINES: dict = {}


def make_engine(cfg, *, spec_k=0, cache_size=32, lora=None, adapter_slots=0, fresh=False):
    """One paged engine with a token budget: it can run BOTH the sequential
    round (prefill_chunk/decode_paged/verify_paged) and the packed step, so
    parity drains share every weight bit by construction.  Also returns the
    raw (pre-slot-stacked) params — LoRA factors extract from those.

    Engines are cached per config so tests reuse jit caches (pools live on
    the scheduler, so sharing is safe); ``fresh=True`` opts out for tests
    that assert on the engine's compile telemetry from a clean slate."""
    key = (cfg.family, spec_k, cache_size, lora is not None, adapter_slots)
    if not fresh and key in _ENGINES:
        return _ENGINES[key]
    model = build_decode_model(cfg, cache_size=cache_size, lora=lora)
    base = type(model)(cfg, lora=lora, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    window = spec_k + 1 if spec_k else 1
    engine = InferenceEngine(
        cfg,
        params,
        cache_size=cache_size,
        page_size=8,
        num_pages=3 * (cache_size // 8) + 1,
        chunk_size=CHUNK,
        spec_k=spec_k,
        token_budget=MAX_BATCH * window + CHUNK,
        lora=lora,
        adapter_slots=adapter_slots,
    )
    if not fresh:
        _ENGINES[key] = (engine, params)
    return engine, params


def mixed_requests(vocab, *, adapters=False):
    """Mixed lengths (page-straddling + multi-chunk), greedy AND sampled,
    staggered through max_batch=2 slots, with uid 4 likely to hit EOS."""
    rng = np.random.default_rng(11)
    mk = lambda uid, L, new, **kw: Request(
        uid=uid, prompt=rng.integers(1, vocab, L).tolist(), max_new_tokens=new, **kw
    )
    adapter = (lambda uid: (None, "t0", "t1")[uid % 3]) if adapters else (lambda uid: None)
    return [
        mk(1, 13, 6, adapter=adapter(1)),
        mk(2, 5, 9, temperature=0.8, top_p=0.9, adapter=adapter(2)),
        mk(3, 21, 4, adapter=adapter(3)),
        mk(4, 3, 7, temperature=1.1, adapter=adapter(4)),
    ]


def drain(engine, reqs, *, packed, spec="off", **kwargs):
    sched = PagedContinuousBatchingScheduler(
        engine,
        max_batch=MAX_BATCH,
        eos_id=9,
        key=jax.random.PRNGKey(42),
        packed=packed,
        spec=spec,
        **kwargs,
    )
    completions = sched.run(reqs)
    return sched, {uid: c.tokens for uid, c in completions.items()}


# -- the parity oracle --------------------------------------------------------


@pytest.mark.parametrize("spec", ["off", "ngram"])
@pytest.mark.parametrize(
    "cfg",
    [
        TINY_LLAMA,
        # neox rides the slow battery: same row_map code path, but its
        # engine's compile set doesn't fit the tier-1 wall-clock budget
        pytest.param(TINY_NEOX, marks=pytest.mark.slow),
    ],
    ids=["llama", "neox"],
)
def test_packed_token_identical_to_sequential(cfg, spec):
    """The packed single-dispatch drain reproduces the sequential paged
    drain token for token — greedy and sampled rows, with and without
    speculative drafting riding the packed window."""
    engine, _ = make_engine(cfg, spec_k=3)  # shared: spec_k only adds capability
    reqs = mixed_requests(cfg.vocab_size)
    _, want = drain(engine, reqs, packed=False, spec=spec)
    sched, got = drain(engine, reqs, packed=True, spec=spec)
    assert got == want
    assert sched.dispatch_stats()["mode"] == "packed"


@pytest.mark.slow  # compile-heavy (grouped-LoRA engine): full battery only
def test_packed_parity_with_adapters():
    """Multi-tenant rows keep parity: each packed token routes through its
    slot's adapter index exactly as the sequential round does."""
    lspec = LoraSpec(r=4, alpha=8)
    engine, raw = make_engine(TINY_LLAMA, lora=lspec, adapter_slots=3)
    base_factors = extract_lora_factors(raw)

    def tenant_factors(seed):
        # lora_b initializes to zero, so scaling won't do: inject noise into
        # both factors to give each tenant a genuinely different delta
        return jax.tree_util.tree_map(
            lambda t: t
            + 0.1
            * jax.random.normal(jax.random.PRNGKey(seed), t.shape, t.dtype),
            base_factors,
        )

    def registry():
        reg = AdapterRegistry(
            None, 3, expected_r=lspec.r, writer=engine.adapter_writer()
        )
        for g, name in enumerate(("t0", "t1")):
            reg.preload(name, tenant_factors(11 + g), lspec.scale)
        return reg

    reqs = mixed_requests(TINY_LLAMA.vocab_size, adapters=True)
    _, want = drain(engine, reqs, packed=False, adapter_registry=registry())
    _, got = drain(engine, reqs, packed=True, adapter_registry=registry())
    assert got == want
    # adapters actually changed the output: an adapter-less drain on the
    # same engine (every row on slot 0, the identity adapter) differs
    _, plain = drain(engine, mixed_requests(TINY_LLAMA.vocab_size), packed=True)
    assert plain != want


def test_packed_parity_without_prefix_cache():
    engine, _ = make_engine(TINY_LLAMA, spec_k=3)
    reqs = mixed_requests(TINY_LLAMA.vocab_size)
    _, want = drain(engine, reqs, packed=False, prefix_cache=False)
    sched, got = drain(engine, reqs, packed=True, prefix_cache=False)
    assert got == want
    assert sched.allocator.used_pages == 0


# -- one dispatch per round ---------------------------------------------------


def test_loaded_round_is_one_dispatch():
    """A round with a decoding row AND a pending multi-chunk prefill issues
    exactly one step_paged call — none of the sequential trio run."""
    engine, _ = make_engine(TINY_LLAMA, spec_k=3)
    sched = PagedContinuousBatchingScheduler(
        engine, max_batch=MAX_BATCH, packed=True
    )
    sched.submit(Request(uid=1, prompt=[1, 2, 3], max_new_tokens=8))
    sched.step()  # uid 1 prefills (+ first decode) — now decoding
    sched.submit(Request(uid=2, prompt=list(range(1, 22)), max_new_tokens=4))

    before = engine.compile_watcher.call_counts()
    d0 = sched.dispatch_stats()
    sched.step()  # decode row + first prefill chunk of uid 2, together
    after = engine.compile_watcher.call_counts()
    d1 = sched.dispatch_stats()

    delta = lambda name: after.get(name, 0) - before.get(name, 0)
    assert delta("step_paged") == 1
    assert delta("prefill_chunk") == 0
    assert delta("decode_paged") == 0
    assert delta("verify_paged") == 0
    assert d1["model_dispatches"] - d0["model_dispatches"] == 1
    assert d1["rounds"] - d0["rounds"] == 1

    # and the whole remaining drain stays at one dispatch per round
    sched.run([])
    stats = sched.dispatch_stats()
    assert stats["model_dispatches"] == stats["rounds"]
    assert stats["dispatches_per_round"] == 1.0
    assert 0.0 < stats["packed_token_utilization"] <= 1.0


# -- packing is invisible to everything but the dispatch count ----------------


def test_row_isolation_solo_vs_crowded():
    """A greedy request's tokens don't depend on who else rides the packed
    dispatch: alone, or packed beside decode neighbours and a fat prefill."""
    engine, _ = make_engine(TINY_LLAMA, spec_k=3)
    probe = lambda uid: Request(
        uid=uid, prompt=[7, 3, 11, 5, 2, 13, 1], max_new_tokens=6
    )
    _, solo = drain(engine, [probe(1)], packed=True, prefix_cache=False)

    rng = np.random.default_rng(5)
    crowd = [
        probe(1),
        Request(uid=2, prompt=rng.integers(1, 256, 4).tolist(), max_new_tokens=9,
                temperature=0.9),
        Request(uid=3, prompt=rng.integers(1, 256, 19).tolist(), max_new_tokens=5),
    ]
    _, crowded = drain(engine, crowd, packed=True, prefix_cache=False)
    assert crowded[1] == solo[1]


def test_allocator_accounting_unchanged_by_packing():
    """Packing changes dispatch economics only: page alloc/free traffic,
    peak usage, and the end state match the sequential drain exactly."""
    stats = {}
    for packed in (False, True):
        engine, _ = make_engine(TINY_LLAMA, spec_k=3)
        reqs = mixed_requests(TINY_LLAMA.vocab_size)
        sched, _ = drain(engine, reqs, packed=packed)
        sched.prefix_cache.clear()
        assert sched.allocator.used_pages == 0
        alloc = sched.allocator
        stats[packed] = (alloc.free_pages, alloc.peak_used, sched.prefix_cache.stats())
    assert stats[True] == stats[False]


# -- compile discipline -------------------------------------------------------


def test_packed_warmup_no_steady_state_retrace():
    """warmup(packed=True) compiles every token-budget bucket; afterwards a
    churny drain — staggered admits, a mid-decode cancel, spec windows
    filling and draining — never retraces."""
    engine, _ = make_engine(TINY_LLAMA, spec_k=3, fresh=True)
    report = engine.warmup(MAX_BATCH, packed=True)
    assert report["token_budget"] == engine.token_budget
    assert report["packed_buckets"] == list(engine.packed_buckets())
    assert report["shapes"]["step_paged"] == [
        [1, b] for b in engine.packed_buckets()
    ]

    sched = PagedContinuousBatchingScheduler(
        engine, max_batch=MAX_BATCH, eos_id=9, packed=True, spec="ngram"
    )
    rng = np.random.default_rng(3)
    for uid, L in enumerate((2, 7, 9, 17, 23), start=1):
        sched.submit(
            Request(
                uid=uid,
                prompt=rng.integers(1, 256, L).tolist(),
                max_new_tokens=6,
                temperature=0.7 if uid % 2 else 0.0,
            )
        )
        sched.step()
        if uid == 3:
            sched.cancel(1)
    sched.run([])
    assert engine.compile_watcher.steady_state_retraces == 0
