"""Minimal HTTP/1.1 wire helpers shared by the serving tier.

Extracted from serve/server.py so the router and supervisor — which run in
front-end processes that must never pay a jax import — can speak the same
wire format as the replicas.  Stdlib-only (asyncio + json), like
serve/admission.py: everything here must import fast and run anywhere the
linter runs.

The dialect is deliberately tiny: HTTP/1.1, ``Connection: close`` on every
response, ``Content-Length`` bodies on requests, close-delimited bodies on
streaming responses.  This is the subset the stdlib-asyncio server and the
raw-socket test/bench clients have always used; keeping it in one place is
what lets the router proxy byte-for-byte.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional, Tuple

MAX_BODY_BYTES = 16 << 20

REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 405: "Method Not Allowed",
    413: "Payload Too Large", 429: "Too Many Requests", 500: "Internal Server Error",
    502: "Bad Gateway", 503: "Service Unavailable",
}


def head(
    status: int,
    reason: str,
    content_type: str,
    extra: Optional[Dict[str, str]] = None,
    content_length: Optional[int] = None,
) -> bytes:
    lines = [
        f"HTTP/1.1 {status} {reason}",
        f"Content-Type: {content_type}",
        "Connection: close",
    ]
    if content_length is not None:
        lines.append(f"Content-Length: {content_length}")
    for k, v in (extra or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode()


def sse(obj: Dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(obj).encode() + b"\n\n"


async def respond(
    writer: asyncio.StreamWriter,
    status: int,
    body: str,
    *,
    content_type: str = "text/plain",
    extra_headers: Optional[Dict[str, str]] = None,
) -> None:
    payload = body.encode()
    writer.write(
        head(status, REASONS.get(status, "?"), content_type, extra_headers, len(payload))
    )
    writer.write(payload)
    await writer.drain()


async def respond_json(
    writer: asyncio.StreamWriter,
    status: int,
    obj: Dict[str, Any],
    *,
    extra_headers: Optional[Dict[str, str]] = None,
) -> None:
    await respond(
        writer,
        status,
        json.dumps(obj),
        content_type="application/json",
        extra_headers=extra_headers,
    )


async def read_http_request(
    reader: asyncio.StreamReader,
) -> Optional[Tuple[str, str, Dict[str, str], bytes]]:
    """Minimal HTTP/1.1 request parser: request line, headers, Content-Length
    body.  Returns None on an empty connection (health-checker port probes)."""
    line = await reader.readline()
    if not line.strip():
        return None
    parts = line.decode("latin-1").split()
    if len(parts) < 3:
        raise ValueError(f"malformed request line: {line!r}")
    method, target = parts[0].upper(), parts[1]
    headers: Dict[str, str] = {}
    while True:
        raw = await reader.readline()
        if raw in (b"\r\n", b"\n", b""):
            break
        key, _, value = raw.decode("latin-1").partition(":")
        headers[key.strip().lower()] = value.strip()
    length = int(headers.get("content-length", "0") or "0")
    if length > MAX_BODY_BYTES:
        raise ValueError(f"body too large: {length} bytes")
    body = await reader.readexactly(length) if length else b""
    return method, target, headers, body
