"""SLO-driven elastic fleet: autoscaler policy, supervisor scale levers,
the drain/scale race, warming unroutability, and rendezvous re-homing.

Covers the PR's serving acceptance criteria:

- the policy's hysteresis bands: a scale-up needs the whole burn window
  saturated on every replica, a scale-down needs the whole (longer) idle
  window quiet, and cooldown/bounds hold everything else;
- the supervisor's ``scale_up``/``scale_down`` levers really add and drain
  processes, and a rolling drain cancels every scale action requested
  after it began (the SIGTERM race regression, driven by a scripted
  policy);
- a cold replica is registered but unroutable until its compile warmup
  completes (healthz "warming" 503 — the router never routes to it);
- rendezvous re-homing is bounded: growing or shrinking the fleet moves
  only the added/departed replica's tenants.

The full 1→2→1 resize under live HTTP load runs as a scripts/smoke_test.sh
stage and ``bench.py --mode autoscale``; here the execution pipeline is
drilled with cheap sleeper processes so tier-1 stays fast.
"""

import asyncio
import json
import os
import sys
import threading
import time

import pytest

from relora_tpu.obs.fleet import SeriesStore
from relora_tpu.serve.autoscale import (
    ACTIVE_SLOTS_SERIES,
    MAX_BATCH_SERIES,
    QUEUE_DEPTH_SERIES,
    TTFT_P95_SERIES,
    UP_SERIES,
    Autoscaler,
    AutoscalerPolicy,
    Decision,
)
from relora_tpu.serve.router import rendezvous_home
from relora_tpu.serve.server import GenerateServer
from relora_tpu.serve.supervisor import ReplicaSupervisor
from tests.test_router import _FakeReplica

pytestmark = pytest.mark.autoscale

T0 = 1_000_000.0

#: a replica stand-in that binds nothing and exits 0 on SIGTERM — the
#: supervisor appends --port-file args, which a -c script ignores
SLEEPER = [
    sys.executable,
    "-c",
    "import signal,sys,time;"
    "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0));"
    "time.sleep(600)",
]


def feed(store, source, series, values, t0=T0, dt=1.0):
    for i, v in enumerate(values):
        store.add_sample(source, series, float(v), t=t0 + i * dt)


def make_policy(**kw):
    base = dict(
        min_replicas=1,
        max_replicas=4,
        burn_window_s=5.0,
        idle_window_s=10.0,
        cooldown_s=10.0,
        min_samples=3,
    )
    base.update(kw)
    return AutoscalerPolicy(**base)


# -- policy hysteresis --------------------------------------------------------


def test_policy_scales_up_on_sustained_queue_burn():
    store, policy = SeriesStore(), make_policy()
    now = T0 + 4
    for rid in ("r0", "r1"):
        feed(store, rid, QUEUE_DEPTH_SERIES, [8, 9, 8, 10, 8])
    d = policy.decide(store, ["r0", "r1"], 2, now=now)
    assert d.action == "up" and "queue_depth" in d.reason


def test_policy_single_hot_replica_holds():
    """One saturated replica out of two is a routing story, not a capacity
    story — the fleet holds."""
    store, policy = SeriesStore(), make_policy()
    now = T0 + 4
    feed(store, "r0", QUEUE_DEPTH_SERIES, [8, 9, 8, 10, 8])
    feed(store, "r1", QUEUE_DEPTH_SERIES, [0, 0, 0, 0, 0])
    d = policy.decide(store, ["r0", "r1"], 2, now=now)
    assert d.action == "hold" and d.reason == "partial_burn"


def test_policy_brief_spike_does_not_scale():
    """A spike that does not fill the burn window (or fewer samples than
    min_samples) holds — flap resistance is structural."""
    store, policy = SeriesStore(), make_policy()
    now = T0 + 4
    feed(store, "r0", QUEUE_DEPTH_SERIES, [0, 0, 0, 9, 9])  # not sustained
    d = policy.decide(store, ["r0"], 1, now=now)
    assert d.action == "hold"

    store2 = SeriesStore()
    feed(store2, "r0", QUEUE_DEPTH_SERIES, [9, 9], t0=now - 1, dt=0.5)
    d = make_policy().decide(store2, ["r0"], 1, now=now)
    assert d.action == "hold"  # two samples < min_samples


def test_policy_ttft_and_slot_util_signals():
    store, policy = SeriesStore(), make_policy(ttft_p95_target_s=2.0, slot_util_high=0.9)
    now = T0 + 4
    feed(store, "r0", TTFT_P95_SERIES, [3.0, 2.5, 4.0, 3.2, 2.9])
    d = policy.decide(store, ["r0"], 1, now=now)
    assert d.action == "up" and "ttft_p95" in d.reason

    store2 = SeriesStore()
    feed(store2, "r0", ACTIVE_SLOTS_SERIES, [4, 4, 4, 4, 4])
    feed(store2, "r0", MAX_BATCH_SERIES, [4, 4, 4, 4, 4])
    d = make_policy().decide(store2, ["r0"], 1, now=now)
    assert d.action == "up" and "slot_utilization" in d.reason


def test_policy_respects_max_replicas():
    store, policy = SeriesStore(), make_policy(max_replicas=2)
    now = T0 + 4
    for rid in ("r0", "r1"):
        feed(store, rid, QUEUE_DEPTH_SERIES, [8, 9, 8, 10, 8])
    d = policy.decide(store, ["r0", "r1"], 2, now=now)
    assert d.action == "hold" and d.reason == "at_max_replicas"


def test_policy_scales_down_on_sustained_idle_only():
    store, policy = SeriesStore(), make_policy()
    now = T0 + 9  # idle window covers t0..t0+9
    for rid in ("r0", "r1"):
        feed(store, rid, QUEUE_DEPTH_SERIES, [0] * 10)
        feed(store, rid, ACTIVE_SLOTS_SERIES, [0] * 10)
        feed(store, rid, MAX_BATCH_SERIES, [4] * 10)
    d = policy.decide(store, ["r0", "r1"], 2, now=now)
    assert d.action == "down" and d.reason == "sustained_idle"

    # at the floor, idle holds instead
    d = policy.decide(store, ["r0", "r1"], 1, now=now)
    assert d.action == "hold" and d.reason == "at_min_replicas"

    # one queued sample inside the window cancels the drain
    store.add_sample("r1", QUEUE_DEPTH_SERIES, 2.0, t=now - 1.0)
    d = make_policy().decide(store, ["r0", "r1"], 2, now=now)
    assert d.action == "hold"


def test_policy_cooldown_gates_consecutive_actions():
    store, policy = SeriesStore(), make_policy(cooldown_s=10.0)
    now = T0 + 4
    feed(store, "r0", QUEUE_DEPTH_SERIES, [8, 9, 8, 10, 8])
    assert policy.decide(store, ["r0"], 1, now=now).action == "up"
    policy.note_scaled(now)
    d = policy.decide(store, ["r0"], 2, now=now + 5)
    assert d.action == "hold" and d.reason == "cooldown"
    # cooldown expired and the burn persists: acts again
    feed(store, "r0", QUEUE_DEPTH_SERIES, [8, 9, 8, 10, 8], t0=now + 7)
    assert policy.decide(store, ["r0"], 2, now=now + 11).action == "up"


# -- executor -----------------------------------------------------------------


class FakeSupervisor:
    def __init__(self, n=1):
        self.n = n
        self.calls = []
        self.draining = False

    def endpoints(self):
        return {f"r{i}": ("127.0.0.1", 8000 + i) for i in range(self.n)}

    def n_live(self):
        return self.n

    def scale_up(self):
        if self.draining:
            return None
        self.calls.append("up")
        self.n += 1
        return f"r{self.n - 1}"

    def scale_down(self, idx=None):
        if self.draining or self.n <= 1:
            return None
        self.calls.append("down")
        self.n -= 1
        return f"r{self.n}"


class ScriptedPolicy:
    """Fixed decision per step — isolates the executor from the bands."""

    def __init__(self, decisions):
        self.decisions = list(decisions)
        self.scaled_at = []

    def decide(self, store, sources, n_live, now=None):
        return (
            self.decisions.pop(0)
            if self.decisions
            else Decision("hold", "steady", {"n_live": n_live})
        )

    def note_scaled(self, now=None):
        self.scaled_at.append(now)


def test_autoscaler_executes_decisions_and_records_events():
    store = SeriesStore()
    sup = FakeSupervisor(n=1)
    feed(store, "r0", UP_SERIES, [1.0], t0=T0)
    policy = ScriptedPolicy(
        [
            Decision("up", "sustained_burn (queue_depth)"),
            Decision("hold", "cooldown"),
            Decision("hold", "cooldown"),  # duplicate hold: one event only
            Decision("down", "sustained_idle"),
        ]
    )
    asc = Autoscaler(policy, sup, store)
    feed(store, "r1", UP_SERIES, [1.0], t0=T0)  # new replica reports up
    for i in range(4):
        asc.step(now=T0 + i)
    assert sup.calls == ["up", "down"]
    assert len(policy.scaled_at) == 2
    events = store.events(kinds=("autoscale_decision",))
    actions = [e["action"] for e in events]
    assert actions == ["up", "hold", "down"]  # the duplicate hold collapsed
    # replica-count series sampled every step
    assert [v for _, v in store.samples("autoscaler", "replicas_live")] == [
        1.0, 2.0, 2.0, 2.0,
    ]


def test_autoscaler_holds_scale_up_while_replica_warming():
    """Capacity that cannot be routed to yet (healthz "warming" → up == 0)
    must not count as capacity — the executor refuses to stack scale-ups."""
    store = SeriesStore()
    sup = FakeSupervisor(n=2)
    feed(store, "r0", UP_SERIES, [1.0], t0=T0)
    feed(store, "r1", UP_SERIES, [0.0], t0=T0)  # still warming
    policy = ScriptedPolicy([Decision("up", "sustained_burn (queue_depth)")])
    d = Autoscaler(policy, sup, store).step(now=T0 + 1)
    assert d.action == "hold" and d.reason == "replica_warming"
    assert d.metrics["warming"] == "r1"
    assert sup.calls == []


# -- supervisor scale levers (real processes) ---------------------------------


def _events_sink():
    events = []
    lock = threading.Lock()

    def on_event(event, idx, detail):
        with lock:
            events.append((event, idx, dict(detail)))

    return events, on_event


def test_supervisor_scale_up_down_lifecycle(tmp_path):
    events, on_event = _events_sink()
    sup = ReplicaSupervisor(
        SLEEPER, 1, str(tmp_path),
        drain_timeout_s=10.0, poll_interval_s=0.05, on_event=on_event,
    )
    sup.start()
    try:
        assert sup.n_live() == 1
        rid = sup.scale_up()
        assert rid == "r1"
        assert set(sup.endpoints()) == {"r0", "r1"}
        assert sup.n_live() == 2
        assert sup.status()["r1"]["running"]
        time.sleep(0.5)  # let the sleeper install its SIGTERM handler

        # newest drains first; the fleet never treats its exit as a crash
        assert sup.scale_down() == "r1"
        assert set(sup.endpoints()) == {"r0"}
        assert sup.n_live() == 1
        # the floor: never drain the last replica
        assert sup.scale_down() is None
        time.sleep(0.3)  # a few monitor rounds
        kinds = [e[0] for e in events]
        assert "autoscale_up" in kinds and "autoscale_down_complete" in kinds
        assert "crash" not in kinds
        down_done = next(e for e in events if e[0] == "autoscale_down_complete")
        assert down_done[2]["exit_code"] == 0  # clean SIGTERM exit
        # freed indices are never reused: the next scale-up is r2, so a
        # stale port file can never be routed to
        assert sup.scale_up() == "r2"
    finally:
        sup.stop()


def test_rolling_drain_cancels_pending_scale_up(tmp_path):
    """The SIGTERM race regression: a scale-up decided while the rolling
    drain runs must be cancelled, not spawn a process the drain will never
    visit."""
    events, on_event = _events_sink()
    sup = ReplicaSupervisor(
        SLEEPER, 2, str(tmp_path),
        drain_timeout_s=10.0, poll_interval_s=0.05, on_event=on_event,
    )
    sup.start()
    try:
        drainer = threading.Thread(target=sup.begin_rolling_drain, daemon=True)
        drainer.start()
        # the drain flag flips before the drain starts touching processes;
        # from that instant every scale action must refuse
        deadline = time.monotonic() + 5.0
        while not sup._draining and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sup._draining
        assert sup.scale_up() is None  # blocks on the scale lock, then cancels
        assert sup.scale_down() is None
        drainer.join(15.0)
        assert not drainer.is_alive()
        kinds = [e[0] for e in events]
        assert "autoscale_up_cancelled" in kinds
        assert kinds.count("drain_complete") == 2
        # nothing was spawned after the drain began
        assert not any(k == "autoscale_up" for k in kinds)
        assert all(not st["running"] for st in sup.status().values())
    finally:
        sup.stop()


def test_scripted_autoscaler_refuses_during_drain(tmp_path):
    """Same race through the executor: a scripted always-up policy stepping
    concurrently with the drain ends in a cancelled decision, never a new
    replica."""
    store = SeriesStore()
    sup = ReplicaSupervisor(
        SLEEPER, 2, str(tmp_path), drain_timeout_s=10.0, poll_interval_s=0.05
    )
    sup.start()
    try:
        for rid in ("r0", "r1"):
            feed(store, rid, UP_SERIES, [1.0], t0=time.time())
        policy = ScriptedPolicy(
            [Decision("up", "sustained_burn (queue_depth)")] * 3
        )
        asc = Autoscaler(policy, sup, store)
        drainer = threading.Thread(target=sup.begin_rolling_drain, daemon=True)
        drainer.start()
        deadline = time.monotonic() + 5.0
        while not sup._draining and time.monotonic() < deadline:
            time.sleep(0.005)
        d = asc.step()
        assert d.action == "hold" and d.reason == "scale_up_cancelled"
        assert policy.scaled_at == []  # no cooldown burned on a cancel
        drainer.join(15.0)
        assert sup.n_live() == 0 or all(
            not st["running"] for st in sup.status().values()
        )
    finally:
        sup.stop()


# -- rendezvous re-homing (property: bounded churn) ---------------------------


def test_rendezvous_rehoming_moves_only_the_changed_replicas_tenants():
    adapters = [f"tenant-{i}" for i in range(64)]
    groups = [f"r{i}" for i in range(4)]
    before = {a: rendezvous_home(a, groups) for a in adapters}
    # every group homes someone (64 tenants over 4 groups)
    assert set(before.values()) == set(groups)

    # grow: the only tenants that move are the ones landing on the new group
    grown = groups + ["r4"]
    after_grow = {a: rendezvous_home(a, grown) for a in adapters}
    moved = {a for a in adapters if after_grow[a] != before[a]}
    assert moved  # statistically certain: E[|moved|] = 64/5
    assert all(after_grow[a] == "r4" for a in moved)

    # shrink: only the departed group's tenants move, everyone else stays
    shrunk = [g for g in groups if g != "r2"]
    after_shrink = {a: rendezvous_home(a, shrunk) for a in adapters}
    for a in adapters:
        if before[a] == "r2":
            assert after_shrink[a] in shrunk
        else:
            assert after_shrink[a] == before[a]

    # the home is a pure function of the *set* of groups, not their order
    assert all(
        rendezvous_home(a, list(reversed(grown))) == after_grow[a] for a in adapters
    )
    assert rendezvous_home("anyone", []) is None


# -- warming: discoverable but unroutable until warmup completes --------------


class _IdleScheduler:
    """The minimum scheduler surface GenerateServer drives when no requests
    arrive — warming is decided on the model thread before the first real
    scheduler interaction, so nothing else is needed."""

    max_batch = 4
    active_slots = 0
    queue_depth = 0

    def __init__(self):
        from relora_tpu.obs.tracer import NoopTracer

        self.tracer = NoopTracer()
        self.obs_registry = None

    def has_work(self):
        return False

    def step(self):
        pass

    def cancel(self, uid):
        pass

    def fail_all(self, reason="", detail=""):
        pass


def test_server_warming_healthz_until_warmup_completes():
    """A replica with a pending warmup binds its listener (discoverable)
    but answers healthz 503 "warming"; completion of warmup_fn promotes it
    to 200 "ok" and publishes the warmup report."""
    from tests.test_server import _http as server_http

    release = threading.Event()

    def warmup():
        assert release.wait(30), "warmup never released"
        return {"buckets": 1}

    server = GenerateServer(_IdleScheduler(), port=0, max_queue=4, warmup_fn=warmup)
    thread = threading.Thread(
        target=lambda: asyncio.run(
            server.serve_forever(install_signal_handlers=False)
        ),
        daemon=True,
    )
    thread.start()
    try:
        assert server.started.wait(30), "listener never bound"
        # the port is live before warmup finishes — but not routable
        status, _, body = server_http(server.port, "GET", "/healthz")
        payload = json.loads(body)
        assert status == 503
        assert payload["status"] == "warming"
        assert payload["detail"] == "compile warmup in progress"

        release.set()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            status, _, body = server_http(server.port, "GET", "/healthz")
            if status == 200:
                break
            time.sleep(0.02)
        assert status == 200 and json.loads(body)["status"] == "ok"
        assert server.warmup_report == {"buckets": 1}
    finally:
        release.set()
        server.begin_drain()
        thread.join(30)
    assert not thread.is_alive(), "server did not drain"
    assert server._worker_error is None, repr(server._worker_error)


class _WarmingReplica(_FakeReplica):
    """A _FakeReplica whose healthz answers 503 "warming" until the test
    flips ``warming`` off — the serve.py cold-start shape."""

    def __init__(self, **kw):
        self.warming = True
        super().__init__(**kw)

    async def _respond_healthz(self, writer):
        if not self.warming:
            await super()._respond_healthz(writer)
            return
        body = json.dumps(
            {"status": "warming", "detail": "compile warmup in progress"}
        ).encode()
        writer.write(
            f"HTTP/1.1 503 X\r\nContent-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
            + body
        )
        await writer.drain()


def test_router_never_routes_to_warming_replica():
    """With one warm and one warming replica, every request lands on the
    warm one; the warming replica is adopted only after its healthz clears."""
    from tests.test_router import _RouterHarness, _http as router_http

    warm, cold = _FakeReplica(), _WarmingReplica()
    harness = _RouterHarness(
        {"warm": ("127.0.0.1", warm.port), "cold": ("127.0.0.1", cold.port)},
        probe_interval_s=0.05,
    )
    try:
        with harness as router:
            harness.wait_healthy(1)
            assert router.replicas["cold"].healthy is False
            assert router.replicas["cold"].status == "warming"
            for _ in range(6):
                status, headers, _ = router_http(
                    router.port, "POST", "/v1/generate",
                    {"prompt": [1], "max_new_tokens": 2},
                )
                assert status == 200
                assert headers["x-relora-replica"] == "warm"
            assert cold.gen_hits == 0  # zero traffic into the compile stall

            cold.warming = False  # warmup completes -> healthz 200
            harness.wait_healthy(2)
            deadline = time.monotonic() + 10.0
            while cold.gen_hits == 0 and time.monotonic() < deadline:
                router_http(
                    router.port, "POST", "/v1/generate",
                    {"prompt": [1], "max_new_tokens": 2},
                )
            assert cold.gen_hits > 0  # promoted replica now takes traffic
    finally:
        warm.close()
        cold.close()
