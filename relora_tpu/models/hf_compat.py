"""HF checkpoint interop: torch/HF state dicts ↔ our Flax param trees.

This serves three reference capabilities at once:

- ``--warmed_up_model`` warm starts (full-rank weights into a LoRA-wrapped
  model, torchrun_main.py:505-527),
- ``--model_name_or_path EleutherAI/pythia-1b --model_revision step1000``
  loads (the 1B production recipe, training_configs/1B_v1.0.yaml),
- exporting trained models for HF-ecosystem evaluation (run_glue.py).

Transfers are by-name (no torch execution needed beyond reading tensors) and
work with either the scanned (stacked) or unrolled layer layout.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import stack_layers, unstack_layers

PyTree = Any

# my (unrolled) path -> HF llama state_dict key; kernels transpose (in,out)<->(out,in)
_LLAMA_LAYER_MAP = {
    "self_attn.q_proj.kernel": "self_attn.q_proj.weight",
    "self_attn.k_proj.kernel": "self_attn.k_proj.weight",
    "self_attn.v_proj.kernel": "self_attn.v_proj.weight",
    "self_attn.o_proj.kernel": "self_attn.o_proj.weight",
    "mlp.gate_proj.kernel": "mlp.gate_proj.weight",
    "mlp.up_proj.kernel": "mlp.up_proj.weight",
    "mlp.down_proj.kernel": "mlp.down_proj.weight",
    "input_layernorm.scale": "input_layernorm.weight",
    "post_attention_layernorm.scale": "post_attention_layernorm.weight",
}

_NEOX_LAYER_MAP = {
    "attention.query_key_value.kernel": "attention.query_key_value.weight",
    "attention.query_key_value.bias": "attention.query_key_value.bias",
    "attention.dense.kernel": "attention.dense.weight",
    "attention.dense.bias": "attention.dense.bias",
    "mlp.dense_h_to_4h.kernel": "mlp.dense_h_to_4h.weight",
    "mlp.dense_h_to_4h.bias": "mlp.dense_h_to_4h.bias",
    "mlp.dense_4h_to_h.kernel": "mlp.dense_4h_to_h.weight",
    "mlp.dense_4h_to_h.bias": "mlp.dense_4h_to_h.bias",
    "input_layernorm.scale": "input_layernorm.weight",
    "input_layernorm.bias": "input_layernorm.bias",
    "post_attention_layernorm.scale": "post_attention_layernorm.weight",
    "post_attention_layernorm.bias": "post_attention_layernorm.bias",
}


def _set_path(tree: Dict, dotted: str, value) -> None:
    node = tree
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _get_path(tree: Mapping, dotted: str):
    node = tree
    for p in dotted.split("."):
        node = node[p]
    return node


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().to("cpu")
        if t.dtype.__str__() == "torch.bfloat16":
            t = t.float()
        return t.numpy()
    return np.asarray(t)


def hf_to_params(
    state_dict: Mapping[str, Any],
    config: ModelConfig,
    scan_layers: bool = True,
) -> PyTree:
    """Build our param tree (base weights only, no LoRA leaves) from an HF
    torch state_dict for Llama or GPT-NeoX/Pythia."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    if config.family == "llama":
        params = _llama_from_hf(sd, config)
    else:
        params = _neox_from_hf(sd, config)
    if scan_layers:
        params = stack_layers(params, config.num_hidden_layers)
    return params


def _llama_from_hf(sd: Dict[str, np.ndarray], cfg: ModelConfig) -> PyTree:
    p: Dict[str, Any] = {}
    prefix = "model." if "model.embed_tokens.weight" in sd else ""
    _set_path(p, "embed_tokens.embedding", sd[f"{prefix}embed_tokens.weight"])
    _set_path(p, "norm.scale", sd[f"{prefix}norm.weight"])
    _set_path(p, "lm_head.kernel", sd["lm_head.weight"].T)
    for i in range(cfg.num_hidden_layers):
        for ours, theirs in _LLAMA_LAYER_MAP.items():
            w = sd[f"{prefix}layers.{i}.{theirs}"]
            if ours.endswith(".kernel"):
                w = w.T
            _set_path(p, f"layers_{i}.{ours}", w)
    return p


def _neox_from_hf(sd: Dict[str, np.ndarray], cfg: ModelConfig) -> PyTree:
    p: Dict[str, Any] = {}
    prefix = "gpt_neox." if "gpt_neox.embed_in.weight" in sd else ""
    _set_path(p, "embed_in.embedding", sd[f"{prefix}embed_in.weight"])
    _set_path(p, "final_layer_norm.scale", sd[f"{prefix}final_layer_norm.weight"])
    _set_path(p, "final_layer_norm.bias", sd[f"{prefix}final_layer_norm.bias"])
    _set_path(p, "embed_out.kernel", sd["embed_out.weight"].T)
    for i in range(cfg.num_hidden_layers):
        for ours, theirs in _NEOX_LAYER_MAP.items():
            w = sd[f"{prefix}layers.{i}.{theirs}"]
            if ours.endswith(".kernel"):
                w = w.T
            # HF NeoX fuses qkv as interleaved (heads, 3, head_dim) on the out
            # dim; our fused layout matches it exactly (see models/pythia.py),
            # so no reshuffle is needed.
            _set_path(p, f"layers_{i}.{ours}", w)
    return p


def params_to_hf(params: PyTree, config: ModelConfig) -> Dict[str, np.ndarray]:
    """Export base weights (LoRA leaves must be merged/dropped first — see
    core.relora.merged_params) to an HF-style numpy state dict."""
    params = unstack_layers(dict(params))
    sd: Dict[str, np.ndarray] = {}
    if config.family == "llama":
        sd["model.embed_tokens.weight"] = np.asarray(_get_path(params, "embed_tokens.embedding"))
        sd["model.norm.weight"] = np.asarray(_get_path(params, "norm.scale"))
        sd["lm_head.weight"] = np.asarray(_get_path(params, "lm_head.kernel")).T
        for i in range(config.num_hidden_layers):
            for ours, theirs in _LLAMA_LAYER_MAP.items():
                w = np.asarray(_get_path(params, f"layers_{i}.{ours}"))
                if ours.endswith(".kernel"):
                    w = w.T
                sd[f"model.layers.{i}.{theirs}"] = w
    else:
        sd["gpt_neox.embed_in.weight"] = np.asarray(_get_path(params, "embed_in.embedding"))
        sd["gpt_neox.final_layer_norm.weight"] = np.asarray(_get_path(params, "final_layer_norm.scale"))
        sd["gpt_neox.final_layer_norm.bias"] = np.asarray(_get_path(params, "final_layer_norm.bias"))
        sd["embed_out.weight"] = np.asarray(_get_path(params, "embed_out.kernel")).T
        for i in range(config.num_hidden_layers):
            for ours, theirs in _NEOX_LAYER_MAP.items():
                w = np.asarray(_get_path(params, f"layers_{i}.{ours}"))
                if ours.endswith(".kernel"):
                    w = w.T
                sd[f"gpt_neox.layers.{i}.{theirs}"] = w
    return sd


_LORA_KEYS = ("lora_a", "lora_b", "lora_s")


def graft_base_weights(params: PyTree, base: PyTree) -> PyTree:
    """Copy base (non-LoRA) weights from ``base`` into an initialized
    (possibly LoRA-carrying) tree ``params`` — the warm-start operation
    (torchrun_main.py:505-553: load full-rank weights, then wrap with LoRA).

    LoRA leaves are skipped on BOTH sides: leaves in ``params`` keep their
    fresh init, and ``lora_*`` leaves in ``base`` (a checkpoint from a
    previous LoRA run) are ignored rather than grafted — warm-starting from
    an unmerged LoRA checkpoint should merge first (core.relora.merged_params)
    if the delta is wanted.
    """
    import jax.numpy as jnp

    dropped_lora = []

    def walk(p, b, path=""):
        out = dict(p)
        for k, v in b.items():
            here = f"{path}/{k}" if path else k
            if k in _LORA_KEYS:
                dropped_lora.append(here)
                continue
            if isinstance(v, Mapping):
                if k not in p or not isinstance(p[k], Mapping):
                    raise KeyError(
                        f"graft_base_weights: source subtree {here!r} has no "
                        f"matching subtree in the target params "
                        f"({'a leaf sits there' if k in p else f'keys there: {sorted(p)}'})"
                    )
                out[k] = walk(p[k], v, here)
            elif k == "kernel" and k not in p and "kernel_q" in p:
                # int8 target: quantize the f32 source on the fly
                from relora_tpu.ops.quant import quantize_int8

                q, s = quantize_int8(jnp.asarray(v))
                if p["kernel_q"].shape != q.shape:
                    raise ValueError(
                        f"shape mismatch for {here}: {p['kernel_q'].shape} vs {q.shape}"
                    )
                out["kernel_q"], out["kernel_scale"] = q, s
            elif k == "kernel" and k not in p and "kernel_codes" in p:
                # nf4 target: quantize the f32 source on the fly, preserving
                # the target's double-quant layout (bscale_q dtype)
                from relora_tpu.ops.quant import nf4_leaves_to_module, quantize_nf4

                leaves = quantize_nf4(
                    jnp.asarray(v), double_quant=p["kernel_bscale_q"].dtype == jnp.int8
                )
                if p["kernel_codes"].shape != leaves["codes"].shape:
                    raise ValueError(
                        f"shape mismatch for {here}: "
                        f"{p['kernel_codes'].shape} vs {leaves['codes'].shape}"
                    )
                out.update(nf4_leaves_to_module(leaves))
            else:
                if k not in p:
                    raise KeyError(
                        f"graft_base_weights: source leaf {here!r} has no "
                        f"counterpart in the target params (keys there: {sorted(p)})"
                    )
                if p[k].shape != v.shape:
                    raise ValueError(f"shape mismatch for {here}: {p[k].shape} vs {v.shape}")
                out[k] = jnp.asarray(v, dtype=p[k].dtype)
        return out

    grafted = walk(params, base)
    if dropped_lora:
        from relora_tpu.utils.logging import get_logger

        get_logger().warning(
            f"graft_base_weights: dropped {len(dropped_lora)} unmerged lora_* "
            f"leaves from the source checkpoint (e.g. {dropped_lora[0]}); their "
            "learned delta is NOT carried over — merge first "
            "(core.relora.merged_params) if you want it"
        )
    return grafted
