"""Learning-rate schedules as pure jnp functions of the update step.

The reference implements these as torch ``LambdaLR`` lambdas
(peft_pretraining/training_utils.py:173-236).  They are pure math, so here
they become optax-compatible schedules — callables ``step -> lr`` built from
``jnp.where`` so they can live inside a jitted train step (no Python control
flow on traced values).

Semantics match the reference exactly, including its quirks:

- ``cyclical_cosine``: on later cycles the first two warmup steps return the
  tiny constant 1e-7 (training_utils.py:179-183).
- ``cosine_restarts``: after the first warmup, every ``restart_every`` steps
  the LR is re-warmed over ``restart_warmup_steps`` up to the *decayed cosine
  envelope* value, with ``adjust_step`` phase-shifting the restart grid to
  sync with a warm-started checkpoint (training_utils.py:191-236).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def linear_with_warmup(peak_lr: float, warmup_steps: int, num_training_steps: int) -> Schedule:
    """HF-style linear warmup then linear decay to 0 (training_utils.py:71-77)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / max(1, warmup_steps)
        decay = jnp.maximum(
            0.0,
            (num_training_steps - step) / max(1, num_training_steps - warmup_steps),
        )
        return peak_lr * jnp.where(step < warmup_steps, warm, decay)

    return schedule


def cyclical_cosine_with_min_lr(
    peak_lr: float,
    warmup_steps: int,
    num_training_steps: int,
    cycle_length: Optional[int],
    min_lr_ratio: float = 0.1,
) -> Schedule:
    """Cyclical cosine with a min-LR floor (training_utils.py:103-118, 173-188)."""
    if cycle_length is None:
        cycle_length = num_training_steps
    if num_training_steps % cycle_length != 0:
        raise ValueError(
            f"num_training_steps ({num_training_steps}) must be divisible by "
            f"cycle_length ({cycle_length})"
        )
    if not 0 < min_lr_ratio <= 1.0:
        raise ValueError("min_lr_ratio must be in (0, 1]")

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        cycle_step = jnp.mod(step, cycle_length)
        # Later cycles: first 2 warmup steps pinned to 1e-7 (reference quirk).
        warm = jnp.where(
            (step != cycle_step) & (cycle_step < 2),
            1e-7,
            cycle_step / max(1, warmup_steps),
        )
        progress = (cycle_step - warmup_steps) / max(1, cycle_length - warmup_steps)
        cosine = 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        decayed = min_lr_ratio + (1.0 - min_lr_ratio) * cosine
        return peak_lr * jnp.where(cycle_step < warmup_steps, warm, decayed)

    return schedule


def cosine_with_restarts(
    peak_lr: float,
    first_warmup_steps: int,
    restart_warmup_steps: int,
    restart_every: int,
    num_training_steps: int,
    min_lr_ratio: float = 0.1,
    adjust_step: int = 0,
) -> Schedule:
    """Cosine decay with periodic re-warmups to the decayed envelope.

    This is the schedule ReLoRA couples to merge-and-reinit: each restart the
    LR ramps from 0 to the value the cosine envelope would have at the end of
    that warmup, then rejoins the global decay
    (training_utils.py:121-147, 191-236).
    """
    if restart_every is None:
        raise ValueError("restart_every (cycle_length) must be set for cosine_restarts")
    if restart_every <= 0:
        raise ValueError("restart_every must be positive")
    if num_training_steps % restart_every != 0:
        raise ValueError(
            f"num_training_steps ({num_training_steps}) must be divisible by "
            f"restart_every ({restart_every})"
        )
    if not 0 < min_lr_ratio <= 1.0:
        raise ValueError("min_lr_ratio must be in (0, 1]")
    if adjust_step + first_warmup_steps > num_training_steps:
        raise ValueError("warmup + adjust_step exceeds total training steps")
    if adjust_step + first_warmup_steps > restart_every:
        raise ValueError("the first restart would fire before the first warmup is done")

    denom = max(1, num_training_steps - first_warmup_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        first_warm = step / max(1, first_warmup_steps)

        s = step + adjust_step
        restart_step = jnp.mod(s, restart_every)
        restart_number = jnp.floor_divide(s, restart_every)

        # LR target at the end of this restart's warmup: the global envelope
        # evaluated at (restart boundary + restart_warmup_steps).
        end_of_warmup_progress = (
            restart_number * restart_every + restart_warmup_steps - first_warmup_steps
        ) / denom
        envelope = min_lr_ratio + (1.0 - min_lr_ratio) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * end_of_warmup_progress)
        )
        rewarm = restart_step / max(1, restart_warmup_steps) * envelope

        progress = (s - first_warmup_steps) / denom
        decayed = min_lr_ratio + (1.0 - min_lr_ratio) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * progress)
        )

        in_rewarm = (restart_step < restart_warmup_steps) & (step >= restart_every)
        value = jnp.where(in_rewarm, rewarm, decayed)
        return peak_lr * jnp.where(step < first_warmup_steps, first_warm, value)

    return schedule


def make_schedule(
    scheduler_type: str,
    *,
    lr: float,
    num_training_steps: int,
    warmup_steps: int,
    min_lr_ratio: float = 0.1,
    cycle_length: Optional[int] = None,
    restart_warmup_steps: Optional[int] = None,
    adjust_step: int = 0,
) -> Schedule:
    """Factory with the reference's dispatch semantics (training_utils.py:56-100)."""
    if adjust_step != 0 and scheduler_type != "cosine_restarts":
        raise ValueError("adjust_step is only supported for cosine_restarts")
    if scheduler_type == "linear":
        return linear_with_warmup(lr, warmup_steps, num_training_steps)
    if scheduler_type == "cosine":
        return cyclical_cosine_with_min_lr(
            lr, warmup_steps, num_training_steps, cycle_length, min_lr_ratio
        )
    if scheduler_type == "cosine_restarts":
        if restart_warmup_steps is None:
            raise ValueError("restart_warmup_steps must be set for cosine_restarts")
        return cosine_with_restarts(
            lr,
            first_warmup_steps=warmup_steps,
            restart_warmup_steps=restart_warmup_steps,
            restart_every=cycle_length,
            num_training_steps=num_training_steps,
            min_lr_ratio=min_lr_ratio,
            adjust_step=adjust_step,
        )
    raise NotImplementedError(f"Scheduler {scheduler_type!r} is not implemented")
