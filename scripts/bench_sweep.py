"""Parameterized on-chip throughput bench — the lever A/B harness.

Thin CLI over relora_tpu.utils.benchlib.run_throughput_bench (the same
measurement loop bench.py uses), with every lever exposed as a flag so each
configuration runs in its own process (the sandbox's remote-compile helper
holds per-process state; a fresh process per config also sidesteps
compile-cache interference when sweeping microbatch).  Prints ONE JSON line
per run.

Usage::

    python scripts/bench_sweep.py --micro-batch 8 --remat --loss-impl dense
    python scripts/bench_sweep.py --micro-batch 16 --loss-impl chunked \
        --logits-dtype bf16 --attn pallas
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WATCHDOG_SECS = int(os.environ.get("BENCH_WATCHDOG_SECS", "900"))


def _watchdog():
    print(json.dumps({"error": f"no result within {WATCHDOG_SECS}s"}))
    sys.stdout.flush()
    os._exit(2)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama_1b")
    p.add_argument("--micro-batch", type=int, default=8)
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--remat", action="store_true")
    p.add_argument(
        "--remat-policy", default="full", choices=["full", "dots", "dots_narrow", "dots_all"]
    )
    p.add_argument("--loss-impl", default="dense", choices=["dense", "chunked"])
    p.add_argument("--vocab-chunk", type=int, default=8192)
    p.add_argument("--logits-dtype", default="f32", choices=["f32", "bf16"])
    p.add_argument("--attn", default="auto")
    p.add_argument("--rank", type=int, default=128)
    p.add_argument(
        "--quantize", default="", choices=["", "int8", "nf4"], help="frozen-base storage"
    )
    p.add_argument(
        "--base-dtype", default="", choices=["", "bf16"],
        help="unquantized frozen-base storage dtype (default f32 master)",
    )
    p.add_argument("--dropout", type=float, default=0.1)
    p.add_argument("--prng", default="", help="jax_default_prng_impl override (e.g. rbg)")
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--label", default="")
    p.add_argument(
        "--out",
        default="",
        help="also append the JSON result line to this file (partial results "
        "survive a tunnel outage and can be committed as they land)",
    )
    args = p.parse_args()

    if args.prng:
        import jax

        jax.config.update("jax_default_prng_impl", args.prng)

    from relora_tpu.utils.benchlib import run_throughput_bench

    res = run_throughput_bench(
        args.model,
        micro_batch=args.micro_batch,
        grad_accum=args.grad_accum,
        seq=args.seq,
        remat=args.remat,
        remat_policy=args.remat_policy,
        loss_impl=args.loss_impl,
        vocab_chunk=args.vocab_chunk,
        logits_dtype=args.logits_dtype,
        attn=args.attn,
        rank=args.rank,
        quantize=args.quantize or None,
        base_dtype=args.base_dtype or None,
        dropout=args.dropout,
        warmup_steps=args.warmup,
        measure_steps=args.steps,
    )
    line = json.dumps(
        {
            "label": args.label
            or f"{args.model} mb{args.micro_batch} ga{args.grad_accum} seq{args.seq}"
            f" remat={int(args.remat)}:{args.remat_policy}"
            f" {args.loss_impl} {args.logits_dtype}"
            f" attn={args.attn}"
            + (f" quant={args.quantize}" if args.quantize else "")
            + (f" base={args.base_dtype}" if args.base_dtype else ""),
            "tokens_per_sec": res["tokens_per_sec"],
            "mfu": res["mfu"],
            "step_time_s": res["step_time_s"],
            "loss": round(res["loss"], 6),
            "hbm_peak_gb": res.get("hbm_peak_gb"),
            # benchlib floors warmup to 1 step; surface the effective count
            # so a --warmup 0 sweep can't misattribute its measurement
            "warmup_steps_effective": res.get("warmup_steps_effective"),
        }
    )
    print(line)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    timer = threading.Timer(WATCHDOG_SECS, _watchdog)
    timer.daemon = True
    timer.start()
    main()
    timer.cancel()
