"""Fused LoRA kernel + dispatch tests (interpret mode on CPU).

Acceptance for ISSUE 4: the fused ``x@W + ((x@A)@B)*scale`` Pallas composite
must be numerically equivalent to the unfused reference — forward AND
gradients, per-dtype atol — for every tested shape, and dispatch
(``lora_matmul``'s arm selection) may change the compute graph but never the
numerics.  The TPU path shares the exact kernel bodies; only the
``interpret=True`` execution differs.
"""

import logging

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.core.relora import LoraSpec
from relora_tpu.models.lora import LoRALinear
from relora_tpu.ops.lora_dispatch import (
    ARMS,
    choose_arm,
    estimate_arm_times,
    lora_matmul,
    plan_blocks,
)
from relora_tpu.ops.pallas_lora_matmul import (
    fused_lora_matmul,
    fused_lora_matmul_int8,
)
from relora_tpu.ops.quant import dequantize_int8, quantize_int8

# Per-dtype forward/grad tolerance: both paths accumulate in f32, so f32 is
# near-exact; bf16 differs by the final output rounding (and the unfused
# arms' intermediate casts), which scales with sqrt(K)-magnitude outputs.
TOL = {jnp.float32: 1e-4, jnp.bfloat16: 0.5}


def _operands(M, K, N, r, dtype=jnp.float32, seed=0):
    k = jax.random.PRNGKey(seed)
    x = jax.random.normal(jax.random.fold_in(k, 1), (M, K), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(k, 2), (K, N), jnp.float32)
    a = jax.random.normal(jax.random.fold_in(k, 3), (K, r), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.fold_in(k, 4), (r, N), jnp.float32) * 0.1
    return tuple(t.astype(dtype) for t in (x, w, a, b))


def _reference(x, w, a, b, scale):
    """The unfused ordered composite, computed in f32."""
    x32, w32, a32, b32 = (t.astype(jnp.float32) for t in (x, w, a, b))
    return x32 @ w32 + (x32 @ a32) @ b32 * scale


def _max_err(got, want):
    return float(jnp.abs(got.astype(jnp.float32) - jnp.asarray(want)).max())


# ---------------------------------------------------------------------------
# fused kernel: forward + backward parity vs the unfused reference
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16], ids=["f32", "bf16"])
@pytest.mark.parametrize("r", [8, 128])
def test_fused_forward_parity(dtype, r):
    M, K, N = 64, 256, 128
    x, w, a, b = _operands(M, K, N, r, dtype)
    got = fused_lora_matmul(x, w, a, b, 0.5, block_m=32, block_n=128, interpret=True)
    assert got.dtype == dtype
    assert _max_err(got, _reference(x, w, a, b, 0.5)) < TOL[dtype]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16], ids=["f32", "bf16"])
@pytest.mark.parametrize("r", [8, 128])
def test_fused_grad_parity(dtype, r):
    """dx, dA, dB from the fused custom_vjp == grads of the unfused
    reference; the frozen base W gets a symbolically-zero cotangent."""
    M, K, N = 32, 256, 128
    x, w, a, b = _operands(M, K, N, r, dtype)

    def loss_fused(x, w, a, b, s):
        y = fused_lora_matmul(x, w, a, b, s, block_m=32, block_n=128, interpret=True)
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    def loss_ref(x, w, a, b, s):
        # round y through the output dtype like the kernel does — sin() is
        # nonlinear, so comparing cotangents of a bf16 y against an f32 y
        # would measure the dtype, not the kernel
        y = _reference(x, w, a, b, s).astype(dtype)
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    s = jnp.float32(0.5)
    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, w, a, b, s)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, w, a, b, s)
    for name, f_, r_ in zip("xwabs", gf, gr):
        if name == "w":
            # frozen-base contract: fused returns exactly zero for W
            assert float(jnp.abs(f_).max()) == 0.0
            continue
        assert _max_err(f_, r_.astype(jnp.float32)) < TOL[dtype], f"d{name}"


@pytest.mark.parametrize("r", [8, 128])
def test_fused_int8_parity(r):
    """Int8-base variant: dequant folded into the kernel.  Forward, dx/dA/dB,
    and the true dqscale gradient all match dequantize-then-reference."""
    M, K, N = 32, 256, 128
    x, w, a, b = _operands(M, K, N, r)
    q, qs = quantize_int8(w * 0.1)

    def loss_fused(x, qs, a, b):
        y = fused_lora_matmul_int8(
            x, q, qs, a, b, 0.5, block_m=32, block_n=128, interpret=True
        )
        return jnp.sum(jnp.sin(y))

    def loss_ref(x, qs, a, b):
        return jnp.sum(jnp.sin(_reference(x, q.astype(jnp.float32) * qs, a, b, 0.5)))

    got = fused_lora_matmul_int8(x, q, qs, a, b, 0.5, block_m=32, block_n=128, interpret=True)
    want = _reference(x, q.astype(jnp.float32) * qs, a, b, 0.5)
    assert _max_err(got, want) < 1e-4

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, qs, a, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, qs, a, b)
    for name, f_, r_ in zip(("x", "qscale", "a", "b"), gf, gr):
        denom = max(1.0, float(jnp.abs(r_).max()))
        assert _max_err(f_, r_) / denom < 1e-4, f"d{name}"


def test_fused_trainable_scale_grad():
    """ds (the trainable-scaling cotangent) matches the reference."""
    M, K, N, r = 32, 256, 128, 8
    x, w, a, b = _operands(M, K, N, r)

    def loss(s, fn):
        return jnp.sum(jnp.sin(fn(s)))

    fused = lambda s: fused_lora_matmul(x, w, a, b, s, block_m=32, block_n=128, interpret=True)
    ref = lambda s: _reference(x, w, a, b, s)
    gs_f = jax.grad(loss)(jnp.float32(0.37), fused)
    gs_r = jax.grad(loss)(jnp.float32(0.37), ref)
    np.testing.assert_allclose(float(gs_f), float(gs_r), rtol=1e-5)


def test_fused_batched_leading_dims():
    """(B, T, K) activations flatten to (B*T, K) and reshape back."""
    B, T, K, N, r = 4, 16, 256, 128, 8
    x2, w, a, b = _operands(B * T, K, N, r)
    x = x2.reshape(B, T, K)
    got = lora_matmul(x, w, a, b, 0.5, arm="fused", interpret=True)
    assert got.shape == (B, T, N)
    want = _reference(x2, w, a, b, 0.5).reshape(B, T, N)
    assert _max_err(got, want) < 1e-4


def test_fused_validation_errors():
    x, w, a, b = _operands(32, 256, 128, 8)
    with pytest.raises(ValueError, match="tile"):
        fused_lora_matmul(x[:30], w, a, b, 1.0, block_m=8, block_n=128, interpret=True)
    with pytest.raises(ValueError, match="mismatch|shape"):
        fused_lora_matmul(x, w[:128], a, b, 1.0, block_m=32, block_n=128, interpret=True)


# ---------------------------------------------------------------------------
# dispatch: cost model + the never-changes-numerics property
# ---------------------------------------------------------------------------


def test_plan_blocks():
    assert plan_blocks(256, 256) == (256, 256)
    assert plan_blocks(40, 128) == (8, 128)  # sublane shrinks to keep tiling
    assert plan_blocks(7, 128) is None  # M has no candidate divisor
    assert plan_blocks(32, 100) is None  # N not lane-aligned


def test_choose_arm_regimes():
    """The selections the cost model exists for (docs/kernels.md)."""
    # decode-sized M with static (serving) weights: merged amortizes to a
    # bare matmul
    assert choose_arm(8, 2048, 2048, 128, weights_static=True) == "merged"
    # training-sized M on TPU: fused
    assert choose_arm(512, 2048, 2048, 128) == "fused"
    # very large M: merged wins on FLOPs alone (Run LoRA Run crossover
    # M > K*N/(K+N))
    assert choose_arm(65536, 2048, 2048, 128) == "merged"
    # fused unavailable (non-TPU backend): never fused
    assert choose_arm(512, 2048, 2048, 128, fused_available=False) != "fused"
    # untileable shape: fused struck even when nominally available
    assert choose_arm(7, 2048, 2048, 128) != "fused"
    # allow= restricts the candidate set
    assert choose_arm(512, 2048, 2048, 128, allow=("ordered",)) == "ordered"


def test_estimate_arm_times_sane():
    t = estimate_arm_times(512, 2048, 2048, 128)
    assert set(t) == set(ARMS)
    assert all(v > 0 for v in t.values())
    # fused reads strictly fewer bytes with fewer launches than ordered
    assert t["fused"] < t["ordered"]


@pytest.mark.parametrize("quantized", [False, True], ids=["dense", "int8"])
@pytest.mark.parametrize("M", [8, 32, 4096])
def test_dispatch_never_changes_numerics(M, quantized):
    """The property the whole dispatcher rests on: every arm (and auto, and
    both weights_static settings) produces the same value within tolerance —
    dispatch changes the compute graph, never the result."""
    K, N, r = 256, 128, 8
    x, w, a, b = _operands(M, K, N, r, seed=M)
    base = quantize_int8(w * 0.1) if quantized else w
    wd = dequantize_int8(*base, jnp.float32) if quantized else w
    want = _reference(x, wd, a, b, 0.25)

    arms = list(ARMS) + ["auto"]
    for arm in arms:
        for ws in (False, True):
            got = lora_matmul(
                x, base, a, b, 0.25, arm=arm, weights_static=ws, interpret=True
            )
            assert _max_err(got, want) < 1e-4, f"arm={arm} weights_static={ws}"


def test_dispatch_grads_arm_independent():
    """d(x, a, b) agree across arms (the base is stop_gradient'd by the
    module caller; here we diff only the trainable operands)."""
    M, K, N, r = 32, 256, 128, 8
    x, w, a, b = _operands(M, K, N, r)

    def loss(x, a, b, arm):
        y = lora_matmul(x, jax.lax.stop_gradient(w), a, b, 0.25, arm=arm, interpret=True)
        return jnp.sum(jnp.sin(y))

    ref = jax.grad(loss, argnums=(0, 1, 2))(x, a, b, "ordered")
    for arm in ("fused", "merged", "auto"):
        got = jax.grad(loss, argnums=(0, 1, 2))(x, a, b, arm)
        for name, g_, r_ in zip("xab", got, ref):
            denom = max(1.0, float(jnp.abs(r_).max()))
            assert _max_err(g_, r_) / denom < 1e-4, f"arm={arm} d{name}"


def test_dispatch_untileable_falls_back():
    """Forcing arm="fused" on a shape with no block plan quietly takes the
    ordered path — bit-identical to it, no error."""
    M, K, N, r = 7, 256, 100, 8  # neither M nor N tiles
    x, w, a, b = _operands(M, K, N, r)
    forced = lora_matmul(x, w, a, b, 0.25, arm="fused", interpret=True)
    ordered = lora_matmul(x, w, a, b, 0.25, arm="ordered")
    np.testing.assert_array_equal(np.asarray(forced), np.asarray(ordered))


def test_dispatch_rejects_unknown_arm():
    x, w, a, b = _operands(8, 256, 128, 8)
    with pytest.raises(ValueError, match="unknown arm"):
        lora_matmul(x, w, a, b, arm="bogus")


def test_auto_never_interprets_on_cpu():
    """On a non-TPU backend, arm="auto" must not pick the fused interpreter."""
    M, K, N, r = 512, 256, 128, 8
    assert jax.default_backend() != "tpu"
    arm = choose_arm(M, K, N, r, fused_available=jax.default_backend() == "tpu")
    assert arm != "fused"


# ---------------------------------------------------------------------------
# module integration: LoRALinear with spec.fused
# ---------------------------------------------------------------------------


def _init(model, x, seed=0):
    return nn.meta.unbox(model.init(jax.random.PRNGKey(seed), x, deterministic=True))


def _perturb_lora_b(params, seed=9):
    """lora_b is zeros at init (init-equivalence invariant); perturb it so
    the LoRA branch actually contributes and parity tests bite."""
    p = jax.tree_util.tree_map(lambda t: t, params)
    b = p["params"]["lora_b"]
    p["params"]["lora_b"] = jax.random.normal(jax.random.PRNGKey(seed), b.shape, b.dtype) * 0.1
    return p


@pytest.mark.parametrize("quantize", [None, "int8"], ids=["dense", "int8"])
@pytest.mark.parametrize("fused", [True, "auto"], ids=["fused", "auto"])
@pytest.mark.parametrize("trainable_scaling", [False, True], ids=["static-s", "tanh-s"])
def test_module_fused_matches_unfused(quantize, fused, trainable_scaling):
    """LoRALinear(spec.fused) == LoRALinear(historical) — same param tree,
    same forward — for dense and int8 bases, with bias, both scale modes."""
    spec_kw = dict(r=8, alpha=16, trainable_scaling=trainable_scaling)
    m_ref = LoRALinear(
        features=128, use_bias=True, lora=LoraSpec(**spec_kw),
        dtype=jnp.float32, quantize=quantize,
    )
    m_fused = LoRALinear(
        features=128, use_bias=True, lora=LoraSpec(fused=fused, **spec_kw),
        dtype=jnp.float32, quantize=quantize,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    p = _perturb_lora_b(_init(m_ref, x))
    # identical param trees: both paths define the same name-keyed leaves
    p_fused = _init(m_fused, x)
    assert jax.tree_util.tree_structure(p) == jax.tree_util.tree_structure(p_fused)

    want = m_ref.apply(p, x, deterministic=True)
    got = m_fused.apply(p, x, deterministic=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_module_fused_grads_match_unfused():
    """Training-relevant parity: d(lora_a, lora_b) identical across paths;
    the frozen kernel gets zero grad under dispatch (stop_gradient contract —
    the optimizer mask never applies base updates either way)."""
    spec = dict(r=8, alpha=16)
    m_ref = LoRALinear(features=128, lora=LoraSpec(**spec), dtype=jnp.float32)
    m_fused = LoRALinear(features=128, lora=LoraSpec(fused=True, **spec), dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    p = _perturb_lora_b(_init(m_ref, x))

    def loss(params, model):
        return jnp.sum(model.apply(params, x, deterministic=True) ** 2)

    g_ref = jax.grad(loss)(p, m_ref)["params"]
    g_fused = jax.grad(loss)(p, m_fused)["params"]
    for leaf in ("lora_a", "lora_b"):
        np.testing.assert_allclose(
            np.asarray(g_fused[leaf]), np.asarray(g_ref[leaf]), atol=1e-4
        )
    assert float(jnp.abs(g_fused["kernel"]).max()) == 0.0


def test_module_dropout_keeps_historical_path():
    """Dropout-active calls can't fuse (branch input differs from base
    input): spec.fused must still produce the historical dropout forward."""
    spec = LoraSpec(r=8, alpha=16, dropout=0.5, fused=True)
    m = LoRALinear(features=128, lora=spec, dtype=jnp.float32)
    m_ref = LoRALinear(
        features=128, lora=LoraSpec(r=8, alpha=16, dropout=0.5), dtype=jnp.float32
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    p = _perturb_lora_b(_init(m, x))
    rng = {"dropout": jax.random.PRNGKey(3)}
    got = m.apply(p, x, deterministic=False, rngs=rng)
    want = m_ref.apply(p, x, deterministic=False, rngs=rng)
    # same dropout mask (same rng), same math -> identical outputs
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)
    # and the deterministic call dispatches without dropout
    det = m.apply(p, x, deterministic=True)
    assert bool(jnp.isfinite(det).all())


def test_module_untileable_width_falls_back():
    """features=100 never lane-aligns: the dispatched path must still be
    correct (ordered fallback inside the dispatcher)."""
    m_ref = LoRALinear(features=100, lora=LoraSpec(r=8, alpha=16), dtype=jnp.float32)
    m_fused = LoRALinear(
        features=100, lora=LoraSpec(r=8, alpha=16, fused=True), dtype=jnp.float32
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (10, 64))
    p = _perturb_lora_b(_init(m_ref, x))
    np.testing.assert_allclose(
        np.asarray(m_fused.apply(p, x, deterministic=True)),
        np.asarray(m_ref.apply(p, x, deterministic=True)),
        atol=1e-5,
    )


def test_lora_spec_validates_fused():
    with pytest.raises(ValueError, match="fused"):
        LoraSpec(r=8, alpha=16, fused="sometimes")
    for ok in (True, False, "auto"):
        LoraSpec(r=8, alpha=16, fused=ok)


def test_pallas_quant_env_hoisted_to_construction(monkeypatch):
    """RELORA_TPU_PALLAS_QUANT is read once at module construction, never in
    the traced __call__ (the RTL1xx retrace footgun).  Flipping the env after
    construction must not change behavior; the explicit field wins over env."""
    monkeypatch.delenv("RELORA_TPU_PALLAS_QUANT", raising=False)
    m_off = LoRALinear(features=128, quantize="int8", lora=LoraSpec(r=4, alpha=8))
    assert m_off.pallas_quant is False
    monkeypatch.setenv("RELORA_TPU_PALLAS_QUANT", "1")
    m_on = LoRALinear(features=128, quantize="int8", lora=LoraSpec(r=4, alpha=8))
    assert m_on.pallas_quant is True
    # flipping the env after construction does not retro-affect the module
    monkeypatch.delenv("RELORA_TPU_PALLAS_QUANT", raising=False)
    assert m_on.pallas_quant is True
    # explicit field beats env
    assert LoRALinear(features=8, pallas_quant=False).pallas_quant is False


def test_dequant_matmul_bwd_warns_once():
    """Satellite fix: the standalone int8 kernel's backward fallback
    (dequantize-then-matmul) logs once per shape at trace time instead of
    silently misattributing backward cost in kernel benchmarks."""
    from relora_tpu.ops.pallas_quant_matmul import _BWD_FALLBACK_WARNED, dequant_matmul

    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64))
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 128)) * 0.1
    q, s = quantize_int8(w)
    _BWD_FALLBACK_WARNED.discard((8, 64, 128))  # isolate from suite ordering

    def loss(x):
        return jnp.sum(dequant_matmul(x, q, s, block_m=8, block_n=128, interpret=True))

    # capture on the module logger directly: utils/logging.get_logger sets
    # propagate=False on the "relora_tpu" parent, so caplog's root handler
    # would miss these records once any other test has configured logging
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    module_logger = logging.getLogger("relora_tpu.ops.pallas_quant_matmul")
    handler = _Capture(level=logging.INFO)
    old_level = module_logger.level
    module_logger.addHandler(handler)
    module_logger.setLevel(logging.INFO)
    try:
        jax.grad(loss)(x)
        jax.grad(loss)(x)
    finally:
        module_logger.removeHandler(handler)
        module_logger.setLevel(old_level)
    hits = [r for r in records if "fallback" in r.getMessage()]
    assert len(hits) == 1
