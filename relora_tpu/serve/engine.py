"""Jitted prefill/decode step functions over the cache-aware model forwards.

The models gained a ``decode=True`` mode (models/llama.py, models/pythia.py):
attention keeps per-layer K/V buffers of fixed capacity in the flax ``cache``
variable collection, writes the current chunk at its absolute positions, and
attends with the ``j <= position`` visibility mask (ops/attention.py:
cached_attention).  This module wraps that into an inference engine:

- ``prefill(ids, lengths)`` — run the whole (right-padded) prompt batch in one
  forward, returning full logits and a populated cache.  Pad tokens write
  garbage K/V beyond each row's length, but an entry at index ``j`` only
  becomes visible to queries at positions ``>= j`` — and the decode loop
  overwrites index ``j`` at the step that reaches position ``j``, before it
  ever attends.  So right-padding needs no separate pad mask.
- ``decode(cache, token, pos)`` — one token per row against the cache, cache
  buffers donated so XLA updates them in place (no per-step reallocation).
- ``insert(dcache, pcache, slot)`` — copy a freshly prefilled single-row cache
  into slot ``slot`` of the persistent decode cache (continuous batching
  admission).  ``slot`` is traced, so admissions never retrace.

Prompt lengths are bucketed to powers of two (``bucket_length``) to bound the
number of prefill compilations.

Paged mode (``page_size``/``num_pages`` set): the contiguous per-slot cache is
replaced by one shared page pool (serve/paging.py) and two entry points —
``prefill_chunk(ids, start, pool, block_table)`` writes one fixed-size prompt
chunk straight into the pool through the request's block table (no insert
copy), and ``decode_paged(pool, token, pos, block_tables)`` decodes every slot
through its table.  Both compile exactly once: prompt length appears in no
compiled shape, and cache HBM scales with ``num_pages``, not
``max_batch × cache_size``.

Shardings: with a mesh, params shard per the model's logical annotations
(parallel/mesh.py LOGICAL_RULES), cache buffers shard their batch axis over
``data``×``fsdp``, and K/V heads — contiguous cache and page pool alike —
shard over ``tensor`` when divisible, matching the ``kv`` logical axis of
the k/v projection kernels.  Sharding the pool by head drops per-chip pool
bytes by the tp degree, and the engine returns that HBM as proportionally
more pages (``num_pages`` is the per-chip page budget).  Without a mesh the
same code runs single-host (CPU tests, dev boxes).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.obs import memory as obs_memory
from relora_tpu.obs.compile import CompileWatcher
from relora_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, param_shardings
from relora_tpu.serve.paging import NULL_PAGE
from relora_tpu.serve.sampling import SamplingParams, sample

PyTree = Any

# leaves are (B, capacity, kv_heads, head_dim), plus a leading scan-layers
# axis when the model scans; the batch axis is always ndim-4
_CACHE_RANK = 4


def _cache_batch_axis(leaf) -> int:
    return leaf.ndim - _CACHE_RANK


def bucket_length(n: int, minimum: int = 16) -> int:
    """Round a prompt length up to the next power of two (>= minimum) so
    prefill compiles once per bucket, not once per prompt length."""
    if n < 1:
        raise ValueError(f"prompt length must be >= 1, got {n}")
    return max(minimum, 1 << (n - 1).bit_length())


# multi-tenant slot writes: stacked lora_a/lora_b leaves are
# (…, num_slots, in, r) / (…, num_slots, r, out) — the slot axis sits at
# ndim-3 (a leading scan-layers axis may precede it); the per-slot scale
# lora_s is (…, num_slots) with the slot axis last
_LORA_FACTOR_LEAVES = ("lora_a", "lora_b")


def _factor_slot_axis(stacked) -> int:
    return stacked.ndim - 3


def _set_adapter_slot(stacked, block, slot):
    axis = _factor_slot_axis(stacked)
    block = jnp.expand_dims(jnp.asarray(block).astype(stacked.dtype), axis)
    starts = [0] * stacked.ndim
    starts[axis] = slot
    return jax.lax.dynamic_update_slice(stacked, block, tuple(starts))


def _set_adapter_scale(s_leaf, scale, slot):
    shape = list(s_leaf.shape)
    shape[-1] = 1
    block = jnp.full(tuple(shape), scale, s_leaf.dtype)
    starts = [0] * s_leaf.ndim
    starts[-1] = slot
    return jax.lax.dynamic_update_slice(s_leaf, block, tuple(starts))


def _write_adapter_slot_tree(params, factors, scale, slot):
    """Pure slot overwrite: returns ``params`` with adapter ``slot``'s
    lora_a/lora_b slabs replaced by ``factors`` (zeros where the adapter has
    no factor for a module) and its lora_s entry set to ``scale``.  ``slot``
    and ``scale`` are traced — one compile serves every load/evict/swap."""
    out = {}
    for key, value in params.items():
        f = factors.get(key) if isinstance(factors, dict) else None
        if isinstance(value, dict):
            out[key] = _write_adapter_slot_tree(
                value, f if isinstance(f, dict) else {}, scale, slot
            )
        elif key in _LORA_FACTOR_LEAVES:
            if f is None:
                axis = _factor_slot_axis(value)
                f = jnp.zeros(value.shape[:axis] + value.shape[axis + 1 :], value.dtype)
            out[key] = _set_adapter_slot(value, f, slot)
        elif key == "lora_s":
            out[key] = _set_adapter_scale(value, scale, slot)
        else:
            out[key] = value
    return out


def _reload_params_tree(params, fresh):
    """Pure full-tree weight swap: returns ``params`` with every leaf present
    in ``fresh`` replaced.  Leaves ``fresh`` omits (the multi-tenant adapter
    slabs, which a checkpoint reload must never clobber) pass through from
    the live tree.  The live tree is donated, so the swap reuses its HBM
    buffers instead of doubling resident params mid-serve."""
    out = {}
    for key, value in params.items():
        f = fresh.get(key) if isinstance(fresh, dict) else None
        if isinstance(value, dict):
            out[key] = _reload_params_tree(value, f if isinstance(f, dict) else {})
        elif f is None:
            out[key] = value
        else:
            out[key] = f
    return out


def _pages_axis(ndim: int) -> int:
    """Pages axis of a pool leaf: code leaves are ``(..., num_pages,
    page_size, kv_heads, head_dim)`` (axis ndim-4), int8 scale leaves are
    ``(..., num_pages, kv_heads)`` (axis ndim-2) — a leading layers axis
    when scanned shifts both the same way."""
    return ndim - 4 if ndim >= 4 else ndim - 2


def build_decode_model(
    model_cfg: ModelConfig,
    *,
    cache_size: int,
    dtype=jnp.float32,
    scan_layers: bool = True,
    attention_impl: str = "auto",
    lora: Optional[LoraSpec] = None,
    page_size: int = 0,
    num_pages: int = 0,
    kv_dtype: str = "bf16",
    adapter_slots: int = 0,
):
    """The serving twin of train.trainer.build_model: same family dispatch,
    decode cache enabled, no remat.  ``lora=None`` (the default) serves a
    merged, LoRA-free param tree; passing the checkpoint's ``LoraSpec``
    serves the factors unmerged (quantized bases that can't absorb the
    delta, or adapter hot-swap).  An unmerged spec is rewritten for decode:
    ``weights_static`` tells ops/lora_dispatch's cost model that W/A/B are
    constant across steps, and ``fused=False`` is promoted to ``"auto"`` so
    the decode forward actually routes through the dispatcher — which picks
    the merged ``x @ (W + s·A@B)`` arm at decode-sized M.

    ``adapter_slots > 0`` switches every LoRA leaf to the stacked
    multi-tenant layout (models/lora.py ``num_slots``): factors become
    ``(adapter_slots, …)`` HBM slabs and every forward takes a per-row
    ``adapter_idx`` routed through the grouped kernel.  Slot 0 is the
    zero-initialized identity adapter."""
    if lora is not None:
        lora = dataclasses.replace(
            lora,
            weights_static=True,
            fused="auto" if lora.fused is False else lora.fused,
            num_slots=adapter_slots if adapter_slots else lora.num_slots,
        )
    kwargs = dict(
        config=model_cfg,
        lora=lora,
        dtype=dtype,
        scan_layers=scan_layers,
        remat=False,
        attention_impl=attention_impl,
        logits_dtype=jnp.float32,
        decode=True,
        cache_size=cache_size,
        page_size=page_size,
        num_pages=num_pages,
        kv_dtype=kv_dtype,
    )
    if model_cfg.family == "llama":
        from relora_tpu.models.llama import LlamaForCausalLM

        return LlamaForCausalLM(**kwargs)
    if model_cfg.family == "neox":
        from relora_tpu.models.pythia import GPTNeoXForCausalLM

        return GPTNeoXForCausalLM(**kwargs)
    raise ValueError(f"Unknown model family {model_cfg.family!r}")


class InferenceEngine:
    """Owns the decode-mode model, the jitted step functions, and placement.

    ``params`` must match the training layout (scan-stacked layers when
    ``scan_layers``): a merged LoRA-free tree by default (see
    train.checkpoint.restore_serving_params), or — with ``lora=`` set to the
    checkpoint's spec — the raw tree with its LoRA factors still separate.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        params: PyTree,
        *,
        cache_size: int,
        dtype=jnp.float32,
        scan_layers: bool = True,
        attention_impl: str = "auto",
        mesh: Optional[Mesh] = None,
        lora: Optional[LoraSpec] = None,
        compile_watcher: Optional[CompileWatcher] = None,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        chunk_size: int = 64,
        kv_dtype: str = "bf16",
        spec_k: int = 0,
        adapter_slots: int = 0,
        token_budget: Optional[int] = None,
    ):
        if cache_size < 1:
            raise ValueError(f"cache_size must be >= 1, got {cache_size}")
        if token_budget is not None:
            if page_size is None:
                raise ValueError(
                    "token_budget requires the paged engine (page_size set)"
                )
            if token_budget < 1:
                raise ValueError(f"token_budget must be >= 1, got {token_budget}")
        self.token_budget = token_budget or 0
        if adapter_slots:
            if lora is None:
                raise ValueError(
                    "adapter_slots > 0 requires the checkpoint's LoraSpec "
                    "(multi-tenant serving runs the factors unmerged)"
                )
            if adapter_slots < 2:
                raise ValueError(
                    f"adapter_slots must be >= 2 (slot 0 is the identity "
                    f"adapter), got {adapter_slots}"
                )
        self.adapter_slots = adapter_slots
        if kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"kv_dtype must be 'bf16' or 'int8', got {kv_dtype!r}")
        if kv_dtype == "int8" and page_size is None:
            raise ValueError("kv_dtype='int8' requires the paged engine (page_size set)")
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if spec_k and page_size is None:
            raise ValueError("spec_k > 0 requires the paged engine (page_size set)")
        self.spec_k = spec_k
        # "bf16" means the pool stores at the engine compute dtype
        # (unquantized — bf16 in the serving default, f32 in CPU tests, so
        # the bitwise paged-vs-contiguous parity invariant is untouched);
        # "int8" stores codes + per-(page, kv_head) f32 scales
        self.kv_dtype = kv_dtype
        self.config = model_cfg
        self.cache_size = cache_size
        self.mesh = mesh
        # paged mode: page_size enables the block-granular pool (see
        # serve/paging.py); cache_size stays the per-request capacity bound
        # (validate_request semantics unchanged) and must page-align so the
        # gathered table width W*page_size equals the contiguous contraction
        # length C — the bitwise token-parity invariant
        self.paged = page_size is not None
        if self.paged:
            if page_size < 1:
                raise ValueError(f"page_size must be >= 1, got {page_size}")
            if cache_size % page_size:
                raise ValueError(
                    f"cache_size ({cache_size}) must be a multiple of "
                    f"page_size ({page_size}) for paged decode"
                )
            self.block_table_width = cache_size // page_size
            if num_pages is None:
                raise ValueError("paged decode requires num_pages")
            if num_pages < self.block_table_width + 1:
                raise ValueError(
                    f"num_pages ({num_pages}) cannot hold one max-size request: "
                    f"need >= {self.block_table_width} + 1 (page 0 is the null page)"
                )
            if chunk_size < 1:
                raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        self.page_size = page_size or 0
        # tp sharding of the pool: each tensor shard holds kv_heads/kv_shards
        # heads of EVERY page, so per-chip pool bytes drop by kv_shards — the
        # freed HBM is returned as kv_shards× more pages (num_pages is the
        # per-chip page budget; the pool grows with the chips serving it)
        self.kv_shards = 1
        if mesh is not None and mesh.shape[TENSOR_AXIS] > 1:
            if model_cfg.kv_heads % mesh.shape[TENSOR_AXIS] == 0:
                self.kv_shards = mesh.shape[TENSOR_AXIS]
        self.requested_num_pages = num_pages or 0
        self.num_pages = (num_pages or 0) * (self.kv_shards if num_pages else 1)
        self.chunk_size = min(chunk_size, cache_size)
        self.model = build_decode_model(
            model_cfg,
            cache_size=cache_size,
            dtype=dtype,
            scan_layers=scan_layers,
            attention_impl=attention_impl,
            lora=lora,
            adapter_slots=adapter_slots,
        )
        if adapter_slots:
            # the checkpoint carries unstacked (in, r) factors; the slotted
            # model wants (num_slots, in, r) slabs.  Rebuild: non-LoRA leaves
            # from the checkpoint, LoRA leaves fresh (zeros / spec scale) so
            # slot 0 is the identity adapter — the base checkpoint's own A/B
            # are deliberately dropped (tenants load theirs via the registry)
            params = self._stack_adapter_params(params, lora)
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if mesh is not None:
            from relora_tpu.models.params_util import logical_partition_specs

            sample_ids = jnp.zeros((1, 1), jnp.int32)
            specs = logical_partition_specs(self.model, sample_ids)
            shardings = param_shardings(mesh, specs)
            params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        self.params = params
        # optional second tree for model-drafted speculation (--spec model):
        # same shapes/dtypes/shardings as params, installed via
        # load_draft_params, fed through the SAME compiled paged programs
        self.draft_params: Optional[PyTree] = None

        def prefill_fn(p, ids, positions, cache, adapter_idx):
            logits, variables = self.model.apply(
                {"params": p, "cache": cache}, ids, positions=positions,
                adapter_idx=adapter_idx, mutable=["cache"]
            )
            return logits, variables["cache"]

        def decode_fn(p, cache, token, pos, adapter_idx):
            logits, variables = self.model.apply(
                {"params": p, "cache": cache}, token, positions=pos,
                adapter_idx=adapter_idx, mutable=["cache"]
            )
            return logits[:, -1, :], variables["cache"]

        def insert_fn(dcache, pcache, slot):
            def ins(d, src):
                starts = [0] * d.ndim
                starts[_cache_batch_axis(d)] = slot
                return jax.lax.dynamic_update_slice(d, src.astype(d.dtype), tuple(starts))

            return jax.tree_util.tree_map(ins, dcache, pcache)

        # the fresh prefill cache and the persistent decode cache are both
        # donated: the step's output cache reuses the input buffers in place.
        # The compile watcher tracks each entry point's abstract signatures:
        # warmup() compiles are tagged expected, anything after counts toward
        # compile_steady_state_retraces (docs/observability.md)
        self.compile_watcher = compile_watcher or CompileWatcher(service="engine")
        cw = self.compile_watcher
        self._prefill = cw.wrap("prefill", jax.jit(prefill_fn, donate_argnums=(3,)))
        self._decode = cw.wrap("decode", jax.jit(decode_fn, donate_argnums=(1,)))
        self._insert = cw.wrap("insert", jax.jit(insert_fn, donate_argnums=(0,)))
        self._sample = jax.jit(sample, static_argnames=("top_k",))

        if adapter_slots:
            # slot writes donate the param tree and trace slot/scale: every
            # adapter load/evict/swap reuses one compiled program (the
            # zero-steady-state-retrace contract for mid-traffic churn)
            self._write_slot = cw.wrap(
                "adapter_write", jax.jit(_write_adapter_slot_tree, donate_argnums=(0,))
            )
            self._factor_template = self._adapter_factor_template()
        # full-tree hot swap (reload_params): the adapter-writer seam scaled
        # up to the whole merged tree — donated live params, host leaves cast
        # onto the live dtypes, one compiled program across every reload
        self._reload = cw.wrap(
            "params_reload", jax.jit(_reload_params_tree, donate_argnums=(0,))
        )

        if self.paged:
            # a second model instance over the same params: cache variables
            # are the shared (num_pages, page_size, n_kv, head_dim) pool and
            # every forward takes a block table.  There is no insert —
            # prefill chunks write straight into the pool through the table.
            self.paged_model = build_decode_model(
                model_cfg,
                cache_size=cache_size,
                dtype=dtype,
                scan_layers=scan_layers,
                attention_impl=attention_impl,
                lora=lora,
                page_size=self.page_size,
                num_pages=self.num_pages,
                kv_dtype=kv_dtype,
                adapter_slots=adapter_slots,
            )

            def prefill_chunk_fn(p, ids, positions, pool, block_tables, adapter_idx):
                logits, variables = self.paged_model.apply(
                    {"params": p, "cache": pool},
                    ids,
                    positions=positions,
                    block_tables=block_tables,
                    adapter_idx=adapter_idx,
                    mutable=["cache"],
                )
                return logits, variables["cache"]

            def decode_paged_fn(p, pool, token, pos, block_tables, adapter_idx):
                logits, variables = self.paged_model.apply(
                    {"params": p, "cache": pool},
                    token,
                    positions=pos,
                    block_tables=block_tables,
                    adapter_idx=adapter_idx,
                    mutable=["cache"],
                )
                return logits[:, -1, :], variables["cache"]

            # the pool argument is donated AND (under a mesh) committed to
            # pool_shardings by init_pool: jit infers the input sharding from
            # the committed buffers, donation reuses them in place, and the
            # output pool keeps the same placement — so the kv-head shards
            # never move for the lifetime of the serve loop
            self._prefill_chunk = cw.wrap(
                "prefill_chunk", jax.jit(prefill_chunk_fn, donate_argnums=(3,))
            )
            self._decode_paged = cw.wrap(
                "decode_paged", jax.jit(decode_paged_fn, donate_argnums=(1,))
            )
            # speculative verify shares prefill_chunk's contract — a
            # multi-token forward returning FULL window logits — but runs at
            # (B, spec_k+1) with per-row positions and a W+1-wide table, so
            # it gets its own watcher entry and jit cache
            self._verify_paged = cw.wrap(
                "verify_paged", jax.jit(prefill_chunk_fn, donate_argnums=(3,))
            )

            def step_paged_fn(p, ids, positions, pool, block_tables, row_map, adapter_idx):
                # the packed mixed-batch forward: one (1, Tb) token-major
                # window where row_map[t] names the slot token t belongs to.
                # Attention routes each token through its own block table
                # (models/llama.attend_with_paged_cache row_map path), so a
                # single dispatch serves every decode row, verify window, and
                # however many prefill chunks the token budget admitted.
                logits, variables = self.paged_model.apply(
                    {"params": p, "cache": pool},
                    ids,
                    positions=positions,
                    block_tables=block_tables,
                    adapter_idx=adapter_idx,
                    row_map=row_map,
                    mutable=["cache"],
                )
                return logits, variables["cache"]

            self._step_paged = cw.wrap(
                "step_paged", jax.jit(step_paged_fn, donate_argnums=(3,))
            )

            # page-run migration seam (disaggregated prefill/decode): gather
            # pulls a run of pool pages to host-bound slices, scatter writes
            # a received run into freshly allocated pages.  Same shape
            # discipline as the adapter writer: ids are bucketed (padded with
            # the null page) so every steady-state transfer replays one of
            # the warmed programs — zero retraces after a migrated insert.
            def gather_pages_fn(pool, ids):
                return jax.tree_util.tree_map(
                    lambda leaf: jnp.take(leaf, ids, axis=_pages_axis(leaf.ndim)),
                    pool,
                )

            def scatter_pages_fn(pool, ids, vals):
                def put(leaf, val):
                    axis = _pages_axis(leaf.ndim)
                    out = jnp.moveaxis(leaf, axis, 0).at[ids].set(
                        jnp.moveaxis(val, axis, 0)
                    )
                    return jnp.moveaxis(out, 0, axis)

                return jax.tree_util.tree_map(put, pool, vals)

            self._gather_pages = cw.wrap(
                "page_gather", jax.jit(gather_pages_fn)
            )
            self._scatter_pages = cw.wrap(
                "page_scatter", jax.jit(scatter_pages_fn, donate_argnums=(0,))
            )

    # -- cache construction --------------------------------------------------

    def cache_shapes(self, batch: int) -> PyTree:
        """Abstract (shape, dtype) tree of the cache for a given batch size —
        eval_shape over model.init, so no FLOPs or memory."""
        ids = jnp.zeros((batch, 1), jnp.int32)
        variables = jax.eval_shape(
            lambda: self.model.init(jax.random.PRNGKey(0), ids)
        )
        return variables["cache"]

    def cache_shardings(self, batch: int) -> Optional[PyTree]:
        """Batch axis over data×fsdp; K/V heads over tensor when divisible,
        matching the ``kv`` logical axis the k/v projection kernels shard
        over — the cache a tp shard writes is exactly the heads it computed,
        so no resharding collective sits between projection and cache."""
        if self.mesh is None:
            return None

        def spec(leaf):
            axes = [None] * leaf.ndim
            n_shards = (
                self.mesh.shape[DATA_AXIS] * self.mesh.shape[FSDP_AXIS]
            )
            if batch % n_shards == 0:
                axes[_cache_batch_axis(leaf)] = (DATA_AXIS, FSDP_AXIS)
            if self.kv_shards > 1:
                axes[leaf.ndim - 2] = TENSOR_AXIS  # (..., kv_heads, head_dim)
            return NamedSharding(self.mesh, P(*axes))

        return jax.tree_util.tree_map(spec, self.cache_shapes(batch))

    def init_cache(self, batch: int) -> PyTree:
        """Concrete zero cache for ``batch`` rows, placed per the mesh."""
        shardings = self.cache_shardings(batch)
        shapes = self.cache_shapes(batch)
        if shardings is None:
            return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        return jax.tree_util.tree_map(
            lambda s, sh: jax.device_put(jnp.zeros(s.shape, s.dtype), sh),
            shapes,
            shardings,
        )

    # -- multi-tenant adapter slots (adapter_slots set at construction) ------

    def _stack_adapter_params(self, params: PyTree, lora: LoraSpec) -> PyTree:
        """Rebuild the checkpoint tree for the slotted model: every non-LoRA
        leaf comes from the checkpoint, every lora_a/lora_b leaf becomes its
        zero stacked ``(num_slots, …)`` twin and lora_s fills with the spec
        scale — so every slot starts as the identity adapter."""
        from flax import linen as nn

        shapes = nn.meta.unbox(
            jax.eval_shape(
                lambda: self.model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32))
            )["params"]
        )
        params = nn.meta.unbox(params)

        def merge(ckpt, init):
            out = {}
            for key, value in init.items():
                if isinstance(value, dict):
                    sub = ckpt.get(key) if isinstance(ckpt, dict) else None
                    out[key] = merge(sub if isinstance(sub, dict) else {}, value)
                elif key in _LORA_FACTOR_LEAVES:
                    out[key] = jnp.zeros(value.shape, value.dtype)
                elif key == "lora_s":
                    out[key] = jnp.full(value.shape, lora.scale, value.dtype)
                else:
                    if not isinstance(ckpt, dict) or key not in ckpt:
                        raise ValueError(
                            f"checkpoint is missing param leaf {key!r} required "
                            "by the slotted decode model"
                        )
                    # copy, don't alias: slot writes donate the whole param
                    # tree, and donating a buffer the caller still holds
                    # would delete it out from under them
                    out[key] = jnp.array(ckpt[key], copy=True)
            return out

        return merge(params, shapes)

    def _adapter_factor_template(self) -> PyTree:
        """Zero factors tree shaped like one adapter's lora_a/lora_b leaves
        (the stacked leaves minus the slot axis).  Every real load is cast
        onto this template so the slot-write jit sees one signature."""

        def walk(p):
            out = {}
            for key, value in p.items():
                if isinstance(value, dict):
                    sub = walk(value)
                    if sub:
                        out[key] = sub
                elif key in _LORA_FACTOR_LEAVES:
                    axis = _factor_slot_axis(value)
                    out[key] = jnp.zeros(
                        value.shape[:axis] + value.shape[axis + 1 :], value.dtype
                    )
            return out

        return walk(self.params)

    def _require_slots(self):
        if not self.adapter_slots:
            raise ValueError("engine was built without adapter_slots: no slot writes")

    def write_adapter_slot(self, slot: int, factors: PyTree, scale: float) -> None:
        """Copy one adapter's unmerged factors into HBM slot ``slot`` (a
        traced dynamic_update_slice over the donated param tree — pure data
        movement, zero steady-state retraces).  ``factors`` is the
        lora_a/lora_b subtree an AdapterRegistry loader returns; leaves are
        cast onto the engine's template so dtype drift between checkpoints
        cannot change the compiled signature."""
        self._require_slots()
        if not (0 < slot < self.adapter_slots):
            raise ValueError(
                f"slot must be in [1, {self.adapter_slots}) (slot 0 is the "
                f"identity adapter), got {slot}"
            )

        def cast(tmpl, f):
            out = {}
            for key, value in tmpl.items():
                sub = f.get(key) if isinstance(f, dict) else None
                if isinstance(value, dict):
                    out[key] = cast(value, sub if isinstance(sub, dict) else {})
                elif sub is None:
                    out[key] = value  # module the adapter does not touch: zeros
                else:
                    leaf = jnp.asarray(sub)
                    if leaf.shape != value.shape:
                        raise ValueError(
                            f"adapter factor {key!r} has shape {leaf.shape}, "
                            f"expected {value.shape}"
                        )
                    out[key] = leaf.astype(value.dtype)
            return out

        self.params = self._write_slot(
            self.params,
            cast(self._factor_template, factors),
            jnp.asarray(scale, jnp.float32),
            jnp.asarray(slot, jnp.int32),
        )

    def adapter_writer(self):
        """The ``writer(slot, factors, scale)`` callback an AdapterRegistry
        wants (serve/adapters.py)."""
        self._require_slots()
        return lambda slot, factors, scale: self.write_adapter_slot(slot, factors, scale)

    # -- in-place weight reload (continuous deployment) ----------------------

    def _prepare_reload_tree(self, live: PyTree, new: PyTree, prefix: str = "params") -> PyTree:
        """Validate a restored checkpoint tree against the live tree and cast
        it for the jitted swap: every live leaf must have a same-shape twin
        (mismatches fail closed with the offending leaf named), dtypes are
        cast host-side onto the live leaf so every reload presents one
        abstract signature, and — on adapter-slot engines — incoming LoRA
        factors are dropped so tenant slabs survive the swap."""
        if not isinstance(new, dict):
            raise ValueError(f"reload: expected a subtree at {prefix}, got {type(new).__name__}")
        extra = set(new) - set(live)
        if extra:
            raise ValueError(
                f"reload: checkpoint leaf {prefix}/{sorted(extra)[0]} does not "
                "exist in the live tree (wrong model config?)"
            )
        out = {}
        for key, value in live.items():
            path = f"{prefix}/{key}"
            if self.adapter_slots and key in (*_LORA_FACTOR_LEAVES, "lora_s"):
                continue  # tenant slabs: never overwritten by a base reload
            if isinstance(value, dict):
                out[key] = self._prepare_reload_tree(value, new.get(key, {}), path)
                continue
            if key not in new:
                raise ValueError(f"reload: checkpoint is missing leaf {path}")
            f = np.asarray(new[key])
            if tuple(f.shape) != tuple(value.shape):
                raise ValueError(
                    f"reload: shape mismatch at {path}: checkpoint "
                    f"{tuple(f.shape)} vs live {tuple(value.shape)}"
                )
            if f.dtype != value.dtype:
                f = f.astype(value.dtype)
            if self.mesh is not None:
                # place on the live leaf's sharding so the jitted swap never
                # reshards (and the signature stays placement-stable)
                f = jax.device_put(f, value.sharding)
            out[key] = f
        return out

    def reload_params(self, new_params: PyTree) -> None:
        """In-place hot swap of the full serving tree — the deployment twin
        of ``write_adapter_slot``.  ``new_params`` is a restored host tree
        (``train/checkpoint.restore_serving_params``); shapes are enforced
        against the live tree before any device write, the live tree is
        donated (no transient 2x params in HBM), and the jitted swap keeps
        one signature across reloads, so the CompileWatcher pins zero
        steady-state retraces under reload churn.  On any validation error
        the live tree is untouched — the server's fail-closed contract."""
        fresh = self._prepare_reload_tree(self.params, new_params)
        self.params = self._reload(self.params, fresh)
        # surface transfer/execution errors here, not on the next decode
        jax.block_until_ready(self.params)

    # -- draft model (model-drafted speculative decoding) --------------------

    def load_draft_params(self, new_params: PyTree) -> None:
        """Install a second (draft) param tree next to the base — the
        pruned+merged checkpoint ``--spec model`` proposes from.

        Same validation and placement as ``reload_params`` (every live leaf
        needs a same-shape twin, dtypes cast host-side, shards placed on the
        live leaf's sharding) but with NO donation: base and draft stay
        resident together, sharing the one page pool, tokenizer, and — the
        point — the already-compiled paged programs.  The params argument of
        every paged jit is traced, and the draft tree presents the identical
        abstract signature, so draft forwards replay the base's executables:
        zero new compiles in steady state, pinned by CompileWatcher."""
        self._require_paged()
        if self.adapter_slots:
            raise ValueError(
                "draft models and adapter slots are mutually exclusive: the "
                "draft tree is a merged base with no tenant slabs (serve the "
                "draft from a dedicated replica instead)"
            )
        fresh = self._prepare_reload_tree(self.params, new_params)
        self.draft_params = jax.tree_util.tree_map(jnp.asarray, fresh)
        jax.block_until_ready(self.draft_params)

    def _require_draft(self):
        if self.draft_params is None:
            raise ValueError("no draft model loaded (call load_draft_params first)")

    def draft_prefill_chunk(
        self, ids: jax.Array, start: int, pool: PyTree, block_table
    ) -> Tuple[jax.Array, PyTree]:
        """``prefill_chunk`` through the draft weights: same chunk, same
        positions, the draft's own block table (draft pages are allocated
        alongside the base's at admission).  Replays the compiled
        prefill_chunk program — the traced param tree is the only change."""
        self._require_paged()
        self._require_draft()
        B, T = ids.shape
        positions = jnp.asarray(start, jnp.int32) + jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (B, T)
        )
        return self._prefill_chunk(
            self.draft_params,
            jnp.asarray(ids),
            positions,
            pool,
            jnp.asarray(block_table, jnp.int32),
            self._row_idx(None, B),
        )

    def draft_decode_paged(
        self, pool: PyTree, token: jax.Array, pos: jax.Array, block_tables
    ) -> Tuple[jax.Array, PyTree]:
        """One autoregressive draft-proposal step (``--spec model``): the
        draft model's ``decode_paged`` over the draft block tables.  Null
        rows follow the same convention as the base step — all-null tables
        and ``pos = cache_size`` clip their writes into the null page."""
        self._require_paged()
        self._require_draft()
        return self._decode_paged(
            self.draft_params,
            pool,
            jnp.asarray(token),
            jnp.asarray(pos, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            self._row_idx(None, token.shape[0]),
        )

    def _row_idx(self, adapter_idx, rows: int) -> jax.Array:
        """Normalize an optional per-row adapter index to a concrete (rows,)
        int32 array (None -> all slot 0, the identity adapter)."""
        if adapter_idx is None:
            return jnp.zeros((rows,), jnp.int32)
        idx = jnp.asarray(adapter_idx, jnp.int32)
        if idx.shape != (rows,):
            raise ValueError(f"adapter_idx must have shape ({rows},), got {idx.shape}")
        return idx

    # -- step functions ------------------------------------------------------

    def prefill(self, ids: jax.Array, lengths=None, adapter_idx=None) -> Tuple[jax.Array, PyTree]:
        """Run a right-padded prompt batch ``(B, T)``; returns full logits
        ``(B, T, V)`` and the populated cache.  ``T`` must be <= cache_size
        (bucket prompts with ``bucket_length`` before calling).
        ``adapter_idx`` is an optional ``(B,)`` slot index per row (slot 0 —
        the identity adapter — when omitted)."""
        B, T = ids.shape
        if T > self.cache_size:
            raise ValueError(f"prompt length {T} exceeds cache capacity {self.cache_size}")
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        cache = self.init_cache(B)
        return self._prefill(
            self.params, jnp.asarray(ids), positions, cache, self._row_idx(adapter_idx, B)
        )

    def decode(self, cache: PyTree, token: jax.Array, pos: jax.Array, adapter_idx=None) -> Tuple[jax.Array, PyTree]:
        """One decode step: ``token``/``pos`` are ``(B, 1)``; returns logits
        ``(B, V)`` and the updated cache.  The input cache is donated —
        the caller must not reuse it after this call."""
        B = token.shape[0]
        return self._decode(
            self.params, cache, jnp.asarray(token), jnp.asarray(pos, jnp.int32),
            self._row_idx(adapter_idx, B),
        )

    def insert(self, dcache: PyTree, pcache: PyTree, slot) -> PyTree:
        """Copy a single-row prefilled cache into decode slot ``slot``.
        ``dcache`` is donated; ``slot`` is traced (no retrace per slot)."""
        return self._insert(dcache, pcache, jnp.asarray(slot, jnp.int32))

    # -- paged step functions (page_size set at construction) ----------------

    def _require_paged(self):
        if not self.paged:
            raise ValueError("engine was built without page_size: no paged entry points")

    def pool_shapes(self) -> PyTree:
        """Abstract tree of the shared K/V page pool — per-layer leaves of
        shape (num_pages, page_size, kv_heads, head_dim) (a leading layers
        axis when scanned).  Its byte size scales with ``num_pages``, not
        ``max_batch × cache_size`` — the paged memory win, visible in
        ``memory_plans()``'s pytree breakdown."""
        self._require_paged()
        ids = jnp.zeros((1, 1), jnp.int32)
        bt = jnp.zeros((1, self.block_table_width), jnp.int32)
        variables = jax.eval_shape(
            lambda: self.paged_model.init(jax.random.PRNGKey(0), ids, block_tables=bt)
        )
        return variables["cache"]

    def pool_bytes(self) -> int:
        """Resident bytes of the shared K/V page pool — codes plus (int8)
        the per-page scale leaves.  The ``serve/kv_cache_bytes`` gauge."""
        self._require_paged()
        return sum(
            int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(self.pool_shapes())
        )

    def kv_bytes_per_token(self) -> float:
        """Pool bytes amortized per cacheable token position
        (``num_pages × page_size`` across the whole pool) — the
        ``serve/kv_bytes_per_token`` gauge.  ~2×heads×head_dim×itemsize per
        layer; int8 roughly quarters it against an f32 pool."""
        self._require_paged()
        return self.pool_bytes() / float(self.num_pages * self.page_size)

    def pool_shardings(self) -> Optional[PyTree]:
        """NamedSharding tree for the page pool: the kv_heads axis shards
        over ``tensor`` when divisible (matching the ``kv`` logical axis the
        k/v projection kernels shard over), everything else replicated.
        Code leaves are ``(..., num_pages, page_size, kv_heads, head_dim)``
        (kv axis at ndim-2); int8 scale leaves are ``(..., num_pages,
        kv_heads)`` (kv axis last).  The pool has no batch axis — every
        request's pages live on every tp shard, sliced by head."""
        self._require_paged()
        if self.mesh is None:
            return None

        def spec(leaf):
            axes = [None] * leaf.ndim
            if self.kv_shards > 1:
                axes[leaf.ndim - 2 if leaf.ndim >= 4 else leaf.ndim - 1] = TENSOR_AXIS
            return NamedSharding(self.mesh, P(*axes))

        return jax.tree_util.tree_map(spec, self.pool_shapes())

    def init_pool(self) -> PyTree:
        """Concrete zero page pool, kv-head-sharded over ``tensor`` when a
        mesh is set (pool_shardings); the committed placement is what the
        donated prefill_chunk/decode_paged steps inherit, so the pool never
        leaves its shards across the whole serve loop."""
        self._require_paged()
        shardings = self.pool_shardings()
        shapes = self.pool_shapes()
        if shardings is None:
            return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        return jax.tree_util.tree_map(
            lambda s, sh: jax.device_put(jnp.zeros(s.shape, s.dtype), sh),
            shapes,
            shardings,
        )

    def prefill_chunk(
        self, ids: jax.Array, start: int, pool: PyTree, block_table, adapter_idx=None
    ) -> Tuple[jax.Array, PyTree]:
        """Prefill one fixed-size chunk of a single prompt: ``ids`` is
        ``(1, chunk_size)`` (right-padded past the prompt), written at
        absolute positions ``start .. start+chunk_size-1`` through
        ``block_table`` ``(1, W)``.  Returns full chunk logits
        ``(1, chunk_size, V)`` and the updated pool (input pool donated).
        One compiled shape total — chunking is what keeps a long prompt off
        the decode loop's critical path for more than one chunk."""
        self._require_paged()
        B, T = ids.shape
        positions = jnp.asarray(start, jnp.int32) + jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (B, T)
        )
        return self._prefill_chunk(
            self.params,
            jnp.asarray(ids),
            positions,
            pool,
            jnp.asarray(block_table, jnp.int32),
            self._row_idx(adapter_idx, B),
        )

    def decode_paged(
        self, pool: PyTree, token: jax.Array, pos: jax.Array, block_tables, adapter_idx=None
    ) -> Tuple[jax.Array, PyTree]:
        """One paged decode step: ``token``/``pos`` are ``(B, 1)``,
        ``block_tables`` is ``(B, W)``.  Rows without an active decoding
        request must carry all-null tables so their garbage write lands in
        the null page, never in a page another request is prefilling into.
        Returns logits ``(B, V)`` and the updated pool (input donated)."""
        self._require_paged()
        return self._decode_paged(
            self.params,
            pool,
            jnp.asarray(token),
            jnp.asarray(pos, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            self._row_idx(adapter_idx, token.shape[0]),
        )

    def verify_paged(
        self, pool: PyTree, tokens: jax.Array, pos: jax.Array, block_tables, adapter_idx=None
    ) -> Tuple[jax.Array, PyTree]:
        """Speculative verify step: ``tokens``/``pos`` are ``(B, S)`` with
        ``S = spec_k + 1`` (last committed token followed by the drafted
        candidates, at consecutive positions), ``block_tables`` is
        ``(B, W+1)`` — the request's table plus a trailing null column so
        any write past ``cache_size`` (padding rows, drafts beyond a row's
        remaining budget) clips into the null page instead of a live one.
        Rows without an active decoding request carry all-null tables and
        ``pos = cache_size`` everywhere.  Returns FULL window logits
        ``(B, S, V)`` (row ``i`` judges drafted token ``i+1``; the last row
        is the bonus distribution) and the updated pool (input donated).
        Rejected drafts need no pool rollback: their K/V land inside the
        request's worst-case admission allocation (or the null page) and are
        overwritten by the next round's forward before any query can attend
        them."""
        self._require_paged()
        return self._verify_paged(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(pos, jnp.int32),
            pool,
            jnp.asarray(block_tables, jnp.int32),
            self._row_idx(adapter_idx, tokens.shape[0]),
        )

    def step_paged(
        self,
        pool: PyTree,
        ids: jax.Array,
        positions: jax.Array,
        block_tables,
        row_map,
        adapter_idx=None,
    ) -> Tuple[jax.Array, PyTree]:
        """One packed mixed-batch step: ``ids``/``positions`` are ``(1, Tb)``
        token-major, ``row_map`` is ``(Tb,)`` mapping each packed token to
        the block-table row it belongs to, ``block_tables`` is
        ``(rows, W+1)`` — every slot's table plus a trailing null column and
        a final all-null pad row.  Pad tokens carry ``row_map = rows-1`` and
        ``positions = cache_size`` so their writes clip into the null page.
        ``adapter_idx`` is per-TOKEN here (``(Tb,)``), not per-row — the
        grouped LoRA kernel sees one row per packed token.  Returns full
        window logits ``(1, Tb, V)`` and the updated pool (input donated).
        Token t's K/V is written before any token attends, so later packed
        tokens of the same request attend earlier same-dispatch tokens —
        whole prompts can prefill inside one step."""
        self._require_paged()
        T = ids.shape[1]
        return self._step_paged(
            self.params,
            jnp.asarray(ids),
            jnp.asarray(positions, jnp.int32),
            pool,
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(row_map, jnp.int32),
            self._row_idx(adapter_idx, T),
        )

    def packed_buckets(self) -> Tuple[int, ...]:
        """The packed-step shapes warmed and used at steady state: halving
        from ``token_budget`` down to 8, so a lightly loaded round (a few
        decode rows, no prefill backlog) pads to a small bucket instead of
        the full budget.  A handful of shapes replaces the per-bucket
        chunk/decode/verify warmup trio."""
        self._require_paged()
        if not self.token_budget:
            raise ValueError("engine was built without token_budget: no packed step")
        buckets = set()
        t = self.token_budget
        while True:
            buckets.add(t)
            if t <= 8:
                break
            t = max(8, t // 2)
        return tuple(sorted(buckets))

    def _warm_page_run(self, pool: PyTree) -> PyTree:
        """Compile the migration gather/scatter pair at every page-run
        bucket (null-page ids: reads/writes touch only the page nothing
        attends).  Called inside warmup's ``expected_compiles`` block so a
        migrated-slot insert at steady state is never a retrace."""
        for nb in self.page_run_buckets():
            ids = jnp.full((nb,), NULL_PAGE, jnp.int32)
            vals = self._gather_pages(pool, ids)
            pool = self._scatter_pages(pool, ids, vals)
        return pool

    def page_run_buckets(self) -> Tuple[int, ...]:
        """Page-count shapes the migration gather/scatter compile for:
        powers of two up to ``block_table_width`` (the widest run a single
        request can own), plus the width itself.  Transfers pad their page
        ids (with the null page) and payload (with zeros) up to the next
        bucket, so steady-state migration replays warmed programs only."""
        self._require_paged()
        buckets: List[int] = []
        t = 1
        while t < self.block_table_width:
            buckets.append(t)
            t *= 2
        buckets.append(self.block_table_width)
        return tuple(buckets)

    def _page_run_bucket(self, n: int) -> int:
        for b in self.page_run_buckets():
            if b >= n:
                return b
        raise ValueError(
            f"page run of {n} pages exceeds block_table_width {self.block_table_width}"
        )

    def export_page_run(
        self, pool: PyTree, pages: Sequence[int]
    ) -> List[Tuple[str, str, Tuple[int, ...], bytes]]:
        """Pull the pool slices for a page run to host bytes, ready for
        :func:`wire.encode_page_run`.  One gather dispatch at the padded
        bucket shape, then a host-side trim back to ``len(pages)`` — the
        wire carries only real pages (int8 codes + their scales), the 4×
        transfer win over a bf16 pool."""
        self._require_paged()
        n = len(pages)
        if n < 1:
            raise ValueError("empty page run")
        bucket = self._page_run_bucket(n)
        ids = list(pages) + [NULL_PAGE] * (bucket - n)
        slices = self._gather_pages(pool, jnp.asarray(ids, jnp.int32))
        flat, _ = jax.tree_util.tree_flatten_with_path(jax.device_get(slices))
        out: List[Tuple[str, str, Tuple[int, ...], bytes]] = []
        for path, leaf in flat:
            arr = np.asarray(leaf)
            arr = np.take(arr, range(n), axis=_pages_axis(arr.ndim))
            out.append(
                (jax.tree_util.keystr(path), str(arr.dtype), tuple(arr.shape),
                 np.ascontiguousarray(arr).tobytes())
            )
        return out

    def import_page_run(
        self,
        pool: PyTree,
        pages: Sequence[int],
        entries: Sequence[Tuple[str, str, Sequence[int], bytes]],
    ) -> PyTree:
        """Scatter a received page run into freshly allocated ``pages`` of
        ``pool`` (donated).  Validates every entry against the engine's own
        pool leaves — name set, dtype, and shape (with the pages axis equal
        to ``len(pages)``) — and raises ValueError on any mismatch, so a
        frame from a differently configured peer is rejected before a byte
        lands in the pool.  Pads ids/payload up to the gather/scatter bucket
        (pad writes land in the null page)."""
        self._require_paged()
        n = len(pages)
        if n < 1:
            raise ValueError("empty page run")
        bucket = self._page_run_bucket(n)
        flat, treedef = jax.tree_util.tree_flatten_with_path(self.pool_shapes())
        by_name = {jax.tree_util.keystr(p): leaf for p, leaf in flat}
        got = {e[0]: e for e in entries}
        if set(got) != set(by_name):
            raise ValueError(
                f"page-run leaves mismatch: got {sorted(got)}, want {sorted(by_name)}"
            )
        vals = []
        for path, spec in flat:
            name = jax.tree_util.keystr(path)
            _, dtype, shape, raw = got[name]
            axis = _pages_axis(spec.ndim)
            want = list(spec.shape)
            want[axis] = n
            if str(dtype) != str(spec.dtype) or list(shape) != want:
                raise ValueError(
                    f"page-run leaf {name!r}: got {dtype}{list(shape)}, "
                    f"want {spec.dtype}{want}"
                )
            arr = np.frombuffer(raw, dtype=np.dtype(str(dtype)))
            if arr.size != int(np.prod(shape)):
                raise ValueError(f"page-run leaf {name!r}: payload size mismatch")
            arr = arr.reshape(shape)
            if bucket > n:
                pad = [(0, 0)] * arr.ndim
                pad[axis] = (0, bucket - n)
                arr = np.pad(arr, pad)
            vals.append(arr)
        ids = list(pages) + [NULL_PAGE] * (bucket - n)
        return self._scatter_pages(
            pool,
            jnp.asarray(ids, jnp.int32),
            jax.tree_util.tree_unflatten(treedef, vals),
        )

    def default_prompt_buckets(self) -> Tuple[int, ...]:
        """Every prefill shape a prompt can actually land in: powers of two
        from the bucket minimum up, capped at ``cache_size`` (which is
        itself a bucket when it is not a power of two).  Warming all of
        them means the first long prompt is never a steady-state retrace."""
        buckets: List[int] = []
        t = bucket_length(1)
        while t < self.cache_size:
            buckets.append(t)
            t *= 2
        buckets.append(self.cache_size)
        return tuple(buckets)

    def warmup(
        self,
        batch: int,
        *,
        prompt_buckets: Optional[Sequence[int]] = None,
        packed: bool = False,
        migrate: bool = False,
    ) -> dict:
        """Compile the serving step functions before traffic arrives.
        An online server calls this at startup so the first real request
        pays queueing latency, not XLA compilation.

        Contiguous engine: one prefill per prompt bucket — defaulting to
        *every* power-of-two bucket up to ``cache_size`` (a prompt can land
        in any of them; warming only the smallest made the first long
        prompt a steady-state retrace) — plus one insert and one decode at
        ``batch`` rows.  Paged engine: exactly two shapes total, the
        ``(1, chunk_size)`` prefill chunk and the ``(batch, 1)`` paged
        decode — prompt length no longer appears in any compiled shape.
        Packed paged engine (``packed=True``, requires ``token_budget``):
        one ``step_paged`` compile per token-budget bucket
        (``packed_buckets()``) replaces the chunk/decode/verify trio —
        the scheduler's round then never issues any other model entry, so
        admission/cancel/spec churn cannot retrace.

        Returns a report of what was compiled — shapes plus per-compile
        durations — so operators can log it and compile telemetry can tell
        these expected compiles apart from steady-state retraces."""
        cw = self.compile_watcher
        n_before = len(cw.compile_events())
        if packed:
            self._require_paged()
            buckets = self.packed_buckets()
            W1 = self.block_table_width + 1
            with cw.expected_compiles("warmup"):
                pool = self.init_pool()
                logits = None
                for Tb in buckets:
                    logits, pool = self.step_paged(
                        pool,
                        jnp.zeros((1, Tb), jnp.int32),
                        jnp.full((1, Tb), self.cache_size, jnp.int32),
                        jnp.zeros((batch + 1, W1), jnp.int32),
                        jnp.full((Tb,), batch, jnp.int32),
                    )
                if self.adapter_slots:
                    self.write_adapter_slot(
                        self.adapter_slots - 1, self._factor_template, 0.0
                    )
                if migrate:
                    pool = self._warm_page_run(pool)
                jax.block_until_ready(logits)
            events = cw.compile_events()[n_before:]
            shapes: dict = {"step_paged": [[1, Tb] for Tb in buckets]}
            if self.adapter_slots:
                shapes["adapter_write"] = [self.adapter_slots]
            if migrate:
                shapes["page_run"] = list(self.page_run_buckets())
            return {
                "batch": batch,
                "prompt_buckets": [],
                "packed_buckets": list(buckets),
                "token_budget": self.token_budget,
                "kv_dtype": self.kv_dtype,
                "spec_k": self.spec_k,
                "shapes": shapes,
                "n_compiles": len(events),
                "compiles": [
                    {"fn": ev.fn, "duration_s": round(ev.duration_s, 4), "reason": ev.reason}
                    for ev in events
                ],
            }
        if self.paged:
            with cw.expected_compiles("warmup"):
                pool = self.init_pool()
                _, pool = self.prefill_chunk(
                    jnp.zeros((1, self.chunk_size), jnp.int32),
                    0,
                    pool,
                    jnp.zeros((1, self.block_table_width), jnp.int32),
                )
                logits, pool = self.decode_paged(
                    pool,
                    jnp.zeros((batch, 1), jnp.int32),
                    jnp.zeros((batch, 1), jnp.int32),
                    jnp.zeros((batch, self.block_table_width), jnp.int32),
                )
                if self.spec_k > 0:
                    S = self.spec_k + 1
                    logits, pool = self.verify_paged(
                        pool,
                        jnp.zeros((batch, S), jnp.int32),
                        jnp.full((batch, S), self.cache_size, jnp.int32),
                        jnp.zeros((batch, self.block_table_width + 1), jnp.int32),
                    )
                if self.adapter_slots:
                    # zeros into the last free slot: a no-op write that
                    # compiles the one slot-write program before any tenant
                    # load (warm up BEFORE preloading adapters)
                    self.write_adapter_slot(
                        self.adapter_slots - 1, self._factor_template, 0.0
                    )
                if migrate:
                    pool = self._warm_page_run(pool)
                jax.block_until_ready(logits)
            events = cw.compile_events()[n_before:]
            shapes = {
                "prefill_chunk": [1, self.chunk_size],
                "decode_paged": [batch, 1],
            }
            if self.spec_k > 0:
                shapes["verify_paged"] = [batch, self.spec_k + 1]
            if self.adapter_slots:
                shapes["adapter_write"] = [self.adapter_slots]
            if migrate:
                shapes["page_run"] = list(self.page_run_buckets())
            return {
                "batch": batch,
                "prompt_buckets": [],
                "kv_dtype": self.kv_dtype,
                "spec_k": self.spec_k,
                "shapes": shapes,
                "n_compiles": len(events),
                "compiles": [
                    {"fn": ev.fn, "duration_s": round(ev.duration_s, 4), "reason": ev.reason}
                    for ev in events
                ],
            }
        if prompt_buckets is None:
            prompt_buckets = self.default_prompt_buckets()
        buckets: List[int] = []
        with cw.expected_compiles("warmup"):
            pcache = None
            for bucket in prompt_buckets:
                T = min(bucket_length(bucket), self.cache_size)
                if T not in buckets:
                    buckets.append(T)
                _, pcache = self.prefill(jnp.zeros((1, T), jnp.int32))
            cache = self.init_cache(batch)
            if pcache is not None:
                cache = self.insert(cache, pcache, 0)
            logits, cache = self.decode(
                cache, jnp.zeros((batch, 1), jnp.int32), jnp.zeros((batch, 1), jnp.int32)
            )
            if self.adapter_slots:
                self.write_adapter_slot(
                    self.adapter_slots - 1, self._factor_template, 0.0
                )
            jax.block_until_ready(logits)
        events = cw.compile_events()[n_before:]
        shapes = {
            "prefill": [[1, T] for T in buckets],
            "insert": [[batch], [1]],
            "decode": [batch, 1],
        }
        if self.adapter_slots:
            shapes["adapter_write"] = [self.adapter_slots]
        return {
            "batch": batch,
            "prompt_buckets": buckets,
            "shapes": shapes,
            "n_compiles": len(events),
            "compiles": [
                {"fn": ev.fn, "duration_s": round(ev.duration_s, 4), "reason": ev.reason}
                for ev in events
            ],
        }

    def memory_plans(self, batch: int, *, prompt_buckets: Optional[Sequence[int]] = None) -> dict:
        """Static HBM plans for every jitted serving entry point (per-bucket
        prefill, insert, decode at ``batch`` rows — or the chunk/decode pair
        when paged) plus the per-pytree breakdown of what stays resident
        (params, KV cache).  On a paged engine the ``kv_cache`` entry is the
        shared page pool, whose bytes scale with ``num_pages`` rather than
        ``max_batch × cache_size``.

        Uses AOT lower+compile, which does NOT warm the traced-call cache —
        each plan pays a real compile (tagged expected), so call this at
        startup or in reports, not per request.  Off-accelerator the XLA
        numbers describe host buffers, but the relative breakdown holds."""
        i32 = jnp.int32
        if self.paged:
            pool = self.pool_shapes()
            plans: dict = {
                "pytree": obs_memory.pytree_breakdown(
                    {"params": self.params, "kv_cache": pool}
                )
            }
            plans["prefill_chunk"] = obs_memory.plan_for(
                self._prefill_chunk,
                self.params,
                jax.ShapeDtypeStruct((1, self.chunk_size), i32),
                jax.ShapeDtypeStruct((1, self.chunk_size), i32),
                pool,
                jax.ShapeDtypeStruct((1, self.block_table_width), i32),
                jax.ShapeDtypeStruct((1,), i32),
            )
            plans["decode_paged"] = obs_memory.plan_for(
                self._decode_paged,
                self.params,
                pool,
                jax.ShapeDtypeStruct((batch, 1), i32),
                jax.ShapeDtypeStruct((batch, 1), i32),
                jax.ShapeDtypeStruct((batch, self.block_table_width), i32),
                jax.ShapeDtypeStruct((batch,), i32),
            )
            if self.spec_k > 0:
                S = self.spec_k + 1
                plans["verify_paged"] = obs_memory.plan_for(
                    self._verify_paged,
                    self.params,
                    jax.ShapeDtypeStruct((batch, S), i32),
                    jax.ShapeDtypeStruct((batch, S), i32),
                    pool,
                    jax.ShapeDtypeStruct((batch, self.block_table_width + 1), i32),
                    jax.ShapeDtypeStruct((batch,), i32),
                )
            if self.token_budget:
                Tb = self.token_budget
                plans["step_paged"] = obs_memory.plan_for(
                    self._step_paged,
                    self.params,
                    jax.ShapeDtypeStruct((1, Tb), i32),
                    jax.ShapeDtypeStruct((1, Tb), i32),
                    pool,
                    jax.ShapeDtypeStruct((batch + 1, self.block_table_width + 1), i32),
                    jax.ShapeDtypeStruct((Tb,), i32),
                    jax.ShapeDtypeStruct((Tb,), i32),
                )
            return plans
        if prompt_buckets is None:
            prompt_buckets = self.default_prompt_buckets()
        plans = {
            "pytree": obs_memory.pytree_breakdown(
                {"params": self.params, "kv_cache": self.cache_shapes(batch)}
            )
        }
        dcache = self.cache_shapes(batch)
        pcache1 = self.cache_shapes(1)
        # AOT plans bypass __call__, so the watcher never sees them — no
        # expected_compiles block needed
        for bucket in prompt_buckets:
            T = min(bucket_length(bucket), self.cache_size)
            plans[f"prefill_b{T}"] = obs_memory.plan_for(
                self._prefill,
                self.params,
                jax.ShapeDtypeStruct((1, T), i32),
                jax.ShapeDtypeStruct((1, T), i32),
                pcache1,
                jax.ShapeDtypeStruct((1,), i32),
            )
        plans["insert"] = obs_memory.plan_for(
            self._insert, dcache, pcache1, jax.ShapeDtypeStruct((), i32)
        )
        plans["decode"] = obs_memory.plan_for(
            self._decode,
            self.params,
            dcache,
            jax.ShapeDtypeStruct((batch, 1), i32),
            jax.ShapeDtypeStruct((batch, 1), i32),
            jax.ShapeDtypeStruct((batch,), i32),
        )
        return plans

    # -- convenience: one-shot batch generation ------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        max_new_tokens: int,
        sampling: SamplingParams = SamplingParams(),
        eos_id: Optional[int] = None,
        key: Optional[jax.Array] = None,
        adapter_idx: Optional[Sequence[int]] = None,
    ) -> List[List[int]]:
        """Batch generation without continuous batching: pad all prompts to one
        bucket, prefill, then decode until every row hits EOS/max_new_tokens.
        The scheduler (serve/scheduler.py) is the production path; this is the
        one-shot ``--prompt`` path and the parity-test oracle."""
        if not prompts:
            return []
        if key is None:
            key = jax.random.PRNGKey(0)
        lengths = np.array([len(p) for p in prompts], np.int32)
        if lengths.min() < 1:
            raise ValueError("empty prompt")
        T = min(bucket_length(int(lengths.max())), self.cache_size)
        if int(lengths.max()) + max_new_tokens > self.cache_size:
            raise ValueError(
                f"prompt ({lengths.max()}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds cache capacity {self.cache_size}"
            )
        B = len(prompts)
        ids = np.zeros((B, T), np.int32)
        for i, p in enumerate(prompts):
            ids[i, : lengths[i]] = np.asarray(p, np.int32)

        idx = None
        if adapter_idx is not None:
            idx = jnp.asarray(adapter_idx, jnp.int32)
        logits, cache = self.prefill(jnp.asarray(ids), lengths, adapter_idx=idx)
        last = jnp.take_along_axis(
            logits, jnp.asarray(lengths - 1)[:, None, None], axis=1
        )[:, 0, :]
        token = self._sample(
            last,
            jax.random.fold_in(key, 0),
            temperature=sampling.temperature,
            top_k=sampling.top_k,
            top_p=sampling.top_p,
        )
        pos = jnp.asarray(lengths, jnp.int32)
        out: List[List[int]] = [[] for _ in range(B)]
        done = np.zeros(B, bool)
        for step in range(max_new_tokens):
            host_tok = np.asarray(token)
            for i in range(B):
                if not done[i]:
                    out[i].append(int(host_tok[i]))
                    if eos_id is not None and host_tok[i] == eos_id:
                        done[i] = True
            if done.all() or step == max_new_tokens - 1:
                break
            logits, cache = self.decode(cache, token[:, None], pos[:, None], adapter_idx=idx)
            pos = pos + 1
            token = self._sample(
                logits,
                jax.random.fold_in(key, step + 1),
                temperature=sampling.temperature,
                top_k=sampling.top_k,
                top_p=sampling.top_p,
            )
        return out
