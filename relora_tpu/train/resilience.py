"""Preemption-safe shutdown and automatic loss-spike recovery.

ReLoRA runs are long pretraining jobs on preemptible fleets whose periodic
merge-and-reinit resets make optimizer/scheduler state unusually fragile
across interruptions: a SIGTERM mid-step loses up to ``save_every`` steps of
work, and a data-induced loss spike previously required a *manual*
``skip_batches`` blacklist plus a hand-driven restart.  Two host-side
primitives fix both; the Trainer wires them into the update loop:

- ``PreemptionGuard``  — signal handler (SIGTERM/SIGINT) that *requests* a
  graceful stop; the Trainer honors it at the next update boundary with an
  emergency checkpoint, so the committed step counter and data cursor stay
  aligned.  A second SIGINT escalates to the default KeyboardInterrupt for
  operators who really mean it.
- ``LossSpikeDetector``— rolling median/MAD outlier test over recent
  losses.  A *sustained* run of outliers (``patience`` consecutive) yields a
  ``SpikeEvent``; the Trainer then rolls back to the last checkpoint
  preceding the spike and auto-extends ``skip_batches`` over the poisoned
  update window — automating the reference's manual
  ``--skip_batches`` parity path while keeping the data stream aligned.

Median/MAD (not mean/std) so the spike itself cannot drag the baseline up
and mask a slow-motion divergence; outliers are excluded from the window for
the same reason.
"""

from __future__ import annotations

import dataclasses
import math
import signal
import threading
from collections import deque
from typing import Optional

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PreemptionGuard:
    """Context manager turning SIGTERM/SIGINT into a polled ``requested``
    flag.  Installs only in the main thread (signal.signal raises elsewhere);
    previous handlers are restored on exit, so nested uses and test harness
    handlers survive."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), enabled: bool = True):
        self._signals = tuple(signals)
        self._enabled = enabled
        self._prev: dict = {}
        self.requested = False
        self.signum: Optional[int] = None

    def __enter__(self) -> "PreemptionGuard":
        if not self._enabled:
            return self
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "PreemptionGuard skipped: signal handlers require the main thread"
            )
            return self
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _handle(self, signum, frame) -> None:
        if signum == signal.SIGINT and self.requested:
            # the operator pressed Ctrl-C twice: stop waiting for the
            # boundary and unwind now
            raise KeyboardInterrupt
        self.requested = True
        self.signum = signum
        # the boundary may be seconds away (or never, if the step hangs) and
        # the preemptor's grace window is short: dump the flight recorder NOW,
        # from the handler, so the last spans survive even a hard kill
        try:
            from relora_tpu.obs import flight

            flight.dump_on_fault("sigterm")
        except Exception:
            pass  # a failed dump must never break the signal handler
        logger.warning(
            f"received signal {signum}; requesting emergency checkpoint at the "
            "next update boundary (SIGINT again to abort immediately)"
        )


@dataclasses.dataclass
class SpikeEvent:
    """A sustained loss spike: ``first_step``..``last_step`` are the logged
    update steps of the consecutive outliers that crossed ``patience``."""

    first_step: int
    last_step: int
    loss: float
    median: float
    mad: float


class LossSpikeDetector:
    """Rolling median/MAD outlier detector over per-update losses.

    ``update(step, loss)`` returns a ``SpikeEvent`` once ``patience``
    consecutive losses exceed ``median + threshold * 1.4826 * MAD`` (1.4826
    scales MAD to sigma-equivalents for Gaussian noise).  NaN/inf losses
    always count as outliers — a sustained-NaN run is the worst spike there
    is.  Outliers are *not* admitted to the window, so the pre-spike baseline
    stays clean during the streak; ``min_deviation`` floors the margin so a
    near-zero MAD in a flat loss region cannot flag noise.
    """

    def __init__(
        self,
        threshold: float,
        window: int = 64,
        min_history: int = 16,
        patience: int = 3,
        min_deviation: float = 0.05,
    ):
        if threshold <= 0:
            raise ValueError("threshold must be > 0 (gate construction on it)")
        if patience < 1:
            raise ValueError("patience must be >= 1")
        if min_history < 4:
            raise ValueError("min_history must be >= 4")
        self.threshold = threshold
        self.patience = patience
        self.min_history = min_history
        self.min_deviation = min_deviation
        self._window: deque = deque(maxlen=window)
        self._streak = 0
        self._first_step: Optional[int] = None
        self.last_median = float("nan")
        self.last_mad = float("nan")

    @staticmethod
    def _median(values) -> float:
        s = sorted(values)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def update(self, step: int, loss: float) -> Optional[SpikeEvent]:
        outlier = False
        if len(self._window) >= self.min_history:
            med = self._median(self._window)
            mad = self._median(abs(v - med) for v in self._window)
            self.last_median, self.last_mad = med, mad
            margin = max(self.threshold * 1.4826 * mad, self.min_deviation)
            outlier = not math.isfinite(loss) or loss > med + margin
        if outlier:
            self._streak += 1
            if self._streak == 1:
                self._first_step = step
            if self._streak >= self.patience:
                return SpikeEvent(
                    first_step=self._first_step,
                    last_step=step,
                    loss=loss,
                    median=self.last_median,
                    mad=self.last_mad,
                )
        else:
            self._streak = 0
            self._first_step = None
            if math.isfinite(loss):
                self._window.append(loss)
        return None

    def reset_streak(self) -> None:
        """Forget the current outlier run (after a rollback, or when a spike
        fired but no rollback target exists) while keeping the clean
        baseline window."""
        self._streak = 0
        self._first_step = None
