"""Tests for LoRA leaf classification and the pure merge-and-reinit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.core.relora import (
    LoraSpec,
    frozen_param_mask,
    kaiming_uniform,
    lora_param_mask,
    merge_and_reinit,
    merged_params,
    split_param_counts,
    trainable_param_mask,
)


def make_params(rng=0, in_dim=16, out_dim=24, r=4, trainable_scaling=False):
    k = jax.random.PRNGKey(rng)
    ks = jax.random.split(k, 6)
    mod = {
        "kernel": jax.random.normal(ks[0], (in_dim, out_dim)) * 0.1,
        "lora_a": jax.random.normal(ks[1], (in_dim, r)) * 0.1,
        "lora_b": jax.random.normal(ks[2], (r, out_dim)) * 0.1,
    }
    if trainable_scaling:
        mod["lora_s"] = jnp.asarray([0.5])
    return {
        "embed": {"embedding": jax.random.normal(ks[3], (32, in_dim))},
        "layer": {
            "q_proj": mod,
            "norm": {"scale": jnp.ones((in_dim,))},
            "plain": {"kernel": jax.random.normal(ks[4], (in_dim, in_dim)), "bias": jnp.zeros(in_dim)},
        },
    }


def test_masks():
    params = make_params()
    lora = lora_param_mask(params)
    assert lora["layer"]["q_proj"]["lora_a"] is True
    assert lora["layer"]["q_proj"]["lora_b"] is True
    assert lora["layer"]["q_proj"]["kernel"] is False
    assert lora["embed"]["embedding"] is False

    frozen = frozen_param_mask(params)
    assert frozen["layer"]["q_proj"]["kernel"] is True
    assert frozen["layer"]["plain"]["kernel"] is False
    assert frozen["layer"]["norm"]["scale"] is False

    train = trainable_param_mask(params)
    assert train["layer"]["q_proj"]["kernel"] is False
    assert train["layer"]["q_proj"]["lora_a"] is True
    assert train["embed"]["embedding"] is True
    assert train["layer"]["plain"]["kernel"] is True

    only = trainable_param_mask(params, lora_only=True)
    assert only["embed"]["embedding"] is False
    assert only["layer"]["q_proj"]["lora_a"] is True


def test_param_counts():
    params = make_params(in_dim=8, out_dim=8, r=2)
    counts = split_param_counts(params)
    lora_n = 8 * 2 + 2 * 8
    assert counts["lora_params"] == lora_n
    assert counts["equivalent_params"] == counts["total_params"] - lora_n
    assert counts["trainable_params"] == counts["total_params"] - 8 * 8  # minus frozen kernel


def test_merge_math_and_reinit():
    spec = LoraSpec(r=4, alpha=32)
    params = make_params()
    q = params["layer"]["q_proj"]
    expected = q["kernel"] + (q["lora_a"] @ q["lora_b"]) * spec.scale

    out = merge_and_reinit(params, jax.random.PRNGKey(1), spec)
    q2 = out["layer"]["q_proj"]
    np.testing.assert_allclose(np.asarray(q2["kernel"]), np.asarray(expected), rtol=1e-5)
    # B zeroed, A re-drawn within the kaiming bound
    assert float(jnp.abs(q2["lora_b"]).max()) == 0.0
    bound = 1.0 / np.sqrt(q["lora_a"].shape[0])
    assert float(jnp.abs(q2["lora_a"]).max()) <= bound
    assert float(jnp.abs(q2["lora_a"]).max()) > 0.0
    # untouched leaves identical
    np.testing.assert_array_equal(np.asarray(out["embed"]["embedding"]), np.asarray(params["embed"]["embedding"]))
    # structure preserved
    assert jax.tree_util.tree_structure(out) == jax.tree_util.tree_structure(params)


def test_merge_preserves_bf16_base_storage():
    """A bf16-stored frozen base (LoraSpec.base_dtype='bf16') merges in f32
    and casts back to bf16 — dtype preserved, value within one bf16 ulp of
    the f32 merge."""
    spec = LoraSpec(r=4, alpha=32, base_dtype="bf16")
    params = make_params()
    q = params["layer"]["q_proj"]
    expected = (
        q["kernel"].astype(jnp.float32)
        + (q["lora_a"].astype(jnp.float32) @ q["lora_b"].astype(jnp.float32)) * spec.scale
    )
    params["layer"]["q_proj"] = dict(q, kernel=q["kernel"].astype(jnp.bfloat16))

    out = merge_and_reinit(params, jax.random.PRNGKey(1), spec)
    merged = out["layer"]["q_proj"]["kernel"]
    assert merged.dtype == jnp.bfloat16
    # one bf16 rounding of the f32 merge: relative error <= 2^-8
    np.testing.assert_allclose(
        np.asarray(merged, np.float32), np.asarray(expected), rtol=2 ** -7, atol=1e-3
    )


def test_merge_trainable_scaling_uses_tanh_and_resets():
    spec = LoraSpec(r=4, alpha=32, trainable_scaling=True)
    params = make_params(trainable_scaling=True)
    q = params["layer"]["q_proj"]
    expected = q["kernel"] + (q["lora_a"] @ q["lora_b"]) * jnp.tanh(q["lora_s"])
    out = merge_and_reinit(params, jax.random.PRNGKey(1), spec)
    np.testing.assert_allclose(
        np.asarray(out["layer"]["q_proj"]["kernel"]), np.asarray(expected), rtol=1e-5
    )
    assert float(out["layer"]["q_proj"]["lora_s"][0]) == 0.0


def test_merge_is_jittable_and_donation_safe():
    spec = LoraSpec(r=4, alpha=32)
    params = make_params()
    fn = jax.jit(lambda p, k: merge_and_reinit(p, k, spec))
    out = fn(params, jax.random.PRNGKey(2))
    ref = merge_and_reinit(params, jax.random.PRNGKey(2), spec)
    np.testing.assert_allclose(
        np.asarray(out["layer"]["q_proj"]["kernel"]),
        np.asarray(ref["layer"]["q_proj"]["kernel"]),
        rtol=1e-6,
    )


def test_merged_params_drops_lora_leaves():
    spec = LoraSpec(r=4, alpha=32)
    params = make_params()
    merged = merged_params(params, spec)
    assert "lora_a" not in merged["layer"]["q_proj"]
    q = params["layer"]["q_proj"]
    np.testing.assert_allclose(
        np.asarray(merged["layer"]["q_proj"]["kernel"]),
        np.asarray(q["kernel"] + (q["lora_a"] @ q["lora_b"]) * spec.scale),
        rtol=1e-5,
    )


def test_kaiming_uniform_bound_matches_torch_semantics():
    # torch kaiming_uniform_(a=sqrt(5)) on (r, in): U(-1/sqrt(in), 1/sqrt(in))
    key = jax.random.PRNGKey(0)
    sample = kaiming_uniform(key, (64, 8))
    bound = 1 / np.sqrt(64)
    assert float(sample.max()) <= bound
    assert float(sample.min()) >= -bound
    # roughly uniform: std ~ bound/sqrt(3)
    assert float(sample.std()) == pytest.approx(bound / np.sqrt(3), rel=0.15)


def test_repeated_merges_accumulate_high_rank():
    """The ReLoRA thesis: k merges of rank-r updates give rank up to k*r."""
    spec = LoraSpec(r=2, alpha=2)  # scale 1
    rng = jax.random.PRNGKey(3)
    in_dim = out_dim = 16
    params = {
        "m": {
            "kernel": jnp.zeros((in_dim, out_dim)),
            "lora_a": jax.random.normal(jax.random.PRNGKey(10), (in_dim, 2)),
            "lora_b": jax.random.normal(jax.random.PRNGKey(11), (2, out_dim)),
        }
    }
    for i in range(4):
        params = merge_and_reinit(params, jax.random.fold_in(rng, i), spec)
        # simulate training: give B some random value so next merge adds new directions
        params["m"]["lora_b"] = jax.random.normal(jax.random.PRNGKey(20 + i), (2, out_dim))
    # after 4 merges with re-randomized factors, kernel rank should exceed r
    rank = np.linalg.matrix_rank(np.asarray(params["m"]["kernel"]), tol=1e-5)
    assert rank > 2
