"""Rank/SVD analysis of learned weight updates — the paper's core claim.

Systematizes the reference's analysis notebooks (notebooks/05_check_ranks,
06_svd, 08_ranks_before_and_after — SURVEY.md §4): given two checkpoints
(e.g. the warm-start point and the end of ReLoRA training), compute the
singular-value spectrum and effective rank of ΔW for every wrapped linear,
demonstrating that repeated rank-r updates accumulate a high-rank total
update.

Usage::

    python tools/analyze_rank.py --before ckpts/warmup/model_10000 \
        --after ckpts/relora/model_20000 [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def effective_rank(singular_values: np.ndarray, threshold: float = 1e-3) -> int:
    """Number of singular values above threshold * sigma_max."""
    if singular_values.size == 0:
        return 0
    return int((singular_values > threshold * singular_values[0]).sum())


def entropy_rank(singular_values: np.ndarray) -> float:
    """exp(Shannon entropy of the normalized spectrum) — a soft rank."""
    p = singular_values / max(singular_values.sum(), 1e-12)
    p = p[p > 0]
    return float(np.exp(-(p * np.log(p)).sum()))


def delta_spectra(before: dict, after: dict, prefix: str = "") -> dict:
    """Walk two (unstacked or stacked) param trees, SVD every kernel delta."""
    out = {}
    for k in before:
        if k not in after:
            continue
        b, a = before[k], after[k]
        if isinstance(b, dict):
            out.update(delta_spectra(b, a, prefix=f"{prefix}{k}."))
        elif k == "kernel" and getattr(b, "ndim", 0) >= 2:
            delta = np.asarray(a, np.float64) - np.asarray(b, np.float64)
            if delta.ndim == 2:
                deltas = {f"{prefix}kernel": delta}
            else:  # scan-stacked: one entry per layer
                deltas = {
                    f"{prefix}kernel[layer{i}]": delta[i] for i in range(delta.shape[0])
                }
            for name, d in deltas.items():
                s = np.linalg.svd(d, compute_uv=False)
                out[name] = {
                    "shape": list(d.shape),
                    "frobenius": float(np.linalg.norm(d)),
                    "effective_rank": effective_rank(s),
                    "entropy_rank": entropy_rank(s),
                    "top_singular_values": s[:16].tolist(),
                }
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--before", required=True, help="checkpoint dir (model_N)")
    p.add_argument("--after", required=True)
    p.add_argument("--json", default=None, help="write full report here")
    args = p.parse_args(argv)

    sys.path.insert(0, ".")
    import jax

    # offline tool: host CPU is all we need, and restoring through a TPU
    # tunnel backend can stall
    jax.config.update("jax_platforms", "cpu")
    from relora_tpu.train.checkpoint import restore_params_host

    before = restore_params_host(args.before)
    after = restore_params_host(args.after)
    report = delta_spectra(before, after)

    ranks = [v["effective_rank"] for v in report.values()]
    print(f"analyzed {len(report)} weight deltas")
    if ranks:
        print(f"effective rank of ΔW: min={min(ranks)} median={int(np.median(ranks))} max={max(ranks)}")
    for name, v in sorted(report.items())[:10]:
        print(f"  {name}: shape={v['shape']} eff_rank={v['effective_rank']} |ΔW|={v['frobenius']:.3f}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"full report -> {args.json}")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        # stdout piped into `head` that already exited (smoke_test.sh does
        # this); the truncated output is what the reader asked for
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), 1)
