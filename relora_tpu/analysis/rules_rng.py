"""RTL4xx — PRNG key hygiene.

JAX PRNG keys are values, not stateful generators: passing the same key to
two distribution calls yields *identical* randomness — dropout masks that
repeat every step, LoRA re-inits that collide across restarts — and nothing
crashes.  The repo's convention (see ``utils/random.py`` idiom) is
``key, sub = jax.random.split(key)`` before every consumption and
``fold_in(key, step)`` for per-step streams.

- RTL401: the same key expression is passed to two *consuming* calls
  (distribution samplers) without an intervening ``split``/``fold_in``
  rebind.  Derivation calls (``split``, ``fold_in``, ``PRNGKey``) don't
  consume, they create.
- RTL402: a key seeded from wallclock/OS entropy (``time.*``,
  ``os.urandom``/``os.getpid``, ``random.*``, ``uuid.*``, ``secrets.*``)
  — runs are unreproducible and restarts silently resample; seeds must
  come from config.

Identity for RTL401 is the unparsed expression text within one function
body, reset on any rebind of the root name; cross-function flows and
subscripted key arrays are out of scope (and rarely misused in practice).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from relora_tpu.analysis.core import (
    FileContext,
    Finding,
    catalog,
    checker,
    dotted_name,
)

catalog(
    RTL401="PRNG key consumed twice without split/fold_in (identical randomness)",
    RTL402="PRNG key seeded from wallclock/OS entropy (unreproducible runs)",
)

#: jax.random callables that CONSUME a key (same key twice = same samples)
CONSUMERS = frozenset(
    {
        "bernoulli",
        "categorical",
        "choice",
        "dirichlet",
        "exponential",
        "gamma",
        "gumbel",
        "laplace",
        "normal",
        "permutation",
        "poisson",
        "randint",
        "shuffle",
        "truncated_normal",
        "uniform",
    }
)
#: derive a new key or stream — not a consumption
DERIVERS = frozenset({"split", "fold_in", "PRNGKey", "key", "clone"})

BAD_SEED_CALLS = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.perf_counter",
        "os.urandom",
        "os.getpid",
        "random.random",
        "random.randint",
        "random.getrandbits",
        "uuid.uuid4",
        "uuid.uuid1",
        "secrets.randbits",
        "secrets.token_bytes",
    }
)


def _random_fn(name: str) -> str:
    """'uniform' from 'jax.random.uniform' / 'jrandom.uniform' / 'random.normal';
    '' when the call is not a jax.random-style function."""
    if not name:
        return ""
    head, _, tail = name.rpartition(".")
    if tail in CONSUMERS | DERIVERS:
        # require a random-ish namespace (or bare name imported from it)
        if head == "" or head.endswith("random") or head in ("jr", "jrandom", "jax.random"):
            return tail
    return ""


def _root_name(node: ast.AST) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


class _FnScanner:
    """Per-function scan in source order.  ``seen`` maps key-expression text
    -> line of first consumption; a rebind of the root name clears it."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: List[Finding] = []

    def scan(self, fn: ast.AST) -> None:
        seen: Dict[str, int] = {}
        self._walk(fn.body, seen)

    # -- helpers ------------------------------------------------------------

    def _clear_root(self, seen: Dict[str, int], root: str) -> None:
        if not root:
            return
        for expr in [e for e in seen if _root_of_text(e) == root]:
            del seen[expr]

    def _handle_call(self, call: ast.Call, seen: Dict[str, int]) -> None:
        fn_name = dotted_name(call.func)
        tail = _random_fn(fn_name)
        if not tail or not call.args:
            return
        key_arg = call.args[0]
        try:
            key_text = ast.unparse(key_arg)
        except Exception:  # pragma: no cover - unparse is total on 3.10
            return
        if tail in DERIVERS:
            return  # split/fold_in consume nothing; rebind handled at Assign
        prev = seen.get(key_text)
        if prev is not None:
            self.findings.append(
                self.ctx.finding(
                    call,
                    "RTL401",
                    f"key `{key_text}` already consumed at line {prev} — "
                    "reusing it yields identical randomness; "
                    "`key, sub = jax.random.split(key)` first",
                )
            )
        else:
            seen[key_text] = call.lineno

    def _walk_expr(self, node: ast.AST, seen: Dict[str, int]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._handle_call(sub, seen)

    def _walk(self, body, seen: Dict[str, int]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                self._walk_expr(stmt.value, seen)
                for tgt in stmt.targets:
                    for leaf in ast.walk(tgt):
                        if isinstance(leaf, (ast.Name, ast.Attribute)):
                            self._clear_root(seen, _root_name(leaf))
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    self._walk_expr(stmt.value, seen)
                self._clear_root(seen, _root_name(stmt.target))
            elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                for field in ("test", "iter"):
                    val = getattr(stmt, field, None)
                    if val is not None:
                        self._walk_expr(val, seen)
                if isinstance(stmt, ast.For):
                    for leaf in ast.walk(stmt.target):
                        if isinstance(leaf, ast.Name):
                            self._clear_root(seen, leaf.id)
                # branches see the same pre-branch state; if/else arms are
                # exclusive at runtime, so give each arm an isolated copy
                if isinstance(stmt, ast.If):
                    body_seen = dict(seen)
                    self._walk(stmt.body, body_seen)
                    else_seen = dict(seen)
                    self._walk(stmt.orelse, else_seen)
                    # keep only facts every arm agrees on
                    seen.clear()
                    seen.update(
                        {
                            k: v
                            for k, v in body_seen.items()
                            if else_seen.get(k) == v
                        }
                    )
                else:
                    for sub_body in ("body", "orelse", "finalbody"):
                        self._walk(getattr(stmt, sub_body, []) or [], seen)
                    for handler in getattr(stmt, "handlers", []):
                        self._walk(handler.body, seen)
            elif isinstance(stmt, (ast.Expr, ast.Return, ast.Assert, ast.Raise)):
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self._walk_expr(child, seen)
            elif isinstance(stmt, ast.FunctionDef):
                self.scan(stmt)  # nested def: fresh scope


def _root_of_text(expr_text: str) -> str:
    return expr_text.split(".", 1)[0].split("[", 1)[0]


@checker
def check_rng(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []

    # -- RTL402: entropy-seeded keys ---------------------------------------
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if _random_fn(name) not in ("PRNGKey", "key"):
            continue
        for arg in node.args:
            for sub in ast.walk(arg):
                if (
                    isinstance(sub, ast.Call)
                    and dotted_name(sub.func) in BAD_SEED_CALLS
                ):
                    findings.append(
                        ctx.finding(
                            node,
                            "RTL402",
                            f"PRNG key seeded from {dotted_name(sub.func)}() — "
                            "unreproducible; take the seed from config",
                        )
                    )

    # -- RTL401: double consumption per function scope ---------------------
    # Scan only outermost functions: _walk recurses into nested defs itself
    # (with a fresh scope), so scanning them again would duplicate findings.
    nested: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    nested.add(id(sub))
    scanner = _FnScanner(ctx)
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and id(node) not in nested
        ):
            scanner.scan(node)
    findings.extend(scanner.findings)
    return findings
