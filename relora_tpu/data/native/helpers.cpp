// relora-tpu native dataset index builders.
//
// C++ equivalents of the reference's runtime-compiled pybind11 helpers
// (peft_pretraining/megatron_dataset/helpers.cpp): the O(total_tokens) /
// O(total_samples) index-construction loops that are too slow in Python for
// billion-token corpora.  Re-implemented as a flat extern-C API loaded via
// ctypes (pybind11 is not part of this toolchain); NumPy-owned buffers are
// passed as raw pointers, so no copies are made in either direction.
//
// Differential-tested against the pure-NumPy implementations in
// relora_tpu/data/sample_index.py and blendable.py (the same oracle strategy
// the reference uses: dataset.py:275-320 is its Python fallback).
//
// Build: see native/build.py (g++ -O3 -shared -fPIC, no dependencies).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <random>
#include <vector>

// ---------------------------------------------------------------------------
// Sample-index packing (parity: helpers.cpp:91-259)
//
// Walk the (epoch-repeated, shuffled) document list, packing windows of
// seq_length + 1 tokens; record the (position-in-doc_idx, offset-in-doc)
// pair at each sample boundary.  The +1/-1 bookkeeping exists because
// consecutive samples share one boundary token (input/target shift).
//
// sample_idx must hold 2 * (num_samples + 1) entries.  Returns 0 on success,
// -1 if the documents ran out before num_samples were packed (corrupt input).
// ---------------------------------------------------------------------------

template <typename IndexT>
static int pack_sample_index(const int32_t* sizes,
                             const IndexT* doc_idx,
                             int64_t doc_idx_len,
                             int32_t seq_length,
                             int64_t num_samples,
                             IndexT* sample_idx) {
  int64_t out = 0;
  int64_t doc_pos = 0;     // index into doc_idx
  int64_t doc_offset = 0;  // token offset within the current document

  sample_idx[2 * out] = static_cast<IndexT>(doc_pos);
  sample_idx[2 * out + 1] = static_cast<IndexT>(doc_offset);
  ++out;

  while (out <= num_samples) {
    int64_t remaining = static_cast<int64_t>(seq_length) + 1;
    while (remaining > 0) {
      if (doc_pos >= doc_idx_len) return -1;
      const int64_t doc_len = static_cast<int64_t>(sizes[doc_idx[doc_pos]]) - doc_offset;
      if (doc_len >= remaining) {
        // window ends inside this document; next sample re-reads the
        // boundary token (hence the -1)
        doc_offset += remaining - 1;
        remaining = 0;
      } else {
        remaining -= doc_len;
        ++doc_pos;
        doc_offset = 0;
      }
    }
    sample_idx[2 * out] = static_cast<IndexT>(doc_pos);
    sample_idx[2 * out + 1] = static_cast<IndexT>(doc_offset);
    ++out;
  }
  return 0;
}

static void fisher_yates_i64(int64_t* data, int64_t n, std::mt19937_64& rng) {
  for (int64_t i = n - 1; i > 0; --i) {
    std::uniform_int_distribution<int64_t> dist(0, i);
    std::swap(data[i], data[dist(rng)]);
  }
}

extern "C" {

int relora_build_sample_idx_i32(const int32_t* sizes,
                                const int32_t* doc_idx,
                                int64_t doc_idx_len,
                                int32_t seq_length,
                                int64_t num_samples,
                                int32_t* sample_idx) {
  return pack_sample_index<int32_t>(sizes, doc_idx, doc_idx_len, seq_length,
                                    num_samples, sample_idx);
}

int relora_build_sample_idx_i64(const int32_t* sizes,
                                const int64_t* doc_idx,
                                int64_t doc_idx_len,
                                int32_t seq_length,
                                int64_t num_samples,
                                int64_t* sample_idx) {
  return pack_sample_index<int64_t>(sizes, doc_idx, doc_idx_len, seq_length,
                                    num_samples, sample_idx);
}

// ---------------------------------------------------------------------------
// Weighted-blend index construction (parity: helpers.cpp:34-89)
//
// Greedy max-error interleave: at each global sample, emit the dataset whose
// achieved count lags its target fraction the most.  dataset_index gets the
// chosen dataset id; dataset_sample_index the running per-dataset counter.
// ---------------------------------------------------------------------------

void relora_build_blending_indices(uint8_t* dataset_index,
                                   int64_t* dataset_sample_index,
                                   const double* weights,
                                   int32_t num_datasets,
                                   int64_t size) {
  std::vector<int64_t> taken(num_datasets, 0);
  for (int64_t i = 0; i < size; ++i) {
    const double position = std::max(static_cast<double>(i), 1.0);
    int32_t best = 0;
    double best_error = weights[0] * position - static_cast<double>(taken[0]);
    for (int32_t d = 1; d < num_datasets; ++d) {
      const double err = weights[d] * position - static_cast<double>(taken[d]);
      if (err > best_error) {
        best_error = err;
        best = d;
      }
    }
    dataset_index[i] = static_cast<uint8_t>(best);
    dataset_sample_index[i] = taken[best];
    ++taken[best];
  }
}

// ---------------------------------------------------------------------------
// In-place Fisher-Yates shuffle (mirrors the shuffle the reference embeds in
// its BERT mapping builders)
// ---------------------------------------------------------------------------

void relora_shuffle_i64(int64_t* data, int64_t n, uint64_t seed) {
  std::mt19937_64 rng(seed);
  fisher_yates_i64(data, n, rng);
}

}  // extern "C"
