"""CLI: ``python -m relora_tpu.analysis [paths] [options]``.

Exit codes: 0 clean (baselined/noqa'd findings allowed), 1 new findings or
stale baseline entries, 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from relora_tpu.analysis import (
    RULE_CATALOG,
    format_baseline_entry,
    lint_paths,
)

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = REPO_ROOT / "tools" / "lint_baseline.txt"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m relora_tpu.analysis",
        description="JAX/TPU footgun linter (RTL1xx-RTL5xx)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files or directories to lint (default: relora_tpu/ under the repo root)",
    )
    parser.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE),
        help="baseline file of grandfathered findings (default: %(default)s)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline; report every finding as new",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="print baseline lines for all NEW findings (justifications "
        "left as TODO; paste into the baseline file and justify)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog and exit"
    )
    parser.add_argument(
        "--family",
        action="append",
        default=None,
        metavar="RTL#",
        help="only report findings/stale entries whose code starts with this "
        "prefix (repeatable, e.g. --family RTL6 --family RTL7)",
    )
    parser.add_argument(
        "--call-graph-dump",
        action="store_true",
        help="print the project symbol table (thread roots + resolved call "
        "edges per module) instead of linting",
    )
    parser.add_argument(
        "--root",
        default=str(REPO_ROOT),
        help="root for repo-relative paths (default: %(default)s)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULE_CATALOG):
            print(f"{code}  {RULE_CATALOG[code]}")
        return 0

    paths = args.paths or [str(REPO_ROOT / "relora_tpu")]

    if args.call_graph_dump:
        from relora_tpu.analysis.core import FileContext, ProjectIndex, _iter_py_files
        import os

        contexts = {}
        for path in paths:
            for fpath in _iter_py_files(path):
                rel = os.path.relpath(os.path.abspath(fpath), args.root)
                try:
                    with open(fpath, encoding="utf-8") as fh:
                        contexts[rel] = FileContext(fpath, rel, fh.read())
                except (SyntaxError, UnicodeDecodeError) as e:
                    print(f"parse error: {rel}: {e}", file=sys.stderr)
        print(ProjectIndex(contexts).call_graph_dump())
        return 0

    baseline = None
    if not args.no_baseline and Path(args.baseline).is_file():
        baseline = args.baseline

    try:
        report = lint_paths(paths, root=args.root, baseline=baseline)
    except ValueError as e:  # malformed baseline
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.family:
        prefixes = tuple(p.upper() for p in args.family)
        report.new = [f for f in report.new if f.code.startswith(prefixes)]
        report.stale_baseline = [
            e for e in report.stale_baseline if e.code.startswith(prefixes)
        ]

    for f in report.new:
        print(f.render())
    if args.write_baseline and report.new:
        print("\n# --- baseline lines for the findings above ---", file=sys.stderr)
        for f in report.new:
            print(format_baseline_entry(f), file=sys.stderr)
    for entry in report.stale_baseline:
        print(
            f"{args.baseline}:{entry.lineno}: stale baseline entry "
            f"({entry.path} | {entry.code}) no longer matches — remove it",
            file=sys.stderr,
        )
    for err in report.parse_errors:
        print(f"parse error: {err}", file=sys.stderr)

    print(
        f"[relora-lint] {report.files_scanned} files, "
        f"{len(report.findings)} findings "
        f"({len(report.new)} new, {report.baselined} baselined, "
        f"{report.noqa_suppressed} noqa), "
        f"{len(report.stale_baseline)} stale baseline entries",
        file=sys.stderr,
    )
    if report.parse_errors:
        return 2
    if report.new or report.stale_baseline:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
