"""Async HTTP/1.1 serving front-end: streaming generation over the scheduler.

Stdlib-only (asyncio + sockets, like the analysis package keeps to ast): one
listener accepts requests while a dedicated **model thread** drives the
blocking jitted engine through the scheduler's incremental core — the decode
loop never blocks the event loop, and the event loop never touches jax.

Endpoints:

- ``POST /v1/generate`` — body ``{"prompt": [ids...], "max_new_tokens": N,
  "temperature": T, "top_p": P, "stream": true, "deadline_s": S}``.
  Streaming responses are Server-Sent Events (``text/event-stream``): one
  ``data: {"uid", "index", "token"}`` event per token as it is sampled, a
  final ``data: {...finish record...}`` with the full token list and
  latency fields, then ``data: [DONE]``.  ``"stream": false`` returns the
  finish record as a single JSON body.
- ``GET /healthz`` — readiness: 200 while accepting; 503 with ``status``
  ``"draining"`` (SIGTERM), ``"stuck"`` (stall watchdog: no decode step for
  ``stall_timeout_s``), ``"error"`` (model thread died), or ``"warming"``
  (``warmup_fn`` still paying compile buckets: the replica is discoverable
  but not yet routable) — the router (serve/router.py) ejects a replica on
  any 503 and (re-)adopts it when the status clears.  Paged schedulers attach a ``paging`` block (pool
  pressure, prefix-cache stats, and — under ``paging.dispatch`` — the
  dispatch-economics counters: dispatches per round, tokens per dispatch,
  and packed-token utilization when ``--packed`` is on).
- ``GET /metrics`` — Prometheus text exposition (serve/admission.ServeMetrics).

Flow control, end to end:

- **Backpressure**: the AdmissionController is the only waiting room; when
  its bounded queue is full new requests get **429 + Retry-After** — memory
  is fixed at ``max_batch`` decoding + ``max_queue`` waiting, no matter the
  offered load, and in-flight streams are unaffected.
- **Deadlines**: ``deadline_s`` bounds a request's wall time; the scheduler
  expires it at the next step boundary and the stream finishes with its
  partial output and ``finish_reason: "timeout"``.
- **Disconnects**: a client that goes away mid-stream flips the ticket's
  ``cancelled`` event; the model thread cancels the request at the next
  step boundary, freeing the slot for the next admission.
- **Graceful drain**: SIGTERM (or ``begin_drain()``) stops admissions (new
  requests get **503**), finishes everything in flight *and* everything
  already queued, then shuts the listener down — the update-boundary
  pattern from train/resilience.PreemptionGuard, with the decode step as
  the boundary.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Set, Tuple
from urllib.parse import urlsplit

from relora_tpu.obs.flight import dump_on_fault
from relora_tpu.obs.tracer import NoopTracer, Tracer, new_trace_id
from relora_tpu.serve import disagg
from relora_tpu.serve.admission import (
    AdmissionController,
    Draining,
    QueueFull,
    ServeMetrics,
    Ticket,
)
from relora_tpu.serve.scheduler import (
    Completion,
    ContinuousBatchingScheduler,
    Request,
)
from relora_tpu.serve.wire import (
    decode_page_run as _decode_page_run,
    encode_page_run as _encode_page_run,
    head as _head,
    read_http_request as _read_http_request,
    respond as _respond,
    respond_json as _respond_json,
    sse as _sse,
)
from relora_tpu.utils import faults
from relora_tpu.utils.logging import MetricsLogger, get_logger

logger = get_logger(__name__)

_REQUEST_TIMEOUT_S = 30.0
_IDLE_POP_S = 0.02


def _completion_record(completion: Completion) -> Dict[str, Any]:
    record = {
        "uid": completion.uid,
        "finish_reason": completion.finish_reason,
        "tokens": completion.tokens,
        "prompt_tokens": completion.prompt_tokens,
        "output_tokens": len(completion.tokens),
        "ttft_s": round(completion.ttft_s, 6),
        "latency_s": round(completion.latency_s, 6),
    }
    if completion.error is not None:
        record["error"] = completion.error
    return record


class BadRequest(Exception):
    """Malformed request body — HTTP 400."""


class _ReloadRequest:
    """One pending in-place weight reload, handed to the model thread.

    ``apply`` is the prepared host->device closure (the checkpoint is already
    verified and restored to host memory when this exists); the model thread
    runs it at an idle decode boundary and completes ``done`` with ``ok`` /
    ``error`` filled in.
    """

    def __init__(self, apply: Callable[[], None], version: int, checkpoint: str):
        self.apply = apply
        self.version = version
        self.checkpoint = checkpoint
        self.done = threading.Event()
        self.ok = False
        self.error: Optional[str] = None


def parse_generate_body(
    body: bytes,
    *,
    default_max_new_tokens: int,
    default_temperature: float,
    default_top_p: float,
) -> Dict[str, Any]:
    """Validate the /v1/generate JSON body into plain fields (no uid yet).
    Raises BadRequest with a reader-facing message on any violation."""
    try:
        payload = json.loads(body.decode("utf-8") or "{}")
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise BadRequest(f"body is not valid JSON: {e}") from None
    if not isinstance(payload, dict):
        raise BadRequest("body must be a JSON object")
    prompt = payload.get("prompt")
    if not isinstance(prompt, list) or not all(
        isinstance(t, int) and not isinstance(t, bool) for t in prompt
    ):
        raise BadRequest('"prompt" must be a list of token ids (ints)')
    max_new = payload.get("max_new_tokens", default_max_new_tokens)
    if not isinstance(max_new, int) or isinstance(max_new, bool) or max_new < 1:
        raise BadRequest('"max_new_tokens" must be an int >= 1')
    temperature = payload.get("temperature", default_temperature)
    top_p = payload.get("top_p", default_top_p)
    if not isinstance(temperature, (int, float)) or temperature < 0:
        raise BadRequest('"temperature" must be a number >= 0')
    if not isinstance(top_p, (int, float)) or not 0.0 < top_p <= 1.0:
        raise BadRequest('"top_p" must be in (0, 1]')
    stream = payload.get("stream", True)
    if not isinstance(stream, bool):
        raise BadRequest('"stream" must be a boolean')
    deadline_s = payload.get("deadline_s")
    if deadline_s is not None and (
        not isinstance(deadline_s, (int, float)) or deadline_s <= 0
    ):
        raise BadRequest('"deadline_s" must be a number > 0')
    # per-request speculative opt-out: "spec": false skips drafting for this
    # request on a --spec server (output distribution is identical either way);
    # a no-op when the server runs without speculation
    spec = payload.get("spec", True)
    if not isinstance(spec, bool):
        raise BadRequest('"spec" must be a boolean')
    # multi-tenant: "adapter" names a LoRA adapter dir under --adapter-dir;
    # absent/null decodes the base model.  Whether the name is servable is
    # the scheduler's call (validate_request -> registry.known)
    adapter = payload.get("adapter")
    if adapter is not None and (not isinstance(adapter, str) or not adapter.strip()):
        raise BadRequest('"adapter" must be a non-empty string')
    return {
        "prompt": prompt,
        "max_new_tokens": max_new,
        "temperature": float(temperature),
        "top_p": float(top_p),
        "stream": stream,
        "deadline_s": deadline_s,
        "spec": spec,
        "adapter": adapter.strip() if isinstance(adapter, str) else None,
    }


class GenerateServer:
    """Asyncio front-end over a ContinuousBatchingScheduler.

    The constructor takes an *idle* scheduler (the server's model thread
    becomes its single driving thread).  ``serve_forever()`` binds, starts
    the model thread, and runs until a drain completes; ``begin_drain()``
    (thread-safe, also wired to SIGTERM) initiates shutdown.
    """

    def __init__(
        self,
        scheduler: ContinuousBatchingScheduler,
        *,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_queue: int = 64,
        default_max_new_tokens: int = 64,
        default_temperature: float = 0.0,
        default_top_p: float = 1.0,
        retry_after_s: float = 1.0,
        stall_timeout_s: float = 0.0,
        error_linger_s: float = 1.0,
        metrics: Optional[MetricsLogger] = None,
        tracer: Optional[Tracer] = None,
        reload_prepare: Optional[Callable[[str], Callable[[], None]]] = None,
        weights_version: int = 0,
        weights_checkpoint: str = "",
        warmup_fn: Optional[Callable[[], Any]] = None,
        peer_file: Optional[str] = None,
        fleet_url: Optional[str] = None,
        migrate_timeout_s: float = 30.0,
    ):
        self.scheduler = scheduler
        self.host = host
        self.port = port  # rebound to the real port after bind (port=0 = ephemeral)
        # disaggregated fleet identity: replicas carry disjoint uid spaces so
        # a migrated request's donor uid (folded into its sampling keys, so
        # it must travel unchanged) can never collide with a local mint
        self.replica_id = os.environ.get("RELORA_TPU_REPLICA_ID", f"pid{os.getpid()}")
        uid_base = (
            (zlib.crc32(self.replica_id.encode()) % 1021 + 1) << 21
            if "RELORA_TPU_REPLICA_ID" in os.environ
            else 0
        )
        self.admission = AdmissionController(
            max_queue, retry_after_s=retry_after_s, uid_base=uid_base
        )
        self.stats = ServeMetrics()
        self.metrics = metrics
        if tracer is None:
            # per-process JSONL sink (pid-suffixed: supervisor fleets run N
            # replicas against one trace dir) so tools/trace_report.py can
            # merge replica spans with the router's under one request id
            trace_dir = os.environ.get("RELORA_TPU_TRACE_DIR")
            tracer = Tracer(
                service="serve",
                jsonl_path=(
                    os.path.join(trace_dir, f"serve_spans_{os.getpid()}.jsonl")
                    if trace_dir
                    else None
                ),
            )
        self.tracer = tracer
        # thread the server's tracer + registry into the scheduler so
        # prefill/insert/decode spans carry the same request trace ids and
        # the per-phase histograms land on this /metrics endpoint (a
        # scheduler built with its own tracer/registry keeps them)
        if isinstance(scheduler.tracer, NoopTracer):
            scheduler.tracer = self.tracer
        if scheduler.obs_registry is None:
            scheduler.obs_registry = self.stats
        # multi-tenant: materialize the per-adapter series at zero so a
        # scrape taken before any tenant traffic still shows every adapter
        # the server can route to (absent-vs-zero is a real distinction for
        # dashboards doing rate() over counters)
        registry = getattr(scheduler, "adapter_registry", None)
        if registry is not None:
            if registry.metrics is None:
                registry.metrics = self.stats  # evictions counter + load histogram
            self.stats.inc("adapter_requests_total", ("adapter", "base"), 0)
            for name in registry.list_adapters():
                self.stats.inc("adapter_requests_total", ("adapter", name), 0)
            self.stats.inc("adapter_evictions_total", by=0)
            self.stats.set_gauge("adapter_slots_used", registry.slots_used())
            self.stats.materialize_histogram("adapter_load_seconds")
        # the collector's error_rate is derived from requests_finished_total
        # deltas; materialize the counter at zero so a replica that has not
        # finished a request yet still exports error_rate = 0.0 (absent
        # series would blind the SLO engine during warmup)
        self.stats.inc("requests_finished_total", ("reason", "stop"), 0)
        self.stats.inc("requests_finished_total", ("reason", "error"), 0)
        self.default_max_new_tokens = default_max_new_tokens
        self.default_temperature = default_temperature
        self.default_top_p = default_top_p
        self.started = threading.Event()  # set once the listener is bound
        self.drained = threading.Event()  # set once the model thread exits
        self._t_start = time.monotonic()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._shutdown: Optional[asyncio.Event] = None
        self._handler_tasks: Set[asyncio.Task] = set()
        self._active: Dict[int, Ticket] = {}  # model thread only
        self._worker = threading.Thread(
            target=self._model_loop, name="serve-model", daemon=True
        )
        self._worker_error: Optional[BaseException] = None
        # -- self-diagnosis ----------------------------------------------------
        # stall watchdog: no decode step completed for stall_timeout_s while
        # the scheduler had work -> healthz flips to 503 "stuck" + one flight
        # dump per episode (0 disables; set it above your worst cold compile)
        self.stall_timeout_s = stall_timeout_s
        # after the model thread dies, keep the listener up this long so
        # health probes observe the 503 "error" state (a router ejects on
        # status, not just connection-refused) before the process exits
        self.error_linger_s = error_linger_s
        # feeds faults.serve_tick; incremented from the model thread (local
        # decode) AND the event loop (migration-relay streams), so locked
        self._tokens_emitted = 0
        self._emitted_lock = threading.Lock()
        # -- in-place weight reload (continuous deployment) --------------------
        # reload_prepare(path) runs off the model thread (verify manifest +
        # restore to host memory) and returns the apply closure the model
        # thread honors at an idle decode boundary — the PreemptionGuard
        # "honor at the boundary" shape, with the decode round as boundary
        self.reload_prepare = reload_prepare
        self.weights_version = weights_version
        self.weights_checkpoint = weights_checkpoint
        self.stats.set_gauge("weights_version", weights_version)
        self._reload_lock = threading.Lock()
        self._pending_reload: Optional[_ReloadRequest] = None
        self._last_step_t = time.monotonic()
        self._model_busy = False  # model thread writes; watchdog reads
        self._stuck = False  # watchdog writes; healthz reads
        self._watchdog: Optional[threading.Thread] = None
        # -- router-aware warmup ----------------------------------------------
        # warmup_fn runs first on the model thread: the listener binds (and
        # the port file lands) immediately so the supervisor/collector see
        # the replica, but /healthz answers 503 "warming" until the compile
        # buckets are paid for — a health-probing router never sends live
        # traffic into a cold replica's compile stall.  Promotion to "ok" is
        # the warmup report completing; a warmup failure takes the normal
        # worker-error path instead.
        self.warmup_fn = warmup_fn
        self.warmup_report: Optional[Any] = None
        self._warming = warmup_fn is not None
        self.stats.set_gauge("warming", 1 if self._warming else 0)
        # -- disaggregated prefill/decode tier ---------------------------------
        # role comes from the scheduler (serve.py --role); peer_file is the
        # supervisor-maintained roster; fleet_url reaches the collector's
        # /fleet/prefix directory.  The inbox carries cross-thread work INTO
        # the model thread (handoff outcomes, migrated-run inserts, prefix
        # exports) — drained once per model-loop iteration, the same
        # idle-boundary discipline as _ReloadRequest.
        self.role = getattr(scheduler, "role", "mixed")
        self.peer_file = peer_file
        self.fleet_url = fleet_url
        self.migrate_timeout_s = migrate_timeout_s
        self._disagg_inbox: Deque[Tuple[str, Any]] = deque()
        if hasattr(scheduler, "migration_sink"):
            if self.role == "prefill" and peer_file:
                scheduler.migration_sink = self._migration_sink
            if fleet_url:
                scheduler.prefix_fetch = self._prefix_fetch
            # materialize the disagg counters at zero at startup (RTL703 +
            # the collector's *_per_s derivations need the series from the
            # very first scrape, not the first migration)
            for name in (
                "pages_migrated_total",
                "migration_bytes_total",
                "migration_failures_total",
                "migrated_inserts_total",
                "prefix_fetch_total",
                "prefix_fetch_failures_total",
            ):
                self.stats.inc(name, by=0)

    # -- lifecycle -----------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting (new requests get 503), finish in-flight and queued
        work, then shut down.  Thread-safe and idempotent."""
        if self.admission.draining:
            return
        logger.info("drain requested: rejecting new requests, finishing in-flight")
        self.admission.begin_drain()
        self.stats.set_gauge("draining", 1)
        if self.metrics is not None:
            self.metrics.event(
                "serve_drain_begin",
                queue_depth=self.admission.depth(),
                active_slots=self.scheduler.active_slots,
            )

    async def serve_forever(self, *, install_signal_handlers: bool = True) -> None:
        self._loop = asyncio.get_running_loop()
        self._shutdown = asyncio.Event()
        server = await asyncio.start_server(self._client_connected, self.host, self.port)
        self.port = server.sockets[0].getsockname()[1]
        if install_signal_handlers:
            try:
                self._loop.add_signal_handler(signal.SIGTERM, self.begin_drain)
            except (NotImplementedError, RuntimeError):
                # non-main thread or non-Unix loop: callers drain explicitly
                logger.warning("SIGTERM handler unavailable; use begin_drain()")
        self.stats.set_gauge("draining", 0)
        self._worker.start()
        if self.stall_timeout_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-watchdog", daemon=True
            )
            self._watchdog.start()
        self.started.set()
        logger.info(f"serving on http://{self.host}:{self.port}")
        async with server:
            await self._shutdown.wait()
            server.close()
            await server.wait_closed()
        if self._handler_tasks:
            # finish events are already queued on the loop; give handlers a
            # bounded grace to flush their final bytes
            await asyncio.wait(set(self._handler_tasks), timeout=10.0)
        if self.metrics is not None:
            self.metrics.event("serve_drain_complete", **self.stats.snapshot())
        logger.info("drain complete; server stopped")
        if self._worker_error is not None:
            raise RuntimeError("model thread died") from self._worker_error

    def _signal_shutdown(self) -> None:
        loop, shutdown = self._loop, self._shutdown
        if loop is None or shutdown is None:
            return
        try:
            loop.call_soon_threadsafe(shutdown.set)
        except RuntimeError:
            pass  # loop already closed

    # -- model thread --------------------------------------------------------

    def _model_loop(self) -> None:
        """The scheduler's single driving thread: claim tickets while slots
        are free, apply cancellations, run one decode round, repeat.  Exits
        when draining and nothing is left anywhere."""
        sched = self.scheduler
        try:
            if self.warmup_fn is not None:
                t0 = time.monotonic()
                logger.info("warmup: paying compile buckets before going routable")
                self.warmup_report = self.warmup_fn()
                self._warming = False
                self.stats.set_gauge("warming", 0)
                self._last_step_t = time.monotonic()
                logger.info(
                    f"warmup complete in {time.monotonic() - t0:.1f}s; healthz -> ok"
                )
                if self.metrics is not None:
                    detail = (
                        self.warmup_report
                        if isinstance(self.warmup_report, dict)
                        else {}
                    )
                    self.metrics.event(
                        "serve_warm", duration_s=round(time.monotonic() - t0, 3),
                        **detail,
                    )
            while True:
                faults.serve_tick(self._tokens_emitted)  # serving drills only
                # a pending reload pauses *claiming* only: queued tickets wait
                # in admission (nothing is dropped), in-flight requests finish
                # entirely on the old weights (per-request version purity),
                # and the swap happens at the idle boundary below
                reload_req = self._pending_reload
                while reload_req is None and (
                    sched.active_slots + sched.queue_depth < sched.max_batch
                ):
                    ticket = self.admission.pop(timeout=None)
                    if ticket is None:
                        break
                    self._claim(ticket)
                for uid, ticket in list(self._active.items()):
                    if ticket.cancelled.is_set():
                        sched.cancel(uid)  # fires on_finish -> _active cleanup
                self._drain_disagg_inbox()
                self.stats.set_gauge(
                    "queue_depth", self.admission.depth() + sched.queue_depth
                )
                self.stats.set_gauge("active_slots", sched.active_slots)
                self.stats.set_gauge(
                    "retry_after_s", round(self.admission.retry_after_s, 3)
                )
                if sched.has_work():
                    self._model_busy = True
                    sched.step()
                    self._last_step_t = time.monotonic()
                    continue
                self._model_busy = False
                self._last_step_t = time.monotonic()  # idle is not a stall
                if reload_req is not None:
                    # the boundary: no active slots, no scheduler queue — swap
                    # weights now, then resume claiming on the next iteration
                    self._apply_reload(reload_req)
                    continue
                if self.admission.draining and self.admission.depth() == 0:
                    break
                ticket = self.admission.pop(timeout=_IDLE_POP_S)
                if ticket is not None:
                    self._claim(ticket)
        except BaseException as e:
            self._worker_error = e
            logger.error(f"model thread died: {e!r}")
            self._fail_pending(e)
        finally:
            self._fail_reload("model thread exited")
            self.drained.set()
            if self._worker_error is not None and self.error_linger_s > 0:
                time.sleep(self.error_linger_s)
            self._signal_shutdown()

    def _fail_pending(self, error: BaseException) -> None:
        """Model-thread death: terminally complete every active and queued
        request with ``finish_reason="error"`` instead of stranding its
        stream until the client gives up.  Host-side bookkeeping only — safe
        even when the jitted step itself is what blew up."""
        detail = f"model thread died: {error!r}"
        self.stats.set_gauge("model_dead", 1)
        try:
            # requests the scheduler owns (decoding or scheduler-queued):
            # fail_all fires the normal on_finish wrappers, so metrics, spans
            # and the SSE finish events all flow through the standard path
            self.scheduler.fail_all(reason="error", detail=detail)
        except Exception as e:
            logger.error(f"fail_all after model-thread death failed too: {e!r}")
            for _uid, ticket in list(self._active.items()):
                self._active.pop(_uid, None)
                try:
                    ticket.on_finish(
                        Completion(
                            uid=ticket.uid,
                            tokens=[],
                            finish_reason="error",
                            prompt_tokens=len(ticket.request.prompt),
                            ttft_s=0.0,
                            latency_s=0.0,
                            error=detail,
                        )
                    )
                except Exception:
                    pass
        # tickets still waiting in the admission queue, never claimed
        while True:
            ticket = self.admission.pop(timeout=None)
            if ticket is None:
                break
            self.stats.inc("requests_finished_total", ("reason", "error"))
            if ticket.queue_span is not None:
                ticket.queue_span.set(outcome="error").end()
            if ticket.span is not None:
                ticket.span.set(finish_reason="error", output_tokens=0).end()
            try:
                ticket.on_finish(
                    Completion(
                        uid=ticket.uid,
                        tokens=[],
                        finish_reason="error",
                        prompt_tokens=len(ticket.request.prompt),
                        ttft_s=0.0,
                        latency_s=0.0,
                        error=detail,
                    )
                )
            except Exception as e:
                logger.warning(f"request {ticket.uid}: finish callback failed: {e!r}")

    # -- in-place weight reload ----------------------------------------------

    def request_reload(self, apply: Callable[[], None], version: int, checkpoint: str) -> _ReloadRequest:
        """Queue a prepared weight swap for the model thread's next idle
        boundary.  Thread-safe; raises RuntimeError while another reload is
        still pending (one swap at a time keeps versions totally ordered)."""
        req = _ReloadRequest(apply, version, checkpoint)
        with self._reload_lock:
            if self._pending_reload is not None:
                raise RuntimeError("a weight reload is already pending")
            self._pending_reload = req
        return req

    def _apply_reload(self, req: _ReloadRequest) -> None:
        """Model thread, idle boundary: run the prepared swap.  Any failure
        fails closed — the old weights keep serving, the version does not
        move, and the error is reported to the requester."""
        try:
            faults.maybe_fail("deploy_reload")
            req.apply()
        except Exception as e:
            req.error = f"{e!r}"
            self.stats.inc("weights_reload_failures_total")
            logger.error(
                f"weight reload to {req.checkpoint!r} failed ({e!r}); "
                f"keeping weights_version {self.weights_version}"
            )
            if self.metrics is not None:
                self.metrics.event(
                    "serve_reload_failed", checkpoint=req.checkpoint, error=f"{e!r}"
                )
        else:
            req.ok = True
            self.weights_version = req.version
            self.weights_checkpoint = req.checkpoint
            self.stats.inc("weights_reloads_total")
            self.stats.set_gauge("weights_version", req.version)
            logger.info(
                f"weights hot-swapped to version {req.version} ({req.checkpoint})"
            )
            if self.metrics is not None:
                self.metrics.event(
                    "serve_reload", weights_version=req.version, checkpoint=req.checkpoint
                )
        finally:
            with self._reload_lock:
                self._pending_reload = None
            req.done.set()

    def _fail_reload(self, detail: str) -> None:
        """Complete a still-pending reload with an error so its requester
        never hangs (model-thread death or drain exit)."""
        with self._reload_lock:
            req, self._pending_reload = self._pending_reload, None
        if req is not None and not req.done.is_set():
            req.error = detail
            self.stats.inc("weights_reload_failures_total")
            req.done.set()

    # -- stall watchdog ------------------------------------------------------

    def _watchdog_loop(self) -> None:
        """Decode-progress watchdog: when the scheduler had work but no step
        completed for ``stall_timeout_s`` (wedged device call, injected
        ``serve_stall``, runaway compile), flip ``/healthz`` to 503 "stuck"
        so the router ejects this replica, and dump the flight recorder once
        per episode for offline triage.  Un-sticks by itself when a step
        completes — a recovered replica goes back into rotation."""
        interval = max(0.02, min(self.stall_timeout_s / 4.0, 1.0))
        while not self.drained.is_set():
            time.sleep(interval)
            # _model_busy/_last_step_t freeze at their last values while the
            # model thread is wedged — which is exactly the signal
            stalled = (
                self._model_busy
                and time.monotonic() - self._last_step_t > self.stall_timeout_s
            )
            if stalled and not self._stuck:
                self._stuck = True
                self.stats.set_gauge("stuck", 1)
                logger.error(
                    f"watchdog: no decode step for {self.stall_timeout_s:.1f}s "
                    "with work queued; healthz -> 503 stuck"
                )
                dump_on_fault("serve_stall")
                if self.metrics is not None:
                    self.metrics.event(
                        "serve_stall_detected",
                        stall_timeout_s=self.stall_timeout_s,
                        active_slots=self.scheduler.active_slots,
                    )
            elif not stalled and self._stuck:
                self._stuck = False
                self.stats.set_gauge("stuck", 0)
                logger.warning("watchdog: decode progress resumed; healthz -> ok")

    def _claim(self, ticket: Ticket) -> None:
        """Hand one admitted ticket to the scheduler (model thread only)."""
        # the queue-wait span opened at admission ends here, where the model
        # thread claims the ticket (cross-thread: started on the event loop)
        if ticket.queue_span is not None:
            self.stats.observe("queue_wait_seconds", ticket.queue_span.end())
        if ticket.cancelled.is_set():
            # client left while the request was still queued: never admit it
            self.stats.inc("requests_finished_total", ("reason", "cancelled"))
            if ticket.span is not None:
                ticket.span.set(finish_reason="cancelled", output_tokens=0).end()
            ticket.on_finish(
                Completion(
                    uid=ticket.uid,
                    tokens=[],
                    finish_reason="cancelled",
                    prompt_tokens=len(ticket.request.prompt),
                    ttft_s=0.0,
                    latency_s=0.0,
                )
            )
            return
        self._active[ticket.uid] = ticket
        self.scheduler.submit(
            ticket.request,
            on_token=lambda uid, tok, idx, _t=ticket: self._token_cb(_t, uid, tok, idx),
            on_finish=lambda completion, _t=ticket: self._finish_cb(_t, completion),
            deadline=ticket.deadline,
            trace_id=ticket.trace_id,
        )

    def _token_cb(self, ticket: Ticket, uid: int, token: int, index: int) -> None:
        """Per-token bookkeeping shared by local decode and relayed migration
        streams: latency histograms, the Retry-After TPOT estimate, and the
        client's own on_token."""
        now = time.monotonic()
        if index == 0:
            self.stats.observe("ttft_seconds", now - ticket.t_enqueue)
        elif ticket.t_last_token is not None:
            tpot = now - ticket.t_last_token
            self.stats.observe("tpot_seconds", tpot)
            self.admission.note_tpot(tpot)  # feeds the Retry-After hint
        ticket.t_last_token = now
        with self._emitted_lock:
            self._tokens_emitted += 1
        self.stats.inc("tokens_generated_total")
        ticket.on_token(uid, token, index)

    def _finish_cb(self, ticket: Ticket, completion: Completion) -> None:
        """Finish bookkeeping shared by local decode and relayed migration
        streams: counters, e2e latency, the root span, the client stream."""
        self._active.pop(completion.uid, None)
        self.stats.inc(
            "requests_finished_total", ("reason", completion.finish_reason)
        )
        self.stats.observe("e2e_latency_seconds", time.monotonic() - ticket.t_enqueue)
        if ticket.span is not None:
            ticket.span.set(
                finish_reason=completion.finish_reason,
                output_tokens=len(completion.tokens),
            ).end()
        ticket.on_finish(completion)

    # -- disaggregated handoff / fleet prefix fetch --------------------------
    #
    # Thread contract: the scheduler is model-thread-only, so every disagg
    # mutation (handoff outcome, migrated-run insert, prefix export) crosses
    # from the event loop through _disagg_inbox and is applied by
    # _drain_disagg_inbox inside the model loop.  The donor-side relay
    # (_migrate_task) and the internal HTTP handlers live on the event loop;
    # _migration_sink and _prefix_fetch are called *by* the scheduler on the
    # model thread.

    def _drain_disagg_inbox(self) -> None:
        """Model thread: apply queued cross-thread disagg work."""
        sched = self.scheduler
        while self._disagg_inbox:
            kind, payload = self._disagg_inbox.popleft()
            try:
                if kind == "failed":
                    sched.migration_failed(payload[0], payload[1])
                elif kind == "commit":
                    sched.migration_commit(payload[0], bytes_sent=payload[1])
                elif kind == "abort":
                    sched.migration_abort(payload[0], payload[1])
                elif kind == "insert":
                    self._apply_migrate_insert(*payload)
                elif kind == "export_prefix":
                    self._apply_prefix_export(*payload)
            except Exception as e:
                # inbox work must never kill the model thread; each message
                # has its own fail-open story and this is the last resort
                logger.warning(f"disagg inbox {kind!r} failed: {e!r}")

    def _apply_migrate_insert(
        self,
        record: Dict[str, Any],
        arrays: Any,
        ticket: Ticket,
        done: threading.Event,
        result: Dict[str, Any],
    ) -> None:
        """Model thread: adopt a migrated page run into a decode slot.  Any
        raise lands in ``result["error"]`` and the donor fails open."""
        try:
            if ticket.cancelled.is_set():
                raise RuntimeError("donor went away before the insert")
            self.scheduler.submit_migrated(
                record,
                arrays,
                on_token=lambda uid, tok, idx, _t=ticket: self._token_cb(
                    _t, uid, tok, idx
                ),
                on_finish=lambda completion, _t=ticket: self._finish_cb(
                    _t, completion
                ),
                deadline=ticket.deadline,
                trace_id=ticket.trace_id,
            )
            self._active[ticket.uid] = ticket
        except Exception as e:
            result["error"] = str(e)
        finally:
            done.set()

    def _apply_prefix_export(
        self, digest_hex: str, done: threading.Event, result: Dict[str, Any]
    ) -> None:
        """Model thread: pin + export a locally cached prefix run for a peer
        (GET /internal/prefix/<digest>).  ``result["blob"]`` stays absent on
        a miss — the handler answers 404 and the peer falls open.  The
        acquire/decref pair is the donor-side pin: LRU eviction cannot free
        the run while export_page_run is copying it off the device."""
        try:
            sched = self.scheduler
            cache = getattr(sched, "prefix_cache", None)
            if cache is None:
                return
            acquired = cache.acquire(digest_hex)
            if acquired is None:
                return
            pages, n_tokens = acquired
            try:
                entries = sched.engine.export_page_run(sched._ensure_pool(), pages)
            finally:
                sched.allocator.decref(pages)  # release the transfer pin
            result["blob"] = _encode_page_run(
                {
                    "digest": digest_hex,
                    "n_tokens": n_tokens,
                    "n_pages": len(pages),
                },
                entries,
            )
        except Exception as e:
            result["error"] = str(e)
        finally:
            done.set()

    def _migration_sink(self, record: Dict[str, Any], entries: Any) -> bool:
        """Model thread (scheduler._maybe_migrate): pick decode peers, frame
        the run, and launch the async handoff.  Returning False means the
        handoff could not even start — the scheduler fails open on the spot."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return False
        ticket = self._active.get(int(record["uid"]))
        if ticket is None or ticket.cancelled.is_set():
            return False
        peers = disagg.load_peers(self.peer_file)
        candidates = disagg.pick_peers(
            peers, role="decode", exclude_rid=self.replica_id
        )
        if not candidates:
            return False
        # enrich with what only the server knows: the remaining deadline and
        # the request id, so the peer's deadline/spans behave like a direct hit
        if ticket.deadline is not None:
            record["deadline_s"] = max(0.1, ticket.deadline - time.monotonic())
        if ticket.trace_id:
            record["trace_id"] = ticket.trace_id
        try:
            blob = _encode_page_run(record, entries)
        except Exception as e:
            logger.warning(f"request {record['uid']}: wire encode failed: {e!r}")
            return False
        asyncio.run_coroutine_threadsafe(
            self._migrate_task(record, blob, ticket, candidates[:2]), loop
        )
        return True

    async def _migrate_task(
        self, record: Dict[str, Any], blob: bytes, ticket: Ticket, candidates: list
    ) -> None:
        """Event loop: drive the handoff against each candidate peer.  Per
        attempt: "relayed" (peer finished the stream — commit the donor
        slot), "rejected" (no token reached the client — the next peer, or
        fail open to local decode, is still token-identical), "aborted"
        (peer died after relaying a token — the PR 9 idempotency boundary
        forbids a silent replay, so the client gets a typed error finish)."""
        uid = int(record["uid"])
        detail = "no decode peer accepted the handoff"
        for peer in candidates:
            try:
                outcome, detail = await self._migrate_attempt(
                    record, blob, ticket, peer
                )
            except Exception as e:
                outcome, detail = "rejected", f"{peer.get('rid')}: {e!r}"
            if outcome == "relayed":
                self._disagg_inbox.append(("commit", (uid, len(blob))))
                return
            if outcome == "aborted":
                self._disagg_inbox.append(("abort", (uid, detail)))
                try:
                    self._finish_cb(
                        ticket,
                        Completion(
                            uid=uid,
                            tokens=[],
                            finish_reason="error",
                            prompt_tokens=len(ticket.request.prompt),
                            ttft_s=0.0,
                            latency_s=time.monotonic() - ticket.t_enqueue,
                            error=f"migration_failed: {detail}",
                        ),
                    )
                except Exception:
                    pass
                if self.metrics is not None:
                    self.metrics.event(
                        "migration_failed", uid=uid, detail=str(detail), aborted=True
                    )
                return
            logger.warning(
                f"request {uid}: handoff to {peer.get('rid')} rejected ({detail})"
            )
        self._disagg_inbox.append(("failed", (uid, detail)))
        if self.metrics is not None:
            self.metrics.event("migration_failed", uid=uid, detail=str(detail))

    async def _migrate_attempt(
        self, record: Dict[str, Any], blob: bytes, ticket: Ticket, peer: Dict[str, Any]
    ) -> Tuple[str, str]:
        """One POST /internal/migrate exchange: ship the framed run, then
        relay the peer's SSE continuation into the client's ticket callbacks.
        Returns ("relayed" | "rejected" | "aborted", detail)."""
        host = str(peer.get("host") or "127.0.0.1")
        port = int(peer["port"])
        uid = int(record["uid"])
        relayed_any = False
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), timeout=5.0
            )
        except (OSError, asyncio.TimeoutError) as e:
            return "rejected", f"connect {host}:{port}: {e!r}"
        try:
            writer.write(
                (
                    f"POST /internal/migrate HTTP/1.1\r\n"
                    f"Host: {host}:{port}\r\n"
                    f"Content-Type: application/octet-stream\r\n"
                    f"Content-Length: {len(blob)}\r\n"
                    f"Connection: close\r\n\r\n"
                ).encode()
            )
            writer.write(blob)
            await asyncio.wait_for(writer.drain(), timeout=self.migrate_timeout_s)
            status_line = await asyncio.wait_for(
                reader.readline(), timeout=self.migrate_timeout_s
            )
            parts = status_line.decode("latin-1", "replace").split()
            status = int(parts[1]) if len(parts) >= 2 and parts[1].isdigit() else 0
            while True:  # response headers; SSE or JSON body follows
                line = await asyncio.wait_for(
                    reader.readline(), timeout=self.migrate_timeout_s
                )
                if line in (b"\r\n", b"\n", b""):
                    break
            if status != 200:
                body = await reader.read(4096)
                return "rejected", f"{host}:{port} -> {status} {body[:200]!r}"
            while True:
                if ticket.cancelled.is_set():
                    # client left: abandon the relay (closing our end is the
                    # peer's disconnect signal — it cancels and frees pages),
                    # count the cancel, and commit the donor slot away
                    self._finish_cb(
                        ticket,
                        Completion(
                            uid=uid,
                            tokens=[],
                            finish_reason="cancelled",
                            prompt_tokens=len(ticket.request.prompt),
                            ttft_s=0.0,
                            latency_s=time.monotonic() - ticket.t_enqueue,
                        ),
                    )
                    return "relayed", "client cancelled mid-relay"
                line = await asyncio.wait_for(
                    reader.readline(), timeout=self.migrate_timeout_s
                )
                if not line:
                    if relayed_any:
                        return "aborted", f"{host}:{port}: peer died mid-stream"
                    return "rejected", f"{host}:{port}: peer died before first token"
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: ") :]
                if data == b"[DONE]":
                    continue  # finish record already handled below
                try:
                    rec = json.loads(data.decode("utf-8"))
                except (UnicodeDecodeError, json.JSONDecodeError):
                    continue
                if not isinstance(rec, dict):
                    continue
                if "finish_reason" in rec:
                    if rec["finish_reason"] == "error" and not relayed_any:
                        # peer failed before anything reached the client:
                        # safe to try the next peer / fail open locally
                        return "rejected", f"{host}:{port}: {rec.get('error')}"
                    self._finish_cb(
                        ticket,
                        Completion(
                            uid=uid,
                            tokens=[int(t) for t in rec.get("tokens", [])],
                            finish_reason=str(rec["finish_reason"]),
                            prompt_tokens=int(
                                rec.get("prompt_tokens", len(ticket.request.prompt))
                            ),
                            ttft_s=float(rec.get("ttft_s", 0.0)),
                            latency_s=time.monotonic() - ticket.t_enqueue,
                            error=rec.get("error"),
                        ),
                    )
                    return "relayed", "ok"
                if "token" in rec:
                    relayed_any = True
                    self._token_cb(ticket, uid, int(rec["token"]), int(rec["index"]))
        except (asyncio.TimeoutError, ConnectionError, OSError) as e:
            if relayed_any:
                return "aborted", f"{host}:{port}: {e!r}"
            return "rejected", f"{host}:{port}: {e!r}"
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _prefix_fetch(self, digests: list) -> Optional[Tuple[int, Any, int]]:
        """Model thread (scheduler._fetch_prefix): resolve the longest known
        prefix digest via the fleet directory, then pull the run from the
        holder's /internal/prefix endpoint.  Returns ``(n_tokens, entries,
        nbytes)`` or None; raises propagate into the scheduler's fail-open
        accounting (prefix_fetch_failures_total)."""
        url = self.fleet_url
        if not url:
            return None
        if os.path.exists(url):
            # the supervisor hands replicas a router-port *file* (the router
            # binds an ephemeral port after the replicas spawn)
            try:
                with open(url) as f:
                    url = f.read().strip()
                if ":" not in url:
                    url = f"127.0.0.1:{int(url)}"
            except (OSError, ValueError):
                return None
        parts = urlsplit(url if "//" in url else f"//{url}")
        status, body = disagg.http_fetch(
            parts.hostname or "127.0.0.1",
            parts.port or 80,
            "/fleet/prefix?d=" + ",".join(digests) + "&exclude=" + self.replica_id,
            timeout_s=2.0,
        )
        if status != 200:
            return None
        doc = json.loads(body.decode("utf-8"))
        digest = doc.get("digest")
        if not digest or doc.get("replica") == self.replica_id:
            return None
        status, blob = disagg.http_fetch(
            str(doc["host"]),
            int(doc["port"]),
            f"/internal/prefix/{digest}",
            timeout_s=5.0,
        )
        if status != 200:
            return None  # stale directory entry: the holder evicted the run
        meta, arrays = _decode_page_run(blob)
        return int(meta["n_tokens"]), arrays, len(blob)

    # -- asyncio handlers ----------------------------------------------------

    async def _client_connected(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._handler_tasks.add(task)
            task.add_done_callback(self._handler_tasks.discard)
        try:
            await self._handle(reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError, TimeoutError):
            pass  # client went away; per-request cleanup already ran
        except Exception as e:
            logger.warning(f"handler error: {e!r}")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if faults.should("serve_accept_drop"):
            # drill: an accepted connection that dies before a byte of
            # response — the shape a router's pre-stream retry must absorb
            self.stats.inc("accept_drops_total")
            return
        try:
            parsed = await asyncio.wait_for(_read_http_request(reader), _REQUEST_TIMEOUT_S)
        except ValueError as e:
            await _respond_json(writer, 400, {"error": str(e)})
            return
        if parsed is None:
            return
        method, path, headers, body = parsed
        route = path.split("?", 1)[0]
        if route == "/healthz" and method == "GET":
            self.stats.inc("http_requests_total", ("route", "healthz"))
            await self._handle_healthz(writer)
        elif route == "/metrics" and method == "GET":
            self.stats.inc("http_requests_total", ("route", "metrics"))
            await _respond(writer, 200, self.stats.render(), content_type="text/plain; version=0.0.4")
        elif route == "/v1/generate":
            self.stats.inc("http_requests_total", ("route", "generate"))
            if method != "POST":
                await _respond_json(writer, 405, {"error": "use POST"})
                return
            await self._handle_generate(reader, writer, body, headers)
        elif route == "/admin/reload":
            self.stats.inc("http_requests_total", ("route", "reload"))
            if method != "POST":
                await _respond_json(writer, 405, {"error": "use POST"})
                return
            await self._handle_reload(writer, body)
        elif route == "/internal/migrate":
            self.stats.inc("http_requests_total", ("route", "migrate"))
            if method != "POST":
                await _respond_json(writer, 405, {"error": "use POST"})
                return
            await self._handle_migrate(reader, writer, body)
        elif route.startswith("/internal/prefix/"):
            self.stats.inc("http_requests_total", ("route", "prefix"))
            if method != "GET":
                await _respond_json(writer, 405, {"error": "use GET"})
                return
            await self._handle_prefix(writer, route[len("/internal/prefix/") :])
        else:
            self.stats.inc("http_requests_total", ("route", "other"))
            await _respond_json(writer, 404, {"error": f"no route {route}"})

    async def _handle_healthz(self, writer: asyncio.StreamWriter) -> None:
        # precedence: a dead worker trumps everything, a wedged worker trumps
        # drain state, drain trumps warming — the router must stop routing
        # (or never start, for "warming") on all four
        if self._worker_error is not None:
            state, status = "error", 503
        elif self._stuck:
            state, status = "stuck", 503
        elif self.admission.draining:
            state, status = "draining", 503
        elif self._warming:
            state, status = "warming", 503
        else:
            state, status = "ok", 200
        payload = {
            "status": state,
            "active_slots": self.scheduler.active_slots,
            "queue_depth": self.admission.depth() + self.scheduler.queue_depth,
            "max_batch": self.scheduler.max_batch,
            "max_queue": self.admission.max_queue,
            "retry_after_s": round(self.admission.retry_after_s, 3),
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            # numeric, so the fleet collector ingests it as a free
            # healthz_weights_version series per replica; the checkpoint path
            # is what a rolling updater reads back for its rollback target
            "weights_version": self.weights_version,
            "weights_checkpoint": self.weights_checkpoint,
            # disaggregated tier: the router reads role for pool routing; the
            # collector feeds the fleet prefix-page directory from the digest
            # list (both skipped by its numeric-only metrics ingestion)
            "role": self.role,
        }
        prefix_cache = getattr(self.scheduler, "prefix_cache", None)
        if prefix_cache is not None:
            try:
                payload["prefix_digests"] = prefix_cache.digests()
            except RuntimeError:
                pass  # model thread mutated the cache mid-iteration; next probe
        if self._worker_error is not None:
            payload["detail"] = f"model thread died: {self._worker_error!r}"
        elif self._stuck:
            payload["detail"] = (
                f"no decode step completed for {self.stall_timeout_s:.1f}s"
            )
        elif self._warming:
            payload["detail"] = "compile warmup in progress"
        # paged scheduler: pool pressure for the allocator-exhaustion triage
        # flow (docs/operations.md) — queued-but-healthy vs queued-and-starved
        paging_stats = getattr(self.scheduler, "paging_stats", None)
        if paging_stats is not None:
            payload["paging"] = paging_stats()
        # multi-tenant scheduler: slot occupancy + residency for the
        # adapter-slot-thrash triage flow (docs/operations.md)
        adapter_stats = getattr(self.scheduler, "adapter_stats", None)
        if adapter_stats is not None:
            stats = adapter_stats()
            if stats is not None:
                payload["adapters"] = stats
        await _respond_json(writer, status, payload)

    async def _handle_reload(self, writer: asyncio.StreamWriter, body: bytes) -> None:
        """POST /admin/reload {"checkpoint": path}: verify + restore the
        checkpoint off the model thread, then hand the swap to the model
        thread's idle boundary and wait for its verdict.  Every failure mode
        (no reload path, bad body, verify/restore error, swap error) leaves
        the old weights serving — the endpoint can only move the version
        forward on full success."""
        if self.reload_prepare is None:
            await _respond_json(
                writer, 501,
                {"error": "no reload path configured (start with a --checkpoint)"},
            )
            return
        if self._worker_error is not None:
            await _respond_json(
                writer, 503, {"error": f"model thread died: {self._worker_error!r}"}
            )
            return
        try:
            payload = json.loads(body.decode("utf-8") or "{}")
            path = payload.get("checkpoint")
            if not isinstance(path, str) or not path.strip():
                raise BadRequest('"checkpoint" must be a non-empty path string')
        except (UnicodeDecodeError, json.JSONDecodeError, BadRequest) as e:
            await _respond_json(writer, 400, {"error": str(e)})
            return
        path = path.strip()
        from relora_tpu.serve.deploy import checkpoint_step

        version = checkpoint_step(path)
        if version is None:
            version = self.weights_version + 1  # non-model_N dirs still order
        loop = asyncio.get_running_loop()
        try:
            # verify manifest + restore to host memory off the event loop AND
            # off the model thread — decode keeps running while this works
            apply = await loop.run_in_executor(None, self.reload_prepare, path)
        except Exception as e:
            self.stats.inc("weights_reload_failures_total")
            logger.error(f"reload rejected before any device write: {e!r}")
            if self.metrics is not None:
                self.metrics.event("serve_reload_failed", checkpoint=path, error=f"{e!r}")
            await _respond_json(
                writer, 422,
                {"error": f"{e}", "weights_version": self.weights_version},
            )
            return
        try:
            req = self.request_reload(apply, version, path)
        except RuntimeError as e:
            await _respond_json(
                writer, 409, {"error": str(e), "weights_version": self.weights_version}
            )
            return
        await loop.run_in_executor(None, req.done.wait)
        await _respond_json(
            writer,
            200 if req.ok else 500,
            {
                "ok": req.ok,
                "weights_version": self.weights_version,
                "weights_checkpoint": self.weights_checkpoint,
                **({"error": req.error} if req.error else {}),
            },
        )

    async def _handle_migrate(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        body: bytes,
    ) -> None:
        """POST /internal/migrate — adopt a donor's finished page run into a
        decode slot and stream the continuation back as SSE (the donor
        relays it to the real client).  Every rejection is a non-200 the
        donor maps to fail-open local decode, so rejecting here is always
        safe; accepting means this replica now owns the request's stream."""
        if self._worker_error is not None or self._warming or self.admission.draining:
            await _respond_json(writer, 503, {"error": "replica not accepting handoffs"})
            return
        try:
            record, arrays = _decode_page_run(body)
            if not isinstance(record, dict):
                raise ValueError("page-run meta must be an object")
            req = Request(
                uid=int(record["uid"]),
                prompt=[int(t) for t in record["prompt"]],
                max_new_tokens=int(record["max_new_tokens"]),
                temperature=float(record.get("temperature", 0.0)),
                top_p=float(record.get("top_p", 1.0)),
                spec=bool(record.get("spec", True)),
                adapter=record.get("adapter"),
            )
        except (ValueError, KeyError, TypeError) as e:
            await _respond_json(writer, 400, {"error": f"bad page run: {e}"})
            return
        loop = asyncio.get_running_loop()
        events: "asyncio.Queue[Tuple[str, Any, Any]]" = asyncio.Queue()

        def post(kind: str, a: Any = None, b: Any = None) -> None:
            try:
                loop.call_soon_threadsafe(events.put_nowait, (kind, a, b))
            except RuntimeError:
                pass

        deadline_s = record.get("deadline_s")
        ticket = Ticket(
            uid=req.uid,
            request=req,
            deadline=(
                time.monotonic() + float(deadline_s)
                if isinstance(deadline_s, (int, float)) and deadline_s > 0
                else None
            ),
            on_token=lambda uid, tok, idx: post("token", tok, idx),
            on_finish=lambda completion: post("finish", completion),
            trace_id=record.get("trace_id"),
        )
        done = threading.Event()
        result: Dict[str, Any] = {}
        self._disagg_inbox.append(("insert", (record, arrays, ticket, done, result)))
        ok = await loop.run_in_executor(None, done.wait, self.migrate_timeout_s)
        if not ok:
            # flag the ticket so a late insert is rejected (or, if it already
            # landed, the cancel scan frees the slot) — never decode blind
            ticket.cancelled.set()
            await _respond_json(writer, 503, {"error": "migrated insert timed out"})
            return
        if result.get("error"):
            await _respond_json(writer, 409, {"error": result["error"]})
            return
        await self._stream_response(reader, writer, ticket, events)

    async def _handle_prefix(self, writer: asyncio.StreamWriter, digest_hex: str) -> None:
        """GET /internal/prefix/<digest> — export a pinned prefix page run
        for a peer.  404 on a miss (stale directory entry): the requester
        falls open to local prefill."""
        if self._worker_error is not None or self._warming:
            await _respond_json(writer, 503, {"error": "replica not serving prefixes"})
            return
        done = threading.Event()
        result: Dict[str, Any] = {}
        self._disagg_inbox.append(
            ("export_prefix", (digest_hex.strip(), done, result))
        )
        loop = asyncio.get_running_loop()
        ok = await loop.run_in_executor(None, done.wait, 10.0)
        blob = result.get("blob") if ok else None
        if blob is None:
            await _respond_json(
                writer,
                404,
                {"error": result.get("error") or "prefix not cached on this replica"},
            )
            return
        writer.write(
            _head(200, "OK", "application/octet-stream", content_length=len(blob))
        )
        writer.write(blob)
        await writer.drain()

    async def _handle_generate(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        # the request id is the span trace id AND the X-Request-Id response
        # header: a caller-supplied header is honored (so a gateway's id
        # threads through every phase span), otherwise one is minted here
        rid = ((headers or {}).get("x-request-id") or "").strip() or new_trace_id()
        rid_header = {"X-Request-Id": rid}
        if self._worker_error is not None:
            # dead worker, listener lingering for health probes: fail fast
            # instead of queueing a ticket nothing will ever claim
            self.stats.inc("rejected_total", ("reason", "error"))
            await _respond_json(
                writer,
                500,
                {"error": f"model thread died: {self._worker_error!r}"},
                extra_headers=rid_header,
            )
            return
        try:
            fields = parse_generate_body(
                body,
                default_max_new_tokens=self.default_max_new_tokens,
                default_temperature=self.default_temperature,
                default_top_p=self.default_top_p,
            )
            req = Request(
                uid=self.admission.next_uid(),
                prompt=fields["prompt"],
                max_new_tokens=fields["max_new_tokens"],
                temperature=fields["temperature"],
                top_p=fields["top_p"],
                spec=fields["spec"],
                adapter=fields["adapter"],
            )
            # capacity/validity errors surface as 400 here, before admission,
            # instead of crashing the decode loop later
            self.scheduler.validate_request(req)
        except (BadRequest, ValueError) as e:
            self.stats.inc("rejected_total", ("reason", "bad_request"))
            await _respond_json(writer, 400, {"error": str(e)}, extra_headers=rid_header)
            return

        loop = asyncio.get_running_loop()
        events: "asyncio.Queue[Tuple[str, Any, Any]]" = asyncio.Queue()

        def post(kind: str, a: Any = None, b: Any = None) -> None:
            try:
                loop.call_soon_threadsafe(events.put_nowait, (kind, a, b))
            except RuntimeError:
                pass  # loop closed mid-drain; the record still lands in metrics

        deadline = (
            time.monotonic() + fields["deadline_s"]
            if fields["deadline_s"] is not None
            else None
        )
        # root span for the whole request; queue_wait opens now and is ended
        # by the model thread when it claims the ticket (cross-thread span)
        root = self.tracer.start_span(
            "request", trace_id=rid, uid=req.uid, route="generate",
            prompt_tokens=len(req.prompt),
        )
        ticket = Ticket(
            uid=req.uid,
            request=req,
            deadline=deadline,
            on_token=lambda uid, tok, idx: post("token", tok, idx),
            on_finish=lambda completion: post("finish", completion),
            trace_id=rid,
            span=root,
            queue_span=self.tracer.start_span(
                "queue_wait", trace_id=rid, parent=root, uid=req.uid
            ),
        )
        try:
            self.admission.try_admit(ticket)
        except QueueFull as e:
            self.stats.inc("rejected_total", ("reason", "queue_full"))
            ticket.queue_span.set(outcome="queue_full").end()
            root.set(finish_reason="rejected_queue_full").end()
            await _respond_json(
                writer,
                429,
                {"error": str(e)},
                extra_headers={
                    "Retry-After": f"{self.admission.retry_after_s:.0f}",
                    **rid_header,
                },
            )
            return
        except Draining as e:
            self.stats.inc("rejected_total", ("reason", "draining"))
            ticket.queue_span.set(outcome="draining").end()
            root.set(finish_reason="rejected_draining").end()
            await _respond_json(
                writer,
                503,
                {"error": str(e)},
                extra_headers={
                    "Retry-After": f"{self.admission.retry_after_s:.0f}",
                    **rid_header,
                },
            )
            return

        if fields["stream"]:
            await self._stream_response(reader, writer, ticket, events)
        else:
            await self._unary_response(reader, writer, ticket, events)

    async def _stream_response(self, reader, writer, ticket, events) -> None:
        writer.write(
            _head(
                200,
                "OK",
                "text/event-stream",
                {
                    "Cache-Control": "no-cache",
                    "X-Request-Id": ticket.trace_id or "",
                    # which weights serve this stream: a canary client can
                    # assert it hit the post-swap version without a healthz
                    # round trip (the version cannot change mid-request —
                    # swaps only happen with zero slots active)
                    "X-Relora-Weights": str(self.weights_version),
                },
            )
        )
        await writer.drain()
        eof_watch = asyncio.ensure_future(reader.read(1))
        try:
            while True:
                getter = asyncio.ensure_future(events.get())
                done, _ = await asyncio.wait(
                    {getter, eof_watch}, return_when=asyncio.FIRST_COMPLETED
                )
                if eof_watch in done and getter not in done:
                    getter.cancel()
                    self._client_gone(ticket)
                    return
                kind, a, b = getter.result()
                if kind == "token":
                    event = {"uid": ticket.uid, "index": b, "token": a}
                    # manual span, explicit parent: handlers interleave on one
                    # thread, so the tracer's ambient (thread-local) nesting
                    # would cross-wire concurrent streams
                    flush = self.tracer.start_span(
                        "sse_flush",
                        trace_id=ticket.trace_id,
                        parent=ticket.span,
                        index=b,
                    )
                    writer.write(_sse(event))
                    try:
                        await writer.drain()
                    except (ConnectionError, OSError):
                        flush.set(outcome="disconnect").end()
                        self._client_gone(ticket)
                        return
                    self.stats.observe("sse_flush_seconds", flush.end())
                else:  # finish
                    writer.write(_sse(_completion_record(a)))
                    writer.write(b"data: [DONE]\n\n")
                    await writer.drain()
                    return
        finally:
            if not eof_watch.done():
                eof_watch.cancel()

    async def _unary_response(self, reader, writer, ticket, events) -> None:
        eof_watch = asyncio.ensure_future(reader.read(1))
        try:
            while True:
                getter = asyncio.ensure_future(events.get())
                done, _ = await asyncio.wait(
                    {getter, eof_watch}, return_when=asyncio.FIRST_COMPLETED
                )
                if eof_watch in done and getter not in done:
                    getter.cancel()
                    self._client_gone(ticket)
                    return
                kind, a, _b = getter.result()
                if kind == "finish":
                    await _respond_json(
                        writer,
                        500 if a.finish_reason == "error" else 200,
                        _completion_record(a),
                        extra_headers={
                            "X-Request-Id": ticket.trace_id or "",
                            "X-Relora-Weights": str(self.weights_version),
                        },
                    )
                    return
        finally:
            if not eof_watch.done():
                eof_watch.cancel()

    def _client_gone(self, ticket: Ticket) -> None:
        """The client disconnected mid-request: flag the ticket so the model
        thread frees its slot at the next step boundary."""
        ticket.cancelled.set()
        self.stats.inc("disconnects_total")


def run_server(
    scheduler: ContinuousBatchingScheduler,
    *,
    host: str = "127.0.0.1",
    port: int = 8000,
    ready_cb: Optional[Callable[["GenerateServer"], None]] = None,
    **kwargs: Any,
) -> int:
    """Blocking entry point for the CLI: build a GenerateServer, run it until
    a SIGTERM drain completes.  ``ready_cb(server)`` fires once the listener
    is bound (the CLI writes the chosen port for --port 0)."""
    server = GenerateServer(scheduler, host=host, port=port, **kwargs)

    async def _main() -> None:
        serve = asyncio.ensure_future(server.serve_forever())
        while not server.started.is_set():
            await asyncio.sleep(0.01)
            if serve.done():
                break
        if ready_cb is not None and not serve.done():
            ready_cb(server)
        await serve

    asyncio.run(_main())
    return 0
