"""Elastic resume: reshard a checkpoint onto a different chip count / mesh.

ReLoRA's economics assume cheap, *resizable* capacity: a run that
checkpoints on an 8-chip mesh must be able to continue on 4 chips after a
partial preemption and grow back to 8 when capacity returns — without
losing the optimizer state or bending the loss curve.  Orbax's fast path
(``checkpoint.restore_checkpoint``) restores shards straight onto the mesh
the state was saved under; that is exactly what breaks when the topology
changed.

This module is the slow-but-shape-free path:

1. restore the full TrainState **host-side** via the manifest
   (``restore_state_host`` — every leaf forced to numpy, no device layout
   assumed);
2. re-apply the regex partition rules for the *new* mesh — the Trainer has
   already done this by building a fresh sharded ``TrainState`` template
   from ``LOGICAL_RULES``, so the template's per-leaf shardings ARE the
   rules resolved against the new topology;
3. re-place every restored array onto its template leaf's sharding
   (``jax.device_put``).  Optimizer moments, LoRA A/B factors, and the
   frozen base all ride the same walk — there is one rule table.

Validation comes first: the checkpoint manifest records the mesh shape,
chip count, and partition-rule fingerprint it was saved under
(``checkpoint.save_checkpoint`` / ``mesh.mesh_metadata``).  A reshard is
only attempted when the *rules* match — shapes and chip counts may differ
(that is the point), but a drifted rule table means the logical-axis names
no longer describe the arrays and re-placing would be silently wrong.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np

from relora_tpu.parallel.mesh import mesh_metadata, partition_rule_version
from relora_tpu.train import checkpoint as ckpt
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PyTree = Any

# re-exported for callers that reach elastic-first (tests, tools)
load_manifest_metadata = ckpt.load_manifest_metadata


def needs_reshard(meta: Optional[dict], mesh) -> bool:
    """Does restoring under ``mesh`` require the host-side reshard path?

    ``meta`` is the checkpoint's manifest metadata (``None`` for legacy
    checkpoints — those take the fast path; they carry no topology claim to
    contradict).  True when the recorded mesh shape or chip count differs
    from the current mesh."""
    if meta is None:
        return False
    here = mesh_metadata(mesh)
    if meta.get("chip_count") != here["chip_count"]:
        return True
    recorded = meta.get("mesh_shape")
    return recorded is not None and recorded != here["mesh_shape"]


def validate_reshard(meta: Optional[dict], mesh) -> Tuple[bool, str]:
    """Can a checkpoint saved under ``meta`` be resharded onto ``mesh``?

    Returns ``(ok, reason)`` with a *named* reason — callers surface it
    verbatim.  Rules:

    - ``missing_metadata``: no manifest metadata — the checkpoint predates
      topology stamping, so a reshard target cannot be validated.
    - ``partition_rule_mismatch``: the checkpoint was laid out under a
      different ``LOGICAL_RULES`` fingerprint; re-applying today's rules to
      its arrays would place them wrong.
    - ``ok``: rules match; any chip count / mesh shape is fair game.
    """
    if meta is None:
        return False, "missing_metadata"
    want = partition_rule_version()
    got = meta.get("partition_rule_version")
    if got != want:
        return False, (
            f"partition_rule_mismatch (checkpoint rules {got}, runtime rules {want})"
        )
    return True, "ok"


def _normalized_paths(tree: PyTree):
    """``[(path_tuple, leaf)]`` with every keypath entry collapsed to a
    string, so a dataclass field, a dict key, a namedtuple field, and a
    tuple index all compare under the one naming scheme Orbax uses on disk
    (field/dict names verbatim, sequence positions as ``"0"``, ``"1"``…)."""
    out = []
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        parts = []
        for entry in keypath:
            if hasattr(entry, "key"):  # DictKey / FlattenedIndexKey
                parts.append(str(entry.key))
            elif hasattr(entry, "name"):  # GetAttrKey (dataclass, namedtuple)
                parts.append(str(entry.name))
            elif hasattr(entry, "idx"):  # SequenceKey
                parts.append(str(entry.idx))
            else:
                parts.append(str(entry))
        out.append((tuple(parts), leaf))
    return out


def reshard_tree(host_tree: PyTree, template: PyTree) -> PyTree:
    """Place a host-restored tree onto ``template``'s shardings.

    ``host_tree`` is whatever ``restore_state_host`` returned (nested
    containers of numpy arrays, structure-as-serialized); ``template`` is a
    live sharded tree (e.g. the Trainer's freshly built ``TrainState``).
    Leaves are matched by normalized key path — positional zip would
    misalign a dict-restored ``TrainState`` whose dict ordering differs
    from the dataclass field order.  Returns the *template's* structure
    with every leaf replaced by the restored value, device_put onto the
    template leaf's sharding."""
    host = dict(_normalized_paths(host_tree))
    t_paths = _normalized_paths(template)
    missing = [p for p, _ in t_paths if p not in host]
    if missing:
        raise ValueError(
            f"checkpoint is missing {len(missing)} arrays the current state "
            f"needs; first: {'/'.join(missing[0])}"
        )
    leaves = []
    for path, t_leaf in t_paths:
        value = np.asarray(host[path])
        t_shape = tuple(getattr(t_leaf, "shape", ()) or ())
        if value.shape != t_shape:
            raise ValueError(
                f"shape mismatch at {'/'.join(path)}: checkpoint "
                f"{value.shape} vs current state {t_shape} — elastic resume "
                f"reshapes the mesh, never the arrays"
            )
        dtype = getattr(t_leaf, "dtype", None)
        if dtype is not None and value.dtype != dtype:
            value = value.astype(dtype)
        sharding = getattr(t_leaf, "sharding", None)
        leaves.append(jax.device_put(value, sharding) if sharding is not None else value)
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def restore_resharded(path: str, template_state: PyTree) -> PyTree:
    """Restore the checkpoint at ``path`` onto ``template_state``'s mesh.

    The elastic slow path: host-side manifest restore, then per-leaf
    re-placement onto the template's shardings.  The caller is expected to
    have validated the target first (``validate_reshard``)."""
    host = ckpt.restore_state_host(path)
    state = reshard_tree(host, template_state)
    logger.info(f"Elastically resharded checkpoint {path} onto the current mesh")
    return state
